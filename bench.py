#!/usr/bin/env python
"""Benchmark: MobileNetV2/CIFAR-10 training throughput per chip.

Measures steady-state images/sec of the full jitted training step (raw
uint8 32x32 batch in -> on-device augmentation -> forward -> backward ->
Adam update -> metrics) on the reference workload shape (224x224, the
reference's single-V100 config trains ~94.7 img/s, BASELINE.md). Prints
ONE JSON line; vs_baseline is the ratio to that single-GPU baseline.

Input batches are pre-staged on device and cycled with fresh RNG keys so
the number measures the accelerator compute path; the real input path
ships the same uint8 batches (3 KB/image), far below HBM/PCIe limits.

Synchronization: the timed region ends by waiting on the whole updated
train state AND fetching one parameter element to the host — on this
platform ``jax.block_until_ready`` on a small step output (metrics) was
observed returning before the chained computation finished, which would
time async dispatch instead of execution. A parameter element is
data-dependent on the last step's gradient/Adam work, so its fetched
value cannot exist early.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

# Persistent compiled-program cache: TPU compiles in this environment go
# through a slow remote-compile relay, so cache hits across runs matter.
# Must be set via jax.config (not env): sitecustomize imports jax before
# this script runs, so jax has already read the environment. The repo-
# local .jax_cache (shared with scripts/roofline_attrib.py) survives
# tempdir cleanup; convention lives in tpunet.utils.cache.
from tpunet.utils.cache import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache(
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"))

BASELINE_IMG_PER_SEC = 94.7  # 1x V100, BASELINE.md ("north star" x4 target)

# Dense bf16 peak FLOP/s per chip by device kind (for the MFU estimate;
# public spec-sheet numbers). Unknown kinds (and CPU) report mfu: null.
_PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("trillium", 918e12), ("v4", 275e12), ("v3", 123e12),
)

# HBM bandwidth per chip (public spec-sheet numbers, bytes/s) — the
# roofline's second axis. MobileNetV2 is depthwise/elementwise-heavy:
# its arithmetic intensity sits far below the MXU ridge point, so the
# MXU-peak MFU is the wrong denominator ("wrong units, not 4% of
# attainable" — VERDICT r3). roofline_attainable below is the classic
# two-resource bound: attainable img/s = 1 / max(flops_img/peak_flops,
# bytes_img/hbm_bw); pct_of_roofline = measured / attainable.
#
# Method note — the bytes term. XLA's cost_analysis "bytes accessed"
# counts every op's operands+outputs as HBM traffic, re-counting
# values that fusion keeps on-chip; measured on the v5e it OVERcounts
# ~2x (a "roofline" built from it put measured throughput at 198% of
# attainable — not a bound at all). Instead the traffic model walks
# the step's jaxpr and counts the MATERIALIZED tensors: operands +
# results of convolutions and dot_generals only (elementwise/BN/
# cast/reduce chains are assumed fused into their producers — how the
# TPU compiler actually schedules them), scan bodies multiplied by
# trip count. That is a fusion-OPTIMISTIC lower bound on true
# traffic, so roofline_attainable is a true upper bound on attainable
# throughput and pct_of_roofline a meaningful "fraction of what a
# perfectly-fused program could reach". The raw cost-analysis count
# ships alongside as xla_bytes_accessed for reference.
_HBM_BW = (
    ("v5 lite", 819e9), ("v5e", 819e9), ("v5p", 2765e9),
    ("v6", 1640e9), ("trillium", 1640e9), ("v4", 1228e9), ("v3", 900e9),
)


def _conv_dot_traffic(jaxpr, mult: float = 1.0) -> float:
    """Materialized-tensor HBM traffic estimate (method note above):
    sum of operand+result bytes over conv/dot equations, recursing
    into pjit/scan/cond/custom-vjp sub-jaxprs (scan bodies scaled by
    trip count)."""
    total = 0.0

    def nbytes(v):
        aval = v.aval
        try:
            return aval.size * aval.dtype.itemsize
        except Exception:
            return 0.0

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in ("conv_general_dilated", "dot_general"):
            total += mult * (sum(nbytes(v) for v in eqn.invars)
                             + sum(nbytes(v) for v in eqn.outvars))
            continue
        sub_mult = mult
        if name == "scan":
            sub_mult = mult * eqn.params.get("length", 1)
        for pname, p in eqn.params.items():
            vals = p if isinstance(p, (list, tuple)) else (p,)
            for item in vals:
                inner = getattr(item, "jaxpr", None)   # ClosedJaxpr
                if inner is None and hasattr(item, "eqns"):
                    inner = item                       # bare Jaxpr
                if inner is not None:
                    total += _conv_dot_traffic(inner, sub_mult)
    return total


def _chip_spec(table) -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    return next((v for k, v in table if k in kind), None)


def _peak_flops_per_chip() -> float | None:
    return _chip_spec(_PEAK_FLOPS)


def _note(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def _model_overrides(argv) -> dict:
    """ModelConfig overrides from the variant flags, so lever A/Bs are
    one command each (docs/performance.md "A/B workflow"):
    --block-remat / --no-block-remat, --fused-ir / --no-fused-ir,
    --fused-bn / --no-fused-bn, --pallas-depthwise. Repeated flags are
    last-wins in argv order, matching the train CLI's argparse
    BooleanOptionalAction (so a sweep script may append an override to
    a base command)."""
    spec = {}
    for flag, field in (("block-remat", "block_remat"),
                        ("fused-ir", "fused_ir"),
                        ("fused-bn", "fused_bn"),
                        ("pallas-depthwise", "use_pallas_depthwise")):
        spec[f"--{flag}"] = (field, True)
        spec[f"--no-{flag}"] = (field, False)
    out = {}
    for arg in argv:
        if arg in spec:
            field, value = spec[arg]
            out[field] = value
    return out


def _measure(per_chip_batch: int, timed: int = 24, image_size: int = 224,
             model_overrides: dict | None = None):
    """Steady-state throughput of the full train step at the given
    per-chip batch. Returns (img/s/chip, flops-per-execution or 0)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import shard_host_batch
    from tpunet.train.loop import Trainer
    from tpunet.utils.prng import step_key

    n_chips = jax.device_count()
    batch = per_chip_batch * n_chips
    cfg = TrainConfig(
        data=DataConfig(dataset="synthetic", batch_size=batch,
                        image_size=image_size),
        model=ModelConfig(**(model_overrides or {})),  # bf16 compute
        optim=OptimConfig(),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    ds = synthetic_cifar10(n_train=4 * batch, n_test=batch)
    trainer = Trainer(cfg, dataset=ds)
    # Identity stamp for the BENCH record: run_id + config fingerprint
    # let the run-history store (tpunet/obs/history/) join this bench
    # round to training runs of the same workload — previously they
    # correlated only by BENCH_r* filename convention.
    identity = {k: v for k, v in trainer.obs.registry.identity().items()
                if k in ("run_id", "config_fingerprint")}

    # Pre-staged device batches (cycled), fresh rng per step.
    batches = []
    rng = np.random.default_rng(0)
    for _ in range(4):
        x = rng.integers(0, 256, size=(batch, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, size=batch).astype(np.int32)
        batches.append(shard_host_batch(trainer.mesh, x, y))

    state = trainer.state
    step = trainer.train_step

    def sync(state):
        # Belt and braces: wait on every leaf, then fetch one parameter
        # element — a value data-dependent on the final Adam update (the
        # step counter alone would only force its increment chain; a
        # param element cannot exist before the gradient work ran).
        jax.block_until_ready(state)
        leaf = jax.tree_util.tree_leaves(state.params)[0]
        return float(np.asarray(leaf.ravel()[0]))

    warmup, reps = 3, 2
    _note(f"compiling + warming up ({jax.devices()[0].platform}, "
          f"batch {batch})...")
    t0 = time.perf_counter()
    for i in range(warmup):
        gx, gy = batches[i % len(batches)]
        state, _ = step(state, gx, gy, step_key(0, i))
    sync(state)
    _note(f"warmup done in {time.perf_counter()-t0:.1f}s")

    # XLA's own FLOP count for one execution of the whole step program
    # (augment + fwd + bwd + Adam) feeds the MFU estimate; the roofline
    # bytes come from the materialized-tensor jaxpr walk (method note
    # at _HBM_BW), with the raw cost-analysis count kept for reference
    # and DECOMPOSED by op category from the optimized module text
    # (tpunet/obs/hlo_bytes.py) so a bytes regression names the
    # category that moved.
    flops = xla_bytes = traffic = 0.0
    bytes_breakdown = None
    try:
        gx, gy = batches[0]
        compiled = step.lower(state, gx, gy, step_key(0, 0)).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        xla_bytes = float(ca.get("bytes accessed", 0.0))
        try:
            from tpunet.obs import hlo_bytes
            # compiled.as_text() is the per-device SPMD module, like
            # cost_analysis — scale by the per-chip image count.
            bytes_breakdown = hlo_bytes.per_image_breakdown(
                compiled.as_text(), batch // n_chips)
        except Exception as e:
            _note(f"byte attribution unavailable: {e}")
    except Exception as e:  # cost analysis is best-effort per backend
        _note(f"cost_analysis unavailable: {e}")
    try:
        jx = jax.make_jaxpr(step)(state, gx, gy, step_key(0, 0))
        # global-program tensors; per-chip share for the roofline
        traffic = _conv_dot_traffic(jx.jaxpr) / n_chips
    except Exception as e:
        _note(f"jaxpr traffic walk unavailable: {e}")

    best_dt, k = float("inf"), warmup
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(timed):
            gx, gy = batches[k % len(batches)]
            state, _ = step(state, gx, gy, step_key(0, k))
            k += 1
        sync(state)
        best_dt = min(best_dt, time.perf_counter() - t0)

    trainer.close()
    return (timed * batch / best_dt / n_chips, flops, best_dt / timed,
            traffic, xla_bytes, batch // n_chips, bytes_breakdown,
            identity)


def main() -> None:
    n_chips = jax.device_count()
    overrides = _model_overrides(sys.argv[1:])
    if overrides and "--enforce-budget" in sys.argv[1:]:
        # The budget is the accepted measurement of the DEFAULT tree;
        # gating a deliberately non-default lever state against it
        # manufactures a false REGRESSION (e.g. --no-fused-ir measures
        # the legacy path, which is over the ratcheted budget by
        # design). Refuse loudly rather than letting the combination
        # masquerade as a regression — same posture as bench_serve's
        # --http --enforce-budget refusal.
        _note("--enforce-budget gates the default configuration; "
              f"refusing with lever overrides {overrides} (run the "
              "gate without override flags, or compare A/B records "
              "by hand per docs/performance.md)")
        sys.exit(2)
    if "--smoke" in sys.argv[1:]:
        # Harness sanity check on small shapes (CPU-friendly); numbers
        # are meaningless, the JSON plumbing is what's exercised.
        (peak_ips, flops, dt_step, traffic, xla_bytes, pcb,
         breakdown, identity) = _measure(8, timed=3, image_size=32,
                                         model_overrides=overrides)
        ref_ips = _measure(4, timed=3, image_size=32,
                           model_overrides=overrides)[0]
    elif "--peak-only" in sys.argv[1:]:
        # Flag/variant sweeps: just the peak-shape number (the batch-128
        # companion costs a second warmup and doesn't move with flags).
        # The batch128_* fields become null — aliasing them to the
        # batch-512 figure would fabricate a measurement under a name
        # that promises the reference shape.
        (peak_ips, flops, dt_step, traffic, xla_bytes, pcb,
         breakdown, identity) = _measure(512, model_overrides=overrides)
        ref_ips = None
    else:
        # Peak-throughput shape (per-chip batch sweep optimum) and the
        # reference's exact shape (cifar10_128batch.py:59: batch 128).
        (peak_ips, flops, dt_step, traffic, xla_bytes, pcb,
         breakdown, identity) = _measure(512, model_overrides=overrides)
        ref_ips = _measure(128, model_overrides=overrides)[0]

    peak = _peak_flops_per_chip()
    bw = _chip_spec(_HBM_BW)
    mfu = None
    if peak and flops:
        # Compiled.cost_analysis() reports the PER-DEVICE FLOPs of the
        # SPMD-partitioned module (verified empirically on a sharded
        # matmul), so it divides by step time and chip peak directly.
        mfu = round(flops / dt_step / peak, 4)

    # Two-resource roofline (method note at _HBM_BW): attainable
    # img/s/chip = 1 / max(compute time, memory time) per image; the
    # binding resource says which wall the step leans on. On this
    # depthwise model the bytes term binds — the MXU MFU is reported
    # for continuity but pct_of_roofline is the meaningful "how close"
    # number.
    roofline = pct = bound = None
    if peak and bw and flops and traffic:
        t_img = max(flops / peak, traffic / bw) / pcb
        roofline = round(1.0 / t_img, 2)
        pct = round(peak_ips / roofline, 4)
        bound = ("hbm" if traffic / bw > flops / peak else "compute")

    record = {
        "metric": "train_images_per_sec_per_chip",
        "value": round(peak_ips, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(peak_ips / BASELINE_IMG_PER_SEC, 3),
        # reference-shape figure (per-chip batch 128, the V100 config) so
        # the vs_baseline ratio has a shape-matched companion
        "batch128_img_per_sec_per_chip": (
            round(ref_ips, 2) if ref_ips is not None else None),
        "batch128_vs_baseline": (
            round(ref_ips / BASELINE_IMG_PER_SEC, 3)
            if ref_ips is not None else None),
        "mfu": mfu,
        "roofline_attainable": roofline,
        "pct_of_roofline": pct,
        "roofline_bound": bound,
        "roofline_bytes_per_image": (round(traffic / pcb)
                                     if traffic else None),
        "xla_bytes_accessed_per_image": (round(xla_bytes / pcb)
                                         if xla_bytes else None),
        # Per-HLO-op-category decomposition of the cost-analysis bytes
        # (tpunet/obs/hlo_bytes.py; 'total' is the parsed sum, which
        # tracks xla_bytes_accessed_per_image to <1%).
        "bytes_per_image_breakdown": breakdown,
        "device_kind": jax.devices()[0].device_kind,
        # History-store join keys (tpunet/obs/history/): the peak-shape
        # trainer's run identity + config fingerprint.
        **identity,
    }
    if overrides:
        # Variant runs are self-describing: a sweep artifact records
        # which levers it measured (default runs omit the field, so
        # the driver's BENCH_r* records keep their shape).
        record["model_overrides"] = overrides
    print(json.dumps(record))

    if "--enforce-budget" in sys.argv[1:]:
        # Regression gate against the checked-in budget
        # (docs/bytes_budget.json): nonzero exit when bytes/image
        # regresses past the budget's tolerance on this device kind.
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        from check_bytes_budget import check_record, load_budget
        ok, msgs = check_record(record, load_budget())
        for m in msgs:
            _note(m)
        if not ok:
            sys.exit(3)


if __name__ == "__main__":
    main()
