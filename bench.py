#!/usr/bin/env python
"""Benchmark: MobileNetV2/CIFAR-10 training throughput per chip.

Measures steady-state images/sec of the full jitted training step (raw
uint8 32x32 batch in -> on-device augmentation -> forward -> backward ->
Adam update -> metrics) on the reference workload shape (224x224, the
reference's single-V100 config trains ~94.7 img/s, BASELINE.md). Prints
ONE JSON line; vs_baseline is the ratio to that single-GPU baseline.

Input batches are pre-staged on device and cycled with fresh RNG keys so
the number measures the accelerator compute path; the real input path
ships the same uint8 batches (3 KB/image), far below HBM/PCIe limits.

Synchronization: the timed region ends by waiting on the whole updated
train state AND fetching one parameter element to the host — on this
platform ``jax.block_until_ready`` on a small step output (metrics) was
observed returning before the chained computation finished, which would
time async dispatch instead of execution. A parameter element is
data-dependent on the last step's gradient/Adam work, so its fetched
value cannot exist early.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import numpy as np

# Persistent compiled-program cache: TPU compiles in this environment go
# through a slow remote-compile relay, so cache hits across runs matter.
# Must be set via jax.config (not env): sitecustomize imports jax before
# this script runs, so jax has already read the environment.
if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

BASELINE_IMG_PER_SEC = 94.7  # 1x V100, BASELINE.md ("north star" x4 target)

# Dense bf16 peak FLOP/s per chip by device kind (for the MFU estimate;
# public spec-sheet numbers). Unknown kinds (and CPU) report mfu: null.
_PEAK_FLOPS = (
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("trillium", 918e12), ("v4", 275e12), ("v3", 123e12),
)


def _peak_flops_per_chip() -> float | None:
    kind = jax.devices()[0].device_kind.lower()
    return next((v for k, v in _PEAK_FLOPS if k in kind), None)


def _note(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def _measure(per_chip_batch: int, timed: int = 24, image_size: int = 224):
    """Steady-state throughput of the full train step at the given
    per-chip batch. Returns (img/s/chip, flops-per-execution or 0)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import shard_host_batch
    from tpunet.train.loop import Trainer
    from tpunet.utils.prng import step_key

    n_chips = jax.device_count()
    batch = per_chip_batch * n_chips
    cfg = TrainConfig(
        data=DataConfig(dataset="synthetic", batch_size=batch,
                        image_size=image_size),
        model=ModelConfig(),              # bf16 compute
        optim=OptimConfig(),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    ds = synthetic_cifar10(n_train=4 * batch, n_test=batch)
    trainer = Trainer(cfg, dataset=ds)

    # Pre-staged device batches (cycled), fresh rng per step.
    batches = []
    rng = np.random.default_rng(0)
    for _ in range(4):
        x = rng.integers(0, 256, size=(batch, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, size=batch).astype(np.int32)
        batches.append(shard_host_batch(trainer.mesh, x, y))

    state = trainer.state
    step = trainer.train_step

    def sync(state):
        # Belt and braces: wait on every leaf, then fetch one parameter
        # element — a value data-dependent on the final Adam update (the
        # step counter alone would only force its increment chain; a
        # param element cannot exist before the gradient work ran).
        jax.block_until_ready(state)
        leaf = jax.tree_util.tree_leaves(state.params)[0]
        return float(np.asarray(leaf.ravel()[0]))

    warmup, reps = 3, 2
    _note(f"compiling + warming up ({jax.devices()[0].platform}, "
          f"batch {batch})...")
    t0 = time.perf_counter()
    for i in range(warmup):
        gx, gy = batches[i % len(batches)]
        state, _ = step(state, gx, gy, step_key(0, i))
    sync(state)
    _note(f"warmup done in {time.perf_counter()-t0:.1f}s")

    # XLA's own FLOP count for one execution of the whole step program
    # (augment + fwd + bwd + Adam) — feeds the MFU estimate.
    flops = 0.0
    try:
        gx, gy = batches[0]
        ca = step.lower(state, gx, gy, step_key(0, 0)).compile() \
                 .cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
    except Exception as e:  # cost analysis is best-effort per backend
        _note(f"cost_analysis unavailable: {e}")

    best_dt, k = float("inf"), warmup
    for _ in range(reps):
        t0 = time.perf_counter()
        for i in range(timed):
            gx, gy = batches[k % len(batches)]
            state, _ = step(state, gx, gy, step_key(0, k))
            k += 1
        sync(state)
        best_dt = min(best_dt, time.perf_counter() - t0)

    trainer.close()
    return timed * batch / best_dt / n_chips, flops, best_dt / timed


def main() -> None:
    n_chips = jax.device_count()
    if "--smoke" in sys.argv[1:]:
        # Harness sanity check on small shapes (CPU-friendly); numbers
        # are meaningless, the JSON plumbing is what's exercised.
        peak_ips, flops, dt_step = _measure(8, timed=3, image_size=32)
        ref_ips, _, _ = _measure(4, timed=3, image_size=32)
    else:
        # Peak-throughput shape (per-chip batch sweep optimum) and the
        # reference's exact shape (cifar10_128batch.py:59: batch 128).
        peak_ips, flops, dt_step = _measure(512)
        ref_ips, _, _ = _measure(128)

    peak = _peak_flops_per_chip()
    mfu = None
    if peak and flops:
        # Compiled.cost_analysis() reports the PER-DEVICE FLOPs of the
        # SPMD-partitioned module (verified empirically on a sharded
        # matmul), so it divides by step time and chip peak directly.
        mfu = round(flops / dt_step / peak, 4)

    print(json.dumps({
        "metric": "train_images_per_sec_per_chip",
        "value": round(peak_ips, 2),
        "unit": "img/s/chip",
        "vs_baseline": round(peak_ips / BASELINE_IMG_PER_SEC, 3),
        # reference-shape figure (per-chip batch 128, the V100 config) so
        # the vs_baseline ratio has a shape-matched companion
        "batch128_img_per_sec_per_chip": round(ref_ips, 2),
        "batch128_vs_baseline": round(ref_ips / BASELINE_IMG_PER_SEC, 3),
        "mfu": mfu,
        "device_kind": jax.devices()[0].device_kind,
    }))


if __name__ == "__main__":
    main()
