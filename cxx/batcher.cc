// tpunet native host-side batch assembly.
//
// The reference's host data path is torch DataLoader worker processes
// (cifar10_mpi_mobilenet_224.py:126-133, num_workers=2) doing PIL/CPU
// transforms. In tpunet augmentation runs on-device inside the jitted
// step, so the only host work per step is assembling this host's slice
// of the global batch: a permutation gather over the in-RAM uint8
// dataset. This library is the native runtime for that path — a
// multithreaded row gather plus a background prefetcher that keeps a
// ring of ready batches ahead of the device, replacing DataLoader
// workers with threads in one address space (no pickling, no fork).
//
// Built as a plain C ABI shared library; Python binds via ctypes
// (tpunet/data/native.py) with a pure-numpy fallback when the toolchain
// is unavailable.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

void gather_range(const uint8_t* src, const int64_t* idx, int64_t begin,
                  int64_t end, int64_t row_bytes, uint8_t* out) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

void gather_rows_impl(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                      int64_t row_bytes, uint8_t* out, int n_threads) {
  if (n_threads <= 1 || n_idx < 2 * n_threads) {
    gather_range(src, idx, 0, n_idx, row_bytes, out);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  const int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int64_t b = t * chunk;
    const int64_t e = std::min(n_idx, b + chunk);
    if (b >= e) break;
    pool.emplace_back(gather_range, src, idx, b, e, row_bytes, out);
  }
  for (auto& th : pool) th.join();
}

struct Batch {
  std::vector<uint8_t> images;
  std::vector<int32_t> labels;
};

// Background prefetcher: one worker thread assembles batches following
// the epoch's index order into a bounded ring; consumers pop in order.
class Prefetcher {
 public:
  Prefetcher(const uint8_t* images, const int32_t* labels, int64_t n_rows,
             int64_t row_bytes, int64_t local_batch, int depth,
             int n_threads)
      : images_(images),
        labels_(labels),
        n_rows_(n_rows),
        row_bytes_(row_bytes),
        local_batch_(local_batch),
        depth_(depth < 1 ? 1 : depth),
        n_threads_(n_threads < 1 ? 1 : n_threads) {}

  ~Prefetcher() { stop(); }

  // Returns 0 on success, -1 if any index is out of range (the epoch is
  // then not started — failing cleanly instead of a wild memcpy).
  int start_epoch(const int64_t* idx, int64_t n_idx) {
    for (int64_t i = 0; i < n_idx; ++i) {
      if (idx[i] < 0 || idx[i] >= n_rows_) return -1;
    }
    stop();
    idx_.assign(idx, idx + n_idx);
    n_batches_ = n_idx / local_batch_;  // drop remainder, like the pipeline
    consumed_ = 0;
    stopping_ = false;
    worker_ = std::thread(&Prefetcher::run, this);
    return 0;
  }

  // 0 = batch copied out; 1 = epoch exhausted.
  int next(uint8_t* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    if (consumed_ >= n_batches_) return 1;
    ready_cv_.wait(lk, [&] { return !ring_.empty(); });
    Batch b = std::move(ring_.front());
    ring_.pop_front();
    ++consumed_;
    lk.unlock();
    space_cv_.notify_one();
    std::memcpy(out_images, b.images.data(), b.images.size());
    std::memcpy(out_labels, b.labels.data(),
                b.labels.size() * sizeof(int32_t));
    return 0;
  }

 private:
  void run() {
    for (int64_t s = 0; s < n_batches_; ++s) {
      Batch b;
      b.images.resize(static_cast<size_t>(local_batch_ * row_bytes_));
      b.labels.resize(static_cast<size_t>(local_batch_));
      const int64_t* idx = idx_.data() + s * local_batch_;
      gather_rows_impl(images_, idx, local_batch_, row_bytes_,
                       b.images.data(), n_threads_);
      for (int64_t i = 0; i < local_batch_; ++i) b.labels[i] = labels_[idx[i]];
      std::unique_lock<std::mutex> lk(mu_);
      space_cv_.wait(lk, [&] {
        return stopping_ || static_cast<int>(ring_.size()) < depth_;
      });
      if (stopping_) return;
      ring_.push_back(std::move(b));
      lk.unlock();
      ready_cv_.notify_one();
    }
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stopping_ = true;
    }
    space_cv_.notify_all();
    if (worker_.joinable()) worker_.join();
    std::lock_guard<std::mutex> lk(mu_);
    ring_.clear();
  }

  const uint8_t* images_;
  const int32_t* labels_;
  int64_t n_rows_;
  int64_t row_bytes_;
  int64_t local_batch_;
  int depth_;
  int n_threads_;

  std::vector<int64_t> idx_;
  int64_t n_batches_ = 0;
  int64_t consumed_ = 0;
  bool stopping_ = false;
  std::deque<Batch> ring_;
  std::mutex mu_;
  std::condition_variable ready_cv_;
  std::condition_variable space_cv_;
  std::thread worker_;
};

}  // namespace

extern "C" {

void tn_gather_rows(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                    int64_t row_bytes, uint8_t* out, int n_threads) {
  gather_rows_impl(src, idx, n_idx, row_bytes, out, n_threads);
}

void* tn_prefetcher_create(const uint8_t* images, const int32_t* labels,
                           int64_t n_rows, int64_t row_bytes,
                           int64_t local_batch, int depth, int n_threads) {
  return new Prefetcher(images, labels, n_rows, row_bytes, local_batch, depth,
                        n_threads);
}

int tn_prefetcher_start_epoch(void* p, const int64_t* idx, int64_t n_idx) {
  return static_cast<Prefetcher*>(p)->start_epoch(idx, n_idx);
}

int tn_prefetcher_next(void* p, uint8_t* out_images, int32_t* out_labels) {
  return static_cast<Prefetcher*>(p)->next(out_images, out_labels);
}

void tn_prefetcher_destroy(void* p) { delete static_cast<Prefetcher*>(p); }

int tn_abi_version() { return 1; }

}  // extern "C"
