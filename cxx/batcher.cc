// tpunet native host-side batch assembly.
//
// The reference's host data path is torch DataLoader worker processes
// (cifar10_mpi_mobilenet_224.py:126-133, num_workers=2) doing PIL/CPU
// transforms. In tpunet augmentation runs on-device inside the jitted
// step, so the only host work per step is assembling this host's slice
// of the global batch: a permutation gather over the in-RAM uint8
// dataset. This library is the native runtime for that path — a
// multithreaded row gather plus a background prefetcher that keeps a
// ring of ready batches ahead of the device, replacing DataLoader
// workers with threads in one address space (no pickling, no fork).
//
// Built as a plain C ABI shared library; Python binds via ctypes
// (tpunet/data/native.py) with a pure-numpy fallback when the toolchain
// is unavailable.

#include <fcntl.h>
#include <signal.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// Operation journal: the native half of the flight recorder
// (tpunet/obs/flightrec/). A small fixed ring of the last N
// alloc/free/enqueue/shutdown operations, recorded lock-free (one
// relaxed fetch_add per op) from every thread that touches the
// batcher. Two readers: tn_journal_read (live snapshot, Python side)
// and the crash handler below, which spills the ring to a text file
// with async-signal-safe primitives only (open/write/close + manual
// integer formatting) before chaining to the previously installed
// handler (faulthandler's, when Python armed the recorder). This is
// the instrument aimed at the glibc heap-corruption-on-resume bug:
// when malloc aborts, the journal says what the batcher had just
// allocated, freed, or torn down.

// Mirrored in tpunet/obs/flightrec/report.py NATIVE_OPS; bump together.
enum JournalOp : uint32_t {
  kJopCreate = 1,
  kJopDestroy = 2,
  kJopEpochStart = 3,
  kJopEpochReject = 4,
  kJopNextPop = 5,
  kJopNextEof = 6,
  kJopBatchAlloc = 7,
  kJopBatchPush = 8,
  kJopWorkerEnter = 9,
  kJopWorkerExit = 10,
  kJopStopBegin = 11,
  kJopStopJoined = 12,
  kJopGather = 13,
};

// Snapshot/output layout (plain POD, 32 bytes packed): the C ABI for
// tn_journal_read and the crash spill, mirrored by ctypes in
// tpunet/data/native.py. Unchanged since ABI v2.
struct JournalEntry {
  uint64_t seq;
  uint32_t op;
  uint32_t tid;
  int64_t a;
  int64_t b;
};

// Ring storage: a per-slot seqlock. The original ring wrote plain
// fields "racy by design" (seq stored last, readers drop mismatched
// slots) — which worked on x86 but was a formal C++ data race, and
// the first TSan build of this file said so (scripts/
// check_sanitizers.py). Same protocol, now through atomics: writers
// invalidate seq, fill fields relaxed, publish seq with a release
// store; readers acquire-load seq before AND after copying the
// fields and drop the slot on any mismatch. Relaxed/acq-rel atomics
// compile to the same plain MOVs here, so the journal stays ~one
// fetch_add per op — lock-free and async-signal-safe (all five
// atomics are lock-free at these sizes on every supported target).
struct JournalSlot {
  std::atomic<uint64_t> seq{0};
  std::atomic<uint32_t> op{0};
  std::atomic<uint32_t> tid{0};
  std::atomic<int64_t> a{0};
  std::atomic<int64_t> b{0};
};

constexpr uint64_t kJournalSlots = 256;
JournalSlot g_journal[kJournalSlots];
std::atomic<uint64_t> g_journal_seq{0};

uint32_t journal_tid() {
  static thread_local uint32_t tid = static_cast<uint32_t>(
      std::hash<std::thread::id>()(std::this_thread::get_id()));
  return tid;
}

void journal(JournalOp op, int64_t a = 0, int64_t b = 0) {
  const uint64_t seq =
      g_journal_seq.fetch_add(1, std::memory_order_relaxed) + 1;
  JournalSlot& e = g_journal[(seq - 1) % kJournalSlots];
  // Seqlock write: invalidate, fill, publish. The release FENCE after
  // the invalidation is load-bearing on weakly ordered targets: a
  // release *store* only orders PRIOR accesses, so without the fence
  // the relaxed field stores could hoist above seq=0 and a reader
  // could pass both checks on a torn slot. The final release store
  // orders the field stores before the publish.
  e.seq.store(0, std::memory_order_relaxed);
  std::atomic_thread_fence(std::memory_order_release);
  e.op.store(op, std::memory_order_relaxed);
  e.tid.store(journal_tid(), std::memory_order_relaxed);
  e.a.store(a, std::memory_order_relaxed);
  e.b.store(b, std::memory_order_relaxed);
  e.seq.store(seq, std::memory_order_release);
}

int journal_snapshot(JournalEntry* out, int max_entries) {
  const uint64_t cur = g_journal_seq.load(std::memory_order_relaxed);
  const uint64_t span = cur < kJournalSlots ? cur : kJournalSlots;
  int n = 0;
  for (uint64_t s = cur - span + 1; s <= cur && n < max_entries; ++s) {
    const JournalSlot& slot = g_journal[(s - 1) % kJournalSlots];
    // Seqlock read: validate seq on both sides of the field copy — a
    // writer racing us flips seq to 0 first, so any torn copy fails
    // one of the two checks and the slot is dropped, exactly the old
    // semantics minus the undefined behavior. The leading acquire
    // load keeps the field loads from hoisting above it; the acquire
    // FENCE keeps them from sinking below the re-check (an acquire
    // *load* there would only order accesses AFTER itself).
    if (slot.seq.load(std::memory_order_acquire) != s) continue;
    JournalEntry e;
    e.op = slot.op.load(std::memory_order_relaxed);
    e.tid = slot.tid.load(std::memory_order_relaxed);
    e.a = slot.a.load(std::memory_order_relaxed);
    e.b = slot.b.load(std::memory_order_relaxed);
    std::atomic_thread_fence(std::memory_order_acquire);
    if (slot.seq.load(std::memory_order_relaxed) != s) continue;
    e.seq = s;
    out[n++] = e;
  }
  return n;
}

// -- crash handler (async-signal-safe only below this line) -----------------

char g_crash_path[1024] = {0};
struct sigaction g_old_sa[3];
const int g_crash_sigs[3] = {SIGSEGV, SIGABRT, SIGBUS};

void write_str(int fd, const char* s) {
  size_t n = 0;
  while (s[n]) ++n;
  ssize_t r = write(fd, s, n);
  (void)r;
}

void write_dec(int fd, long long v) {
  char buf[24];
  int i = sizeof(buf);
  bool neg = v < 0;
  unsigned long long u =
      neg ? ~static_cast<unsigned long long>(v) + 1ull : v;
  do {
    buf[--i] = '0' + static_cast<char>(u % 10);
    u /= 10;
  } while (u && i > 1);
  if (neg) buf[--i] = '-';
  ssize_t r = write(fd, buf + i, sizeof(buf) - i);
  (void)r;
}

void crash_handler(int sig, siginfo_t*, void*) {
  if (g_crash_path[0]) {
    const int fd =
        open(g_crash_path, O_WRONLY | O_CREAT | O_TRUNC, 0644);
    if (fd >= 0) {
      write_str(fd, "tn-crash sig=");
      write_dec(fd, sig);
      write_str(fd, " seq=");
      write_dec(fd, static_cast<long long>(
          g_journal_seq.load(std::memory_order_relaxed)));
      write_str(fd, "\n");
      // Static snapshot buffer: no malloc in a handler that may be
      // here BECAUSE malloc's heap is corrupted.
      static JournalEntry snap[kJournalSlots];
      const int n = journal_snapshot(snap, kJournalSlots);
      for (int i = 0; i < n; ++i) {
        write_str(fd, "j ");
        write_dec(fd, static_cast<long long>(snap[i].seq));
        write_str(fd, " ");
        write_dec(fd, snap[i].op);
        write_str(fd, " ");
        write_dec(fd, snap[i].tid);
        write_str(fd, " ");
        write_dec(fd, snap[i].a);
        write_str(fd, " ");
        write_dec(fd, snap[i].b);
        write_str(fd, "\n");
      }
      close(fd);
    }
  }
  // Chain: restore whoever was installed before us (faulthandler,
  // which dumps Python stacks and re-raises the default) and
  // re-deliver.
  for (int i = 0; i < 3; ++i) {
    if (g_crash_sigs[i] == sig) {
      sigaction(sig, &g_old_sa[i], nullptr);
      break;
    }
  }
  raise(sig);
}

void gather_range(const uint8_t* src, const int64_t* idx, int64_t begin,
                  int64_t end, int64_t row_bytes, uint8_t* out) {
  for (int64_t i = begin; i < end; ++i) {
    std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes,
                static_cast<size_t>(row_bytes));
  }
}

void gather_rows_impl(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                      int64_t row_bytes, uint8_t* out, int n_threads) {
  journal(kJopGather, n_idx, row_bytes);
  if (n_threads <= 1 || n_idx < 2 * n_threads) {
    gather_range(src, idx, 0, n_idx, row_bytes, out);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(n_threads);
  const int64_t chunk = (n_idx + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    const int64_t b = t * chunk;
    const int64_t e = std::min(n_idx, b + chunk);
    if (b >= e) break;
    pool.emplace_back(gather_range, src, idx, b, e, row_bytes, out);
  }
  for (auto& th : pool) th.join();
}

struct Batch {
  std::vector<uint8_t> images;
  std::vector<int32_t> labels;
};

// Background prefetcher: one worker thread assembles batches following
// the epoch's index order into a bounded ring; consumers pop in order.
class Prefetcher {
 public:
  Prefetcher(const uint8_t* images, const int32_t* labels, int64_t n_rows,
             int64_t row_bytes, int64_t local_batch, int depth,
             int n_threads)
      : images_(images),
        labels_(labels),
        n_rows_(n_rows),
        row_bytes_(row_bytes),
        local_batch_(local_batch),
        depth_(depth < 1 ? 1 : depth),
        n_threads_(n_threads < 1 ? 1 : n_threads) {
    journal(kJopCreate, local_batch, depth_);
  }

  ~Prefetcher() {
    stop();
    journal(kJopDestroy, consumed_, n_batches_);
  }

  // Returns 0 on success, -1 if any index is out of range (the epoch is
  // then not started — failing cleanly instead of a wild memcpy).
  int start_epoch(const int64_t* idx, int64_t n_idx) {
    for (int64_t i = 0; i < n_idx; ++i) {
      if (idx[i] < 0 || idx[i] >= n_rows_) {
        journal(kJopEpochReject, n_idx, idx[i]);
        return -1;
      }
    }
    stop();
    idx_.assign(idx, idx + n_idx);
    n_batches_ = n_idx / local_batch_;  // drop remainder, like the pipeline
    consumed_ = 0;
    stopping_ = false;
    journal(kJopEpochStart, n_idx, n_batches_);
    worker_ = std::thread(&Prefetcher::run, this);
    return 0;
  }

  // 0 = batch copied out; 1 = epoch exhausted.
  int next(uint8_t* out_images, int32_t* out_labels) {
    std::unique_lock<std::mutex> lk(mu_);
    if (consumed_ >= n_batches_) {
      journal(kJopNextEof, consumed_, n_batches_);
      return 1;
    }
    ready_cv_.wait(lk, [&] { return !ring_.empty(); });
    Batch b = std::move(ring_.front());
    ring_.pop_front();
    ++consumed_;
    lk.unlock();
    space_cv_.notify_one();
    journal(kJopNextPop, consumed_, static_cast<int64_t>(b.images.size()));
    std::memcpy(out_images, b.images.data(), b.images.size());
    std::memcpy(out_labels, b.labels.data(),
                b.labels.size() * sizeof(int32_t));
    return 0;
  }

 private:
  void run() {
    journal(kJopWorkerEnter, n_batches_, local_batch_);
    for (int64_t s = 0; s < n_batches_; ++s) {
      Batch b;
      b.images.resize(static_cast<size_t>(local_batch_ * row_bytes_));
      b.labels.resize(static_cast<size_t>(local_batch_));
      journal(kJopBatchAlloc, s,
              static_cast<int64_t>(b.images.size()));
      const int64_t* idx = idx_.data() + s * local_batch_;
      gather_rows_impl(images_, idx, local_batch_, row_bytes_,
                       b.images.data(), n_threads_);
      for (int64_t i = 0; i < local_batch_; ++i) b.labels[i] = labels_[idx[i]];
      std::unique_lock<std::mutex> lk(mu_);
      space_cv_.wait(lk, [&] {
        return stopping_ || static_cast<int>(ring_.size()) < depth_;
      });
      if (stopping_) {
        journal(kJopWorkerExit, s, 1);
        return;
      }
      ring_.push_back(std::move(b));
      lk.unlock();
      ready_cv_.notify_one();
      journal(kJopBatchPush, s, 0);
    }
    journal(kJopWorkerExit, n_batches_, 0);
  }

  void stop() {
    journal(kJopStopBegin, consumed_, n_batches_);
    {
      std::lock_guard<std::mutex> lk(mu_);
      stopping_ = true;
    }
    space_cv_.notify_all();
    if (worker_.joinable()) worker_.join();
    std::lock_guard<std::mutex> lk(mu_);
    ring_.clear();
    journal(kJopStopJoined, consumed_, n_batches_);
  }

  const uint8_t* images_;
  const int32_t* labels_;
  int64_t n_rows_;
  int64_t row_bytes_;
  int64_t local_batch_;
  int depth_;
  int n_threads_;

  std::vector<int64_t> idx_;
  int64_t n_batches_ = 0;
  int64_t consumed_ = 0;
  bool stopping_ = false;
  std::deque<Batch> ring_;
  std::mutex mu_;
  std::condition_variable ready_cv_;
  std::condition_variable space_cv_;
  std::thread worker_;
};

}  // namespace

extern "C" {

void tn_gather_rows(const uint8_t* src, const int64_t* idx, int64_t n_idx,
                    int64_t row_bytes, uint8_t* out, int n_threads) {
  gather_rows_impl(src, idx, n_idx, row_bytes, out, n_threads);
}

void* tn_prefetcher_create(const uint8_t* images, const int32_t* labels,
                           int64_t n_rows, int64_t row_bytes,
                           int64_t local_batch, int depth, int n_threads) {
  return new Prefetcher(images, labels, n_rows, row_bytes, local_batch, depth,
                        n_threads);
}

int tn_prefetcher_start_epoch(void* p, const int64_t* idx, int64_t n_idx) {
  return static_cast<Prefetcher*>(p)->start_epoch(idx, n_idx);
}

int tn_prefetcher_next(void* p, uint8_t* out_images, int32_t* out_labels) {
  return static_cast<Prefetcher*>(p)->next(out_images, out_labels);
}

void tn_prefetcher_destroy(void* p) { delete static_cast<Prefetcher*>(p); }

// -- flight-recorder surface (tpunet/obs/flightrec/) ------------------------

// Live snapshot of the op journal, oldest-first, into a caller buffer
// laid out exactly like JournalEntry (seq u64, op u32, tid u32, a i64,
// b i64 — 32 bytes packed; ctypes mirrors it in tpunet/data/native.py).
int tn_journal_read(void* out, int max_entries) {
  return journal_snapshot(static_cast<JournalEntry*>(out), max_entries);
}

// Arm the crash spill: on SIGSEGV/SIGABRT/SIGBUS, write the journal as
// text to `path`, then chain to the previously installed handler.
// Install AFTER faulthandler so the chain is journal -> Python stacks
// -> default action. Re-install is allowed — and necessary: each
// faulthandler.enable() re-registers ITS handlers over ours, so a new
// recorder install must re-arm. The captured "previous" handler is
// only adopted as the chain target when it is not this handler itself
// (a double install with no faulthandler in between must not make the
// chain loop back into us forever).
int tn_crash_install(const char* path) {
  if (!path || !path[0] ||
      std::strlen(path) >= sizeof(g_crash_path)) {
    return -1;
  }
  std::strncpy(g_crash_path, path, sizeof(g_crash_path) - 1);
  struct sigaction sa;
  std::memset(&sa, 0, sizeof(sa));
  sa.sa_sigaction = crash_handler;
  sigemptyset(&sa.sa_mask);
  // SA_ONSTACK: run on faulthandler's alternate stack when one is
  // configured, so stack-overflow SIGSEGVs still capture.
  sa.sa_flags = SA_SIGINFO | SA_ONSTACK;
  for (int i = 0; i < 3; ++i) {
    struct sigaction prev;
    if (sigaction(g_crash_sigs[i], &sa, &prev) != 0) return -1;
    const bool self =
        (prev.sa_flags & SA_SIGINFO) && prev.sa_sigaction == crash_handler;
    if (!self) g_old_sa[i] = prev;  // first install: zero-init = SIG_DFL
  }
  return 0;
}

int tn_abi_version() { return 2; }

}  // extern "C"
