#!/usr/bin/env bash
# Single-accelerator run — equivalent of the reference's run_gpu128.sh
# (--gres=gpu:1, batch 128), on one TPU chip.
set -euo pipefail
cd "$(dirname "$0")/.."
python train.py --preset single --mesh-data 1 "$@"
