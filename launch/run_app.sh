#!/usr/bin/env bash
# Serve the trained classifier (reference Gradio app, GROUP03.pdf
# pp.22-23) on 0.0.0.0:7861.
set -euo pipefail
cd "$(dirname "$0")/.."
python -m tpunet.infer.app --checkpoint-dir "${1:-checkpoints}"
