#!/usr/bin/env bash
# Serial CPU run — equivalent of the reference's serial.slurm (1 task,
# CPU only, batch 64). Forces the JAX CPU backend.
set -euo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python train.py --preset serial "$@"
