#!/usr/bin/env bash
# Distributed data-parallel run — equivalent of the reference's
# cifar10_gpu_parallel.sh (sbatch + mpirun -np 2). On a TPU VM or pod
# slice there is no mpirun: the same command runs on every worker and
# jax.distributed.initialize discovers the topology from the platform.
#
# Single TPU VM (all local chips):      ./launch/run_pod.sh
# Multi-host pod slice (e.g. v5e-32), from a workstation:
#   gcloud compute tpus tpu-vm ssh "$TPU_NAME" --zone "$ZONE" --worker=all \
#     --command "cd $REPO_DIR && ./launch/run_pod.sh"
set -euo pipefail
cd "$(dirname "$0")/.."
python train.py --preset distributed "$@"
