"""Shared CLI plumbing for the gate scripts.

check_bytes_budget.py and check_serve_budget.py present the same
command line (flag-anywhere ``--budget PATH`` plus one record path or
``-`` for stdin) and accept the same record containers (a plain JSON
file, a piped bench stdout stream whose ``#``-note or warning lines
precede the record — single-line or pretty-printed — or a driver-style
artifact wrapping the record under ``"parsed"``). They also share the
budget-entry lookup (``find_budget``). ``scripts/obs_compare.py``
shares the argv posture through ``split_flags``: unrecognized flags
and wrong positional counts are LOUD exit-2 usage errors — silently
gating the wrong file is a false pass in CI. One module so a fix to
any gate's plumbing cannot silently miss the others.
"""

from __future__ import annotations

import json
import sys
from typing import Dict, List, Optional, Sequence, Tuple, Union


def split_flags(argv: Sequence[str], value_flags: Sequence[str] = (),
                bool_flags: Sequence[str] = (),
                ) -> Union[int, Tuple[Dict[str, object], List[str]]]:
    """Flag-anywhere argv split shared by the gate CLIs.

    Returns ``(flags, positionals)`` where ``flags`` maps recognized
    flag names (without the ``--``) to their value (str) or True
    (bool flags); or an ``int`` exit code on a usage error (message
    already on stderr) — unknown flags are loud, same posture as
    ``load_record_argv``.
    """
    flags: Dict[str, object] = {}
    rest: List[str] = []
    args = list(argv)
    i = 0
    while i < len(args):
        a = args[i]
        if a in value_flags:
            if i + 1 >= len(args):
                print(f"{a} needs a value", file=sys.stderr)
                return 2
            flags[a.lstrip("-")] = args[i + 1]
            i += 2
            continue
        if a in bool_flags:
            flags[a.lstrip("-")] = True
            i += 1
            continue
        if a != "-" and a.startswith("-"):
            print(f"unrecognized arguments: {a}", file=sys.stderr)
            return 2
        rest.append(a)
        i += 1
    return flags, rest


def find_budget(budgets: Optional[Dict], device_kind: Optional[str]
                ) -> Tuple[Optional[str], Optional[Dict]]:
    """Case-insensitive device-kind substring lookup -> (key, entry);
    (None, None) when no budget entry matches this device."""
    kind = (device_kind or "").lower()
    for key, val in (budgets or {}).items():
        if key.lower() in kind:
            return key, val
    return None, None


def _parse_stream_record(raw: str) -> Dict:
    """Parse a record out of a bench stdout stream.

    A clean JSON document parses directly. Otherwise note/warning lines
    may precede or follow the record, and the record itself may be
    pretty-printed (bench_serve emits ``indent=1``, so inner lines also
    start with ``{``): scan line-start braces in order, parse each
    complete top-level document, and skip everything inside a parsed
    document's span — an inner nested dict is never a candidate. The
    last top-level document wins (a stream with several records gates
    the latest one).
    """
    try:
        return json.loads(raw)
    except json.JSONDecodeError:
        pass
    dec = json.JSONDecoder()
    last = None
    consumed_to = 0
    pos = 0
    for ln in raw.splitlines(keepends=True):
        stripped = ln.lstrip()
        start = pos + (len(ln) - len(stripped))
        pos += len(ln)
        if start < consumed_to or not stripped.startswith("{"):
            continue
        try:
            obj, end = dec.raw_decode(raw, start)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            last = obj
            consumed_to = end
    if last is None:
        raise json.JSONDecodeError("no JSON record found in stream",
                                   raw, 0)
    return last


def load_record_argv(argv, default_budget_path: str
                     ) -> Union[int, Tuple[Dict, str]]:
    """Parse the gate CLI and load its record.

    Returns ``(record, budget_path)``, or an ``int`` exit code on a
    usage error (message already printed to stderr).
    """
    budget_path = default_budget_path
    rest = list(argv)
    if "--budget" in rest:
        i = rest.index("--budget")
        if i + 1 >= len(rest):
            print("--budget needs a path", file=sys.stderr)
            return 2
        budget_path = rest[i + 1]
        del rest[i:i + 2]
    # An unrecognized flag must be a loud usage error: silently treating
    # its VALUE as the record path would gate the wrong file and exit 0
    # — a false pass in CI.
    unknown = [a for a in rest if a != "-" and a.startswith("-")]
    if unknown:
        print(f"unrecognized arguments: {' '.join(unknown)}",
              file=sys.stderr)
        return 2
    if not rest:
        print("no record path given", file=sys.stderr)
        return 2
    if len(rest) > 1:
        # Same loud posture: gating only rest[0] of a shell glob like
        # BENCH_r*.json would let a regression in the others pass.
        print(f"expected one record path, got: {' '.join(rest)}",
              file=sys.stderr)
        return 2
    path = rest[0]
    raw = sys.stdin.read() if path == "-" else open(path).read()
    record = _parse_stream_record(raw)
    # Driver-style bench artifacts wrap the record ({"parsed": {...}}).
    if "parsed" in record and isinstance(record["parsed"], dict):
        record = record["parsed"]
    return record, budget_path
