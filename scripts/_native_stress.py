#!/usr/bin/env python
"""Native-batcher stress driver — the workload the sanitizer gates run.

Invoked as a subprocess by scripts/check_sanitizers.py (and the slow
tests) with ``TPUNET_NATIVE_LIB`` pointing at a sanitizer build of
``cxx/batcher.cc`` and the matching runtime ``LD_PRELOAD``ed. Never
imports jax: the point is to hammer the C++ extension's concurrency
surface (the 256-slot lock-free journal ring, worker lifecycle,
create/stop/destroy churn) under ASan/UBSan/TSan, not to train.

Scenarios (``all`` runs every one):

- ``gather``   — concurrent ``gather_rows`` from 8 python threads
  (each fanning out 4 C threads), results checked against numpy.
- ``churn``    — create / start_epoch / consume-a-random-prefix /
  destroy cycles, including mid-epoch destroys (the stop/join path
  that tears down a worker holding batches).
- ``journal``  — N prefetchers running epochs concurrently (journal
  writers on every worker and consumer thread) while a poller thread
  live-snapshots ``tn_journal_read`` in a tight loop — the seqlock
  read/write race TSan exists to judge. Snapshot invariants checked:
  parseable ops, strictly increasing seqs.
- ``restart``  — ``start_epoch`` repeatedly on one prefetcher without
  draining (epoch-abandon stop path), plus an out-of-range reject.

Exit codes: 0 = pass, 3 = native library unavailable (a sanitizer
gate must treat that as its own failure to set up, never as a pass),
1 = assertion failure. A sanitizer abort surfaces as the sanitizer's
own exit code (check_sanitizers.py sets a distinctive one).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import threading

import numpy as np

_REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")


def _load_native():
    """Load tpunet/data/native.py by FILE PATH, not through the
    package: ``tpunet.data.__init__`` imports the augment stack and
    with it jax — which must never enter this process (the gate's
    point is to judge cxx/batcher.cc alone, and the driver must run
    on jax-less CI hosts)."""
    # Hard-block the tpunet package: native.py's OPTIONAL obs imports
    # (try/except around the flightrec registry and the journal op
    # table) must fail fast here rather than drag jax/jaxlib into the
    # sanitized process as uninstrumented noise.
    sys.modules["tpunet"] = None  # type: ignore[assignment]
    path = os.path.join(_REPO, "tpunet", "data", "native.py")
    spec = importlib.util.spec_from_file_location("_tn_native", path)
    assert spec is not None and spec.loader is not None
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


native = _load_native()

ROWS = 2048
ROW_SHAPE = (16, 4)              # 64 bytes/row
BATCH = 32


def _dataset(seed: int = 0):
    rng = np.random.default_rng(seed)
    rows = rng.integers(0, 255, size=(ROWS,) + ROW_SHAPE,
                        dtype=np.uint8)
    labels = rng.integers(0, 10, size=(ROWS,), dtype=np.int32)
    return rows, labels


def scenario_gather() -> None:
    rows, _ = _dataset(1)
    rng = np.random.default_rng(2)
    errors: list = []

    def worker(tid: int) -> None:
        try:
            local = np.random.default_rng(100 + tid)
            for _ in range(20):
                idx = local.integers(0, ROWS, size=512, dtype=np.int64)
                out = native.gather_rows(rows, idx, n_threads=4)
                if not np.array_equal(out, rows[idx]):
                    raise AssertionError("gather mismatch")
        except Exception as e:  # noqa: BLE001 — collected for the exit code
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    if errors:
        raise errors[0]
    del rng


def scenario_churn() -> None:
    rows, labels = _dataset(3)
    rng = np.random.default_rng(4)
    for i in range(24):
        pf = native.NativePrefetcher(rows, labels, BATCH, depth=3,
                                     n_threads=2)
        idx = rng.permutation(ROWS).astype(np.int64)
        consume = int(rng.integers(0, ROWS // BATCH + 1))
        for n, (x, y) in enumerate(pf.iter_epoch(idx)):
            if n == 0:
                if not np.array_equal(x, rows[idx[:BATCH]]):
                    raise AssertionError("first batch mismatch")
                if not np.array_equal(y, labels[idx[:BATCH]]):
                    raise AssertionError("first labels mismatch")
            if n + 1 >= consume:
                break                      # mid-epoch abandon
        pf.close()                         # destroy (possibly mid-flight)


def scenario_journal() -> None:
    rows, labels = _dataset(5)
    stop = threading.Event()
    errors: list = []

    def poller() -> None:
        try:
            while not stop.is_set():
                entries = native.journal_entries(256)
                seqs = [e["seq"] for e in entries]
                if seqs != sorted(seqs):
                    raise AssertionError(f"journal seqs unsorted: "
                                         f"{seqs[:8]}...")
                for e in entries:
                    if not isinstance(e["op"], str):
                        raise AssertionError("unparsed journal op")
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    def epoch_runner(seed: int) -> None:
        try:
            rng = np.random.default_rng(seed)
            pf = native.NativePrefetcher(rows, labels, BATCH, depth=2,
                                         n_threads=2)
            for _ in range(3):
                idx = rng.permutation(ROWS).astype(np.int64)
                for _batch in pf.iter_epoch(idx):
                    pass
            pf.close()
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    pollers = [threading.Thread(target=poller) for _ in range(2)]
    runners = [threading.Thread(target=epoch_runner, args=(10 + i,))
               for i in range(4)]
    for t in pollers + runners:
        t.start()
    for t in runners:
        t.join()
    stop.set()
    for t in pollers:
        t.join()
    if errors:
        raise errors[0]


def scenario_restart() -> None:
    rows, labels = _dataset(6)
    rng = np.random.default_rng(7)
    pf = native.NativePrefetcher(rows, labels, BATCH, depth=4,
                                 n_threads=2)
    for _ in range(10):
        idx = rng.permutation(ROWS).astype(np.int64)
        it = pf.iter_epoch(idx)
        next(it)                 # one batch, then abandon the epoch
    bad = np.array([0, 1, ROWS + 7], dtype=np.int64)
    try:
        list(pf.iter_epoch(bad))
    except IndexError:
        pass
    else:
        raise AssertionError("out-of-range epoch was not rejected")
    full = rng.permutation(ROWS).astype(np.int64)
    n = sum(1 for _ in pf.iter_epoch(full))
    if n != ROWS // BATCH:
        raise AssertionError(f"expected {ROWS // BATCH} batches, got {n}")
    pf.close()


SCENARIOS = {"gather": scenario_gather, "churn": scenario_churn,
             "journal": scenario_journal, "restart": scenario_restart}


def main(argv) -> int:
    names = argv[1:] or ["all"]
    if names == ["all"]:
        names = list(SCENARIOS)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenario(s): {unknown}; have "
              f"{list(SCENARIOS)} or 'all'", file=sys.stderr)
        return 2
    if not native.available():
        lib = os.environ.get("TPUNET_NATIVE_LIB") or "default build"
        print(f"native stress: library unavailable ({lib})",
              file=sys.stderr)
        return 3
    for name in names:
        SCENARIOS[name]()
        print(f"native stress: {name} OK", flush=True)
    print("native stress: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
