#!/usr/bin/env python
"""Measure the checkpoint save-stall: per-epoch wall-clock of the same
synthetic training run with per-epoch full-state saves ON vs OFF.

With async checkpointing (tpunet/ckpt/orbax_io.py) the save dispatch
overlaps the next epoch's compute, so the ON-vs-OFF delta bounds the
stall the step loop actually pays (device->host snapshot + any drain of
the previous write). Writes runs/ckpt-async/STALL.json.

Usage: python scripts/bench_ckpt_stall.py [--epochs N] [--out DIR]
(CPU-friendly; run under the virtual device mesh for the sharded path.)
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run(save_last: bool, epochs: int):
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.train.loop import Trainer

    with tempfile.TemporaryDirectory() as d:
        cfg = TrainConfig(
            epochs=epochs,
            data=DataConfig(dataset="synthetic", image_size=32,
                            batch_size=32),
            model=ModelConfig(width_mult=0.5, dtype="float32"),
            optim=OptimConfig(learning_rate=1e-3),
            mesh=MeshConfig(),
            checkpoint=CheckpointConfig(directory=d, save_best=False,
                                        save_last=save_last),
        )
        tr = Trainer(cfg, dataset=synthetic_cifar10(n_train=512,
                                                    n_test=32))
        times, dispatch = [], []
        try:
            tr.train_one_epoch(0)            # compile warmup
            for e in range(1, epochs + 1):
                t0 = time.perf_counter()
                tr.train_one_epoch(e)
                if save_last:
                    t1 = time.perf_counter()
                    tr.ckpt.save_state(e, tr._payload())
                    dispatch.append(time.perf_counter() - t1)
                times.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            tr.ckpt.wait()
            drain = time.perf_counter() - t0
        finally:
            tr.close()
        return times, dispatch, drain


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "runs", "ckpt-async"))
    args = ap.parse_args()

    t_off, _, _ = run(False, args.epochs)
    t_on, dispatch, drain = run(True, args.epochs)
    mean = lambda xs: sum(xs) / len(xs)
    # dispatch_seconds is what the step loop actually pays per save
    # (the on-device snapshot + worker handoff — the TPU-relevant
    # stall); the epoch delta additionally includes this CPU harness's
    # core CONTENTION with the background writer (training and the
    # orbax serializer share the same 8 host cores here, a cost a TPU
    # chip does not pay). Epoch 1 carries the one-time manager
    # initialization; the pre-async baseline measured ~13s first
    # dispatch / ~1.0s steady BLOCKING per save at this exact shape.
    rec = {
        "epochs": args.epochs,
        "epoch_seconds_no_save": [round(t, 4) for t in t_off],
        "epoch_seconds_with_save": [round(t, 4) for t in t_on],
        "dispatch_seconds": [round(t, 4) for t in dispatch],
        "mean_dispatch": round(mean(dispatch[1:]), 4),
        "pre_async_dispatch_first_and_steady": [12.975, 1.0],
        "first_save_epoch_seconds": round(t_on[0], 4),
        "mean_no_save": round(mean(t_off[1:]), 4),
        "mean_with_save": round(mean(t_on[1:]), 4),
        "epoch_delta_incl_cpu_contention": round(
            mean(t_on[1:]) - mean(t_off[1:]), 4),
        "final_drain_seconds": round(drain, 4),
        "note": "fully-async saves (tpunet/ckpt/orbax_io.py): the "
                "step loop pays dispatch_seconds (on-device snapshot "
                "+ worker handoff; measured 0.24-0.47s when the "
                "writer keeps up, vs ~1.0s blocking + 13s first-save "
                "before async). On a 1-core host the background "
                "writer COMPETES with the step loop, so when epochs "
                "are shorter than the write the >1-outstanding "
                "back-pressure (by design, bounding snapshot HBM) "
                "surfaces as multi-second dispatch stalls - the "
                "mean_dispatch here includes them; with a spare host "
                "core the steady figure is the honest expectation. "
                "The write residue surfaces as final_drain_seconds "
                "at wait().",
    }
    os.makedirs(args.out, exist_ok=True)
    path = os.path.join(args.out, "STALL.json")
    with open(path, "w") as f:
        json.dump(rec, f, indent=1)
    print(json.dumps(rec))


if __name__ == "__main__":
    main()
