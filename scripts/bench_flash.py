#!/usr/bin/env python
"""Flash-kernel microbenchmark: fwd and fwd+bwd per attention impl.

Reproduces (and extends) the round-1 kernel measurement — forward at
B=4, T=4096, H=8, D=64, causal, bfloat16 on one chip — now that the
causal grid is triangular (forward/dQ) with dead copies elided
elsewhere. Round-1 recorded numbers for the same shape (rectangular
grid + @pl.when skip): flash 10.7 ms fwd vs dense 25.6 ms vs blockwise
17.1 ms (tpunet/ops/flash.py module docstring).

Prints one JSON line per (impl, mode). Synchronization fetches a value
data-dependent on the result (this backend's block_until_ready can
return early on small outputs — BASELINE sync pitfall).

    python scripts/bench_flash.py [--t 4096] [--steps 20] [--seg]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def sync(x):
    # Fetch ONE element data-dependent on the result: a full-array
    # np.asarray would ship the whole tensor through the (slow) tunnel
    # and dominate the measurement; block_until_ready alone can return
    # early on this backend (BASELINE sync pitfall).
    leaf = jax.tree_util.tree_leaves(x)[0]
    return float(np.asarray(leaf.ravel()[0]))


def bench(fn, args, steps, warmup=3, reps=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn(*args)
        sync(out)
        best = min(best, time.perf_counter() - t0)
    return best / steps * 1e3  # ms


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--b", type=int, default=4)
    p.add_argument("--t", type=int, default=4096)
    p.add_argument("--h", type=int, default=8)
    p.add_argument("--d", type=int, default=64)
    p.add_argument("--steps", type=int, default=20)
    p.add_argument("--block", type=int, default=512)
    p.add_argument("--seg", action="store_true",
                   help="also bench the segmented (packed) variant")
    args = p.parse_args()

    from tpunet.ops.attention import blockwise_attention, dense_attention
    from tpunet.ops.flash import flash_attention

    rng = np.random.default_rng(0)
    shp = (args.b, args.t, args.h, args.d)
    q = jnp.asarray(rng.standard_normal(shp), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal(shp), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal(shp), jnp.bfloat16)
    # 4 packed docs per row for the segmented bench (last doc absorbs
    # the t % 4 remainder)
    seg_row = np.concatenate([
        np.full(args.t // 4, i + 1, np.int32) for i in range(3)
    ] + [np.full(args.t - 3 * (args.t // 4), 4, np.int32)])
    seg = jnp.asarray(seg_row[None].repeat(args.b, 0))

    impls = {
        "flash": lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=args.block, block_k=args.block),
        "dense": lambda q, k, v: dense_attention(q, k, v, causal=True),
        "blockwise": lambda q, k, v: blockwise_attention(
            q, k, v, causal=True, block_size=args.block),
    }
    if args.seg:
        impls["flash+seg"] = lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=args.block, block_k=args.block,
            segment_ids=(seg, seg))

    meta = {"b": args.b, "t": args.t, "h": args.h, "d": args.d,
            "dtype": "bfloat16", "causal": True,
            "platform": jax.devices()[0].platform,
            "device_kind": jax.devices()[0].device_kind}
    for name, f in impls.items():
        fwd = jax.jit(f)
        ms_f = bench(fwd, (q, k, v), args.steps)
        loss = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(f(q, k, v).astype(jnp.float32) ** 2),
            argnums=(0, 1, 2)))
        ms_b = bench(loss, (q, k, v), args.steps)
        print(json.dumps({"impl": name, "fwd_ms": round(ms_f, 3),
                          "fwd_bwd_ms": round(ms_b, 3), **meta}),
              flush=True)


if __name__ == "__main__":
    main()
