#!/usr/bin/env python
"""Long-context LM training throughput (tokens/sec) per attention impl.

Measures the FULL jitted train step (forward + backward + Adam) of the
decoder-only LM family at a long sequence length, comparing the
attention cores (dense / blockwise / flash). Not driver-run (bench.py
stays the reference-workload benchmark); this is the long-context perf
evidence for the attention stack.

    python scripts/bench_lm.py [--seq-len 2048] [--batch 8] [--depth 4]

Synchronization: fetch a parameter element that is data-dependent on
the last step's update (jax.block_until_ready on a small output can
return before chained computation finishes on this platform — see
bench.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def analytic_train_flops(b: int, t: int, c: int, depth: int,
                         mlp_ratio: float, vocab: int) -> float:
    """Standard analytic model-FLOPs for one causal-LM train step
    (PaLM-style MFU accounting: matmul FLOPs only, backward = 2x
    forward, causal attention at half the full-score cost). Used for
    MFU instead of XLA cost_analysis because the Pallas flash kernel
    is a custom call whose FLOPs XLA does not count — and analytic
    model-FLOPs is the honest MFU numerator anyway (rematerialized
    recompute must not inflate utilization)."""
    per_block = (8 + 4 * mlp_ratio) * b * t * c * c   # qkv+out+mlp
    attn = 2 * b * t * t * c                          # scores+values, causal
    head = 2 * b * t * c * vocab                      # tied logits
    fwd = depth * (per_block + attn) + head
    return 3.0 * fwd                                  # fwd + 2x bwd


_PEAK_FLOPS = (       # bf16 peak per chip (same table as bench.py)
    ("v5 lite", 197e12), ("v5e", 197e12), ("v5p", 459e12),
    ("v6", 918e12), ("trillium", 918e12), ("v4", 275e12), ("v3", 123e12),
)


def peak_flops_per_chip() -> float:
    kind = jax.devices()[0].device_kind.lower()
    return next((v for k, v in _PEAK_FLOPS if k in kind), 0.0)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--attention-block", type=int, default=None,
                   help="flash kernel block_q/block_k override")
    p.add_argument("--attention", nargs="+",
                   default=["dense", "blockwise", "flash"])
    p.add_argument("--model", choices=("lm", "lm_pp"), default="lm",
                   help="lm_pp benches the PIPELINED formulation "
                        "(stacked-scan blocks; dense attention only) — "
                        "on one chip this measures the pipe=1 overhead "
                        "of the formulation itself")
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (the long-context "
                        "recipe: without it, backward residuals are "
                        "O(T^2) for every attention impl)")
    args = p.parse_args()

    from tpunet.config import ModelConfig, OptimConfig
    from tpunet.models import create_model, init_variables
    from tpunet.train.state import TrainState, make_optimizer
    from tpunet.train.steps import make_lm_train_step
    from tpunet.utils.prng import step_key

    rng = np.random.default_rng(0)
    toks = rng.integers(0, args.vocab, (args.batch, args.seq_len))
    toks = jax.numpy.asarray(toks, jax.numpy.int32)
    if jax.default_backend() != "tpu" and "flash" in args.attention:
        print("# WARNING: not on TPU — 'flash' falls back to dense "
              "attention, so its column would just re-measure dense; "
              "skipping it", file=sys.stderr, flush=True)
        args.attention = [a for a in args.attention if a != "flash"]

    if args.model == "lm_pp" and set(args.attention) - {"dense", "flash",
                                                        "auto"}:
        args.attention = ["auto"]      # pipelined blocks: dense/flash only

    results, mfus = {}, {}
    flops_step = analytic_train_flops(args.batch, args.seq_len,
                                      args.hidden, args.depth, 4.0,
                                      args.vocab)
    peak = peak_flops_per_chip()
    for attn in args.attention:
        mcfg = ModelConfig(
            name=args.model, vit_hidden=args.hidden,
            vit_depth=args.depth,
            vit_heads=args.heads, vocab_size=args.vocab,
            max_seq_len=args.seq_len, dropout_rate=0.0, attention=attn,
            remat=args.remat and args.model == "lm",
            **({"attention_block": args.attention_block}
               if args.attention_block else {}))
        model = create_model(mcfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=args.seq_len)
        state = TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            batch_stats={}, ema_params={}, ema_batch_stats={},
            tx=make_optimizer(OptimConfig(), 100, 1))
        step = jax.jit(make_lm_train_step(OptimConfig(), mcfg),
                       donate_argnums=0)

        def sync(state):
            jax.block_until_ready(state)
            leaf = jax.tree_util.tree_leaves(state.params)[0]
            return float(np.asarray(leaf.ravel()[0]))

        print(f"# {attn}: compiling...", file=sys.stderr, flush=True)
        for i in range(3):
            state, m = step(state, toks, None, step_key(0, i))
        sync(state)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            for i in range(args.steps):
                state, m = step(state, toks, None, step_key(0, i + 3))
            sync(state)
            best = min(best, (time.perf_counter() - t0) / args.steps)
        tok_s = args.batch * args.seq_len / best
        results[attn] = round(tok_s, 1)
        mfu = (flops_step / best / peak) if peak else None
        if mfu is not None:
            mfus[attn] = round(mfu, 4)
        # Cross-check only: XLA's count misses Pallas custom-call FLOPs
        # (flash) and counts remat recompute (remat), so the analytic
        # number above is the MFU numerator.
        xla_flops = 0.0
        try:
            ca = step.lower(state, toks, None,
                            step_key(0, 0)).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            xla_flops = float(ca.get("flops", 0.0))
        except Exception:
            pass
        print(f"# {attn}: {best * 1e3:.1f} ms/step, "
              f"{tok_s:,.0f} tok/s"
              + (f", MFU {mfu:.3f} (analytic {flops_step / 1e9:.1f} "
                 f"GFLOP/step; xla counts {xla_flops / 1e9:.1f})"
                 if mfu is not None else ""),
              file=sys.stderr, flush=True)

    print(json.dumps({
        "metric": "lm_train_tokens_per_sec",
        "config": {"model": args.model, "batch": args.batch,
                   "seq_len": args.seq_len,
                   "hidden": args.hidden, "depth": args.depth,
                   "heads": args.heads, "remat": args.remat,
                   "attention_block": args.attention_block,
                   "platform": jax.devices()[0].platform},
        "value": results,
        "unit": "tok/s",
        "analytic_flops_per_step": flops_step,
        "peak_flops_per_chip": peak,
        "mfu": mfus,
    }))


if __name__ == "__main__":
    main()
