#!/usr/bin/env python
"""Long-context LM training throughput (tokens/sec) per attention impl.

Measures the FULL jitted train step (forward + backward + Adam) of the
decoder-only LM family at a long sequence length, comparing the
attention cores (dense / blockwise / flash). Not driver-run (bench.py
stays the reference-workload benchmark); this is the long-context perf
evidence for the attention stack.

    python scripts/bench_lm.py [--seq-len 2048] [--batch 8] [--depth 4]

Synchronization: fetch a parameter element that is data-dependent on
the last step's update (jax.block_until_ready on a small output can
return before chained computation finishes on this platform — see
bench.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import jax
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

if not os.environ.get("JAX_COMPILATION_CACHE_DIR"):
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--hidden", type=int, default=512)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--heads", type=int, default=8)
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--attention", nargs="+",
                   default=["dense", "blockwise", "flash"])
    p.add_argument("--model", choices=("lm", "lm_pp"), default="lm",
                   help="lm_pp benches the PIPELINED formulation "
                        "(stacked-scan blocks; dense attention only) — "
                        "on one chip this measures the pipe=1 overhead "
                        "of the formulation itself")
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--reps", type=int, default=2)
    p.add_argument("--remat", action="store_true",
                   help="jax.checkpoint each block (the long-context "
                        "recipe: without it, backward residuals are "
                        "O(T^2) for every attention impl)")
    args = p.parse_args()

    from tpunet.config import ModelConfig, OptimConfig
    from tpunet.models import create_model, init_variables
    from tpunet.train.state import TrainState, make_optimizer
    from tpunet.train.steps import make_lm_train_step
    from tpunet.utils.prng import step_key

    rng = np.random.default_rng(0)
    toks = rng.integers(0, args.vocab, (args.batch, args.seq_len))
    toks = jax.numpy.asarray(toks, jax.numpy.int32)
    if jax.default_backend() != "tpu" and "flash" in args.attention:
        print("# WARNING: not on TPU — 'flash' falls back to dense "
              "attention, so its column would just re-measure dense; "
              "skipping it", file=sys.stderr, flush=True)
        args.attention = [a for a in args.attention if a != "flash"]

    if args.model == "lm_pp" and set(args.attention) - {"dense", "flash",
                                                        "auto"}:
        args.attention = ["auto"]      # pipelined blocks: dense/flash only

    results = {}
    for attn in args.attention:
        mcfg = ModelConfig(
            name=args.model, vit_hidden=args.hidden,
            vit_depth=args.depth,
            vit_heads=args.heads, vocab_size=args.vocab,
            max_seq_len=args.seq_len, dropout_rate=0.0, attention=attn,
            remat=args.remat and args.model == "lm")
        model = create_model(mcfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=args.seq_len)
        state = TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            batch_stats={}, ema_params={}, ema_batch_stats={},
            tx=make_optimizer(OptimConfig(), 100, 1))
        step = jax.jit(make_lm_train_step(OptimConfig(), mcfg),
                       donate_argnums=0)

        def sync(state):
            jax.block_until_ready(state)
            leaf = jax.tree_util.tree_leaves(state.params)[0]
            return float(np.asarray(leaf.ravel()[0]))

        print(f"# {attn}: compiling...", file=sys.stderr, flush=True)
        for i in range(3):
            state, m = step(state, toks, None, step_key(0, i))
        sync(state)
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.perf_counter()
            for i in range(args.steps):
                state, m = step(state, toks, None, step_key(0, i + 3))
            sync(state)
            best = min(best, (time.perf_counter() - t0) / args.steps)
        tok_s = args.batch * args.seq_len / best
        results[attn] = round(tok_s, 1)
        print(f"# {attn}: {best * 1e3:.1f} ms/step, "
              f"{tok_s:,.0f} tok/s", file=sys.stderr, flush=True)

    print(json.dumps({
        "metric": "lm_train_tokens_per_sec",
        "config": {"model": args.model, "batch": args.batch,
                   "seq_len": args.seq_len,
                   "hidden": args.hidden, "depth": args.depth,
                   "heads": args.heads, "remat": args.remat,
                   "platform": jax.devices()[0].platform},
        "value": results,
        "unit": "tok/s",
    }))


if __name__ == "__main__":
    main()
