#!/usr/bin/env python
"""Closed-loop load generator for the serving engine.

Drives the in-process ``tpunet.serve.Engine`` (no HTTP overhead in the
measurement; ``--http`` targets a running server instead) with N
concurrent closed-loop clients — each client keeps exactly one request
in flight, so offered load is the concurrency level — and reports
total throughput (tok/s), TTFT / end-to-end latency percentiles, and
queue depth per concurrency level, plus the sequential
one-request-at-a-time baseline the continuous-batching speedup is
measured against (the ISSUE acceptance bar: >= 2x at concurrency 4).

    python scripts/bench_serve.py                 # synthetic weights
    python scripts/bench_serve.py --checkpoint-dir ckpt --vit-hidden 192
    python scripts/bench_serve.py --http http://HOST:PORT --prompt-len 64
    python scripts/bench_serve.py --enforce-budget  # + absolute floor gate

``--enforce-budget`` checks ``tokens_per_s_per_slot`` (peak engine
tok/s over the offered-load sweep, divided by the KV slot count)
against the checked-in floor in docs/serve_budget.json — the
bytes-budget mechanism pointed at serving capacity (exit 3 on a
drop past tolerance; scripts/check_serve_budget.py is the standalone
form). The >=2x-vs-sequential RELATIVE test lives in tests/test_serve;
the absolute floor catches both paths slowing down together.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def pct(xs, q):
    if not xs:
        return None
    from tpunet.obs.registry import percentile_of_sorted
    return percentile_of_sorted(sorted(xs), q)


def ms(xs, q):
    """Percentile in milliseconds, or None on no samples — an
    all-errors run must still report its 'errors' list instead of
    crashing on round(None)."""
    p = pct(xs, q)
    return None if p is None else round(1e3 * p, 2)


def run_level(engine, concurrency, *, prompt_len, new_tokens,
              requests_per_client, vocab, seed=0):
    """Closed loop: each of ``concurrency`` clients fires
    ``requests_per_client`` requests back-to-back."""
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
               for _ in range(concurrency)]
    ttfts, e2es, depths = [], [], []
    errors = []
    done_tokens = [0] * concurrency

    def client(i):
        try:
            for _ in range(requests_per_client):
                req = engine.submit(prompts[i],
                                    max_new_tokens=new_tokens)
                req.result(timeout=600)
                ttfts.append(req.ttft_s)
                e2es.append(req.e2e_s)
                done_tokens[i] += len(req.tokens)
                depths.append(engine.queue.depth())
        except Exception as e:  # noqa: BLE001 — report, don't hang
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total_tokens = sum(done_tokens)
    return {
        "concurrency": concurrency,
        "requests": concurrency * requests_per_client,
        "errors": errors,
        "total_tokens": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p90_ms": ms(ttfts, 90),
        "ttft_p99_ms": ms(ttfts, 99),
        "e2e_p50_ms": ms(e2es, 50),
        "e2e_p99_ms": ms(e2es, 99),
        "queue_depth_mean": round(float(np.mean(depths)), 2)
        if depths else 0.0,
        "queue_depth_max": int(max(depths)) if depths else 0,
    }


def run_http_level(base, concurrency, *, prompt_len, new_tokens,
                   requests_per_client, vocab, seed=0):
    """Same closed loop against a live server's /v1/generate."""
    import urllib.request
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=prompt_len).tolist()
               for _ in range(concurrency)]
    ttfts, e2es = [], []
    tokens = [0] * concurrency
    errors = []

    def client(i):
        for _ in range(requests_per_client):
            body = json.dumps({"tokens": prompts[i],
                               "max_new_tokens": new_tokens}).encode()
            req = urllib.request.Request(
                base + "/v1/generate", body,
                {"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=600) as r:
                    out = json.loads(r.read())
                tokens[i] += len(out["tokens"])
                ttfts.append(out["ttft_ms"] / 1e3)
                e2es.append(out["e2e_ms"] / 1e3)
            except Exception as e:  # noqa: BLE001
                errors.append(f"client {i}: {type(e).__name__}: {e}")
                return

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(tokens)
    return {
        "concurrency": concurrency,
        "requests": concurrency * requests_per_client,
        "errors": errors,
        "total_tokens": total,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total / wall, 1),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p99_ms": ms(ttfts, 99),
        "e2e_p50_ms": ms(e2es, 50),
        "e2e_p99_ms": ms(e2es, 99),
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--http", default="",
                    help="bench a RUNNING server at this base URL "
                         "instead of an in-process engine")
    ap.add_argument("--checkpoint-dir", default="",
                    help="LM best checkpoint (default: random tiny "
                         "weights — throughput shape, not quality)")
    ap.add_argument("--vit-hidden", type=int, default=64)
    ap.add_argument("--vit-depth", type=int, default=2)
    ap.add_argument("--vit-heads", type=int, default=4)
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--max-seq-len", type=int, default=512)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--requests-per-client", type=int, default=2)
    ap.add_argument("--concurrency", default="1,2,4,8",
                    help="comma-separated offered-load levels")
    ap.add_argument("--out", default="",
                    help="also write the result JSON here")
    ap.add_argument("--enforce-budget", action="store_true",
                    help="exit 3 when tokens_per_s_per_slot falls below "
                         "the docs/serve_budget.json floor for this "
                         "device kind")
    args = ap.parse_args()
    levels = [int(c) for c in args.concurrency.split(",") if c]

    if args.http:
        if args.enforce_budget:
            # The floor is keyed on device kind, which a remote HTTP
            # record does not carry — refuse loudly rather than
            # letting the flag silently no-op.
            print("--enforce-budget is not supported with --http "
                  "(no device kind in the record); run the in-process "
                  "engine bench instead", file=sys.stderr)
            sys.exit(2)
        results = [run_http_level(
            args.http.rstrip("/"), c, prompt_len=args.prompt_len,
            new_tokens=args.new_tokens,
            requests_per_client=args.requests_per_client,
            vocab=args.vocab_size) for c in levels]
        out = {"mode": "http", "target": args.http, "levels": results}
        print(json.dumps(out, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return

    import jax

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables, num_params
    from tpunet.models.lm import generate
    from tpunet.serve import Engine

    model_cfg = ModelConfig(
        name="lm", vit_hidden=args.vit_hidden, vit_depth=args.vit_depth,
        vit_heads=args.vit_heads, vocab_size=args.vocab_size,
        max_seq_len=args.max_seq_len, dropout_rate=0.0, dtype="float32")
    if args.checkpoint_dir:
        from tpunet.infer.generate import load_lm
        model, variables = load_lm(model_cfg,
                                   checkpoint_dir=args.checkpoint_dir)
    else:
        model = create_model(model_cfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=16)

    # Sequential baseline: the pre-serve shape — one request at a time
    # through models.lm.generate (warmed compile).
    p = np.zeros((1, args.prompt_len), np.int32)
    generate(model, variables, p, n_new=2)
    t0 = time.perf_counter()
    n_seq = max(2, args.requests_per_client)
    for _ in range(n_seq):
        generate(model, variables, p, n_new=args.new_tokens)
    seq_wall = time.perf_counter() - t0
    seq_tps = n_seq * args.new_tokens / seq_wall

    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    cfg = ServeConfig(slots=args.slots, queue_max=max(64, 4 * args.slots),
                      prefill_buckets=(min(bucket, args.max_seq_len),),
                      emit_every_s=0.0)
    engine = Engine(model, variables, cfg).start()
    try:
        # warm prefill + decode programs outside the measurement
        engine.submit(np.zeros(args.prompt_len, np.int32),
                      max_new_tokens=2).result(timeout=600)
        results = [run_level(
            engine, c, prompt_len=args.prompt_len,
            new_tokens=args.new_tokens,
            requests_per_client=args.requests_per_client,
            vocab=args.vocab_size) for c in levels]
    finally:
        engine.stop()
    out = {
        "mode": "engine",
        "device": jax.devices()[0].device_kind,
        "model_params": num_params(variables["params"]),
        "slots": args.slots,
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "sequential_tokens_per_s": round(seq_tps, 1),
        "levels": results,
        "speedup_vs_sequential": {
            str(r["concurrency"]): round(r["tokens_per_s"] / seq_tps, 2)
            for r in results},
    }
    from check_serve_budget import tokens_per_s_per_slot
    tpss = tokens_per_s_per_slot(out)
    if tpss is not None:
        out["tokens_per_s_per_slot"] = round(tpss, 1)
    print(json.dumps(out, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    if args.enforce_budget:
        from check_serve_budget import check_record, load_budget
        ok, msgs = check_record(out, load_budget())
        for m in msgs:
            print(f"# {m}", file=sys.stderr, flush=True)
        if not ok:
            sys.exit(3)


if __name__ == "__main__":
    main()
