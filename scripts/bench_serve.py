#!/usr/bin/env python
"""Closed-loop load generator for the serving engine.

Drives the in-process ``tpunet.serve.Engine`` (no HTTP overhead in the
measurement; ``--http`` targets a running server instead) with N
concurrent closed-loop clients — each client keeps exactly one request
in flight, so offered load is the concurrency level — and reports
total throughput (tok/s), TTFT / end-to-end latency percentiles, and
queue depth per concurrency level, plus the sequential
one-request-at-a-time baseline the continuous-batching speedup is
measured against (the ISSUE acceptance bar: >= 2x at concurrency 4).

    python scripts/bench_serve.py                 # synthetic weights
    python scripts/bench_serve.py --checkpoint-dir ckpt --vit-hidden 192
    python scripts/bench_serve.py --http http://HOST:PORT --prompt-len 64
    python scripts/bench_serve.py --enforce-budget  # + absolute floor gate

``--enforce-budget`` checks ``tokens_per_s_per_slot`` (peak engine
tok/s over the offered-load sweep, divided by the KV slot count)
against the checked-in floor in docs/serve_budget.json — the
bytes-budget mechanism pointed at serving capacity (exit 3 on a
drop past tolerance; scripts/check_serve_budget.py is the standalone
form). The >=2x-vs-sequential RELATIVE test lives in tests/test_serve;
the absolute floor catches both paths slowing down together.

``--prefix-frac`` switches to the shared-prompt workload that
measures the prefix KV cache: that fraction of requests share the
same ``--prefix-tokens``-long page-aligned prompt prefix (the system-
prompt traffic shape), and the SAME workload runs cache-on and
cache-off. The record reports ``prefill_tokens_per_request`` for both
(the cache-on number must drop toward the suffix length),
``prefix_hit_rate`` from the engine's own counters, and shared-prefix
TTFT percentiles — ``shared_prefix_ttft_p99_ms`` is the budget-gated
ceiling:

    python scripts/bench_serve.py --prefix-frac 0.75 --prompt-len 64
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def pct(xs, q):
    if not xs:
        return None
    from tpunet.obs.registry import percentile_of_sorted
    return percentile_of_sorted(sorted(xs), q)


def ms(xs, q):
    """Percentile in milliseconds, or None on no samples — an
    all-errors run must still report its 'errors' list instead of
    crashing on round(None)."""
    p = pct(xs, q)
    return None if p is None else round(1e3 * p, 2)


def _p99_exemplar(samples):
    """trace_id of the request at the p99 e2e rank — the slow-request
    lookup key for the joined timeline (scripts/obs_timeline.py)."""
    if not samples:
        return None
    ordered = sorted(samples)
    return ordered[min(len(ordered) - 1, int(0.99 * len(ordered)))][1]


def run_level(engine, concurrency, *, prompt_len, new_tokens,
              requests_per_client, vocab, seed=0):
    """Closed loop: each of ``concurrency`` clients fires
    ``requests_per_client`` requests back-to-back."""
    from tpunet.obs import tracing
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=prompt_len).astype(np.int32)
               for _ in range(concurrency)]
    ttfts, e2es, depths = [], [], []
    queues, prefills = [], []
    exemplars = []  # (e2e_s, trace_id) — p99 slow-request lookup key
    errors = []
    done_tokens = [0] * concurrency

    def client(i):
        try:
            for _ in range(requests_per_client):
                tid = tracing.mint_trace_id()
                req = engine.submit(prompts[i],
                                    max_new_tokens=new_tokens,
                                    trace_id=tid)
                req.result(timeout=600)
                ttfts.append(req.ttft_s)
                e2es.append(req.e2e_s)
                if req.queue_s is not None:
                    queues.append(req.queue_s)
                if req.prefill_s is not None:
                    prefills.append(req.prefill_s)
                if req.e2e_s is not None:
                    exemplars.append((req.e2e_s, tid))
                done_tokens[i] += len(req.tokens)
                depths.append(engine.queue.depth())
        except Exception as e:  # noqa: BLE001 — report, don't hang
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total_tokens = sum(done_tokens)
    return {
        "concurrency": concurrency,
        "requests": concurrency * requests_per_client,
        "errors": errors,
        "total_tokens": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p90_ms": ms(ttfts, 90),
        "ttft_p99_ms": ms(ttfts, 99),
        # TTFT decomposition from the scheduler's phase stamps:
        # queue-wait (submit -> prefill launch) vs prefill compute.
        "ttft_queue_p50_ms": ms(queues, 50),
        "ttft_queue_p99_ms": ms(queues, 99),
        "ttft_prefill_p50_ms": ms(prefills, 50),
        "ttft_prefill_p99_ms": ms(prefills, 99),
        "e2e_p50_ms": ms(e2es, 50),
        "e2e_p99_ms": ms(e2es, 99),
        "p99_exemplar_trace_id": _p99_exemplar(exemplars),
        "queue_depth_mean": round(float(np.mean(depths)), 2)
        if depths else 0.0,
        "queue_depth_max": int(max(depths)) if depths else 0,
    }


def run_http_level(base, concurrency, *, prompt_len, new_tokens,
                   requests_per_client, vocab, seed=0):
    """Same closed loop against a live server's /v1/generate."""
    import urllib.request
    rng = np.random.default_rng(seed)
    prompts = [rng.integers(0, vocab, size=prompt_len).tolist()
               for _ in range(concurrency)]
    ttfts, e2es = [], []
    tokens = [0] * concurrency
    errors = []

    def client(i):
        for _ in range(requests_per_client):
            body = json.dumps({"tokens": prompts[i],
                               "max_new_tokens": new_tokens}).encode()
            req = urllib.request.Request(
                base + "/v1/generate", body,
                {"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=600) as r:
                    out = json.loads(r.read())
                tokens[i] += len(out["tokens"])
                ttfts.append(out["ttft_ms"] / 1e3)
                e2es.append(out["e2e_ms"] / 1e3)
            except Exception as e:  # noqa: BLE001
                errors.append(f"client {i}: {type(e).__name__}: {e}")
                return

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    total = sum(tokens)
    return {
        "concurrency": concurrency,
        "requests": concurrency * requests_per_client,
        "errors": errors,
        "total_tokens": total,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total / wall, 1),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p99_ms": ms(ttfts, 99),
        "e2e_p50_ms": ms(e2es, 50),
        "e2e_p99_ms": ms(e2es, 99),
    }


def run_cold_start_child(args) -> None:
    """Hidden mode (--_cold-start-child): build the engine in THIS
    fresh process and print cold_start_to_first_token_s — the wall
    time from engine construction (weights already initialized; that
    cost is variant-independent) to the first generated token. The
    parent controls what is warm: JAX_COMPILATION_CACHE_DIR in the
    environment, the AOT store via --_aot-dir."""
    import jax

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables
    from tpunet.serve.engine import Engine, build_aot_store
    from tpunet.utils.cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    model_cfg = ModelConfig(
        name="lm", vit_hidden=args.vit_hidden, vit_depth=args.vit_depth,
        vit_heads=args.vit_heads, vocab_size=args.vocab_size,
        max_seq_len=args.max_seq_len, dropout_rate=0.0, dtype="float32")
    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    cfg = ServeConfig(slots=args.slots, queue_max=64,
                      prefill_buckets=(min(bucket, args.max_seq_len),),
                      emit_every_s=0.0)
    model = create_model(model_cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=16)
    store = None
    if args._aot_dir:
        store = build_aot_store(args._aot_dir, model_cfg, cfg)
    t0 = time.perf_counter()
    engine = Engine(model, variables, cfg, aot_store=store).start()
    try:
        req = engine.submit(np.zeros(args.prompt_len, np.int32),
                            max_new_tokens=1)
        req.result(timeout=600)
        cold_start = req.first_token_t - t0
    finally:
        engine.stop()
    print(json.dumps({
        "cold_start_to_first_token_s": round(cold_start, 3),
        "aot_status": engine.aot_status,
        "device": jax.devices()[0].device_kind}))


def _cold_start_variant(argv_base, *, cache_dir, aot_dir=""):
    """One fresh-process boot measurement."""
    import subprocess
    env = dict(os.environ, JAX_COMPILATION_CACHE_DIR=cache_dir)
    argv = argv_base + ["--_cold-start-child"]
    if aot_dir:
        argv += ["--_aot-dir", aot_dir]
    out = subprocess.run(argv, env=env, capture_output=True, text=True,
                         timeout=900)
    if out.returncode != 0:
        raise RuntimeError(f"cold-start child failed (rc "
                           f"{out.returncode}):\n{out.stderr[-2000:]}")
    return json.loads(out.stdout.strip().splitlines()[-1])


def run_cold_start_bench(args) -> dict:
    """Measure cold_start_to_first_token_s for the three boot modes
    (fresh process each — an in-process A/B would hit jax's live jit
    caches):

    - ``cold``       — empty persistent compilation cache, no AOT;
    - ``persistent`` — the compilation cache the cold boot populated;
    - ``aot``        — deserialized AOT executables against an EMPTY
      compilation cache, so only the AOT store contributes.

    The acceptance bar (and the serve-budget gate): aot < cold, and
    aot under the checked-in ceiling."""
    import tempfile

    base = [sys.executable, os.path.abspath(__file__),
            "--vit-hidden", str(args.vit_hidden),
            "--vit-depth", str(args.vit_depth),
            "--vit-heads", str(args.vit_heads),
            "--vocab-size", str(args.vocab_size),
            "--max-seq-len", str(args.max_seq_len),
            "--slots", str(args.slots),
            "--prompt-len", str(args.prompt_len)]
    with tempfile.TemporaryDirectory() as tmp:
        cache1 = os.path.join(tmp, "cache1")
        cache2 = os.path.join(tmp, "cache2")
        aot = os.path.join(tmp, "aot")
        os.makedirs(cache1)
        os.makedirs(cache2)
        cold = _cold_start_variant(base, cache_dir=cache1)
        persistent = _cold_start_variant(base, cache_dir=cache1)
        # Prepare the AOT store (timing discarded), then boot from it
        # with a cache dir that has never seen these programs.
        _cold_start_variant(base, cache_dir=cache1, aot_dir=aot)
        aot_boot = _cold_start_variant(base, cache_dir=cache2,
                                       aot_dir=aot)
    assert all(v == "loaded" for v in aot_boot["aot_status"].values()), \
        f"AOT boot did not deserialize: {aot_boot['aot_status']}"
    record = {
        "mode": "cold_start",
        "device": cold["device"],
        "slots": args.slots,
        "prompt_len": args.prompt_len,
        "cold_start_to_first_token_s": {
            "cold": cold["cold_start_to_first_token_s"],
            "persistent": persistent["cold_start_to_first_token_s"],
            "aot": aot_boot["cold_start_to_first_token_s"],
        },
    }
    if record["cold_start_to_first_token_s"]["aot"] > 0:
        record["aot_speedup_vs_cold"] = round(
            record["cold_start_to_first_token_s"]["cold"]
            / record["cold_start_to_first_token_s"]["aot"], 2)
    return record


def _lever_overrides(args) -> dict:
    """ServeConfig overrides from the paged-KV / sampling lever flags
    (None = keep the config default, so the default bench measures the
    shipping configuration)."""
    over = {"kv_pages": args.kv_pages,
            "kv_page_tokens": args.kv_page_tokens,
            "kv_dtype": args.kv_dtype}
    if args.paged_kv is not None:
        over["paged_kv"] = args.paged_kv
    if args.device_sampling is not None:
        over["device_sampling"] = args.device_sampling
    return over


def run_slots_sweep(args, model, variables) -> dict:
    """Fixed-KV-pool-bytes capacity sweep (the paging acceptance
    measurement): take the DENSE pool's byte footprint at
    ``--slots`` slots as the budget, size a paged (+ optionally int8)
    pool to AT MOST those bytes, then drive ascending offered
    concurrency through it and report tokens/s + the admitted-slot
    high-water mark per level. ``slot_capacity`` is the analytic
    concurrent-request capacity at the sweep workload's length
    (prompt + new tokens); the engine's slot count is capped at
    4x the dense baseline so the jitted batch stays benchable."""
    from tpunet.config import ServeConfig
    from tpunet.serve import Engine

    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    bucket = min(bucket, args.max_seq_len)
    dense_cfg = ServeConfig(slots=args.slots, queue_max=1024,
                            prefill_buckets=(bucket,), emit_every_s=0.0,
                            paged_kv=False, device_sampling=False)
    dense_engine = Engine(model, variables, dense_cfg)
    pool_budget = dense_engine.kv_pool_bytes()
    dense_bytes_per_slot = pool_budget / args.slots
    del dense_engine

    # Probe the paged per-page byte cost (pool bytes are linear in
    # pages+garbage), then size the pool to the dense budget.
    pt = args.kv_page_tokens
    kv_dtype = args.kv_dtype
    probe = Engine(model, variables, ServeConfig(
        slots=1, queue_max=1, prefill_buckets=(bucket,),
        emit_every_s=0.0, kv_pages=1, kv_page_tokens=pt,
        kv_dtype=kv_dtype))
    bytes_per_page = probe.kv_pool_bytes() / 2     # 1 usable + garbage
    del probe
    usable = max(1, int(pool_budget // bytes_per_page) - 1)
    req_tokens = args.prompt_len + args.new_tokens
    pages_per_req = -(-req_tokens // pt)
    slot_capacity = max(1, usable // pages_per_req)
    sweep_slots = min(slot_capacity, 4 * args.slots)
    sampling = (args.device_sampling if args.device_sampling is not None
                else ServeConfig.device_sampling)
    cfg = ServeConfig(slots=sweep_slots, queue_max=4096,
                      prefill_buckets=(bucket,), emit_every_s=0.0,
                      kv_pages=usable, kv_page_tokens=pt,
                      kv_dtype=kv_dtype, device_sampling=sampling)
    engine = Engine(model, variables, cfg).start()
    levels = sorted({max(1, sweep_slots // 4), sweep_slots // 2,
                     sweep_slots} - {0})
    rows = []
    try:
        engine.submit(np.zeros(args.prompt_len, np.int32),
                      max_new_tokens=2).result(timeout=600)
        for c in levels:
            engine.peak_active_slots = 0
            r = run_level(engine, c, prompt_len=args.prompt_len,
                          new_tokens=args.new_tokens,
                          requests_per_client=args.requests_per_client,
                          vocab=args.vocab_size)
            r["admitted_slots_peak"] = engine.peak_active_slots
            rows.append(r)
        paged_pool = engine.kv_pool_bytes()
        bytes_per_token = engine.kv_bytes_per_token()
    finally:
        engine.stop()
    import jax
    peak = max((r["admitted_slots_peak"] for r in rows), default=0)
    return {
        "mode": "slots_sweep",
        "device": jax.devices()[0].device_kind,
        "device_sampling": sampling,
        "kv_dtype": kv_dtype,
        "kv_page_tokens": pt,
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "fixed_pool_bytes": int(pool_budget),
        "dense_slots": args.slots,
        "dense_kv_hbm_bytes_per_slot": round(dense_bytes_per_slot, 1),
        "paged_pool_bytes": int(paged_pool),
        "paged_kv_pages": usable,
        "kv_bytes_per_token": round(bytes_per_token, 2),
        "slot_capacity": slot_capacity,
        "slot_capacity_vs_dense": round(slot_capacity / args.slots, 2),
        "admitted_slots_peak": peak,
        "admitted_vs_dense": round(peak / args.slots, 2),
        "levels": rows,
    }


def _prefix_workload(concurrency, *, prompt_len, shared_len,
                     prefix_frac, requests_per_client, vocab, seed=0):
    """Per-client request plans for the shared-prompt workload —
    built ONCE so the cache-on and cache-off engines serve the exact
    same token streams. Each plan entry is (is_shared, prompt):
    shared requests start with the common ``shared_len`` prefix and
    differ only in the suffix."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, vocab, size=shared_len).astype(np.int32)
    plans = []
    for i in range(concurrency):
        crng = np.random.default_rng(seed + 1000 + i)
        plan = []
        for _ in range(requests_per_client):
            if crng.random() < prefix_frac:
                sfx = crng.integers(
                    0, vocab,
                    size=prompt_len - shared_len).astype(np.int32)
                plan.append((True, np.concatenate([shared, sfx])))
            else:
                plan.append((False, crng.integers(
                    0, vocab, size=prompt_len).astype(np.int32)))
        plans.append(plan)
    return shared, plans


def _run_prefix_variant(engine, shared, plans, *, new_tokens):
    """Drive one engine through the shared-prompt plans (closed loop,
    one client per plan) and report the prefix-relevant numbers from
    the engine's OWN counters — the bench reads the same instruments
    operators dashboard, not a shadow accounting."""
    # Warm: compile programs and (when the cache is on) adopt the
    # shared prefix, so the measurement sees steady-state hits rather
    # than the one-time cold miss.
    warm = np.concatenate([shared, np.zeros(1, np.int32)])
    engine.submit(warm, max_new_tokens=2).result(timeout=600)
    base = engine.registry.snapshot()
    ttfts, shared_ttfts, e2es = [], [], []
    errors = []
    done_tokens = [0] * len(plans)

    def client(i):
        try:
            for is_shared, p in plans[i]:
                req = engine.submit(p, max_new_tokens=new_tokens)
                req.result(timeout=600)
                ttfts.append(req.ttft_s)
                if is_shared:
                    shared_ttfts.append(req.ttft_s)
                e2es.append(req.e2e_s)
                done_tokens[i] += len(req.tokens)
        except Exception as e:  # noqa: BLE001 — report, don't hang
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(plans))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = engine.registry.snapshot()
    n_requests = sum(len(p) for p in plans)
    prefill = (snap.get("serve_prefill_tokens_total", 0)
               - base.get("serve_prefill_tokens_total", 0))
    lookups = (snap.get("serve_prefix_lookups_total", 0)
               - base.get("serve_prefix_lookups_total", 0))
    hits = (snap.get("serve_prefix_hits_total", 0)
            - base.get("serve_prefix_hits_total", 0))
    hit_tokens = (snap.get("serve_prefix_hit_tokens_total", 0)
                  - base.get("serve_prefix_hit_tokens_total", 0))
    total_tokens = sum(done_tokens)
    return {
        "requests": n_requests,
        "errors": errors,
        "total_tokens": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall else 0.0,
        "prefill_tokens_per_request": round(prefill / n_requests, 2)
        if n_requests else None,
        "prefix_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        "prefix_hit_tokens": int(hit_tokens),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p99_ms": ms(ttfts, 99),
        "shared_ttft_p50_ms": ms(shared_ttfts, 50),
        "shared_ttft_p99_ms": ms(shared_ttfts, 99),
        "e2e_p99_ms": ms(e2es, 99),
    }


def run_prefix_bench(args, model, variables, concurrency) -> dict:
    """Shared-prompt A/B: the same workload (``--prefix-frac`` of
    requests share a ``--prefix-tokens`` page-aligned prefix) through
    a cache-on and a cache-off engine. The acceptance claim is in the
    delta: cache-on ``prefill_tokens_per_request`` collapses toward
    the suffix length while greedy output is identical math (the
    parity tests own that half); ``shared_prefix_ttft_p99_ms`` is the
    budget-gated latency ceiling."""
    from tpunet.config import ServeConfig
    from tpunet.serve import Engine

    pt = args.kv_page_tokens
    shared_len = args.prefix_tokens
    if shared_len <= 0:
        shared_len = (3 * args.prompt_len // 4) // pt * pt
    if not 0 < shared_len < args.prompt_len:
        print(f"--prompt-len {args.prompt_len} leaves no room for a "
              f"page-aligned shared prefix at --kv-page-tokens {pt}; "
              "raise --prompt-len or set --prefix-tokens explicitly",
              file=sys.stderr)
        sys.exit(2)
    shared, plans = _prefix_workload(
        concurrency, prompt_len=args.prompt_len, shared_len=shared_len,
        prefix_frac=args.prefix_frac,
        requests_per_client=args.requests_per_client,
        vocab=args.vocab_size)
    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    bucket = min(bucket, args.max_seq_len)
    variants = {}
    for label, on in (("cache_on", True), ("cache_off", False)):
        cfg = ServeConfig(slots=args.slots,
                          queue_max=max(64, 4 * args.slots),
                          prefill_buckets=(bucket,), emit_every_s=0.0,
                          prefix_cache=on, **_lever_overrides(args))
        engine = Engine(model, variables, cfg).start()
        try:
            variants[label] = _run_prefix_variant(
                engine, shared, plans, new_tokens=args.new_tokens)
        finally:
            engine.stop()
    import jax
    on, off = variants["cache_on"], variants["cache_off"]
    out = {
        "mode": "prefix",
        "device": jax.devices()[0].device_kind,
        "slots": args.slots,
        "prompt_len": args.prompt_len,
        "prefix_tokens": shared_len,
        "prefix_frac": args.prefix_frac,
        "new_tokens": args.new_tokens,
        "kv_page_tokens": pt,
        "concurrency": concurrency,
        "cache_on": on,
        "cache_off": off,
        # headline numbers mirrored at top level for dashboards
        "prefix_hit_rate": on["prefix_hit_rate"],
        "prefill_tokens_per_request": on["prefill_tokens_per_request"],
        "shared_prefix_ttft_p99_ms": on["shared_ttft_p99_ms"],
    }
    if on["prefill_tokens_per_request"] \
            and off["prefill_tokens_per_request"]:
        out["prefill_reduction_vs_cache_off"] = round(
            off["prefill_tokens_per_request"]
            / on["prefill_tokens_per_request"], 2)
    return out


def _spec_workload(concurrency, *, prompt_len, requests_per_client,
                   vocab, seed=0):
    """Per-client prompt plans for the speculative-decoding A/B —
    built ONCE so the spec-on and spec-off engines serve the exact
    same token streams (greedy: bitwise-identical output is pinned by
    tests/test_serve_paged.py; the bench only measures speed)."""
    plans = []
    for i in range(concurrency):
        crng = np.random.default_rng(seed + 2000 + i)
        plans.append([
            crng.integers(0, vocab, size=prompt_len).astype(np.int32)
            for _ in range(requests_per_client)])
    return plans


def _run_spec_variant(engine, plans, *, new_tokens):
    """Drive one engine through the plans (closed loop, one client per
    plan) and report throughput plus the spec counters from the
    engine's OWN registry — ``accepted_tokens_per_verify`` is the
    number the speedup stands on."""
    # The warm request must cover the same position range as the
    # measured run: the burst/verify programs are compiled per
    # attention-window bucket, so a short warm request would leave
    # the deeper buckets to compile inside the measured window — a
    # deployed replica deserializes the full closed set from the AOT
    # store at boot instead.
    warm = np.zeros(max(4, int(plans[0][0].size)), np.int32)
    warm_new = 2
    if getattr(engine, "spec_decode", False):
        warm_new = new_tokens
    engine.submit(warm, max_new_tokens=warm_new).result(timeout=600)
    base = engine.registry.snapshot()
    ttfts, e2es, errors = [], [], []
    done_tokens = [0] * len(plans)

    def client(i):
        try:
            for p in plans[i]:
                req = engine.submit(p, max_new_tokens=new_tokens)
                req.result(timeout=600)
                ttfts.append(req.ttft_s)
                e2es.append(req.e2e_s)
                done_tokens[i] += len(req.tokens)
        except Exception as e:  # noqa: BLE001 — report, don't hang
            errors.append(f"client {i}: {type(e).__name__}: {e}")

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(plans))]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap = engine.registry.snapshot()

    def delta(name):
        return snap.get(name, 0) - base.get(name, 0)

    drafted = delta("serve_spec_draft_tokens_total")
    accepted = delta("serve_spec_accepted_tokens_total")
    verifies = delta("serve_spec_verify_steps_total")
    total_tokens = sum(done_tokens)
    slots = engine.slots
    return {
        "requests": sum(len(p) for p in plans),
        "errors": errors,
        "total_tokens": total_tokens,
        "wall_s": round(wall, 3),
        "tokens_per_s": round(total_tokens / wall, 1) if wall else 0.0,
        "tokens_per_s_per_slot": round(total_tokens / wall / slots, 1)
        if wall else 0.0,
        "decode_steps": int(delta("serve_decode_steps_total")),
        "draft_tokens": int(drafted),
        "accepted_tokens": int(accepted),
        "verify_steps": int(verifies),
        "spec_acceptance_rate": round(accepted / drafted, 4)
        if drafted else 0.0,
        "accepted_tokens_per_verify": round(accepted / verifies, 2)
        if verifies else 0.0,
        "drafter_pool_bytes": engine.drafter_pool_bytes(),
        "ttft_p50_ms": ms(ttfts, 50),
        "ttft_p99_ms": ms(ttfts, 99),
        "e2e_p99_ms": ms(e2es, 99),
    }


def run_spec_bench(args, model_cfg, model, variables,
                   concurrency) -> dict:
    """Speculative-decoding A/B: the identical workload through a
    spec-off and a spec-on engine at the same pool geometry. The
    drafter is FITTED to the bench workload first
    (tpunet.serve.spec.fit_drafter distills a width-mult drafter onto
    the serving model's own greedy trajectories) — the same flow an
    operator uses against logged traffic, scaled down; an unfitted
    drafter drafts noise and spec-on would honestly lose. The
    acceptance claim is ``spec_on.tokens_per_s > spec_off
    .tokens_per_s`` on the same streams (gated unconditionally by
    check_serve_budget.py), with ``accepted_tokens_per_verify`` and
    the drafter pool's extra bytes reported alongside."""
    import jax

    from tpunet.config import ServeConfig
    from tpunet.serve import Engine
    from tpunet.serve import spec as serve_spec

    plans = _spec_workload(
        concurrency, prompt_len=args.prompt_len,
        requests_per_client=args.requests_per_client,
        vocab=args.vocab_size)
    drafter_cfg = serve_spec.drafter_model_config(
        model_cfg, args.spec_width_mult)
    from tpunet.models import create_model, init_variables
    dmodel = create_model(drafter_cfg)
    dparams = init_variables(dmodel, jax.random.PRNGKey(0),
                             seq_len=16)["params"]
    fit_prompts = np.stack([p for plan in plans for p in plan])
    t_fit = time.perf_counter()
    dparams = serve_spec.fit_drafter(
        model, variables["params"], dmodel, dparams, fit_prompts,
        gen_tokens=args.new_tokens, steps=args.spec_fit_steps,
        log=lambda m: print(f"# {m}", file=sys.stderr, flush=True))
    fit_s = time.perf_counter() - t_fit
    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    bucket = min(bucket, args.max_seq_len)
    variants = {}
    for label, on in (("spec_off", False), ("spec_on", True)):
        cfg = ServeConfig(slots=args.slots,
                          queue_max=max(64, 4 * args.slots),
                          prefill_buckets=(bucket,), emit_every_s=0.0,
                          spec_decode=on, spec_k=args.spec_k,
                          spec_draft_width_mult=args.spec_width_mult,
                          **_lever_overrides(args))
        engine = Engine(model, variables, cfg,
                        drafter_params=dparams if on else None).start()
        try:
            variants[label] = _run_spec_variant(
                engine, plans, new_tokens=args.new_tokens)
        finally:
            engine.stop()
    on, off = variants["spec_on"], variants["spec_off"]
    out = {
        "mode": "spec",
        "device": jax.devices()[0].device_kind,
        "slots": args.slots,
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "spec_k": args.spec_k,
        "spec_width_mult": args.spec_width_mult,
        "spec_fit_steps": args.spec_fit_steps,
        "fit_wall_s": round(fit_s, 1),
        "concurrency": concurrency,
        "spec_on": on,
        "spec_off": off,
        # headline numbers mirrored at top level for dashboards
        "tokens_per_s_per_slot": on["tokens_per_s_per_slot"],
        "spec_acceptance_rate": on["spec_acceptance_rate"],
        "accepted_tokens_per_verify": on["accepted_tokens_per_verify"],
        "drafter_pool_bytes": on["drafter_pool_bytes"],
    }
    if off["tokens_per_s"]:
        out["spec_speedup"] = round(
            on["tokens_per_s"] / off["tokens_per_s"], 3)
    return out


def _get_json(url, timeout=10):
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def run_router_bench(args) -> dict:
    """Closed-loop load through a spawned router + replica fleet with
    one replica killed mid-run: fleet tok/s, re-route latency (kill
    -> next completed request), dropped-request count (client-visible
    failures — MUST be 0 for --kill-mode drain; bounded by the
    route-retry budget for sigkill), and respawn recovery."""
    import signal as _signal
    import socket
    import subprocess

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    base = f"http://127.0.0.1:{port}"
    import tempfile
    workdir = tempfile.mkdtemp(prefix="router-bench-")
    argv = [sys.executable, "-m", "tpunet.router",
            "--spawn", str(args.replicas), "--port", str(port),
            "--probe-interval-s", "0.25", "--unhealthy-after", "2",
            "--respawn-backoff-s", "0.5", "--emit-every-s", "2",
            "--min-replicas", str(args.replicas),
            "--metrics-dir", workdir,
            "--aot-cache", os.path.join(workdir, "aot"), "--",
            "--checkpoint-dir", "",
            "--vit-hidden", str(args.vit_hidden),
            "--vit-depth", str(args.vit_depth),
            "--vit-heads", str(args.vit_heads),
            "--vocab-size", str(args.vocab_size),
            "--max-seq-len", str(args.max_seq_len),
            "--slots", str(args.slots),
            "--prefill-buckets", str(min(
                1 << max(4, (args.prompt_len - 1).bit_length()),
                args.max_seq_len))]
    router = subprocess.Popen(argv, stdout=subprocess.DEVNULL,
                              stderr=subprocess.STDOUT)
    out = {"mode": "router", "replicas": args.replicas,
           "kill_mode": args.kill_mode, "workdir": workdir,
           "errors": []}
    try:
        deadline = time.time() + 300
        while time.time() < deadline:
            try:
                h = _get_json(base + "/healthz", timeout=2)
                if h.get("routable", 0) >= args.replicas:
                    break
            except Exception:  # noqa: BLE001
                pass
            time.sleep(0.5)
        else:
            out["errors"].append("fleet never became routable")
            return out

        rng = np.random.default_rng(0)
        concurrency = max(4, args.replicas * 2)
        n_requests = concurrency * max(4, args.requests_per_client)
        prompts = [rng.integers(0, args.vocab_size,
                                size=args.prompt_len).tolist()
                   for _ in range(concurrency)]
        results = []           # (t_done, ok, tokens)
        lock = threading.Lock()
        kill_at = n_requests // 2
        killed = {"t": None, "pid": None}

        def kill_one():
            rows = _get_json(base + "/replicas")["replicas"]
            victim = next((r for r in rows
                           if r.get("alive") and r.get("pid")), None)
            if victim is None:
                out["errors"].append("no live replica to kill")
                return
            killed["pid"] = victim["pid"]
            killed["t"] = time.perf_counter()
            sig = (_signal.SIGKILL if args.kill_mode == "sigkill"
                   else _signal.SIGTERM)
            os.kill(victim["pid"], sig)

        import urllib.request
        counter = {"n": 0}

        def client(i):
            while True:
                with lock:
                    if counter["n"] >= n_requests:
                        return
                    counter["n"] += 1
                    seq = counter["n"]
                if seq == kill_at and args.kill_mode != "none":
                    kill_one()
                body = json.dumps(
                    {"tokens": prompts[i],
                     "max_new_tokens": args.new_tokens}).encode()
                req = urllib.request.Request(
                    base + "/v1/generate", body,
                    {"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req, timeout=600) as r:
                        payload = json.loads(r.read())
                    with lock:
                        results.append((time.perf_counter(), True,
                                        len(payload["tokens"])))
                except Exception:  # noqa: BLE001 — a failed request
                    with lock:     # is the measurement, not a crash
                        results.append((time.perf_counter(), False, 0))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(concurrency)]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        ok = [r for r in results if r[1]]
        dropped = len(results) - len(ok)
        total_tokens = sum(r[2] for r in ok)
        out.update({
            "requests": len(results),
            "dropped_requests": dropped,
            "total_tokens": total_tokens,
            "wall_s": round(wall, 3),
            "fleet_tokens_per_s": round(total_tokens / wall, 1),
        })
        if killed["t"] is not None:
            after = [t for t, good, _ in results
                     if good and t > killed["t"]]
            if after:
                out["reroute_latency_s"] = round(
                    min(after) - killed["t"], 3)
            # Respawn recovery: every replica routable again.
            deadline = time.time() + 180
            while time.time() < deadline:
                try:
                    h = _get_json(base + "/healthz", timeout=2)
                    if h.get("routable", 0) >= args.replicas:
                        out["respawn_recovery_s"] = round(
                            time.perf_counter() - killed["t"], 3)
                        break
                except Exception:  # noqa: BLE001
                    pass
                time.sleep(0.5)
            else:
                out["errors"].append("killed replica never respawned")
        try:
            snap = _get_json(base + "/metrics")
            for key in ("router_requests_total", "router_rerouted_total",
                        "router_rejected_total",
                        "router_failovers_total",
                        "router_evictions_total",
                        "router_respawns_total"):
                if key in snap:
                    out[key] = int(snap[key])
        except Exception:  # noqa: BLE001
            pass
        if args.kill_mode == "drain" and dropped:
            out["errors"].append(
                f"drain kill dropped {dropped} request(s); drain must "
                "drop zero")
    finally:
        router.send_signal(_signal.SIGTERM)
        try:
            router.wait(timeout=90)
        except subprocess.TimeoutExpired:
            router.kill()
    return out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--http", default="",
                    help="bench a RUNNING server at this base URL "
                         "instead of an in-process engine")
    ap.add_argument("--cold-start", action="store_true",
                    help="measure cold_start_to_first_token_s for "
                         "cold / persistent-cache / AOT-deserialized "
                         "replica boots (fresh subprocess each)")
    ap.add_argument("--_cold-start-child", action="store_true",
                    dest="_cold_start_child", help=argparse.SUPPRESS)
    ap.add_argument("--_aot-dir", default="", dest="_aot_dir",
                    help=argparse.SUPPRESS)
    ap.add_argument("--router", action="store_true",
                    help="closed-loop load against a spawned router + "
                         "replica fleet with a mid-run replica kill "
                         "(fleet tok/s, re-route latency, dropped "
                         "requests)")
    ap.add_argument("--replicas", type=int, default=2,
                    help="--router: replica children to spawn")
    ap.add_argument("--kill-mode", default="sigkill",
                    choices=("sigkill", "drain", "none"),
                    help="--router: how the mid-run replica dies "
                         "(drain = SIGTERM graceful; dropped "
                         "requests must be 0 for drain, bounded for "
                         "sigkill)")
    ap.add_argument("--slots-sweep", action="store_true",
                    help="fixed-KV-pool-bytes capacity sweep: size a "
                         "paged pool to the DENSE pool's bytes, then "
                         "report tokens/s and admitted-slot count vs "
                         "offered concurrency — the concurrent-slot "
                         "multiplier paging buys at constant HBM")
    ap.add_argument("--paged-kv", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="engine paged-KV lever for A/Bs (default: "
                         "the ServeConfig default, ON)")
    ap.add_argument("--kv-pages", type=int, default=0,
                    help="usable KV pages (0 = dense-equivalent "
                         "capacity)")
    ap.add_argument("--kv-page-tokens", type=int, default=16,
                    help="tokens per KV page")
    ap.add_argument("--kv-dtype", default="auto",
                    choices=("auto", "bf16", "int8"),
                    help="KV page payload dtype (int8 = quantized "
                         "pages, per-row scale)")
    ap.add_argument("--device-sampling", default=None,
                    action=argparse.BooleanOptionalAction,
                    help="fused on-device sampling lever for A/Bs "
                         "(default: the ServeConfig default, ON)")
    ap.add_argument("--prefix-frac", type=float, default=0.0,
                    help="shared-prompt workload: this fraction of "
                         "requests share one prompt prefix; > 0 "
                         "switches to the prefix-cache A/B bench "
                         "(cache-on vs cache-off over the SAME "
                         "workload)")
    ap.add_argument("--prefix-tokens", type=int, default=0,
                    help="length of the shared prompt prefix (0 = "
                         "largest page multiple <= 3/4 of "
                         "--prompt-len)")
    ap.add_argument("--spec", action="store_true",
                    help="speculative-decoding A/B: fit a drafter to "
                         "the bench workload, then run the identical "
                         "workload spec-on vs spec-off "
                         "(check_serve_budget.py gates spec-on "
                         "tokens/s above spec-off unconditionally)")
    ap.add_argument("--spec-k", type=int, default=8,
                    help="--spec: draft tokens per verify cycle "
                         "(default 8: with --new-tokens 64 the budget "
                         "divides as 1 + 7x9 so no request drops to "
                         "the width-1 tail)")
    ap.add_argument("--spec-width-mult", type=float, default=0.25,
                    help="--spec: drafter width fraction (0.25: the "
                         "drafter burst is K+1 SEQUENTIAL small "
                         "steps, the one part of the cycle the wide "
                         "verify cannot amortize — narrow pays)")
    ap.add_argument("--spec-fit-steps", type=int, default=300,
                    help="--spec: drafter distillation steps (fewer = "
                         "faster bench, lower acceptance)")
    ap.add_argument("--checkpoint-dir", default="",
                    help="LM best checkpoint (default: random tiny "
                         "weights — throughput shape, not quality)")
    ap.add_argument("--vit-hidden", type=int, default=64)
    ap.add_argument("--vit-depth", type=int, default=2)
    ap.add_argument("--vit-heads", type=int, default=4)
    ap.add_argument("--vocab-size", type=int, default=256)
    ap.add_argument("--max-seq-len", type=int, default=512)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--prompt-len", type=int, default=16)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--requests-per-client", type=int, default=2)
    ap.add_argument("--concurrency", default="1,2,4,8",
                    help="comma-separated offered-load levels")
    ap.add_argument("--out", default="",
                    help="also write the result JSON here")
    ap.add_argument("--enforce-budget", action="store_true",
                    help="exit 3 when tokens_per_s_per_slot falls below "
                         "the docs/serve_budget.json floor for this "
                         "device kind")
    args = ap.parse_args()
    levels = [int(c) for c in args.concurrency.split(",") if c]

    if args._cold_start_child:
        run_cold_start_child(args)
        return

    if args.cold_start:
        out = run_cold_start_bench(args)
        print(json.dumps(out, indent=1))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        if args.enforce_budget:
            from check_serve_budget import check_record, load_budget
            ok, msgs = check_record(out, load_budget())
            for m in msgs:
                print(f"# {m}", file=sys.stderr, flush=True)
            if not ok:
                sys.exit(3)
        return

    if args.router:
        out = run_router_bench(args)
        print(json.dumps(out, indent=1))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        if out.get("errors"):
            sys.exit(1)
        return

    if args.http:
        if args.enforce_budget:
            # The floor is keyed on device kind, which a remote HTTP
            # record does not carry — refuse loudly rather than
            # letting the flag silently no-op.
            print("--enforce-budget is not supported with --http "
                  "(no device kind in the record); run the in-process "
                  "engine bench instead", file=sys.stderr)
            sys.exit(2)
        results = [run_http_level(
            args.http.rstrip("/"), c, prompt_len=args.prompt_len,
            new_tokens=args.new_tokens,
            requests_per_client=args.requests_per_client,
            vocab=args.vocab_size) for c in levels]
        out = {"mode": "http", "target": args.http, "levels": results}
        print(json.dumps(out, indent=1))
        if args.out:
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return

    import jax

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables, num_params
    from tpunet.models.lm import generate
    from tpunet.serve import Engine

    model_cfg = ModelConfig(
        name="lm", vit_hidden=args.vit_hidden, vit_depth=args.vit_depth,
        vit_heads=args.vit_heads, vocab_size=args.vocab_size,
        max_seq_len=args.max_seq_len, dropout_rate=0.0, dtype="float32")
    if args.checkpoint_dir:
        from tpunet.infer.generate import load_lm
        model, variables = load_lm(model_cfg,
                                   checkpoint_dir=args.checkpoint_dir)
    else:
        model = create_model(model_cfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=16)

    if args.prefix_frac > 0:
        if args.paged_kv is False:
            print("--no-paged-kv is incompatible with --prefix-frac "
                  "(the prefix cache lives in the paged pool); drop "
                  "one of the flags", file=sys.stderr)
            sys.exit(2)
        out = run_prefix_bench(args, model, variables, max(levels))
        print(json.dumps(out, indent=1))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        if args.enforce_budget:
            from check_serve_budget import check_record, load_budget
            ok, msgs = check_record(out, load_budget())
            for m in msgs:
                print(f"# {m}", file=sys.stderr, flush=True)
            if not ok:
                sys.exit(3)
        return

    if args.spec:
        if args.paged_kv is False or args.device_sampling is False:
            # The engine would raise the same complaint at build time;
            # exit 2 with the reason before any compile work starts.
            print("--spec requires paged KV and device sampling "
                  "(rejection is a page-table rewind; acceptance "
                  "compares against the fused sampler); drop the "
                  "--no-* flags", file=sys.stderr)
            sys.exit(2)
        out = run_spec_bench(args, model_cfg, model, variables,
                             max(levels))
        print(json.dumps(out, indent=1))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        if args.enforce_budget:
            from check_serve_budget import check_record, load_budget
            ok, msgs = check_record(out, load_budget())
            for m in msgs:
                print(f"# {m}", file=sys.stderr, flush=True)
            if not ok:
                sys.exit(3)
        return

    if args.slots_sweep:
        if args.paged_kv is False:
            # The sweep IS the paged-capacity measurement; silently
            # benchmarking the paged pool under a dense flag would
            # mislabel the record — refuse loudly.
            print("--no-paged-kv is incompatible with --slots-sweep "
                  "(the sweep measures paged capacity against the "
                  "dense byte budget); drop one of the flags",
                  file=sys.stderr)
            sys.exit(2)
        out = run_slots_sweep(args, model, variables)
        print(json.dumps(out, indent=1))
        if args.out:
            os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
            with open(args.out, "w") as f:
                json.dump(out, f, indent=1)
        return

    # Sequential baseline: the pre-serve shape — one request at a time
    # through models.lm.generate (warmed compile).
    p = np.zeros((1, args.prompt_len), np.int32)
    generate(model, variables, p, n_new=2)
    t0 = time.perf_counter()
    n_seq = max(2, args.requests_per_client)
    for _ in range(n_seq):
        generate(model, variables, p, n_new=args.new_tokens)
    seq_wall = time.perf_counter() - t0
    seq_tps = n_seq * args.new_tokens / seq_wall

    bucket = 1 << max(4, (args.prompt_len - 1).bit_length())
    cfg = ServeConfig(slots=args.slots, queue_max=max(64, 4 * args.slots),
                      prefill_buckets=(min(bucket, args.max_seq_len),),
                      emit_every_s=0.0, **_lever_overrides(args))
    engine = Engine(model, variables, cfg).start()
    try:
        # warm prefill + decode programs outside the measurement
        engine.submit(np.zeros(args.prompt_len, np.int32),
                      max_new_tokens=2).result(timeout=600)
        results = [run_level(
            engine, c, prompt_len=args.prompt_len,
            new_tokens=args.new_tokens,
            requests_per_client=args.requests_per_client,
            vocab=args.vocab_size) for c in levels]
    finally:
        engine.stop()
    out = {
        "mode": "engine",
        "device": jax.devices()[0].device_kind,
        "model_params": num_params(variables["params"]),
        "slots": args.slots,
        "prompt_len": args.prompt_len,
        "new_tokens": args.new_tokens,
        "paged_kv": engine._paged_kv is not None,
        "kv_dtype": cfg.kv_dtype,
        "device_sampling": engine.device_sampling,
        # KV capacity telemetry: pool bytes pinned per slot and per
        # cacheable token (the serve_budget.json kv_bytes_per_token
        # ceiling gates the latter against silent pool bloat).
        "kv_hbm_bytes_per_slot": round(
            engine.kv_pool_bytes() / engine.slots, 1),
        "kv_bytes_per_token": round(engine.kv_bytes_per_token(), 2),
        "sequential_tokens_per_s": round(seq_tps, 1),
        "levels": results,
        "speedup_vs_sequential": {
            str(r["concurrency"]): round(r["tokens_per_s"] / seq_tps, 2)
            for r in results},
    }
    from check_serve_budget import tokens_per_s_per_slot
    tpss = tokens_per_s_per_slot(out)
    if tpss is not None:
        out["tokens_per_s_per_slot"] = round(tpss, 1)
    print(json.dumps(out, indent=1))
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    if args.enforce_budget:
        from check_serve_budget import check_record, load_budget
        ok, msgs = check_record(out, load_budget())
        for m in msgs:
            print(f"# {m}", file=sys.stderr, flush=True)
        if not ok:
            sys.exit(3)


if __name__ == "__main__":
    main()
