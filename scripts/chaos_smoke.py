#!/usr/bin/env python
"""Standing chaos matrix: four elastic failure legs, end-to-end on CPU.

Each leg drives the REAL stack — `python -m tpunet.main` children
under `tpunet/elastic/` agents, deterministic `--chaos` injection —
and asserts a successfully resumed completion under the original
run_id; the kill legs additionally assert a complete flight-recorder
crash report from the killed child. Wired into
`scripts/run_checks.sh --slow` (docs/elasticity.md "The standing
chaos matrix"); the two kill legs also run smaller in tier-1
(tests/test_elastic.py).

    python scripts/chaos_smoke.py                 # all four legs
    python scripts/chaos_smoke.py --legs sigterm_grace,slow_host_evict
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
import threading
from typing import Dict, List, Optional

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _child_env(extra: Optional[Dict[str, Optional[str]]] = None
               ) -> Dict[str, Optional[str]]:
    from tpunet.utils.cache import cache_dir
    env: Dict[str, Optional[str]] = {
        "XLA_FLAGS": None,               # one CPU device per process
        "PALLAS_AXON_POOL_IPS": None,
        "JAX_PLATFORMS": "cpu",
        "JAX_COMPILATION_CACHE_DIR": cache_dir(),
    }
    env.update(extra or {})
    return env


def _train_cmd(run_dir: str, chaos_spec: str, *, epochs: int = 3,
               batch: int = 16, synthetic: int = 64,
               extra: Optional[List[str]] = None) -> List[str]:
    return [
        sys.executable, "-m", "tpunet.main",
        "--dataset", "synthetic", "--image-size", "32",
        "--batch-size", str(batch), "--synthetic-size", str(synthetic),
        "--model", "vit", "--vit-patch", "8", "--vit-hidden", "32",
        "--vit-depth", "1", "--vit-heads", "2",
        "--dtype", "float32", "--dropout-rate", "0",
        "--epochs", str(epochs), "--checkpoint-dir", run_dir,
        "--no-native-loader", "--chaos", chaos_spec,
    ] + (extra or [])


def _run_gang(workdir: str, cmd: List[str], hosts: Dict[str, dict],
              env_extra: Optional[Dict[str, Optional[str]]] = None,
              join_timeout: float = 420.0) -> Dict[str, int]:
    """Run one agent per host in threads; return exit codes."""
    from tpunet.elastic.agent import AgentConfig, ElasticAgent
    run_dir = os.path.join(workdir, "run")
    rdzv_dir = os.path.join(workdir, "rdzv")
    rcs: Dict[str, int] = {}
    threads = []
    for host, kw in hosts.items():
        cfg = AgentConfig(
            run_dir=run_dir, rdzv_dir=rdzv_dir, host_id=host,
            command=cmd, settle_s=0.4, timeout_s=120.0, beat_s=0.1,
            dead_after_s=10.0, grace_s=3.0,
            env=_child_env(env_extra), **kw)
        t = threading.Thread(
            target=lambda h=host, c=cfg: rcs.__setitem__(
                h, ElasticAgent(c).run()),
            name=f"agent-{host}", daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=join_timeout)
        assert not t.is_alive(), "gang did not converge in time"
    return rcs


def _read_run(workdir: str):
    from tpunet.utils.logging import MetricsLogger
    run_dir = os.path.join(workdir, "run")
    records = MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    with open(os.path.join(run_dir, "run_id")) as f:
        run_id = f.read().strip()
    return records, run_id


def _assert_completed(workdir: str, final_epoch: int = 3) -> list:
    from tpunet.elastic import events
    run_dir = os.path.join(workdir, "run")
    assert events.is_done(run_dir), "no done marker: run never finished"
    records, run_id = _read_run(workdir)
    assert run_id
    for r in records:
        if "run_id" in r:
            assert r["run_id"] == run_id, "stream forked run_ids"
    plain = [r for r in records if "kind" not in r and "epoch" in r]
    assert max(r["epoch"] for r in plain) == final_epoch
    return records


def _assert_crash_report(workdir: str, suffix: str = "") -> None:
    run_dir = os.path.join(workdir, "run")
    pattern = os.path.join(run_dir, "flightrec",
                           f"crash_report{suffix}*")
    reports = glob.glob(pattern)
    assert reports, f"no crash report matching {pattern}"
    with open(reports[0]) as f:
        report = json.load(f)
    for key in ("cause", "events", "stacks", "meta"):
        assert key in report, f"incomplete crash report: missing {key}"
    assert report["events"], "crash report has no ring events"


def _elastic(records, event):
    return [r for r in records
            if r.get("kind") == "obs_elastic" and r["event"] == event]


# -------------------------------------------------------------- legs


def leg_kill_mid_step(workdir: str) -> None:
    """2-process gang; host 1 SIGKILLed mid-epoch; shrink dp 2->1."""
    run_dir = os.path.join(workdir, "run")
    cmd = _train_cmd(
        run_dir, "slow@step=2:delay=2:gen=0;kill@step=3:host=1:gen=0")
    rcs = _run_gang(workdir, cmd, {
        "h0": {"max_restarts": 2},
        "h1": {"max_restarts": 0},
    })
    assert rcs["h0"] == 0 and rcs["h1"] == 2, rcs
    records = _assert_completed(workdir)
    (shrink,) = _elastic(records, "shrink")
    assert shrink["old_world"] == 2 and shrink["new_world"] == 1
    assert _elastic(records, "recovered")[-1]["new_mesh"]["data"] == 1
    _assert_crash_report(workdir, ".p1")


def leg_kill_mid_ckpt(workdir: str) -> None:
    """SIGKILL with the epoch-2 checkpoint write in flight: the torn
    save is skipped, restore comes from the previous intact step."""
    run_dir = os.path.join(workdir, "run")
    cmd = _train_cmd(
        run_dir,
        "kill@ckpt=2:gen=0;slow@step=8:delay=3:steps=4:gen=0")
    rcs = _run_gang(workdir, cmd, {"h0": {"max_restarts": 1}})
    assert rcs["h0"] == 0, rcs
    records = _assert_completed(workdir)
    (restart,) = _elastic(records, "restart")
    assert restart["cause"] == "failed"
    # Restored epoch 1 (the intact save), re-ran epoch 2.
    assert _elastic(records, "recovered")[-1]["epoch"] == 2
    _assert_crash_report(workdir)


def leg_sigterm_grace(workdir: str) -> None:
    """Spot-preemption shape: SIGTERM mid-epoch-2 with a grace
    window; partial save lands inside it; relaunch resumes the same
    epoch and finishes. (Clean exit: no crash report expected.)"""
    run_dir = os.path.join(workdir, "run")
    cmd = _train_cmd(run_dir, "sigterm@step=6:gen=0",
                     extra=["--preempt-grace-s", "30"])
    rcs = _run_gang(workdir, cmd, {"h0": {"max_restarts": 1}})
    assert rcs["h0"] == 0, rcs
    records = _assert_completed(workdir)
    (restart,) = _elastic(records, "restart")
    assert restart["cause"] == "preempted"
    partial = [r for r in records if "kind" not in r
               and r.get("partial")]
    assert partial and partial[0]["epoch"] == 2, \
        "no partial-save row: the grace-window save never landed"


def leg_slow_host_evict(workdir: str) -> None:
    """Proactive checkpoint-and-evict: an injected straggler delay on
    host 1 trips the watchdog's stall detector, the pod checkpoints
    and evicts it, and the survivor re-meshes and finishes."""
    run_dir = os.path.join(workdir, "run")
    cmd = _train_cmd(
        run_dir, "slow@step=10:delay=1.5:steps=6:host=1:gen=0",
        batch=8, synthetic=128,
        extra=["--evict-on-straggler", "--stall-factor", "3",
               "--stall-min-s", "0.2"])
    rcs = _run_gang(workdir, cmd, {
        "h0": {"max_restarts": 2},
        "h1": {"max_restarts": 2},
    }, env_extra={"TPUNET_STOP_POLL_STEPS": "2"})
    # The evicted host leaves CLEANLY (exit 0), the survivor finishes.
    assert rcs["h0"] == 0 and rcs["h1"] == 0, rcs
    records = _assert_completed(workdir)
    # Exactly ONE replica was evicted. Which one is first-claim-wins:
    # in lockstep DP the straggler inflates EVERY replica's step lap,
    # so near-simultaneous watchdog claims are expected
    # (docs/elasticity.md "Proactive checkpoint-and-evict").
    (evict,) = _elastic(records, "evict")
    assert evict["lost"] in (["h0"], ["h1"])
    assert evict["cause"] == "step_stall"
    (shrink,) = _elastic(records, "shrink")
    assert shrink["cause"] == "evict"
    assert shrink["new_world"] == 1
    assert shrink["lost"] == evict["lost"]


LEGS = {
    "kill_mid_step": leg_kill_mid_step,
    "kill_mid_ckpt": leg_kill_mid_ckpt,
    "sigterm_grace": leg_sigterm_grace,
    "slow_host_evict": leg_slow_host_evict,
}


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--legs", default=",".join(LEGS),
                        help="comma-separated subset of: "
                             + ", ".join(LEGS))
    args = parser.parse_args(argv)
    legs = [leg.strip() for leg in args.legs.split(",") if leg.strip()]
    unknown = [leg for leg in legs if leg not in LEGS]
    if unknown:
        print(f"unknown legs: {unknown} (have {sorted(LEGS)})",
              file=sys.stderr)
        return 2
    failed = []
    for leg in legs:
        with tempfile.TemporaryDirectory(
                prefix=f"tpunet-chaos-{leg}-") as workdir:
            print(f"=== chaos leg: {leg}")
            try:
                LEGS[leg](workdir)
                print(f"=== chaos leg: {leg} PASS")
            except Exception as e:  # noqa: BLE001 - report and continue
                print(f"=== chaos leg: {leg} FAIL: {e}",
                      file=sys.stderr)
                failed.append(leg)
    if failed:
        print(f"chaos smoke FAILED: {failed}", file=sys.stderr)
        return 1
    print(f"chaos smoke OK: {len(legs)} leg(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
