#!/usr/bin/env python
"""HBM bytes-per-image regression gate for the training step.

Compares a bench.py JSON record against the checked-in budget
(docs/bytes_budget.json) and exits nonzero when
``xla_bytes_accessed_per_image`` (or any budgeted breakdown category)
regresses more than the budget's tolerance on this device kind.

Usage:
    python bench.py | python scripts/check_bytes_budget.py -
    python scripts/check_bytes_budget.py BENCH_r05.json
    python bench.py --enforce-budget          # same gate, in-process

Budget file semantics (docs/bytes_budget.json):

- ``budgets`` maps a device-kind substring (matched case-insensitively
  against the record's ``device_kind``) to its accepted measurement:
  ``xla_bytes_accessed_per_image`` (bytes) and optionally
  ``breakdown`` ({category: bytes} from ``bytes_per_image_breakdown``).
- The gate FAILS when measured > budget * (1 + tolerance_pct/100).
  The budget is the last ACCEPTED measurement, not an aspiration: a
  PR that improves bytes/image should ratchet the budget down to the
  new measurement in the same change.
- A device kind with no budget entry passes with a note (the CPU
  backend's fusion behavior is not byte-comparable to TPU's, so no
  CPU budget is checked in).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGET = os.path.join(REPO, "docs", "bytes_budget.json")


def load_budget(path: str = DEFAULT_BUDGET) -> Dict:
    with open(path) as fp:
        return json.load(fp)


def _find_budget(budgets: Dict, device_kind: str):
    kind = (device_kind or "").lower()
    for key, val in budgets.items():
        if key.lower() in kind:
            return key, val
    return None, None


def check_record(record: Dict, budget: Dict) -> Tuple[bool, List[str]]:
    """-> (ok, messages). ok is False only on a real regression; a
    missing budget entry or missing measurement passes with a note
    (a broken measurement already shows as null in the bench JSON —
    the gate's job is catching byte REGRESSIONS, not re-checking the
    bench's plumbing)."""
    tol = float(budget.get("tolerance_pct", 5.0)) / 100.0
    key, entry = _find_budget(budget.get("budgets", {}),
                              record.get("device_kind", ""))
    if entry is None:
        return True, [f"no bytes budget for device kind "
                      f"{record.get('device_kind')!r}; nothing to enforce"]
    msgs, ok = [], True

    def gate(name: str, measured, budgeted) -> None:
        nonlocal ok
        if budgeted is None:
            return
        if measured is None:
            msgs.append(f"{name}: no measurement in record (budget "
                        f"{budgeted:.0f}); skipping")
            return
        limit = budgeted * (1.0 + tol)
        verdict = "OK" if measured <= limit else "REGRESSION"
        msgs.append(
            f"{name}: measured {measured / 1e6:.1f} MB vs budget "
            f"{budgeted / 1e6:.1f} MB (+{100 * tol:.0f}% tolerance -> "
            f"limit {limit / 1e6:.1f} MB) [{verdict}]")
        if measured > limit:
            ok = False

    gate(f"{key}: xla_bytes_accessed_per_image",
         record.get("xla_bytes_accessed_per_image"),
         entry.get("xla_bytes_accessed_per_image"))
    bd = record.get("bytes_per_image_breakdown") or {}
    for cat, budgeted in (entry.get("breakdown") or {}).items():
        gate(f"{key}: breakdown[{cat}]", bd.get(cat), budgeted)
    return ok, msgs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    path = argv[0]
    budget_path = DEFAULT_BUDGET
    if "--budget" in argv:
        budget_path = argv[argv.index("--budget") + 1]
    raw = sys.stdin.read() if path == "-" else open(path).read()
    # Accept a plain JSON file (pretty-printed artifacts like
    # BENCH_r05.json included) OR a piped bench stdout stream, whose
    # '#' notes precede the one-line record.
    try:
        record = json.loads(raw)
    except json.JSONDecodeError:
        lines = [ln for ln in raw.splitlines()
                 if ln.strip().startswith("{")]
        record = json.loads(lines[-1])
    # Driver-style bench artifacts wrap the record ({"parsed": {...}}).
    if "parsed" in record and isinstance(record["parsed"], dict):
        record = record["parsed"]
    ok, msgs = check_record(record, load_budget(budget_path))
    for m in msgs:
        print(m)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
