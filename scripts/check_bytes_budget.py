#!/usr/bin/env python
"""HBM bytes-per-image regression gate for the training step.

Compares a bench.py JSON record against the checked-in budget
(docs/bytes_budget.json) and exits nonzero when
``xla_bytes_accessed_per_image`` (or any budgeted breakdown category)
regresses more than the budget's tolerance on this device kind.

Usage:
    python bench.py | python scripts/check_bytes_budget.py -
    python scripts/check_bytes_budget.py BENCH_r05.json
    python bench.py --enforce-budget          # same gate, in-process

Budget file semantics (docs/bytes_budget.json):

- ``budgets`` maps a device-kind substring (matched case-insensitively
  against the record's ``device_kind``) to its accepted measurement:
  ``xla_bytes_accessed_per_image`` (bytes) and optionally
  ``breakdown`` ({category: bytes} from ``bytes_per_image_breakdown``;
  keys starting with ``_`` are annotations, not categories). Budgeted
  categories make a regression ATTRIBUTABLE, not just detectable —
  the verdict names the category that moved.
- The gate FAILS when measured > budget * (1 + tolerance_pct/100).
  The budget is the ACCEPTED bytes number for the CURRENT tree, not
  an aspiration: a PR that improves bytes/image ratchets the budget
  down (and bumps the entry's ``as_of_round``) in the same change.
  ``as_of_round`` is metadata for the artifact-drift test in
  tests/test_hbm_bytes.py (BENCH_rN measures the tree after PR N-1,
  so only artifacts with N > as_of_round are gated against this
  entry); this script gates whatever record it is handed.
- A device kind with no budget entry passes with a note (the CPU
  backend's fusion behavior is not byte-comparable to TPU's, so no
  CPU budget is checked in). A budgeted category missing from the
  record's breakdown (or a record with no breakdown at all) passes
  with a note — the gate catches regressions, not plumbing gaps.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGET = os.path.join(REPO, "docs", "bytes_budget.json")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _gate_cli import find_budget, load_record_argv  # noqa: E402


def load_budget(path: str = DEFAULT_BUDGET) -> Dict:
    with open(path) as fp:
        return json.load(fp)


def check_record(record: Dict, budget: Dict) -> Tuple[bool, List[str]]:
    """-> (ok, messages). ok is False only on a real regression; a
    missing budget entry or missing measurement passes with a note
    (a broken measurement already shows as null in the bench JSON —
    the gate's job is catching byte REGRESSIONS, not re-checking the
    bench's plumbing)."""
    tol = float(budget.get("tolerance_pct", 5.0)) / 100.0
    key, entry = find_budget(budget.get("budgets", {}),
                             record.get("device_kind", ""))
    if entry is None:
        return True, [f"no bytes budget for device kind "
                      f"{record.get('device_kind')!r}; nothing to enforce"]
    msgs, ok = [], True

    def gate(name: str, measured, budgeted) -> None:
        nonlocal ok
        if budgeted is None:
            return
        if measured is None:
            msgs.append(f"{name}: no measurement in record (budget "
                        f"{budgeted:.0f}); skipping")
            return
        limit = budgeted * (1.0 + tol)
        verdict = "OK" if measured <= limit else "REGRESSION"
        msgs.append(
            f"{name}: measured {measured / 1e6:.1f} MB vs budget "
            f"{budgeted / 1e6:.1f} MB (+{100 * tol:.0f}% tolerance -> "
            f"limit {limit / 1e6:.1f} MB) [{verdict}]")
        if measured > limit:
            ok = False

    gate(f"{key}: xla_bytes_accessed_per_image",
         record.get("xla_bytes_accessed_per_image"),
         entry.get("xla_bytes_accessed_per_image"))
    bd = record.get("bytes_per_image_breakdown") or {}
    cats = {cat: budgeted
            for cat, budgeted in (entry.get("breakdown") or {}).items()
            if not cat.startswith("_")}   # "_"-keys are annotations
    if cats and not bd:
        msgs.append(f"{key}: record carries no bytes_per_image_breakdown; "
                    f"skipping {len(cats)} category budgets")
    else:
        for cat, budgeted in cats.items():
            gate(f"{key}: breakdown[{cat}]", bd.get(cat), budgeted)
    return ok, msgs


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    loaded = load_record_argv(argv, DEFAULT_BUDGET)
    if isinstance(loaded, int):
        return loaded
    record, budget_path = loaded
    ok, msgs = check_record(record, load_budget(budget_path))
    for m in msgs:
        print(m)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
