#!/usr/bin/env python
"""Schema-conformance check: no record field leaves the code
undocumented.

docs/metrics_schema.md is the contract between the trainer/server/
aggregator and every consumer — but nothing used to enforce it, and
fields drifted in silently (the PR-3 obs_serve kind shipped fields the
doc didn't know). This script closes the loop from the emitting side:
it drives every obs / serve / agg record-emission path against an
in-memory sink (no run, no devices — CPU jax only), then asserts that
every emitted ``kind`` and every top-level field is documented in the
schema file. The check is one-directional on purpose: the doc may
describe more than one run emits (fields are often conditional), but
the code may never emit what the doc doesn't describe.

Run standalone (exit 1 on drift, listing the offenders), or through
the non-slow ``tests/test_schema_conformance.py``.
"""

from __future__ import annotations

import os
import re
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "..",
                           "docs", "metrics_schema.md")

# The kind assigned to records with no "kind" field (plain epoch rows).
PLAIN = "<plain>"


# ---------------------------------------------------------------------------
# doc side: parse documented kinds and field names
# ---------------------------------------------------------------------------


def _expand_braces(text: str):
    """``ttft_{p50,p90}_s`` -> ttft_p50_s, ttft_p90_s (one level)."""
    m = re.search(r"\{([^{}]*)\}", text)
    if not m:
        yield text
        return
    for alt in m.group(1).split(","):
        yield from _expand_braces(text[:m.start()] + alt.strip()
                                  + text[m.end():])


def _span_tokens(span: str):
    """Field-name tokens inside one backticked span."""
    for expanded in _expand_braces(span):
        for tok in re.findall(r"[A-Za-z_][A-Za-z0-9_]*", expanded):
            yield tok


def parse_schema(path: str = SCHEMA_PATH):
    """-> (kinds, fields_by_kind, global_fields). Field sets are the
    union of identifier tokens in the section's code spans — a
    deliberate superset (prose code spans add stray tokens), since the
    check only runs emitted ⊆ documented."""
    with open(path) as f:
        lines = f.read().splitlines()
    kinds: set = set()
    fields: dict = {}
    global_fields: set = set()
    current = None          # a kind, "GLOBAL", or None
    for line in lines:
        if line.startswith("## "):
            current = None
            m = re.match(r"##\s+`([a-z_]+)`", line)
            if m:
                current = m.group(1)
                kinds.add(current)
                fields.setdefault(current, set())
            elif "Plain epoch record" in line:
                current = PLAIN
                kinds.add(PLAIN)
                fields.setdefault(PLAIN, set())
            elif "Run identity" in line:
                # Identity fields are stamped on EVERY kind.
                current = "GLOBAL"
            continue
        if current is None:
            continue
        dest = global_fields if current == "GLOBAL" else fields[current]
        for span in re.findall(r"`([^`]+)`", line):
            dest.update(_span_tokens(span))
    return kinds, fields, global_fields


# ---------------------------------------------------------------------------
# code side: drive every emission path into a MemorySink
# ---------------------------------------------------------------------------


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def collect_obs_records(tmpdir: str) -> list:
    """obs_epoch / obs_step / obs_alert (every watchdog reason) via
    the real Observability facade."""
    import dataclasses

    from tpunet.config import ObsConfig
    from tpunet.obs import Observability
    from tpunet.obs.health import Watchdog
    from tpunet.obs.registry import MemorySink

    cfg = ObsConfig(step_records_every=1)
    obs = Observability(cfg, checkpoint_dir=tmpdir)
    sink = MemorySink()
    obs.add_sink(sink)
    obs.set_flops_per_unit(1e6)
    obs.begin_epoch(1)
    for step in range(1, 4):
        obs.observe_data_wait(0.002)
        obs.observe_step(step, 0.01 + 0.001 * step)
        obs.observe_loss(step, 1.0)
    obs.registry.counter("ckpt_saves").inc()
    obs.registry.counter("ckpt_wait_s").inc(0.5)
    obs.end_epoch(epoch=1, step=3, units=300.0, train_seconds=0.05,
                  eval_seconds=0.01, partial=True)
    obs.close()

    # Watchdog: drive every alert reason with an injected clock.
    clock = _FakeClock()
    wcfg = dataclasses.replace(
        cfg, stall_factor=2.0, stall_min_s=0.0, loss_spike_factor=2.0,
        heartbeat_timeout_s=10.0, alert_cooldown_steps=0,
        gauge_rules=("some_gauge > 1", "some_gauge + 0.1/s"))
    from tpunet.obs.registry import Registry
    reg = Registry()
    reg.set_identity(run_id="check", process_index=0, host="h")
    reg.add_sink(sink)
    wd = Watchdog(wcfg, reg, expected_processes=2, clock=clock)
    for i in range(Watchdog.MIN_BASELINE):
        wd.observe_step(i, 0.01)
    wd.observe_step(20, 1.0)                      # step_stall
    wd.observe_loss(21, float("nan"))             # nan_loss
    for i in range(Watchdog.MIN_LOSS_OBS + 1):
        wd.observe_loss(22 + i, 1.0)
    wd.observe_loss(40, 100.0)                    # loss_spike
    clock.t += 100.0
    wd.check_heartbeat(step=41)                   # stale_heartbeat
    wd.observe_heartbeat(live=1, step=42)         # missing_processes
    reg.gauge("some_gauge").set(5.0)
    wd.check_gauges(43, reg.snapshot())           # threshold rule
    for i in range(4):                            # growth rule
        reg.gauge("some_gauge").set(5.0 + i)
        clock.t += 1.0
        wd.check_gauges(44 + i, reg.snapshot())
    # thread_stalled: a registered host thread busy past its budget.
    from tpunet.obs.flightrec.threads import THREADS
    handle = THREADS.register("schema-check", stall_after_s=1.0,
                              clock=clock)
    try:
        handle.beat("busy")
        clock.t += 10.0
        wd.check_threads(50)
    finally:
        THREADS.unregister("schema-check")
    return sink.records


def collect_crash_records(tmpdir: str) -> list:
    """obs_crash via the real path: a flightrec artifact dir is
    assembled into a report, detected as a prior crash, and emitted."""
    from tpunet.obs import flightrec
    from tpunet.obs.flightrec import report as frreport
    from tpunet.obs.registry import MemorySink, Registry

    rec = flightrec.FlightRecorder(tmpdir, watcher=False, native=False)
    rec.install()
    rec.record("span", "step 1")
    rec.refresh_threads()
    frreport.write_report(rec.directory)
    rep, path = flightrec.prior_crash_report(tmpdir)
    # Close NOW (restores faulthandler, releases the stacks file):
    # the recorder must not outlive the tmpdir it points into.
    rec.close()
    assert rep is not None
    reg = Registry()
    reg.set_identity(run_id="crash-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    reg.emit("obs_crash", flightrec.crash_record(rep, path))
    return sink.records


def collect_serve_records() -> list:
    """obs_serve via the factored record builder (no engine/model
    needed — the builder IS the record shape). The prefix-KV-cache
    instruments are driven through the REAL host-side cache (lookup
    miss -> insert -> hit -> pin/unpin -> evict), not hand-set, so a
    renamed instrument fails here before it drifts from the doc."""
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.serve.engine import build_serve_record
    from tpunet.serve.prefixcache import PrefixCache, chain_digests

    reg = Registry()
    reg.set_identity(run_id="serve-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    for name in ("serve_requests_total", "serve_requests_completed",
                 "serve_requests_rejected", "serve_tokens_total",
                 "serve_decode_steps_total", "serve_prefills_total"):
        reg.counter(name).inc(3)
    for name in ("serve_ttft_s", "serve_token_s", "serve_e2e_s",
                 "serve_prefill_s"):
        for i in range(5):
            reg.histogram(name).observe(0.01 * (i + 1))
    cache = PrefixCache(page_tokens=4, capacity=4, registry=reg)
    toks = list(range(8))
    assert cache.lookup(toks, 2) == []            # miss
    d0, d1 = chain_digests(toks, 4, 2)
    n0 = cache.insert(d0, None, 0, 1)
    n1 = cache.insert(d1, n0, 1, 2)
    chain = cache.lookup(toks, 2)                 # hit, 2 pages
    assert [n.page for n in chain] == [1, 2]
    cache.pin(chain)
    cache.unpin(chain)
    assert cache.evict_one() == 2                 # leaf-first
    # engine-side counters of the same family (COW copies, shared-FS
    # spill/warm-start) — incremented exactly as the engine does
    for name in ("serve_prefix_cow_total", "serve_prefix_spills_total",
                 "serve_prefix_warm_loads_total"):
        reg.counter(name).inc()
    record = build_serve_record(
        reg, queue_depth=1, active_slots=2, slots=4,
        uptime_s=12.0, window_s=3.0, final=True)
    assert record["prefix_hit_rate"] > 0
    reg.emit("obs_serve", record)
    return sink.records


def collect_spec_serve_records() -> list:
    """obs_serve from a REAL speculative-decoding engine: the
    serve_spec_* instruments only exist when the drafter path runs,
    so a tiny spec engine (2 slots, K=2 self-speculation) decodes one
    request end-to-end and its registry builds the record — a renamed
    spec instrument fails here before it drifts from the doc."""
    import jax
    import numpy as np

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.serve import Engine
    from tpunet.serve.engine import build_serve_record

    cfg = ModelConfig(name="lm", vit_hidden=16, vit_depth=1,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=17, max_seq_len=32)
    model = create_model(cfg)
    variables = init_variables(model, jax.random.PRNGKey(0),
                               seq_len=8)
    reg = Registry()
    reg.set_identity(run_id="spec-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    eng = Engine(model, variables, ServeConfig(
        slots=2, queue_max=4, prefill_buckets=(8,), emit_every_s=0.0,
        spec_decode=True, spec_k=2, spec_draft_width_mult=1.0),
        registry=reg).start()
    try:
        eng.submit(np.arange(4, dtype=np.int32),
                   max_new_tokens=6).result(timeout=120)
    finally:
        eng.stop()
    record = build_serve_record(
        reg, queue_depth=0, active_slots=0, slots=2,
        uptime_s=1.0, window_s=1.0, final=True)
    assert record["spec_draft_tokens_total"] > 0
    assert record["spec_verify_steps_total"] > 0
    assert record["spec_acceptance_rate"] == 1.0  # self-speculation
    reg.emit("obs_serve", record)
    return sink.records


def collect_regression_records() -> list:
    """obs_regression via the real path: two synthetic record streams
    summarized by the history store and compared (quantile rows with
    DKW bounds, scalar rows with tolerance, alert/crash carryover)."""
    from tpunet.obs.history import (compare_summaries, emit_regression,
                                    summarize_run)
    from tpunet.obs.registry import MemorySink, Registry

    def stream(run_id, base, thr):
        records = []
        for ep in range(1, 4):
            records.append({
                "kind": "obs_epoch", "run_id": run_id,
                "config_fingerprint": "fp0", "host": "h", "epoch": ep,
                "step": 10 * ep, "steps": 10,
                "step_time_mean_s": base, "step_time_p50_s": base,
                "step_time_sample": [base + 0.0001 * i
                                     for i in range(16)],
                "tokens_per_sec": thr, "mfu": 0.4,
                "live_processes": 1,
            })
        records.append({
            "kind": "obs_serve", "run_id": run_id,
            "config_fingerprint": "fp0", "uptime_s": 9.0,
            "window_s": 3.0, "queue_depth": 0, "active_slots": 1,
            "slots": 4, "requests_total": 8, "ttft_count": 8,
            "ttft_sample": [base + 0.001 * i for i in range(8)],
            "e2e_count": 8,
            "e2e_sample": [base * 10 + 0.01 * i for i in range(8)],
        })
        records.append({"kind": "obs_alert", "run_id": run_id,
                        "reason": "step_stall", "step": 5,
                        "severity": "warn"})
        return records

    a = summarize_run(stream("run-a", 0.010, 100.0))
    b = summarize_run(stream("run-b", 0.030, 60.0))
    comparison = compare_summaries(a, b)
    reg = Registry()
    reg.set_identity(run_id="compare-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    emit_regression(reg, comparison)
    return sink.records


def collect_elastic_records(tmpdir: str) -> list:
    """obs_elastic via both real emission paths: the agent-side
    append (identity from the run dir, one jsonl line) and the
    trainer-side registry emit — plus the checkpointer's
    ckpt_io_retry obs_alert."""
    import os

    from tpunet.ckpt.orbax_io import emit_io_retry_alert
    from tpunet.elastic import events
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.utils.logging import MetricsLogger

    run_dir = os.path.join(tmpdir, "run")
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, "run_id"), "w") as f:
        f.write("elastic-check\n")
    records = []
    records.append(events.append_elastic_record(
        run_dir, events.build_elastic_record(
            "shrink", cause="host_lost", generation=3, old_world=2,
            new_world=1, hosts=["h0"], lost=["h1"], step=40,
            recovery_s=2.345)))
    records.append(events.append_elastic_record(
        run_dir, events.build_elastic_record(
            "quorum_failed", cause="0 hosts announced", generation=4,
            old_world=1)))
    # The agent-side lines really are metrics.jsonl lines.
    assert MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    reg = Registry()
    reg.set_identity(run_id="elastic-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    reg.emit("obs_elastic", events.build_elastic_record(
        "recovered", generation=3, new_world=1,
        old_mesh={"data": 2, "seq": 1, "pipe": 1, "model": 1},
        new_mesh={"data": 1, "seq": 1, "pipe": 1, "model": 1},
        epoch=2, step=40))
    reg.emit("obs_elastic", events.build_elastic_record(
        "evict_requested", cause="step_stall", epoch=2, step=37,
        detail={"reason": "step_stall", "step_time_s": 1.2}))
    emit_io_retry_alert(reg, what="save",
                        error="chaos: injected transient save IO "
                              "error", max_retries=3, backoff_s=0.1)
    return records + sink.records


def collect_router_records() -> list:
    """obs_router via the factored builders (no replicas needed — the
    builders ARE the record shapes): one window record with live
    counters/histograms + per-replica rows, plus every event flavor
    the control loop emits."""
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.router.records import (build_router_event,
                                       build_router_record)

    reg = Registry()
    reg.set_identity(run_id="router-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    for name in ("requests", "rerouted", "rejected", "affinity_hits",
                 "failovers", "evictions", "respawns", "scale_ups",
                 "scale_downs", "probe_failures"):
        reg.counter(f"router_{name}_total").inc(2)
    for i in range(5):
        reg.histogram("router_e2e_s").observe(0.02 * (i + 1))
    replicas = [
        {"name": "r0", "url": "http://127.0.0.1:8000",
         "state": "healthy", "run_id": "router-replica-0", "slots": 8,
         "queue_depth": 1, "active_slots": 2,
         "serve_requests_total": 9, "requests_routed": 5,
         "requests_failed": 0, "fail_streak": 0},
        {"name": "r1", "url": "http://127.0.0.1:8001",
         "state": "dead", "run_id": "router-replica-1", "slots": 8,
         "queue_depth": 0, "active_slots": 0,
         "serve_requests_total": 4, "requests_routed": 4,
         "requests_failed": 1, "fail_streak": 3},
    ]
    record = build_router_record(
        reg, replicas=replicas, uptime_s=30.0, window_s=10.0,
        scale_decision="scale_up", ttft_slo_burn=1.25, final=True)
    reg.emit("obs_router", record)
    reg.emit("obs_router", build_router_event(
        "evict", replica="r1", url="http://127.0.0.1:8001",
        cause="webhook:straggler",
        detail={"kind": "obs_alert", "reason": "straggler"}))
    reg.emit("obs_router", build_router_event(
        "respawn", replica="r1", url="http://127.0.0.1:8002",
        cause="evicted"))
    reg.emit("obs_router", build_router_event(
        "scale_up", cause="policy", old_replicas=2, new_replicas=3))
    reg.emit("obs_router", build_router_event(
        "scale_down", replica="r0", cause="policy", old_replicas=3,
        new_replicas=2))
    reg.emit("obs_router", build_router_event(
        "failover", replica="r0", url="http://127.0.0.1:8000",
        cause="replica_failed_mid_stream",
        detail={"tokens_relayed": 5}))
    return sink.records


def collect_trace_records() -> list:
    """obs_trace via the factored builder (no router/engine needed —
    the builder IS the record shape): one router-role span with the
    failover seam fields and one replica-role span with the full
    phase decomposition, both fed through ``observe_trace`` so the
    ``trace_*`` instruments exercise their real names."""
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.obs.tracing import build_trace_record, observe_trace

    reg = Registry()
    reg.set_identity(run_id="trace-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    router_rec = build_trace_record(
        trace_id="0123456789abcdef", hop=0, role="router",
        finish_reason="length", tokens=24, failover_count=1,
        tokens_relayed=12, e2e_s=0.9, error="")
    replica_rec = build_trace_record(
        trace_id="0123456789abcdef", hop=2, role="replica",
        finish_reason="length", queue_s=0.01, prefill_s=0.04,
        prefill_bucket=64, first_decode_s=0.002, tokens=12,
        preemptions=1, preempt_wall_s=0.05, resume_offset=12,
        ttft_s=0.06, e2e_s=0.5,
        error="replica failed mid-stream")
    for rec in (router_rec, replica_rec):
        observe_trace(reg, rec)
        reg.emit("obs_trace", rec)
    return sink.records


def collect_slo_records() -> list:
    """obs_slo + the slo_fast_burn / slo_slow_burn obs_alert flavors
    via the real engine (tpunet/obs/slo.py): the default policy is
    loaded, the availability stream is burned hard enough to fire the
    fast-burn page, a probe mismatch carries a trace id into the
    correctness page, and ``evaluate()`` records are emitted exactly
    the way the router control loop emits them."""
    from tpunet.obs.registry import MemorySink, Registry
    from tpunet.obs.slo import SloEngine, load_policy

    clock = _FakeClock()
    reg = Registry()
    reg.set_identity(run_id="slo-check", process_index=0, host="h")
    sink = MemorySink()
    reg.add_sink(sink)
    engine = SloEngine(load_policy(), registry=reg, clock=clock)
    for i in range(40):                     # healthy baseline
        engine.note_request(True)
        engine.note_latency("ttft", 0.01)
        engine.note_latency("e2e", 0.1)
        clock.t += 1.0
    for _ in range(40):                     # sustained burn -> page
        engine.note_request(False)
        clock.t += 1.0
        engine.evaluate()
    engine.note_probe(ok=True, mismatch=True, ttft_s=0.02, e2e_s=0.2,
                      trace_id="0123456789abcdef")   # correctness page
    engine.evaluate()
    for rec in engine.evaluate():           # the control-loop emission
        reg.emit("obs_slo", rec)
    return sink.records


def collect_agg_records() -> list:
    """obs_fleet + every fleet obs_alert reason via a two-stream
    aggregator (one straggling, one leaking, both serving)."""
    from tpunet.obs.agg import Aggregator
    from tpunet.obs.registry import MemorySink

    clock = _FakeClock()
    agg = Aggregator(clock=clock, straggler_factor=1.5,
                     stream_stale_s=5.0,
                     mem_growth_bytes_per_epoch=1.0,
                     rules=("serve_queue_depth > 0",
                            "step_time_p50_s + 1e-9/s"))
    sink = MemorySink()
    agg.registry.add_sink(sink)
    for name, base in (("a", 0.01), ("b", 0.05)):
        for ep in range(1, 5):
            sample = [base + 0.0001 * i for i in range(16)]
            agg.ingest({
                "kind": "obs_epoch", "run_id": name,
                "process_index": 0, "host": name, "epoch": ep,
                "step": 10 * ep, "steps": 16,
                "step_time_mean_s": base, "step_time_p50_s": base,
                "step_time_sample": sample, "tokens_per_sec": 100.0,
                "mfu": 0.4, "live_processes": 1,
                "device_memory": [
                    {"device": 0,
                     "peak_bytes_in_use": 2 ** 20 + ep * 100}],
            })
            for s in range(10 * ep - 2, 10 * ep):
                agg.ingest({"kind": "obs_step", "run_id": name,
                            "process_index": 0, "step": s,
                            "step_time_s": base})
        agg.ingest({
            "kind": "obs_serve", "run_id": f"serve-{name}",
            "process_index": 0, "host": name, "uptime_s": 9.0,
            "window_s": 3.0, "queue_depth": 2, "active_slots": 1,
            "slots": 4, "requests_total": 10, "requests_completed": 8,
            "requests_rejected": 1, "tokens_total": 100,
            "ttft_count": 8, "ttft_p50_s": 0.05,
            "ttft_sample": [0.04 + 0.001 * i for i in range(8)],
            "e2e_count": 8, "e2e_p99_s": 0.9,
            "e2e_sample": [0.8 + 0.01 * i for i in range(8)],
        })
        agg.ingest({"kind": "obs_alert", "run_id": name,
                    "process_index": 0, "reason": "step_stall",
                    "step": 5, "severity": "warn"})
    agg.ingest({"kind": "obs_crash", "run_id": "a",
                "process_index": 0, "cause": "SIGSEGV", "signal": 11,
                "report_path": "/tmp/x.json", "crashed_pid": 1,
                "events": 3, "stack_threads": 2, "native_ops": 5,
                "assembled_t": 1.0})      # crash alert + crashes_total
    agg.ingest({"kind": "obs_elastic", "run_id": "a",
                "process_index": 0, "event": "shrink",
                "severity": "warn", "cause": "host_lost",
                "generation": 2, "old_world": 2, "new_world": 1,
                "time": 1234.5})          # elastic_* rollup fields
    agg.ingest({"kind": "obs_router", "run_id": "router-a",
                "process_index": 0, "uptime_s": 30.0, "window_s": 10.0,
                "replicas": 2, "replicas_healthy": 1,
                "replicas_draining": 0, "replicas_dead": 1,
                "fleet_queue_depth": 3, "fleet_active_slots": 2,
                "fleet_slots": 16, "requests_total": 9,
                "rerouted_total": 1, "rejected_total": 0,
                "affinity_hits_total": 4, "evictions_total": 1,
                "respawns_total": 1, "scale_ups_total": 0,
                "scale_downs_total": 0, "probe_failures_total": 3,
                "scale_decision": "hold",
                "per_replica": []})       # router_* rollup fields
    agg.ingest({"kind": "obs_router", "run_id": "router-a",
                "process_index": 0, "event": "evict", "replica": "r1",
                "severity": "warn", "cause": "probe_failures",
                "time": 1234.6})          # router_last_event
    agg.ingest({"kind": "obs_trace", "run_id": "router-a",
                "process_index": 0, "trace_id": "0123456789abcdef",
                "hop": 0, "role": "router", "finish_reason": "length",
                "tokens": 24, "failover_count": 1,
                "tokens_relayed": 12, "e2e_s": 0.9})
    agg.ingest({"kind": "obs_trace", "run_id": "serve-a",
                "process_index": 0, "trace_id": "0123456789abcdef",
                "hop": 1, "role": "replica", "finish_reason": "length",
                "queue_s": 0.01, "prefill_s": 0.04, "prefill_bucket": 64,
                "first_decode_s": 0.002, "tokens": 12, "ttft_s": 0.06,
                "e2e_s": 0.5})            # trace_* rollup fields
    agg.ingest({"kind": "obs_slo", "run_id": "router-a",
                "process_index": 0, "name": "availability",
                "sli": "availability", "objective": 0.999,
                "compliance_window_s": 3600.0, "events": 120,
                "bad": 3, "error_rate": 0.025,
                "budget_remaining": 0.4, "page_burn_long": 25.0,
                "page_burn_short": 30.0, "page_burn_threshold": 14.4,
                "page_window_long_s": 300.0,
                "page_window_short_s": 36.0, "page_firing": 1,
                "ticket_burn_long": 25.0, "ticket_burn_short": 25.0,
                "ticket_burn_threshold": 3.0,
                "ticket_window_long_s": 3600.0,
                "ticket_window_short_s": 300.0, "pages_total": 1,
                "tickets_total": 1, "probe_requests": 40,
                "probe_failures": 3, "probe_mismatches": 1,
                "last_failed_trace": "0123456789abcdef"
                })                        # fleet_slo_* rollup fields
    agg.emit_rollup()           # straggler + mem_growth + rules + crash
    clock.t += 100.0
    agg.emit_rollup()           # stream_stale for every stream
    return sink.records


# ---------------------------------------------------------------------------


def undocumented(records, kinds, fields, global_fields) -> list:
    bad = set()
    for r in records:
        kind = r.get("kind", PLAIN)
        if kind not in kinds:
            bad.add((kind, "<kind undocumented>"))
            continue
        allowed = fields[kind] | global_fields | {"kind"}
        for f in r:
            if f not in allowed:
                bad.add((kind, f))
    return sorted(bad)


def main() -> int:
    import tempfile

    kinds, fields, global_fields = parse_schema()
    records = []
    with tempfile.TemporaryDirectory() as tmp:
        records += collect_obs_records(tmp)
    with tempfile.TemporaryDirectory() as tmp:
        records += collect_crash_records(tmp)
    records += collect_serve_records()
    records += collect_spec_serve_records()
    records += collect_router_records()
    records += collect_trace_records()
    records += collect_slo_records()
    records += collect_agg_records()
    records += collect_regression_records()
    with tempfile.TemporaryDirectory() as tmp:
        records += collect_elastic_records(tmp)
    emitted_kinds = sorted({r.get("kind", PLAIN) for r in records})
    bad = undocumented(records, kinds, fields, global_fields)
    if bad:
        print("schema drift: emitted but not documented in "
              "docs/metrics_schema.md:", file=sys.stderr)
        for kind, field in bad:
            print(f"  kind={kind!r:<14} field={field!r}",
                  file=sys.stderr)
        return 1
    print(f"schema OK: {len(records)} records across kinds "
          f"{emitted_kinds} all documented")
    return 0


if __name__ == "__main__":
    sys.exit(main())
