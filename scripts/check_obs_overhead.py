#!/usr/bin/env python
"""Tier-2 micro-benchmark: the default-config observability path must
be within noise of a fully disabled one.

The obs design promise (tpunet/obs/__init__.py) is that the default
path adds no device syncs and only host-side ``perf_counter`` laps per
step; this drives the same tiny-LM step loop both ways and fails if
the instrumented loop is measurably slower. Since the flight recorder
(tpunet/obs/flightrec/) is default-ON, a third variant isolates it:
``default`` (recorder on) vs ``no-flightrec`` (same obs config,
recorder off) is the recorder's own A/B — its design budget is well
under the subsystem's 0.5% measured overhead bar (two mmap writes per
span, no syscalls on the step path). A serve-path variant drives one
compiled engine with request tracing off vs on at the router's
default head sampling (tpunet/obs/tracing.py) under the same bar.
A speculative-decoding variant holds the serve_spec_* counter and
gauge updates that ride every verify cycle to the same bar (null
registry vs live registry on identical self-speculation engines).
A prober-armed variant re-runs the paying burst with the SLO
machinery live (tpunet/obs/slo.py): every completion feeds the
default-policy ``SloEngine`` and a synthetic canary stream shares
the slot pool on the prober's cadence — paying traffic must stay
inside the same bar (probing is designed load, not overhead).
Standalone (not collected by pytest) so tier-1 wall time is
unaffected:

    JAX_PLATFORMS=cpu python scripts/check_obs_overhead.py
"""

from __future__ import annotations

import os
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# Generous threshold: CPU step times here are a few ms, where scheduler
# jitter dominates; a real regression (a per-step device sync or record
# write) shows up as 2x+, not 20%.
MAX_RATIO = 1.20
EPOCHS_MEASURED = 5


def build_trainer(obs_enabled: bool, workdir: str,
                  flightrec: bool = True, webhook: str = ""):
    from tpunet.config import (CheckpointConfig, DataConfig,
                               ExportConfig, MeshConfig, ModelConfig,
                               ObsConfig, OptimConfig, TrainConfig)
    from tpunet.train.loop import Trainer

    cfg = TrainConfig(
        epochs=EPOCHS_MEASURED + 1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=256, synthetic_test_size=16,
                        seq_len=64, vocab_size=32, native_loader=False),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0, dtype="float32",
                          vocab_size=32, max_seq_len=64),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=workdir, save_best=False,
                                    save_last=False),
        obs=ObsConfig(enabled=obs_enabled, flightrec=flightrec,
                      export=ExportConfig(webhook=webhook)),
    )
    return Trainer(cfg)


def time_epochs(trainer) -> list:
    # Epoch 1 compiles; measure the rest.
    trainer.train_one_epoch(1)
    times = []
    for epoch in range(2, 2 + EPOCHS_MEASURED):
        t0 = time.perf_counter()
        trainer.train_one_epoch(epoch)
        times.append(time.perf_counter() - t0)
    return times


SERVE_ROUNDS = 7
SERVE_REQS = 32


def serve_trace_ratio() -> float:
    """Serve-path A/B on ONE compiled engine: a burst of requests with
    tracing fully off vs tracing on at the router's default head
    sampling (1%, tpunet/obs/tracing.py). At default sampling the
    per-request cost on the untraced path is an empty-``trace_id``
    check per phase — it must stay inside the same bar as the training
    path."""
    import jax
    import numpy as np

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables
    from tpunet.obs import tracing
    from tpunet.serve import Engine

    model_cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                            vit_heads=2, dropout_rate=0.0,
                            dtype="float32", vocab_size=31,
                            max_seq_len=48)
    model = create_model(model_cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    eng = Engine(model, variables,
                 ServeConfig(slots=4, queue_max=2 * SERVE_REQS,
                             prefill_buckets=(8, 16),
                             default_max_new_tokens=6,
                             emit_every_s=0.0)).start()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 31, size=6).astype(np.int32)
               for _ in range(SERVE_REQS)]

    def burst(traced: bool) -> None:
        reqs = []
        for p in prompts:
            tid = ""
            if traced:
                t = tracing.mint_trace_id()
                if tracing.should_sample(0.01, t):
                    tid = t
            reqs.append(eng.submit(p, trace_id=tid))
        for r in reqs:
            r.result(timeout=120)

    try:
        burst(False)          # compile warmup, shared by both arms
        burst(True)
        off_t, on_t = [], []
        for _ in range(SERVE_ROUNDS):   # interleaved: jitter is fair
            t0 = time.perf_counter()
            burst(False)
            off_t.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            burst(True)
            on_t.append(time.perf_counter() - t0)
    finally:
        eng.stop()
    off = statistics.median(off_t)
    on = statistics.median(on_t)
    print(f"serve burst median: trace-off {off * 1e3:.1f}ms, "
          f"trace-default-sampling {on * 1e3:.1f}ms")
    return on / off if off > 0 else float("inf")


def serve_spec_obs_ratio() -> float:
    """Spec-decode obs A/B: the serve_spec_* counters and the
    acceptance-rate gauge ride EVERY verify cycle (engine.py
    ``_spec_burst``), so the same compiled speculative engine
    config is driven twice — once with a null registry that
    swallows every instrument update, once with the real one —
    and the paying burst must stay inside the same bar. The
    drafter is self-speculation (``width_mult`` 1.0, zero fit
    steps), which keeps the A/B about the obs path rather than
    drafter quality: acceptance is 1.0 either way, so both arms
    run an identical accept/emit schedule."""
    import jax
    import numpy as np

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables
    from tpunet.obs.registry import Registry
    from tpunet.serve import Engine

    class _NullInstrument:
        value = 0.0

        def inc(self, n=1):
            pass

        def set(self, v):
            pass

        def observe(self, v):
            pass

        def summary(self):
            return {}

        def export_sample(self):
            return []

    class _NullRegistry(Registry):
        _null = _NullInstrument()

        def counter(self, name):
            return self._null

        def gauge(self, name):
            return self._null

        def histogram(self, name, **kw):
            return self._null

    model_cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                            vit_heads=2, dropout_rate=0.0,
                            dtype="float32", vocab_size=31,
                            max_seq_len=48)
    model = create_model(model_cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 31, size=6).astype(np.int32)
               for _ in range(SERVE_REQS)]

    def make(reg) -> "Engine":
        return Engine(model, variables,
                      ServeConfig(slots=4, queue_max=2 * SERVE_REQS,
                                  prefill_buckets=(8, 16),
                                  default_max_new_tokens=6,
                                  emit_every_s=0.0,
                                  spec_decode=True, spec_k=3,
                                  spec_draft_width_mult=1.0),
                      registry=reg).start()

    def burst(eng) -> None:
        reqs = [eng.submit(p) for p in prompts]
        for r in reqs:
            r.result(timeout=120)

    eng_null = make(_NullRegistry())
    eng_real = make(Registry())
    try:
        burst(eng_null)       # compile warmup, one per arm
        burst(eng_real)
        off_t, on_t = [], []
        for _ in range(SERVE_ROUNDS):   # interleaved: jitter is fair
            t0 = time.perf_counter()
            burst(eng_null)
            off_t.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            burst(eng_real)
            on_t.append(time.perf_counter() - t0)
    finally:
        eng_null.stop()
        eng_real.stop()
    off = statistics.median(off_t)
    on = statistics.median(on_t)
    print(f"spec burst median: counters-null {off * 1e3:.1f}ms, "
          f"counters-live {on * 1e3:.1f}ms")
    return on / off if off > 0 else float("inf")


PROBE_CADENCE_S = 0.25


def serve_probe_ratio() -> float:
    """Prober-armed serve A/B on ONE compiled engine: the same paying
    burst with the SLO machinery dark vs armed. Armed means every
    completion feeds the default-policy ``SloEngine`` (a deque append
    under a lock plus a burn evaluation per probe round) while a
    synthetic canary stream — the prober's known-answer shape — shares
    the slot pool on its cadence. The bar is on the PAYING burst: the
    canary is designed load, so its cost to real traffic must stay
    within noise."""
    import threading

    import jax
    import numpy as np

    from tpunet.config import ModelConfig, ServeConfig
    from tpunet.models import create_model, init_variables
    from tpunet.obs.registry import Registry
    from tpunet.obs.slo import SloEngine, load_policy
    from tpunet.serve import Engine

    model_cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                            vit_heads=2, dropout_rate=0.0,
                            dtype="float32", vocab_size=31,
                            max_seq_len=48)
    model = create_model(model_cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    eng = Engine(model, variables,
                 ServeConfig(slots=4, queue_max=2 * SERVE_REQS + 8,
                             prefill_buckets=(8, 16),
                             default_max_new_tokens=6,
                             emit_every_s=0.0)).start()
    rng = np.random.default_rng(0)
    prompts = [rng.integers(0, 31, size=6).astype(np.int32)
               for _ in range(SERVE_REQS)]
    probe_prompt = np.asarray([1, 2, 3, 5, 7, 11], dtype=np.int32)
    reg = Registry()
    reg.set_identity(run_id="overhead-check", process_index=0,
                     host="h")
    slo = SloEngine(load_policy(), registry=reg)

    def burst(armed: bool) -> None:
        reqs = [eng.submit(p) for p in prompts]
        for r in reqs:
            r.result(timeout=120)
            if armed:               # the router's passive SLI feed
                slo.note_request(True)
                slo.note_latency("ttft", 0.01)
                slo.note_latency("e2e", 0.05)

    def canary(stop: threading.Event) -> None:
        while not stop.is_set():
            req = eng.submit(probe_prompt)
            try:
                req.result(timeout=120)
                slo.note_probe(ok=True, ttft_s=0.01, e2e_s=0.05)
            except Exception:       # noqa: BLE001 — probe self-judges
                slo.note_probe(ok=False)
            slo.evaluate()
            stop.wait(PROBE_CADENCE_S)

    # The timed unit is a full prober cadence of paying work (many
    # bursts), not one burst: a lone canary decode contending for a
    # slot inside a ~20ms burst would overstate probe density ~250x
    # against the 5s production cadence. One probe per cadence of
    # traffic is the designed duty cycle this bar holds.
    bursts_per_round = max(1, int(PROBE_CADENCE_S / 0.02))

    def run(armed: bool) -> None:
        for _ in range(bursts_per_round):
            burst(armed)

    try:
        burst(False)          # compile warmup, shared by both arms
        burst(True)
        off_t, on_t = [], []
        for _ in range(3):              # interleaved: jitter is fair
            t0 = time.perf_counter()
            run(False)
            off_t.append(time.perf_counter() - t0)
            stop = threading.Event()
            th = threading.Thread(target=canary, args=(stop,),
                                  daemon=True)
            th.start()
            t0 = time.perf_counter()
            run(True)
            on_t.append(time.perf_counter() - t0)
            stop.set()
            th.join(timeout=120)
    finally:
        eng.stop()
    off = statistics.median(off_t)
    on = statistics.median(on_t)
    print(f"serve cadence-round median: slo-dark {off * 1e3:.1f}ms, "
          f"prober-armed {on * 1e3:.1f}ms "
          f"({slo.probe_requests} probes interleaved)")
    return on / off if off > 0 else float("inf")


def main() -> int:
    # Fourth variant: the alert webhook configured at a dead endpoint
    # but IDLE (a healthy tiny run fires no alerts) — its default-path
    # cost is one kind-filter per emitted record, which must stay
    # inside the same bar as everything else.
    results = {}
    for label, enabled, rec, hook in (
            ("disabled", False, False, ""),
            ("no-flightrec", True, False, ""),
            ("default", True, True, ""),
            ("webhook-idle", True, True, "http://127.0.0.1:9/hook")):
        with tempfile.TemporaryDirectory() as d:
            trainer = build_trainer(enabled, d, flightrec=rec,
                                    webhook=hook)
            try:
                results[label] = time_epochs(trainer)
            finally:
                trainer.close()
    off = statistics.median(results["disabled"])
    bare = statistics.median(results["no-flightrec"])
    on = statistics.median(results["default"])
    hooked = statistics.median(results["webhook-idle"])
    ratio = on / off if off > 0 else float("inf")
    rec_ratio = on / bare if bare > 0 else float("inf")
    hook_ratio = hooked / off if off > 0 else float("inf")
    print(f"epoch median: obs-disabled {off * 1e3:.1f}ms, "
          f"obs-no-flightrec {bare * 1e3:.1f}ms, "
          f"obs-default {on * 1e3:.1f}ms, "
          f"obs-webhook-idle {hooked * 1e3:.1f}ms")
    print(f"obs-vs-disabled ratio {ratio:.3f}, flightrec-on-vs-off "
          f"ratio {rec_ratio:.3f} ({100 * (rec_ratio - 1):+.2f}%), "
          f"webhook-idle-vs-disabled ratio {hook_ratio:.3f} "
          f"(threshold {MAX_RATIO})")
    fail = False
    if ratio > MAX_RATIO:
        print("FAIL: default observability path exceeds the overhead "
              "budget", file=sys.stderr)
        fail = True
    if rec_ratio > MAX_RATIO:
        print("FAIL: the flight recorder alone exceeds the overhead "
              "budget", file=sys.stderr)
        fail = True
    if hook_ratio > MAX_RATIO:
        print("FAIL: an idle webhook sink exceeds the overhead "
              "budget", file=sys.stderr)
        fail = True
    trace_ratio = serve_trace_ratio()
    print(f"serve-trace-default-vs-off ratio {trace_ratio:.3f} "
          f"(threshold {MAX_RATIO})")
    if trace_ratio > MAX_RATIO:
        print("FAIL: request tracing at default sampling exceeds the "
              "overhead budget", file=sys.stderr)
        fail = True
    spec_ratio = serve_spec_obs_ratio()
    print(f"serve-spec-counters-live-vs-null ratio {spec_ratio:.3f} "
          f"(threshold {MAX_RATIO})")
    if spec_ratio > MAX_RATIO:
        print("FAIL: the speculative-decoding counters exceed the "
              "overhead budget", file=sys.stderr)
        fail = True
    probe_ratio = serve_probe_ratio()
    print(f"serve-prober-armed-vs-dark ratio {probe_ratio:.3f} "
          f"(threshold {MAX_RATIO})")
    if probe_ratio > MAX_RATIO:
        print("FAIL: the armed prober + SLO feed exceeds the overhead "
              "budget on paying traffic", file=sys.stderr)
        fail = True
    if fail:
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
