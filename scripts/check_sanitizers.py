#!/usr/bin/env python
"""ASan/UBSan/TSan gate for the native batcher (cxx/batcher.cc).

The 256-slot lock-free journal ring and the prefetcher's worker
lifecycle are exactly the code sanitizers exist for — the PR-7 heap
corruption burned three rounds because nothing ever ran this
extension under a memory/race detector. This gate builds sanitizer
variants of the shared library and drives scripts/_native_stress.py
(concurrent journal writers + live snapshot readers, create/stop/
destroy churn, epoch cycling, concurrent gathers) in a subprocess
with the variant loaded via ``TPUNET_NATIVE_LIB`` and the sanitizer
runtime ``LD_PRELOAD``ed — the runtime must be first in the link
order, and preloading is how you get there when the host binary
(python) is uninstrumented.

Usage:
    python scripts/check_sanitizers.py                  # asan,ubsan,tsan
    python scripts/check_sanitizers.py --variants tsan
    python scripts/check_sanitizers.py --smoke          # ubsan only, fast
    python scripts/check_sanitizers.py --strict         # skips fail too

Exit codes: 0 = every requested variant passed or SKIPped for a
missing toolchain (the skip is loud; --strict turns it into a
failure), 1 = a sanitizer reported findings (its report is in the
output), 2 = usage error. Wired into the slow suite via
tests/test_native_sanitizers.py and into scripts/run_checks.sh.
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
import tempfile
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_HERE)
_CXX_DIR = os.path.join(_REPO, "cxx")
_SRC = os.path.join(_CXX_DIR, "batcher.cc")
_LIB_DIR = os.path.join(_REPO, "tpunet", "data", "_lib")
_STRESS = os.path.join(_HERE, "_native_stress.py")

# A distinctive exit code so a sanitizer abort can't be confused with
# a python failure of the stress driver itself.
_SAN_EXITCODE = 97

VARIANTS: Dict[str, Dict[str, object]] = {
    "asan": {
        "fsanitize": "address",
        "runtime": "libasan.so",
        # detect_leaks=0: CPython "leaks" by design at interpreter
        # exit; leak noise would bury real heap-corruption reports.
        "env": {"ASAN_OPTIONS":
                f"detect_leaks=0:abort_on_error=0:"
                f"exitcode={_SAN_EXITCODE}"},
    },
    "ubsan": {
        "fsanitize": "undefined",
        "extra_flags": ["-fno-sanitize-recover=undefined"],
        "runtime": "libubsan.so",
        "env": {"UBSAN_OPTIONS": "halt_on_error=1:print_stacktrace=1"},
    },
    "tsan": {
        "fsanitize": "thread",
        "runtime": "libtsan.so",
        # report_thread_leaks=0: daemon python threads outlive main on
        # purpose (the repo's own registry tracks them).
        "env": {"TSAN_OPTIONS":
                f"report_thread_leaks=0:halt_on_error=0:"
                f"exitcode={_SAN_EXITCODE}"},
    },
}

# Fallback for make-less hosts ONLY — keep in sync with SANFLAGS in
# cxx/Makefile (the authoritative list; build_variant prefers make).
_BASE_FLAGS = ["-O1", "-g", "-fno-omit-frame-pointer", "-std=c++17",
               "-Wall", "-Werror=return-type", "-shared", "-fPIC",
               "-pthread"]


@dataclass
class VariantResult:
    variant: str
    status: str          # "PASS" | "SKIP" | "FAIL"
    detail: str = ""


def _cxx() -> str:
    return os.environ.get("CXX", "g++")


def runtime_path(variant: str) -> Optional[str]:
    """Resolve the sanitizer runtime .so for LD_PRELOAD via the
    compiler, or None when the toolchain doesn't ship it."""
    runtime = str(VARIANTS[variant]["runtime"])
    try:
        out = subprocess.run([_cxx(), f"-print-file-name={runtime}"],
                             capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    path = out.stdout.strip()
    # An unknown runtime echoes the bare name back.
    if not path or path == runtime or not os.path.exists(path):
        return None
    return os.path.abspath(path)


def toolchain_supports(variant: str) -> Tuple[bool, str]:
    """(supported, why-not): probe-compiles a trivial TU with the
    sanitizer flag and resolves the preloadable runtime."""
    if not os.path.exists(_SRC):
        return False, f"source missing: {_SRC}"
    fsan = str(VARIANTS[variant]["fsanitize"])
    with tempfile.TemporaryDirectory(prefix="tpunet-san-") as tmp:
        probe = os.path.join(tmp, "probe.cc")
        with open(probe, "w", encoding="utf-8") as f:
            f.write("int main() { return 0; }\n")
        try:
            res = subprocess.run(
                [_cxx(), f"-fsanitize={fsan}", probe, "-o",
                 os.path.join(tmp, "probe")],
                capture_output=True, text=True, timeout=60)
        except (OSError, subprocess.SubprocessError) as e:
            return False, f"compiler unavailable: {e}"
        if res.returncode != 0:
            return False, (f"{_cxx()} cannot link -fsanitize={fsan}: "
                           f"{res.stderr.strip().splitlines()[-1:]}")
    if runtime_path(variant) is None:
        return False, (f"no preloadable {VARIANTS[variant]['runtime']} "
                       f"(needed because python itself is "
                       "uninstrumented)")
    return True, ""


def build_variant(variant: str) -> Tuple[Optional[str], str]:
    """Build the sanitizer .so. The cxx/Makefile targets are the
    single source of the flag set — ``make -C cxx <variant>`` builds
    EXACTLY the binary a human reproducing a report builds; the
    direct-compile path below exists only for make-less hosts and
    mirrors SANFLAGS. Returns (path, error)."""
    os.makedirs(_LIB_DIR, exist_ok=True)
    out = os.path.join(_LIB_DIR, f"libtnbatcher_{variant}.so")
    try:
        res = subprocess.run(
            ["make", "-C", _CXX_DIR, "-B", variant],
            capture_output=True, text=True, timeout=300)
        if res.returncode == 0:
            return out, ""
        make_err: Optional[str] = res.stderr
    except OSError:
        make_err = None      # no make on this host: fall through
    except subprocess.SubprocessError as e:
        make_err = str(e)
    if make_err is not None:
        return None, f"make -C cxx {variant} failed:\n{make_err}"
    fsan = str(VARIANTS[variant]["fsanitize"])
    extra = [str(f) for f in VARIANTS[variant].get("extra_flags", [])]
    cmd = ([_cxx()] + _BASE_FLAGS + [f"-fsanitize={fsan}"] + extra
           + [_SRC, "-o", out])
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=300)
    except (OSError, subprocess.SubprocessError) as e:
        return None, f"build failed: {e}"
    if res.returncode != 0:
        return None, f"build failed:\n{res.stderr}"
    return out, ""


def run_variant(variant: str, scenarios: Sequence[str] = ("all",),
                timeout_s: float = 600.0) -> VariantResult:
    """Build one variant and run the stress driver under it."""
    supported, why = toolchain_supports(variant)
    if not supported:
        return VariantResult(variant, "SKIP", why)
    lib, err = build_variant(variant)
    if lib is None:
        return VariantResult(variant, "FAIL", err)
    runtime = runtime_path(variant)
    assert runtime is not None  # toolchain_supports checked
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)   # the driver never imports jax
    env["TPUNET_NATIVE_LIB"] = lib
    env["LD_PRELOAD"] = runtime
    env.update({k: str(v) for k, v in
                dict(VARIANTS[variant]["env"]).items()})  # type: ignore[arg-type]
    cmd = [sys.executable, _STRESS] + list(scenarios)
    try:
        res = subprocess.run(cmd, capture_output=True, text=True,
                             env=env, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return VariantResult(variant, "FAIL",
                             f"stress timed out after {timeout_s}s "
                             "(wedged worker join?)")
    except OSError as e:
        return VariantResult(variant, "FAIL", f"could not run: {e}")
    tail = "\n".join((res.stdout + "\n" + res.stderr)
                     .strip().splitlines()[-40:])
    if res.returncode == 0:
        return VariantResult(variant, "PASS", tail.splitlines()[-1]
                             if tail else "")
    label = ("sanitizer report"
             if res.returncode == _SAN_EXITCODE or res.returncode < 0
             else f"driver exit {res.returncode}")
    return VariantResult(
        variant, "FAIL",
        f"{label} (cmd: {shlex.join(cmd)})\n{tail}")


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="sanitizer gate for the native batcher "
                    "(docs/static_analysis.md, sanitizer matrix)")
    p.add_argument("--variants", default="asan,ubsan,tsan",
                   help="comma-separated subset of asan,ubsan,tsan")
    p.add_argument("--smoke", action="store_true",
                   help="fast pre-merge mode: ubsan only, churn+restart "
                        "scenarios")
    p.add_argument("--scenarios", default="all",
                   help="comma-separated stress scenarios "
                        "(gather,churn,journal,restart or 'all')")
    p.add_argument("--strict", action="store_true",
                   help="a toolchain SKIP fails the gate")
    p.add_argument("--timeout", type=float, default=600.0)
    args = p.parse_args(argv)

    if args.smoke:
        variants = ["ubsan"]
        scenarios = ["churn", "restart"]
    else:
        variants = [v.strip() for v in args.variants.split(",")
                    if v.strip()]
        scenarios = [s.strip() for s in args.scenarios.split(",")
                     if s.strip()]
    unknown = [v for v in variants if v not in VARIANTS]
    if unknown:
        print(f"check_sanitizers: unknown variant(s) {unknown}; have "
              f"{list(VARIANTS)}", file=sys.stderr)
        return 2

    results = [run_variant(v, scenarios, args.timeout)
               for v in variants]
    failed = False
    for r in results:
        print(f"[{r.status}] {r.variant}"
              + (f": {r.detail}" if r.detail else ""))
        if r.status == "FAIL":
            failed = True
        elif r.status == "SKIP":
            print(f"  NOTE: {r.variant} SKIPPED — this host's "
                  "toolchain cannot run it; the batcher's concurrency "
                  "is UNVERIFIED by this variant here. Run on a host "
                  f"with g++ + {VARIANTS[r.variant]['runtime']}.")
            if args.strict:
                failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
