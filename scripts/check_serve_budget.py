#!/usr/bin/env python
"""Absolute serving-throughput floor for the continuous-batching engine.

The bytes-budget mechanism (scripts/check_bytes_budget.py), pointed at
serving: compares a ``scripts/bench_serve.py`` JSON record against the
checked-in floor (docs/serve_budget.json) and exits nonzero when
``tokens_per_s_per_slot`` — peak engine throughput divided by the KV
slot count, the capacity number a replica is provisioned on — drops
below ``budget * (1 - tolerance_pct/100)`` on this device kind.

Usage:
    python scripts/bench_serve.py | python scripts/check_serve_budget.py -
    python scripts/check_serve_budget.py SERVE_BENCH.json
    python scripts/bench_serve.py --enforce-budget   # same gate, in-process

Semantics mirror the bytes budget, with the direction flipped
(throughput is gated from BELOW):

- ``budgets`` maps a device-kind substring (matched case-insensitively
  against the record's ``device``) to the last ACCEPTED measurement of
  ``tokens_per_s_per_slot``. A PR that speeds serving up should ratchet
  the floor UP to the new measurement in the same change.
- The gate FAILS when measured < budget * (1 - tolerance_pct/100).
  Tolerance is deliberately wide (50%): wall-clock serving throughput
  on a shared/contended host is far noisier than a compiler byte
  count, and the sibling >=2x-vs-sequential RELATIVE regression test
  (tests/test_serve.py) already catches engine-level slowdowns — this
  absolute floor exists to catch the failure mode the relative test
  cannot: both paths getting slower together.
- A device kind with no budget entry passes with a note.
- Mode-dispatched: ``cold_start`` records gate the AOT boot latency
  ceiling (and aot < cold unconditionally); ``prefix`` records gate
  the shared-prefix TTFT p99 ceiling and require the cache-on run to
  prefill fewer tokens per request than cache-off outright; ``spec``
  records require the spec-on run to beat spec-off tokens/s outright
  on the identical workload and gate spec-on tokens_per_s_per_slot
  from below.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, List, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BUDGET = os.path.join(REPO, "docs", "serve_budget.json")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _gate_cli import find_budget, load_record_argv  # noqa: E402


def load_budget(path: str = DEFAULT_BUDGET) -> Dict:
    with open(path) as fp:
        return json.load(fp)


def tokens_per_s_per_slot(record: Dict):
    """Peak tokens/s across offered-load levels, per KV slot. Computed
    here (not only in bench_serve) so the gate also works on older
    artifacts that predate the field.

    A level with client errors still counts when tokens flowed: the
    rate is real served traffic (a lower bound on capacity), and
    dropping it would gate the lower levels' rate against the full
    slot count — one flaky timeout at the peak level would read as a
    false regression. Only a level that served NOTHING is excluded
    (no measurement, and the broken-engine check in check_record
    handles the all-dead case)."""
    if record.get("tokens_per_s_per_slot") is not None:
        return record["tokens_per_s_per_slot"]
    slots = record.get("slots")
    rates = []
    for lv in record.get("levels") or []:
        tps = lv.get("tokens_per_s")
        if tps is None:
            continue
        if lv.get("errors") and not (tps > 0
                                     or (lv.get("total_tokens") or 0) > 0):
            continue                    # errored and served nothing
        rates.append(tps)
    if not slots or not rates:
        return None
    return max(rates) / slots


def check_cold_start(record: Dict, key: str, entry: Dict,
                     tol: float) -> Tuple[bool, List[str]]:
    """Gate a ``bench_serve.py --cold-start`` record: the
    AOT-deserialized boot must (a) beat the cold boot outright — the
    invariant that makes seconds-scale autoscaling real — and (b)
    stay under the checked-in ceiling (a LATENCY: gated from ABOVE,
    ceiling * (1 + tolerance))."""
    times = record.get("cold_start_to_first_token_s") or {}
    aot = times.get("aot")
    cold = times.get("cold")
    msgs: List[str] = []
    ok = True
    if aot is None or cold is None:
        return True, [f"{key}: cold-start record has no aot/cold "
                      "measurement; skipping"]
    if aot >= cold:
        ok = False
        msgs.append(f"{key}: AOT boot {aot:.3f}s did not beat cold "
                    f"boot {cold:.3f}s [REGRESSION]")
    ceiling = entry.get("cold_start_to_first_token_s_aot")
    if ceiling is None:
        msgs.append(f"{key}: no cold_start_to_first_token_s_aot "
                    "ceiling; aot-beats-cold only")
        return ok, msgs
    limit = ceiling * (1.0 + tol)
    within = aot <= limit
    ok = ok and within
    msgs.append(
        f"{key}: cold_start_to_first_token_s aot {aot:.3f}s vs "
        f"ceiling {ceiling:.3f}s (+{100 * tol:.0f}% tolerance -> "
        f"limit {limit:.3f}s) "
        f"[{'OK' if within else 'REGRESSION'}]")
    return ok, msgs


def check_prefix(record: Dict, key: str, entry: Dict,
                 tol: float) -> Tuple[bool, List[str]]:
    """Gate a ``bench_serve.py --prefix-frac`` record: (a) the
    cache-on run must prefill FEWER tokens per request than cache-off
    outright — the compute elision the prefix cache exists for, on
    the same workload — and (b) shared-prefix TTFT p99 stays under
    the checked-in ceiling (a LATENCY: gated from ABOVE,
    ceiling * (1 + tolerance))."""
    on = (record.get("cache_on") or {}).get("prefill_tokens_per_request")
    off = (record.get("cache_off") or {}).get(
        "prefill_tokens_per_request")
    msgs: List[str] = []
    ok = True
    if on is None or off is None:
        return True, [f"{key}: prefix record has no cache-on/off "
                      "prefill measurement; skipping"]
    if on >= off:
        ok = False
        msgs.append(f"{key}: cache-on prefilled {on:.1f} tok/req, no "
                    f"better than cache-off {off:.1f} [REGRESSION]")
    else:
        msgs.append(f"{key}: prefill_tokens_per_request {on:.1f} "
                    f"cache-on vs {off:.1f} cache-off [OK]")
    ceiling = entry.get("shared_prefix_ttft_p99_ms")
    measured = (record.get("cache_on") or {}).get("shared_ttft_p99_ms")
    if ceiling is None:
        msgs.append(f"{key}: no shared_prefix_ttft_p99_ms ceiling; "
                    "prefill-reduction only")
        return ok, msgs
    if measured is None:
        msgs.append(f"{key}: record carries no shared_ttft_p99_ms "
                    f"(ceiling {ceiling:.1f}); skipping")
        return ok, msgs
    limit = ceiling * (1.0 + tol)
    within = measured <= limit
    msgs.append(
        f"{key}: shared_prefix_ttft_p99_ms measured {measured:.1f} vs "
        f"ceiling {ceiling:.1f} (+{100 * tol:.0f}% tolerance -> "
        f"limit {limit:.1f}) [{'OK' if within else 'REGRESSION'}]")
    return ok and within, msgs


def check_spec(record: Dict, key: str, entry: Dict,
               tol: float) -> Tuple[bool, List[str]]:
    """Gate a ``bench_serve.py --spec`` record: (a) the spec-on run
    must move MORE tokens/s than spec-off outright, on the identical
    workload — the whole point of drafting; a drafter that does not
    pay for itself is a regression, not a tuning note — and (b)
    spec-on ``tokens_per_s_per_slot`` stays above the checked-in
    floor (a THROUGHPUT: gated from BELOW, floor * (1 - tolerance))."""
    on = (record.get("spec_on") or {}).get("tokens_per_s")
    off = (record.get("spec_off") or {}).get("tokens_per_s")
    msgs: List[str] = []
    ok = True
    if on is None or off is None:
        return True, [f"{key}: spec record has no spec-on/off "
                      "throughput measurement; skipping"]
    if on <= off:
        ok = False
        msgs.append(f"{key}: spec-on {on:.1f} tok/s, no better than "
                    f"spec-off {off:.1f} [REGRESSION]")
    else:
        msgs.append(f"{key}: tokens_per_s {on:.1f} spec-on vs "
                    f"{off:.1f} spec-off "
                    f"({on / off:.2f}x) [OK]")
    budgeted = entry.get("spec_tokens_per_s_per_slot")
    measured = (record.get("spec_on") or {}).get("tokens_per_s_per_slot")
    if budgeted is None:
        msgs.append(f"{key}: no spec_tokens_per_s_per_slot floor; "
                    "spec-on-beats-spec-off only")
        return ok, msgs
    if measured is None:
        msgs.append(f"{key}: record carries no spec-on "
                    f"tokens_per_s_per_slot (floor {budgeted:.1f}); "
                    "skipping")
        return ok, msgs
    floor = budgeted * (1.0 - tol)
    within = measured >= floor
    msgs.append(
        f"{key}: spec-on tokens_per_s_per_slot measured {measured:.1f}"
        f" vs floor {budgeted:.1f} (-{100 * tol:.0f}% tolerance -> "
        f"limit {floor:.1f}) [{'OK' if within else 'REGRESSION'}]")
    return ok and within, msgs


def check_record(record: Dict, budget: Dict) -> Tuple[bool, List[str]]:
    """-> (ok, messages). ok is False only on a real throughput drop;
    a missing budget entry or an unmeasurable record passes with a
    note (all-errors runs already fail loudly in bench_serve)."""
    tol = float(budget.get("tolerance_pct", 50.0)) / 100.0
    kind = record.get("device") or record.get("device_kind") or ""
    key, entry = find_budget(budget.get("budgets"), kind)
    if entry is None:
        return True, [f"no serve budget for device kind {kind.lower()!r}; "
                      "nothing to enforce"]
    if record.get("mode") == "cold_start":
        return check_cold_start(record, key, entry, tol)
    if record.get("mode") == "prefix":
        return check_prefix(record, key, entry, tol)
    if record.get("mode") == "spec":
        return check_spec(record, key, entry, tol)
    ok_kv, kv_msgs = check_kv_bytes(record, key, entry, tol)
    budgeted = entry.get("tokens_per_s_per_slot")
    measured = tokens_per_s_per_slot(record)
    if budgeted is None:
        return ok_kv, kv_msgs + [f"{key}: budget entry has no "
                                 "tokens_per_s_per_slot; nothing to "
                                 "enforce"]
    if measured is None:
        levels = record.get("levels") or []
        total = sum(lv.get("total_tokens") or 0 for lv in levels)
        if levels and total == 0 and all(lv.get("errors")
                                         for lv in levels):
            # A completely broken engine (every level errored AND zero
            # tokens served) is the WORST regression the floor exists
            # to catch — never let it pass as "no data". (Errored
            # levels where tokens DID flow are real measurements and
            # were already counted by tokens_per_s_per_slot.)
            return False, [f"{key}: every offered-load level errored, "
                           f"0 tokens served "
                           f"({levels[0]['errors'][:1]}...); serving "
                           "is broken [REGRESSION]"]
        return ok_kv, kv_msgs + [
            f"{key}: no usable tokens/s measurement in record "
            f"(floor {budgeted:.0f}); skipping"]
    floor = budgeted * (1.0 - tol)
    ok = measured >= floor
    verdict = "OK" if ok else "REGRESSION"
    return ok and ok_kv, kv_msgs + [
        f"{key}: tokens_per_s_per_slot measured {measured:.1f} vs "
        f"floor {budgeted:.1f} (-{100 * tol:.0f}% tolerance -> "
        f"limit {floor:.1f}) [{verdict}]"]


def check_kv_bytes(record: Dict, key: str, entry: Dict,
                   tol: float) -> Tuple[bool, List[str]]:
    """KV-capacity ceiling: ``kv_bytes_per_token`` (pool bytes pinned
    per cacheable token under the DEFAULT bench invocation) is gated
    from ABOVE — page-table metadata creep or a broken pool auto-size
    silently taxes every slot's HBM, and no throughput floor would
    notice on a tiny CPU model. Records that predate the field skip
    with a note."""
    ceiling = entry.get("kv_bytes_per_token")
    measured = record.get("kv_bytes_per_token")
    if ceiling is None:
        return True, []
    if measured is None:
        return True, [f"{key}: record carries no kv_bytes_per_token "
                      f"(ceiling {ceiling:.0f}); skipping"]
    limit = ceiling * (1.0 + tol)
    ok = measured <= limit
    return ok, [
        f"{key}: kv_bytes_per_token measured {measured:.1f} vs "
        f"ceiling {ceiling:.1f} (+{100 * tol:.0f}% tolerance -> "
        f"limit {limit:.1f}) [{'OK' if ok else 'REGRESSION'}]"]


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    loaded = load_record_argv(argv, DEFAULT_BUDGET)
    if isinstance(loaded, int):
        return loaded
    record, budget_path = loaded
    ok, msgs = check_record(record, load_budget(budget_path))
    for m in msgs:
        print(m)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
