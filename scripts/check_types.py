#!/usr/bin/env python
"""Type gate for the newest subsystems (tpunet/analysis, tpunet/obs/
flightrec).

Two layers, so annotations can't rot even on hosts without a type
checker installed:

1. **mypy**, when importable: runs with the ``[tool.mypy]`` config in
   pyproject.toml (strict ``disallow_untyped_defs`` over
   ``tpunet.analysis``, ``check_untyped_defs`` over flightrec).
   Missing mypy is a LOUD skip of this layer, not a pass of it —
   the container bakes its own deps and this repo does not install.
2. **annotation coverage** (stdlib ast, always runs): every function
   in ``tpunet/analysis/`` must annotate its return and every
   parameter (self/cls excepted); every PUBLIC def in
   ``tpunet/obs/flightrec/`` must as well. This is the floor that
   makes layer 1 meaningful the day mypy does run.

Exit codes: 0 = pass (mypy may have skipped, said loudly), 1 =
coverage gap or mypy errors, 2 = internal error. Wired as a non-slow
test (tests/test_types.py) and into scripts/run_checks.sh.
"""

from __future__ import annotations

import ast
import os
import subprocess
import sys
from typing import Iterator, List, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)

#: (directory, public_only) — analysis is fully strict, flightrec is
#: public-surface strict.
TARGETS: Tuple[Tuple[str, bool], ...] = (
    (os.path.join("tpunet", "analysis"), False),
    (os.path.join("tpunet", "obs", "flightrec"), True),
)


def _py_files(rel_dir: str) -> Iterator[str]:
    root = os.path.join(REPO, rel_dir)
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for name in sorted(filenames):
            if name.endswith(".py"):
                yield os.path.join(dirpath, name)


def _unannotated(fn: ast.AST, public_only: bool,
                 in_class: bool) -> List[str]:
    """Parameter/return annotation gaps of one function def."""
    assert isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
    if public_only and fn.name.startswith("_") \
            and not (fn.name.startswith("__") and fn.name.endswith("__")):
        return []
    gaps: List[str] = []
    args = fn.args
    params = args.posonlyargs + args.args + args.kwonlyargs
    skip_first = in_class and params and params[0].arg in ("self", "cls")
    for i, a in enumerate(params):
        if skip_first and i == 0:
            continue
        if a.annotation is None:
            gaps.append(f"param '{a.arg}'")
    for star in (args.vararg, args.kwarg):
        if star is not None and star.annotation is None:
            gaps.append(f"param '*{star.arg}'")
    if fn.returns is None and fn.name != "__init__":
        gaps.append("return")
    return gaps


def annotation_gaps() -> List[str]:
    """All annotation-coverage violations across TARGETS, rendered as
    'path:line: def name: missing ...' strings."""
    out: List[str] = []
    for rel_dir, public_only in TARGETS:
        for path in _py_files(rel_dir):
            rel = os.path.relpath(path, REPO)
            with open(path, "r", encoding="utf-8") as f:
                tree = ast.parse(f.read(), filename=rel)

            def visit(node: ast.AST, in_class: bool) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)):
                        gaps = _unannotated(child, public_only, in_class)
                        if gaps:
                            out.append(f"{rel}:{child.lineno}: def "
                                       f"{child.name}: missing "
                                       + ", ".join(gaps))
                        visit(child, in_class=False)
                    elif isinstance(child, ast.ClassDef):
                        visit(child, in_class=True)
                    else:
                        visit(child, in_class=in_class)

            visit(tree, in_class=False)
    return out


def run_mypy() -> Tuple[str, int]:
    """('ran'|'skipped', exit code). Skip only when mypy is absent."""
    try:
        import mypy  # noqa: F401
    except ImportError:
        print("check_types: NOTE — mypy is not installed in this "
              "environment; the mypy layer is SKIPPED (annotation-"
              "coverage layer still enforced). The [tool.mypy] config "
              "in pyproject.toml is the contract a mypy-equipped host "
              "runs.", flush=True)
        return "skipped", 0
    res = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file",
         os.path.join(REPO, "pyproject.toml")]
        + [os.path.join(REPO, d) for d, _ in TARGETS],
        capture_output=True, text=True, cwd=REPO, timeout=600)
    if res.returncode != 0:
        print(res.stdout)
        print(res.stderr, file=sys.stderr)
    return "ran", res.returncode


def main() -> int:
    gaps = annotation_gaps()
    for gap in gaps:
        print(f"check_types: {gap}")
    status, mypy_rc = run_mypy()
    if gaps:
        print(f"check_types: FAIL — {len(gaps)} annotation gap(s)")
        return 1
    if mypy_rc != 0:
        print("check_types: FAIL — mypy errors")
        return 1
    print(f"check_types: OK (coverage clean; mypy {status})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
