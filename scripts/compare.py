#!/usr/bin/env python
"""The reference's benchmark-comparison methodology as one command (C16).

The reference's published result is a three-config comparison — serial
CPU vs single GPU vs MPI+DDP — recorded as SLURM run logs
(logs_cifar10_cpu_27299.out, cifar10_128_gpu_27326.out,
cifar_mpi_gpu128_26188.out) and summarized in its README performance
table. This script produces the tpunet equivalent as a committed
artifact: it runs the three presets back-to-back, parses each run's
metrics.jsonl, and emits a markdown table (COMPARE.md) + machine-
readable COMPARE.json with wall-clock, img/s, and accuracy per config.

Real CIFAR-10 is used when present under --data-dir (or downloadable);
otherwise the deterministic synthetic stand-in keeps the artifact
reproducible in no-egress environments (the mode is recorded in the
output). Device placement per mode:

  serial       1 CPU device   (reference: CPU-pinned, :19)
  single       1 device of the default platform (TPU chip when present)
  distributed  all devices of the default platform (8-way virtual CPU
               mesh when no accelerator), per-device batch 128 like the
               reference's per-rank 128 (:117)

    python scripts/compare.py                   # auto: real if present
    python scripts/compare.py --epochs 3 --image-size 96 --synthetic
    python scripts/compare.py --platform cpu    # hermetic CPU run
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# Persistent compile cache path convention has ONE home
# (tpunet.utils.cache), shared with tests/dryruns.
from tpunet.utils.cache import cache_dir  # noqa: E402


def cpu_env(n_devices: int = 1) -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""  # disable forced TPU registration
    flags = [f for f in env.get("XLA_FLAGS", "").split()
             if "force_host_platform_device_count" not in f]
    flags.append(f"--xla_force_host_platform_device_count={n_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", cache_dir())
    return env


def probe_devices(env: dict) -> tuple[str, int]:
    out = subprocess.run(
        [sys.executable, "-c",
         "import jax; d = jax.devices(); print(d[0].platform, len(d))"],
        env=env, cwd=REPO, capture_output=True, text=True, check=True)
    platform, n = out.stdout.strip().split()[-2:]
    return platform, int(n)


def run_mode(mode: str, env: dict, out_dir: str, common: list[str],
             batch: int, log_name: str, label: str | None = None) -> dict:
    """Run one preset; ``label`` names the output row/dirs when the same
    preset appears twice (e.g. the matched-batch control)."""
    label = label or mode
    ckpt = os.path.join(out_dir, label, "ckpt")
    cmd = [sys.executable, "-u", "train.py", "--preset", mode,
           "--batch-size", str(batch), "--checkpoint-dir", ckpt] + common
    print(f"[{label}] {' '.join(cmd[1:])}", flush=True)
    t0 = time.time()
    with open(os.path.join(out_dir, log_name), "w") as log:
        subprocess.run(cmd, env=env, cwd=REPO, stdout=log,
                       stderr=subprocess.STDOUT, check=True)
    wall = time.time() - t0
    rows = [json.loads(l) for l in
            open(os.path.join(ckpt, "metrics.jsonl"))]
    partial = [r for r in rows if r.get("partial")]
    rows = [r for r in rows if not r.get("partial")]
    if partial:
        raise RuntimeError(
            f"[{label}] run was preempted mid-epoch (partial row at epoch "
            f"{partial[-1]['epoch']}); rerun to get a complete comparison")
    total = sum(r["seconds"] for r in rows)
    # Steady state = the fastest epoch: short runs put the (possibly
    # minutes-long on a cold cache) XLA compile inside epoch 1, which
    # the reference's 20-epoch totals amortize away but a 2-epoch
    # artifact does not.
    return {
        "mode": label,
        "preset": mode,
        "global_batch": batch,
        "epochs": len(rows),
        "total_seconds": round(total, 2),
        "wall_seconds": round(wall, 2),  # includes compile/startup
        "images_per_sec": round(sum(r["examples_per_sec"] * r["seconds"]
                                    for r in rows) / total, 2),
        "steady_epoch_seconds": round(min(r["seconds"] for r in rows), 2),
        "steady_images_per_sec": max(r["examples_per_sec"] for r in rows),
        "best_test_accuracy": max(r["test_accuracy"] for r in rows),
        "final_train_loss": rows[-1]["train_loss"],
        # Per-epoch times make run-to-run variance visible in the
        # artifact: at 1 process the single and distributed presets
        # build IDENTICAL configs (tpunet/config.py preset()) and thus
        # identical XLA programs, so any single/distributed gap at
        # n_dist=1 is environment noise, measurable from this column.
        "epoch_seconds": [round(r["seconds"], 2) for r in rows],
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="runs/compare")
    p.add_argument("--data-dir", default="data")
    p.add_argument("--epochs", type=int, default=None,
                   help="default: 20 on real data (reference EPOCHS), "
                        "3 on synthetic")
    p.add_argument("--image-size", type=int, default=None,
                   help="default: 224 on real data (reference), 96 on "
                        "synthetic (keeps the CPU run short)")
    p.add_argument("--synthetic", action="store_true",
                   help="force the synthetic dataset even if CIFAR-10 "
                        "is present")
    p.add_argument("--synthetic-size", type=int, default=2048)
    p.add_argument("--platform", choices=["auto", "cpu"], default="auto",
                   help="cpu: run every mode on CPU devices (hermetic); "
                        "auto: single/distributed use the default "
                        "platform (TPU when attached)")
    p.add_argument("--pretrained", default=None,
                   help="forwarded to train.py on real data (e.g. auto)")
    args = p.parse_args(argv)

    have_real = not args.synthetic and (
        os.path.isdir(os.path.join(args.data_dir, "cifar-10-batches-py"))
        or os.path.exists(os.path.join(args.data_dir,
                                       "cifar-10-python.tar.gz")))
    epochs = args.epochs or (20 if have_real else 3)
    image_size = args.image_size or (224 if have_real else 96)
    out_dir = os.path.join(REPO, args.out)
    os.makedirs(out_dir, exist_ok=True)

    common = ["--epochs", str(epochs), "--image-size", str(image_size),
              "--data-dir", args.data_dir]
    if have_real:
        common += ["--dataset", "cifar10"]
        if args.pretrained:
            common += ["--pretrained", args.pretrained]
    else:
        common += ["--dataset", "synthetic", "--dtype", "float32",
                   "--synthetic-size", str(args.synthetic_size)]

    if args.platform == "cpu":
        accel_env = cpu_env(1)
        dist_env = cpu_env(8)
    else:
        accel_env = dict(os.environ)
        dist_env = dict(os.environ)
    accel_platform, _ = probe_devices(accel_env)
    if accel_platform == "cpu" and args.platform == "auto":
        # No accelerator attached: fall back to the hermetic CPU layout
        # so "distributed" still demonstrates an 8-way mesh.
        accel_env, dist_env = cpu_env(1), cpu_env(8)
        accel_platform = "cpu"
    dist_platform, n_dist = probe_devices(dist_env)

    results = []
    hw = {"serial": "1x cpu", "single": f"1x {accel_platform}",
          "single-b64": f"1x {accel_platform}",
          "distributed": f"{n_dist}x {dist_platform}"}
    results.append(run_mode("serial", cpu_env(1), out_dir, common,
                            64, "serial.log"))
    results.append(run_mode("single", accel_env, out_dir, common,
                            128, "single.log"))
    # Matched-optimization CONTROL (VERDICT r4 #4): the single preset at
    # the SERIAL run's global batch 64 — same step count, same LR, same
    # schedule; the only variable left is the execution mode. The
    # reference's correctness claim is cross-config accuracy parity
    # (README:84-90); serial@64 vs single@128 alone confounds that with
    # 2x the optimizer steps at fixed LR. Skipped on the hermetic
    # CPU-only layout, where it would be byte-identical to the serial
    # run (same config, same 1-CPU-device env — parity trivially true).
    if accel_platform != "cpu":
        results.append(run_mode("single", accel_env, out_dir, common,
                                64, "single-b64.log", label="single-b64"))
    # Reference distributed semantics: 128 PER DEVICE (:117 + mpirun -np N).
    results.append(run_mode("distributed", dist_env, out_dir, common,
                            128 * n_dist, "distributed.log"))

    serial_t = results[0]["total_seconds"]
    serial_s = results[0]["steady_epoch_seconds"]
    for r in results:
        r["hardware"] = hw[r["mode"]]
        r["speedup_vs_serial"] = round(serial_t / r["total_seconds"], 2)
        r["steady_speedup_vs_serial"] = round(
            serial_s / r["steady_epoch_seconds"], 2)

    meta = {
        "dataset": "cifar10" if have_real else "synthetic",
        "image_size": image_size, "epochs": epochs,
        "reference": {
            # the reference's published numbers for the same comparison
            # (SURVEY.md section 6; .out logs)
            "serial_cpu_seconds": 30955.22,
            "single_v100_seconds": 10698.08,
            "dual_v100_mpi_seconds": 5220.57,
            "serial_cpu_best_acc": 0.9617,
            "single_v100_best_acc": 0.9603,
            "dual_v100_best_acc_local": 0.9558,
        },
        "results": results,
    }
    with open(os.path.join(out_dir, "COMPARE.json"), "w") as f:
        json.dump(meta, f, indent=2)

    lines = [
        "# tpunet three-config comparison (reference C16)",
        "",
        f"Dataset: **{meta['dataset']}** @ {image_size}px, "
        f"{epochs} epochs. Serial/single/distributed mirror the "
        "reference's CPU / 1-GPU / MPI+DDP configs (its numbers: "
        "30,955 s / 10,698 s / 5,221 s at ~0.96 best acc on real "
        "CIFAR-10, 20 epochs, 224px).",
        "",
        "| Training Mode | Hardware | Global batch | Total time (s) "
        "| Steady epoch (s) | Steady img/s | Best test acc "
        "| Steady speedup vs serial |",
        "|---|---|---|---|---|---|---|---|",
    ]
    for r in results:
        lines.append(
            f"| {r['mode']} | {r['hardware']} | {r['global_batch']} "
            f"| {r['total_seconds']} | {r['steady_epoch_seconds']} "
            f"| {r['steady_images_per_sec']} "
            f"| {r['best_test_accuracy']:.4f} "
            f"| {r['steady_speedup_vs_serial']:.2f}x |")
    lines += ["",
              "Total time sums per-epoch seconds (train + eval, as the "
              "reference logs do); the steady columns use the fastest "
              "epoch, excluding the XLA compile a short run cannot "
              "amortize (the reference's 20-epoch totals do); accuracy "
              "is globally reduced (the reference's distributed number "
              "was rank-local).", ""]
    by = {r["mode"]: r for r in results}
    if "single-b64" in by:
        s64, c64 = by["serial"], by["single-b64"]
        gap = abs(s64["best_test_accuracy"] - c64["best_test_accuracy"])
        lines += [
            "## Matched-optimization control (execution-mode parity)",
            "",
            "`serial` and `single-b64` run the IDENTICAL optimization "
            "problem — global batch 64, same step count, same LR/"
            "schedule — on different execution modes (1 CPU device vs "
            f"1 {hw['single-b64'].split()[-1]} device). The reference's "
            "cross-config check (README:84-90) is accuracy parity; "
            "here:",
            "",
            f"- serial@64 best acc **{s64['best_test_accuracy']:.4f}**, "
            f"single-b64@64 best acc "
            f"**{c64['best_test_accuracy']:.4f}** "
            f"(|gap| {gap:.4f} — {'PARITY' if gap < 0.02 else 'MISMATCH'}"
            " at the reference's ~1-point bar).",
            f"- The serial@64 vs single@128 accuracy split is therefore "
            "an OPTIMIZATION variable (2x the optimizer steps per "
            "epoch at batch 64, fixed LR), not an execution-mode bug; "
            "single@128 == distributed@128/device remains the "
            "bitwise A/B check (AB_CHECK.json).",
            ""]
    with open(os.path.join(out_dir, "COMPARE.md"), "w") as f:
        f.write("\n".join(lines))
    print("\n".join(lines))
    return 0


if __name__ == "__main__":
    sys.exit(main())
