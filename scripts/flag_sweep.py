#!/usr/bin/env python
"""XLA flag sweep on the flagship bench (roofline push, VERDICT r4 #3).

Runs ``bench.py --peak-only`` in a subprocess per flag set (XLA flags
must be set before backend init) and reports img/s per variant. Only
flags that are semantics-preserving scheduling/memory knobs are tried;
the winner (if any beats baseline by >2%) is a candidate for bench.py's
default environment.

    python scripts/flag_sweep.py            # full sweep
    python scripts/flag_sweep.py baseline vmem64   # named subset

MEASURED RESULT on this environment (2026-08-01, axon-tunneled v5e):
every --xla_tpu_* variant fails with "Unknown flag in XLA_FLAGS" —
the tunnel's CLIENT-side XLA (a CPU build) parses XLA_FLAGS before
relaying, so TPU-backend knobs are unreachable here. Baseline:
4972.5 img/s, 49.1% of roofline. On a directly-attached TPU stack the
sweep is expected to run as written; kept as the documented attempt
and for that future environment.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

VARIANTS = {
    "baseline": "",
    # More VMEM for fusions -> larger tiles -> fewer HBM round trips.
    "vmem64": "--xla_tpu_scoped_vmem_limit_kib=65536",
    "vmem96": "--xla_tpu_scoped_vmem_limit_kib=98304",
    # Aggressive fusion knobs.
    "fusion_all": "--xla_tpu_enable_aggressive_loop_fusion_layout_opt=true",
    "multioutput": "--xla_tpu_enable_multi_level_nested_loop_fusion=true",
    # Async/overlap knobs (mostly collectives; cheap to test).
    "latency_hiding": "--xla_tpu_enable_latency_hiding_scheduler=true",
}


def run_variant(name: str, flags: str) -> dict:
    env = dict(os.environ)
    base = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (base + " " + flags).strip()
    out = subprocess.run(
        [sys.executable, "bench.py", "--peak-only"],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=900)
    line = ""
    for ln in out.stdout.strip().splitlines()[::-1]:
        if ln.startswith("{"):
            line = ln
            break
    if not line:
        return {"variant": name, "error": out.stderr[-500:]}
    d = json.loads(line)
    return {"variant": name, "flags": flags,
            "img_per_sec": d["value"],
            "pct_of_roofline": d.get("pct_of_roofline")}


def main() -> None:
    names = sys.argv[1:] or list(VARIANTS)
    unknown = [n for n in names if n not in VARIANTS]
    if unknown:
        sys.exit(f"unknown variant(s) {unknown}; "
                 f"valid: {', '.join(VARIANTS)}")
    results = []
    for n in names:
        r = run_variant(n, VARIANTS[n])
        print(json.dumps(r), flush=True)
        results.append(r)
    ok = [r for r in results if "img_per_sec" in r]
    if ok:
        best = max(ok, key=lambda r: r["img_per_sec"])
        print(f"# best: {best['variant']} at {best['img_per_sec']} img/s")


if __name__ == "__main__":
    main()
