#!/usr/bin/env python
"""Cross-run regression gate: compare two runs, exit-coded like the
budget gates.

    # compare two run dirs (each containing metrics.jsonl)
    python scripts/obs_compare.py runs/baseline/ runs/candidate/

    # keep (and reuse) summaries in a run-history index
    python scripts/obs_compare.py A/ B/ --history history-dir/

    # page a webhook on a regression verdict, emit the record to a
    # metrics file, or print the full record as JSON
    python scripts/obs_compare.py A/ B/ --webhook http://pager/hook
    python scripts/obs_compare.py A/ B/ --emit out/metrics.jsonl
    python scripts/obs_compare.py A/ B/ --json

Verdicts come from ``tpunet/obs/history/compare.py``: runs align on
their overlapping global-step range and every step-time / serve-SLO
quantile is judged against BOTH runs' DKW rank-error bounds — a
``regression`` verdict means disjoint confidence intervals, never a
wobble inside the bars. Exact scalars (throughput, MFU) use
``--tolerance`` (default 0.05) instead.

Exit codes (budget-gate convention): 0 = ok / within error,
3 = regression, 2 = usage error or incomparable runs (different
config fingerprints without --allow-fingerprint-mismatch, or no
overlapping sample data). Output is deterministic for fixed inputs —
same run dirs, same verdict, byte for byte.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _gate_cli import split_flags  # noqa: E402

VALUE_FLAGS = ("--history", "--tolerance", "--webhook", "--emit")
BOOL_FLAGS = ("--json", "--allow-fingerprint-mismatch")


def _summarize(path: str, history):
    from tpunet.obs.history import summarize_run
    from tpunet.utils.logging import MetricsLogger

    if history is not None:
        return history.ingest_run(path)
    metrics = (path if path.endswith(".jsonl")
               else os.path.join(path, "metrics.jsonl"))
    if not os.path.isfile(metrics):
        raise FileNotFoundError(f"no metrics.jsonl under {path!r}")
    return summarize_run(MetricsLogger.read_records(metrics),
                         source=path)


def _render(cmp: dict) -> str:
    out = [f"obs_compare: {cmp['run_a']} (baseline) vs "
           f"{cmp['run_b']} (candidate)"]
    if cmp.get("step_lo") is not None:
        out.append(f"  aligned steps [{cmp['step_lo']}, "
                   f"{cmp['step_hi']}] — windows "
                   f"{cmp['windows_a']}/{cmp['windows_b']}")
    for m in cmp.get("metrics", []):
        bar = ""
        if "a_lo" in m:
            bar = (f"  [{m['a_lo']:.6g}, {m['a_hi']:.6g}] vs "
                   f"[{m['b_lo']:.6g}, {m['b_hi']:.6g}]")
        elif "tolerance" in m:
            bar = f"  (tolerance {m['tolerance']:g})"
        frac = (f"{100 * m['delta_frac']:+.1f}%"
                if m.get("delta_frac") is not None else "n/a")
        out.append(f"  {m['verdict']:>12}  {m['metric']:<22} "
                   f"{m['a']:.6g} -> {m['b']:.6g} ({frac}){bar}")
    out.append(f"verdict: {cmp['verdict'].upper()} "
               f"({cmp.get('regressions', 0)} regression(s), "
               f"{cmp.get('improvements', 0)} improvement(s))")
    return "\n".join(out)


def main(argv=None) -> int:
    parsed = split_flags(sys.argv[1:] if argv is None else argv,
                         VALUE_FLAGS, BOOL_FLAGS)
    if isinstance(parsed, int):
        return parsed
    flags, paths = parsed
    if len(paths) != 2:
        print("usage: obs_compare.py RUN_A RUN_B [--history DIR] "
              "[--tolerance F] [--webhook URL] [--emit PATH] [--json] "
              "[--allow-fingerprint-mismatch]", file=sys.stderr)
        return 2
    try:
        tolerance = float(flags.get("tolerance", 0.05))
    except ValueError:
        print(f"--tolerance expects a float, got "
              f"{flags['tolerance']!r}", file=sys.stderr)
        return 2

    from tpunet.obs.history import RunHistory, compare_summaries

    history = (RunHistory(str(flags["history"]))
               if "history" in flags else None)
    try:
        a = _summarize(paths[0], history)
        b = _summarize(paths[1], history)
    except (FileNotFoundError, ValueError) as e:
        print(f"obs_compare: {e}", file=sys.stderr)
        return 2
    cmp = compare_summaries(a, b, tolerance=tolerance)

    if cmp.get("fingerprint_match") is False \
            and "allow-fingerprint-mismatch" not in flags:
        print(f"obs_compare: config fingerprints differ "
              f"({a.get('config_fingerprint')} vs "
              f"{b.get('config_fingerprint')}) — these runs computed "
              "different workloads; comparing them would call a "
              "config change a regression. Pass "
              "--allow-fingerprint-mismatch to compare anyway.",
              file=sys.stderr)
        return 2

    if "json" in flags:
        print(json.dumps(cmp, indent=1, sort_keys=True))
    else:
        print(_render(cmp))

    # Optional emission: the obs_regression record reaches a metrics
    # file and/or pages the webhook — the same record body either way.
    if "emit" in flags or "webhook" in flags:
        from tpunet.obs.registry import Registry
        reg = Registry()
        webhook = None
        if "emit" in flags:
            path = str(flags["emit"])

            class _FileSink:
                def write(self, record):
                    with open(path, "a") as f:
                        f.write(json.dumps(record) + "\n")

            reg.add_sink(_FileSink())
        if "webhook" in flags:
            from tpunet.obs.export import AlertWebhook
            webhook = AlertWebhook(str(flags["webhook"]), registry=reg)
            reg.add_sink(webhook)
        from tpunet.obs.history import emit_regression
        emit_regression(reg, cmp)
        if webhook is not None:
            webhook.close()
            st = webhook.stats()
            if st["send_errors"] or st["dropped"]:
                print(f"obs_compare: webhook delivery incomplete: {st}",
                      file=sys.stderr)

    if cmp["verdict"] == "regression":
        return 3
    if cmp["verdict"] == "incomparable":
        print("obs_compare: no overlapping sample data — nothing to "
              "judge", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
