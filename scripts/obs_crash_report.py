#!/usr/bin/env python
"""Render a flight-recorder crash report for humans.

The watcher (tpunet/obs/flightrec/watch.py) leaves
``<run-dir>/flightrec/crash_report.json`` when a run dies; this script
turns it into the post-mortem narrative: what killed the process,
what every thread was doing, the last events before death, and the
native batcher journal. It can also assemble a report directly from a
flightrec artifact dir (``--assemble``) when the watcher never got the
chance (e.g. the artifacts were copied off a dead host).

    python scripts/obs_crash_report.py <run-dir | report.json>
    python scripts/obs_crash_report.py --json <...>     # raw report
    python scripts/obs_crash_report.py --assemble <flightrec-dir>
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpunet.obs.flightrec import report as frreport  # noqa: E402


def find_report(path: str) -> str:
    """Resolve a run dir / flightrec dir / report file to a report
    path (the live report if present, else the newest archive)."""
    if os.path.isfile(path):
        return path
    candidates = []
    for base in (path, os.path.join(path, "flightrec")):
        if not os.path.isdir(base):
            continue
        live = os.path.join(base, frreport.REPORT_NAME)
        if os.path.isfile(live):
            return live
        candidates += glob.glob(os.path.join(base, "crash_report.*.json"))
    if not candidates:
        raise FileNotFoundError(
            f"no crash_report*.json under {path!r} (is this a run dir "
            "with a flightrec/ subdir?)")
    return max(candidates, key=os.path.getmtime)


def _t(ts) -> str:
    if not ts:
        return "?"
    return time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts))


def render(rep: dict, path: str, events_tail: int = 40) -> str:
    out = [f"tpunet crash report — {path}", ""]
    meta = rep.get("meta") or {}
    out.append(f"cause: {rep.get('cause', '?')}"
               + (f" (signal {rep['signal']})"
                  if rep.get("signal") is not None else ""))
    out.append(f"pid {meta.get('pid', '?')}  started {_t(meta.get('started_t'))}"
               f"  assembled {_t(rep.get('assembled_t'))}")
    if meta.get("argv"):
        out.append("argv: " + " ".join(meta["argv"]))
    if meta.get("run_id"):
        out.append(f"run_id: {meta['run_id']}  "
                   f"process_index: {meta.get('process_index', 0)}")
    out.append("")

    threads = rep.get("threads") or []
    if threads:
        out.append(f"HOST THREADS ({len(threads)} registered, last "
                   "epoch-boundary snapshot):")
        for t in threads:
            out.append(f"  {t.get('name', '?'):<22} {t.get('state', '?'):<5} "
                       f"age {t.get('age_s', '?')}s  "
                       f"beats {t.get('beats', '?')}")
        out.append("")

    stacks = rep.get("stacks") or {}
    sthreads = stacks.get("threads") or []
    if sthreads:
        out.append(f"PYTHON STACKS AT DEATH ({len(sthreads)} threads):")
        for t in sthreads:
            tag = "current " if t.get("current") else ""
            out.append(f"  {tag}thread {t.get('ident', '?')}:")
            for frame in t.get("frames", [])[:12]:
                out.append(f"    {frame}")
        out.append("")

    events = rep.get("events") or []
    if events:
        out.append(f"EVENT RING TAIL (last {min(events_tail, len(events))}"
                   f" of {len(events)} captured):")
        for ev in events[-events_tail:]:
            out.append(f"  {ev.get('seq', '?'):>6} {_t(ev.get('t'))} "
                       f"[{ev.get('kind', '?'):<9}] {ev.get('msg', '')}")
        out.append("")

    nj = rep.get("native_journal")
    if nj:
        ops = nj.get("ops") or []
        out.append(f"NATIVE BATCHER JOURNAL ({len(ops)} ops, oldest "
                   "first):")
        for op in ops[-40:]:
            out.append(f"  {op.get('seq', '?'):>6} "
                       f"{op.get('op', '?'):<14} a={op.get('a')} "
                       f"b={op.get('b')} tid={op.get('tid')}")
        out.append("")

    mem = rep.get("device_memory")
    if mem:
        out.append(f"DEVICE MEMORY (last sampled "
                   f"{_t(mem.get('sampled_t'))}):")
        for d in mem.get("devices") or []:
            if not isinstance(d, dict):
                continue
            used = d.get("bytes_in_use")
            out.append(f"  device {d.get('device', '?')}: "
                       + (f"{used / 2**20:.1f} MiB in use, peak "
                          f"{(d.get('peak_bytes_in_use') or 0) / 2**20:.1f}"
                          " MiB" if used is not None else "(no stats)"))
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run dir, flightrec dir, or a "
                                 "crash_report*.json")
    ap.add_argument("--json", action="store_true",
                    help="print the raw report JSON")
    ap.add_argument("--assemble", action="store_true",
                    help="(re)assemble the report from a flightrec "
                         "artifact dir before rendering")
    ap.add_argument("--events", type=int, default=40,
                    help="event-ring tail lines to show")
    args = ap.parse_args(argv)
    if args.assemble:
        d = args.path
        if os.path.isdir(os.path.join(d, "flightrec")):
            d = os.path.join(d, "flightrec")
        path = frreport.write_report(d)
    else:
        path = find_report(args.path)
    with open(path) as f:
        rep = json.load(f)
    if args.json:
        json.dump(rep, sys.stdout, indent=1)
        print()
    else:
        print(render(rep, path, events_tail=args.events))
    return 0


if __name__ == "__main__":
    sys.exit(main())
