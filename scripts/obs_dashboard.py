#!/usr/bin/env python
"""Live terminal dashboard over the tpunet obs record stream.

Single-run modes, one renderer (``tpunet.obs.summary.summarize`` —
the same summarizer ``obs_report.py`` uses, so live and post-mortem
views can never disagree):

    # live-tail a run's metrics.jsonl (follows appends; tolerates the
    # torn trailing line a crash or an in-flight write leaves)
    python scripts/obs_dashboard.py checkpoints/

    # one render, no follow loop (CI / scripting)
    python scripts/obs_dashboard.py checkpoints/ --once

    # receive line-JSON POSTs from a run started with
    #   train.py --obs-http http://HOST:8321/
    python scripts/obs_dashboard.py --listen 8321

Fleet mode (``tpunet.obs.agg``) merges N streams into one view —
give several metrics.jsonl paths (tailed/replayed side by side), or
``--listen --fleet`` to route concurrent POSTs from many runs by
their ``run_id``/``process_index`` identity stamps:

    python scripts/obs_dashboard.py runA/ runB/ --once --html fleet.html
    python scripts/obs_dashboard.py --listen 8321 --fleet --stale-after 60

The fleet view shows exact merged counts/means, bounded-error merged
percentiles, the step-aligned straggler factor, per-stream rows, the
aggregated serve SLO panel (fleet TTFT/e2e, total queue depth,
per-replica reject rates), and fleet alerts (straggler / stale stream
/ memory growth / ``--rule`` GaugePredicates).

``--html report.html`` writes a self-contained static report (stat
tiles, SVG charts, alert and epoch tables; light/dark via CSS custom
properties) instead of — or, in follow mode, alongside — the terminal
view. GET on the ``--listen`` port returns the current text render,
so ``curl :8321`` is a remote status line.
"""

from __future__ import annotations

import argparse
import html as html_mod
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

SPARK = " ▁▂▃▄▅▆▇█"


def sparkline(values, width=32) -> str:
    """Unicode block sparkline, downsampled to ``width`` buckets."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    if len(vals) > width:
        per = -(-len(vals) // width)
        vals = [sum(vals[i:i + per]) / len(vals[i:i + per])
                for i in range(0, len(vals), per)]
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    return "".join(SPARK[1 + int((v - lo) / span * (len(SPARK) - 2))]
                   for v in vals)


def _fmt_rate(v) -> str:
    if v is None:
        return "-"
    return f"{v / 1e3:.1f}k" if v >= 10_000 else f"{v:.0f}"


# ---------------------------------------------------------------------------
# terminal view
# ---------------------------------------------------------------------------


def render_terminal(summary: dict, source: str, last: int = 10) -> str:
    """One full-screen text frame from a summarize() dict."""
    totals = summary["totals"]
    obs = summary["obs_epochs"]
    windows = summary["step_windows"]
    alerts = summary["alerts"]
    out = [f"tpunet obs dashboard — {source} — "
           f"{time.strftime('%H:%M:%S')}"]

    head = []
    if obs:
        r = obs[-1]
        head.append(f"epoch {r['epoch']} step {r.get('step', '?')}")
    thr = totals.get("tokens_per_sec", totals.get("examples_per_sec"))
    if thr is not None:
        unit = "tok/s" if "tokens_per_sec" in totals else "ex/s"
        head.append(f"{_fmt_rate(thr)} {unit}")
    if totals.get("mfu") is not None:
        head.append(f"MFU {totals['mfu']:.3f}")
    if "stall_frac" in totals:
        head.append(f"stall {100 * totals['stall_frac']:.1f}%")
    if totals.get("live_processes") is not None:
        head.append(f"procs {totals['live_processes']}")
    if totals.get("peak_bytes_in_use") is not None:
        head.append(f"mem {totals['peak_bytes_in_use'] / 2**30:.2f} GiB")
    if head:
        out.append("  ".join(head))
    out.append("")

    if alerts:
        out.append(f"ALERTS ({len(alerts)}):")
        for a in alerts[-5:]:
            out.append(f"  step {a.get('step', '?'):>8} "
                       f"[{a.get('severity', 'warn')}] "
                       f"{a.get('reason', '?')}")
        out.append("")

    if obs:
        out.append(f"{'ep':>4} {'steps':>6} {'p50ms':>8} {'p90ms':>8} "
                   f"{'p99ms':>8} {'stall%':>7} {'thruput':>9} {'mfu':>6}")
        for r in obs[-last:]:
            t = r.get("tokens_per_sec", r.get("examples_per_sec"))
            p50 = r.get("step_time_p50_s")
            p90 = r.get("step_time_p90_s")
            p99 = r.get("step_time_p99_s")
            mfu = r.get("mfu")
            out.append(
                f"{r['epoch']:>4} {r.get('steps', 0):>6} "
                f"{'-' if p50 is None else f'{p50 * 1e3:8.1f}'} "
                f"{'-' if p90 is None else f'{p90 * 1e3:8.1f}'} "
                f"{'-' if p99 is None else f'{p99 * 1e3:8.1f}'} "
                f"{100 * r.get('stall_frac', 0.0):>6.1f}% "
                f"{_fmt_rate(t):>9} "
                f"{'-' if mfu is None else f'{mfu:6.3f}'}")
        thr_series = [r.get("tokens_per_sec", r.get("examples_per_sec"))
                      for r in obs]
        spark = sparkline(thr_series)
        if spark:
            out.append(f"throughput/epoch  {spark}")
        out.append("")

    if windows:
        p50s = [w["step_time_p50_s"] for w in windows]
        out.append(f"step-time trend ({windows[0]['step_lo']}"
                   f"→{windows[-1]['step_hi']}, p50 per window): "
                   f"{sparkline(p50s)}")
        out.append(f"  first {p50s[0] * 1e3:.1f}ms  "
                   f"last {p50s[-1] * 1e3:.1f}ms  "
                   f"worst p99 {max(w['step_time_p99_s'] for w in windows) * 1e3:.1f}ms")

    if len(out) <= 3:
        out.append("waiting for records...")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# static HTML report
# ---------------------------------------------------------------------------

# Chart palette: the dataviz reference categorical slots 1-2 (blue,
# orange — adjacent-pair CVD-validated in both modes) plus the status
# red for alerts; text/surface tokens likewise, stepped per mode.
_CSS = """
:root { color-scheme: light dark; }
body {
  margin: 0; padding: 24px; background: #fcfcfb; color: #0b0b0b;
  font: 14px/1.5 system-ui, -apple-system, sans-serif;
  --surface: #fcfcfb; --text-2: #52514e; --grid: #e8e7e3;
  --s1: #2a78d6; --s2: #eb6834; --s3: #7a57c9; --s4: #177c70;
  --s5: #a84f93; --bad: #e34948;
}
@media (prefers-color-scheme: dark) {
  body { background: #1a1a19; color: #fff;
         --surface: #1a1a19; --text-2: #c3c2b7; --grid: #343431;
         --s1: #3987e5; --s2: #d95926; --s3: #9678db; --s4: #2b9486;
         --s5: #c36bad; --bad: #e66767; }
}
h1 { font-size: 18px; margin: 0 0 4px; }
.sub { color: var(--text-2); margin: 0 0 20px; }
.tiles { display: flex; flex-wrap: wrap; gap: 12px; margin: 0 0 24px; }
.tile { border: 1px solid var(--grid); border-radius: 8px;
        padding: 12px 16px; min-width: 110px; }
.tile .v { font-size: 22px; font-weight: 600; }
.tile .k { color: var(--text-2); font-size: 12px; }
.card { border: 1px solid var(--grid); border-radius: 8px;
        padding: 16px; margin: 0 0 20px; }
.card h2 { font-size: 14px; margin: 0 0 8px; }
.legend { color: var(--text-2); font-size: 12px; margin: 0 0 8px; }
.legend .sw { display: inline-block; width: 10px; height: 10px;
              border-radius: 3px; vertical-align: -1px; margin-right: 4px; }
table { border-collapse: collapse; width: 100%; font-size: 13px; }
th { text-align: right; color: var(--text-2); font-weight: 500; }
th:first-child, td:first-child { text-align: left; }
td { text-align: right; }
th, td { padding: 4px 8px; border-bottom: 1px solid var(--grid); }
.alert { color: var(--bad); }
svg text { fill: var(--text-2); font-size: 11px; }
svg .gridline { stroke: var(--grid); stroke-width: 1; }
"""


def _svg_line_chart(series, width=640, height=180, fmt=lambda v: f"{v:g}"):
    """Minimal single-axis SVG line chart. ``series`` is a list of
    (css_color_var, label, [(x, y), ...]); one shared y scale, 2px
    lines, 8px hover targets with native <title> tooltips."""
    pad_l, pad_r, pad_t, pad_b = 48, 12, 8, 22
    pts = [p for _, _, ps in series for p in ps if p[1] is not None]
    if not pts:
        return ""
    xs = [p[0] for p in pts]
    ys = [p[1] for p in pts]
    x_lo, x_hi = min(xs), max(xs)
    y_lo, y_hi = min(ys), max(ys)
    if y_lo > 0 and y_lo < y_hi * 0.5:
        y_lo = 0.0              # near-zero floors: anchor at zero
    x_span = (x_hi - x_lo) or 1.0
    y_span = (y_hi - y_lo) or 1.0
    iw = width - pad_l - pad_r
    ih = height - pad_t - pad_b

    def sx(x):
        return pad_l + (x - x_lo) / x_span * iw

    def sy(y):
        return pad_t + ih - (y - y_lo) / y_span * ih

    parts = [f'<svg viewBox="0 0 {width} {height}" role="img" '
             f'style="width:100%;height:auto">']
    for frac in (0.0, 0.5, 1.0):
        y = pad_t + ih * frac
        val = y_hi - y_span * frac
        parts.append(f'<line class="gridline" x1="{pad_l}" y1="{y:.1f}" '
                     f'x2="{width - pad_r}" y2="{y:.1f}"/>')
        parts.append(f'<text x="{pad_l - 6}" y="{y + 4:.1f}" '
                     f'text-anchor="end">{fmt(val)}</text>')
    parts.append(f'<text x="{pad_l}" y="{height - 6}">{fmt_x(x_lo)}</text>')
    parts.append(f'<text x="{width - pad_r}" y="{height - 6}" '
                 f'text-anchor="end">{fmt_x(x_hi)}</text>')
    for color, label, ps in series:
        ps = [p for p in ps if p[1] is not None]
        if not ps:
            continue
        d = " ".join(f"{sx(x):.1f},{sy(y):.1f}" for x, y in ps)
        parts.append(f'<polyline points="{d}" fill="none" '
                     f'stroke="var({color})" stroke-width="2" '
                     f'stroke-linejoin="round"/>')
        for x, y in ps:
            parts.append(
                f'<circle cx="{sx(x):.1f}" cy="{sy(y):.1f}" r="8" '
                f'fill="transparent" stroke="none">'
                f'<title>{html_mod.escape(label)} @ {fmt_x(x)}: '
                f'{fmt(y)}</title></circle>')
    parts.append("</svg>")
    return "".join(parts)


def fmt_x(x) -> str:
    return f"{int(x):,}"


def render_html(summary: dict, source: str) -> str:
    totals = summary["totals"]
    obs = summary["obs_epochs"]
    epochs = summary["epochs"]
    windows = summary["step_windows"]
    alerts = summary["alerts"]
    e = html_mod.escape

    tiles = []

    def tile(value, key):
        tiles.append(f'<div class="tile"><div class="v">{e(str(value))}'
                     f'</div><div class="k">{e(key)}</div></div>')

    thr = totals.get("tokens_per_sec", totals.get("examples_per_sec"))
    if thr is not None:
        tile(_fmt_rate(thr),
             "tokens/s" if "tokens_per_sec" in totals else "examples/s")
    if totals.get("mfu") is not None:
        tile(f"{totals['mfu']:.3f}", "MFU")
    if "stall_frac" in totals:
        tile(f"{100 * totals['stall_frac']:.1f}%", "input stall")
    if totals.get("peak_bytes_in_use") is not None:
        tile(f"{totals['peak_bytes_in_use'] / 2**30:.2f} GiB",
             "peak device mem")
    if totals.get("live_processes") is not None:
        tile(totals["live_processes"], "live processes")
    tile(totals.get("alerts", 0), "alerts")

    cards = []
    if obs:
        pts = [(r["epoch"],
                r.get("tokens_per_sec", r.get("examples_per_sec")))
               for r in obs]
        chart = _svg_line_chart([("--s1", "throughput", pts)],
                                fmt=_fmt_rate)
        cards.append('<div class="card"><h2>Throughput per epoch</h2>'
                     + chart + "</div>")
    if windows:
        p50 = [(w["step_lo"], w["step_time_p50_s"] * 1e3) for w in windows]
        p99 = [(w["step_lo"], w["step_time_p99_s"] * 1e3) for w in windows]
        chart = _svg_line_chart(
            [("--s1", "p50", p50), ("--s2", "p99", p99)],
            fmt=lambda v: f"{v:.1f}ms")
        cards.append(
            '<div class="card"><h2>Step time trend (per obs_step window)'
            '</h2><div class="legend">'
            '<span class="sw" style="background:var(--s1)"></span>p50'
            '&nbsp;&nbsp;'
            '<span class="sw" style="background:var(--s2)"></span>p99'
            "</div>" + chart + "</div>")

    if alerts:
        rows = "".join(
            f'<tr class="alert"><td>{e(str(a.get("reason", "?")))}</td>'
            f'<td>{a.get("step", "?")}</td>'
            f'<td>{e(str(a.get("severity", "warn")))}</td>'
            f'<td style="text-align:left">'
            f'{e(json.dumps({k: v for k, v in a.items() if k not in ("kind", "reason", "step", "severity")}))}'
            f"</td></tr>" for a in alerts)
        cards.append('<div class="card"><h2>Alerts</h2><table>'
                     "<tr><th>reason</th><th>step</th><th>severity</th>"
                     '<th style="text-align:left">detail</th></tr>'
                     + rows + "</table></div>")

    if epochs or obs:
        by_epoch = {r["epoch"]: dict(r) for r in epochs}
        for r in obs:
            by_epoch.setdefault(r["epoch"], {}).update(r)
        rows = []
        for ep in sorted(by_epoch):
            r = by_epoch[ep]
            t = r.get("tokens_per_sec", r.get("examples_per_sec"))
            p50 = r.get("step_time_p50_s")
            rows.append(
                f"<tr><td>{ep}</td>"
                f"<td>{r.get('seconds', r.get('train_seconds', 0)):.1f}</td>"
                f"<td>{r.get('train_loss', float('nan')):.4f}</td>"
                f"<td>{r.get('test_accuracy', float('nan')):.4f}</td>"
                f"<td>{'-' if t is None else _fmt_rate(t)}</td>"
                f"<td>{'-' if p50 is None else f'{p50 * 1e3:.1f}'}</td>"
                f"<td>{100 * r.get('stall_frac', 0.0):.1f}%</td></tr>")
        cards.append('<div class="card"><h2>Epochs</h2><table>'
                     "<tr><th>ep</th><th>secs</th><th>train loss</th>"
                     "<th>test acc</th><th>thruput</th><th>p50 ms</th>"
                     "<th>stall</th></tr>" + "".join(rows)
                     + "</table></div>")

    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<meta name='viewport' content='width=device-width,"
            "initial-scale=1'>"
            f"<title>tpunet obs — {e(source)}</title>"
            f"<style>{_CSS}</style></head><body>"
            f"<h1>tpunet observability report</h1>"
            f'<p class="sub">{e(source)} — generated '
            f"{time.strftime('%Y-%m-%d %H:%M:%S')}</p>"
            f'<div class="tiles">{"".join(tiles)}</div>'
            + "".join(cards) + "</body></html>")


# ---------------------------------------------------------------------------
# fleet view (tpunet.obs.agg)
# ---------------------------------------------------------------------------

_SERIES = ("--s1", "--s2", "--s3", "--s4", "--s5")


def _ms(v) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}"


def _fleet_alerts(agg):
    """What the fleet alert panels show: the bridge's own fleet-scope
    alerts plus per-run pages ingested from the streams
    (thread_stalled, step_stall, ...) and each stream's last crash —
    deduplicated so a crash the bridge already paged is one row, not
    two — but keyed on the distinguishing detail too (thread name,
    report path), so two stalled threads of one stream stay two rows
    (the per-thread semantics tpunet/obs/health.py promises)."""
    def key(a):
        return (a.get("reason"), a.get("stream"), a.get("thread"),
                a.get("report_path"))

    alerts = list(agg.bridge.alerts)
    seen = {key(a) for a in alerts}
    for a in agg.recent_alerts():
        if key(a) not in seen:
            seen.add(key(a))
            alerts.append(a)
    return alerts


def _regression_rows(agg):
    """Fleet regression panel rows: pairwise last-window compare of
    trainer streams sharing a config fingerprint
    (tpunet/obs/history/compare.stream_regressions) — the cross-run
    view that makes an elastic rerun judgeable against its static
    baseline from the same dashboard."""
    from tpunet.obs.history import stream_regressions
    return stream_regressions(agg.streams())


def render_fleet_terminal(rollup: dict, ages: dict, source: str,
                          alerts=(), regressions=()) -> str:
    """One text frame of the fleet rollup + per-stream table."""
    out = [f"tpunet fleet dashboard — {source} — "
           f"{time.strftime('%H:%M:%S')}"]
    head = [f"streams {rollup.get('streams', 0)}"]
    unit = rollup.get("throughput_unit")
    if unit:
        head.append(f"{_fmt_rate(rollup[f'{unit}_per_sec'])} "
                    f"{'tok/s' if unit == 'tokens' else 'ex/s'} total")
    if rollup.get("step_time_p50_s") is not None:
        head.append(f"fleet p50 {_ms(rollup['step_time_p50_s'])}ms "
                    f"p99 {_ms(rollup.get('step_time_p99_s'))}ms "
                    f"(±{rollup.get('step_time_rank_err', 0):.3f} rank)")
    if rollup.get("straggler_factor") is not None:
        head.append(f"straggler x{rollup['straggler_factor']:.2f}")
    if rollup.get("serve_queue_depth") is not None:
        head.append(f"queue {rollup['serve_queue_depth']}")
    if rollup.get("crashes_total"):
        head.append(f"CRASHES {rollup['crashes_total']}")
    if rollup.get("elastic_events_total"):
        last = rollup.get("elastic_last_event", "")
        gen = rollup.get("elastic_generation")
        head.append(f"ELASTIC {rollup['elastic_events_total']}"
                    + (f" (last {last}"
                       + (f", gen {gen}" if gen is not None else "")
                       + ")" if last else ""))
    if rollup.get("routers"):
        last = rollup.get("router_last_event", "")
        head.append(
            f"ROUTER {rollup.get('router_replicas_healthy', 0)}"
            f"/{rollup.get('router_replicas', 0)} healthy"
            + (f" (last {last})" if last else ""))
    out.append("  ".join(head))
    out.append("")

    if alerts:
        out.append(f"FLEET ALERTS ({len(alerts)}):")
        for a in alerts[-8:]:
            extra = ""
            if a.get("reason") == "crash":
                extra = f" {a.get('cause', '')}"
            elif a.get("reason") == "thread_stalled":
                extra = (f" {a.get('thread', '')} "
                         f"{a.get('age_s', '')}s")
            out.append(f"  [{a.get('scope', '?'):>6}] "
                       f"{a.get('reason', '?')} "
                       f"{a.get('stream', '')}{extra}")
        out.append("")

    rows = rollup.get("per_stream", [])
    if rows:
        out.append(f"{'stream':<24} {'ep':>4} {'step':>8} {'p50ms':>8} "
                   f"{'thruput':>9} {'mfu':>6} {'age s':>6}")
        for r in rows:
            t = r.get("tokens_per_sec", r.get("examples_per_sec"))
            mfu = r.get("mfu")
            age = ages.get(r["stream"])
            out.append(
                f"{r['stream']:<24.24} {r.get('epoch', '-'):>4} "
                f"{r.get('step', '-'):>8} "
                f"{_ms(r.get('step_time_p50_s')):>8} "
                f"{_fmt_rate(t):>9} "
                f"{'-' if mfu is None else f'{mfu:6.3f}'} "
                f"{'-' if age is None else f'{age:6.1f}'}")
        out.append("")

    if regressions:
        flagged = [r for r in regressions
                   if r["verdict"] != "within_error"]
        out.append(f"REGRESSION COMPARE ({len(regressions)} pair(s), "
                   f"{len(flagged)} outside error bars):")
        for r in regressions[-6:]:
            out.append(
                f"  [{r['verdict']:>12}] {r['stream']:<24.24} vs "
                f"{r['base']:<24.24} p50 {_ms(r['a'])} -> "
                f"{_ms(r['b'])}ms "
                f"({100 * (r.get('delta_frac') or 0):+.1f}%)")
        out.append("")

    if rollup.get("routers"):
        out.append(
            f"router: {rollup.get('router_replicas_healthy', 0)}"
            f"/{rollup.get('router_replicas', 0)} replicas healthy  "
            f"queue {rollup.get('router_fleet_queue_depth', 0)}  "
            f"evictions {rollup.get('router_evictions_total', 0)}  "
            f"respawns {rollup.get('router_respawns_total', 0)}  "
            f"scale +{rollup.get('router_scale_ups_total', 0)}"
            f"/-{rollup.get('router_scale_downs_total', 0)}"
            + (f"  last {rollup['router_last_event']}"
               if rollup.get("router_last_event") else ""))
    if rollup.get("serve_replicas"):
        out.append(
            f"serve: {rollup['serve_replicas']} replicas  "
            f"queue {rollup.get('serve_queue_depth', 0)}  "
            f"slots {rollup.get('serve_active_slots', 0)}"
            f"/{rollup.get('serve_slots', 0)}  "
            f"reject {100 * rollup.get('serve_reject_rate', 0.0):.2f}%")
        if rollup.get("serve_ttft_p50_s") is not None:
            out.append(
                f"  TTFT p50 {_ms(rollup['serve_ttft_p50_s'])}ms "
                f"p99 {_ms(rollup.get('serve_ttft_p99_s'))}ms   "
                f"e2e p50 {_ms(rollup.get('serve_e2e_p50_s'))}ms "
                f"p99 {_ms(rollup.get('serve_e2e_p99_s'))}ms")
    if rollup.get("slo_table"):
        worst = rollup.get("fleet_slo_worst_budget_remaining")
        line = (f"SLO: {rollup.get('fleet_slo_firing', 0)} firing  "
                f"pages {rollup.get('fleet_slo_pages_total', 0)}  "
                f"tickets {rollup.get('fleet_slo_tickets_total', 0)}")
        if worst is not None:
            line += (f"  worst budget {100 * worst:.1f}% "
                     f"({rollup.get('fleet_slo_worst_slo', '?')})")
        out.append(line)
        spark = sparkline(rollup.get("slo_burn_spark", []))
        if spark:
            out.append(f"  page-burn trend  {spark}")
        for r in rollup["slo_table"][:8]:
            b = r.get("budget_remaining")
            burn = r.get("page_burn_long")
            flag = ("FIRING" if r.get("page_firing")
                    else "ticket" if r.get("ticket_firing") else "")
            out.append(
                f"  {r.get('name', '?'):<14.14} "
                f"obj {r.get('objective', 0):<7} "
                f"budget {'-' if b is None else f'{100 * b:6.1f}%'}  "
                f"burn {'-' if burn is None else f'{burn:7.2f}x'}  "
                f"{flag}")
        if rollup.get("fleet_slo_probe_requests_total"):
            out.append(
                f"  probes {rollup['fleet_slo_probe_requests_total']}"
                f" ({rollup.get('fleet_slo_probe_failures_total', 0)}"
                f" failed, "
                f"{rollup.get('fleet_slo_probe_mismatches_total', 0)}"
                f" golden mismatches)"
                + (f"  last failed trace "
                   f"{rollup['fleet_slo_last_failed_trace']}"
                   if rollup.get("fleet_slo_last_failed_trace")
                   else ""))
        out.append("")
    if rollup.get("trace_records_total"):
        line = (f"trace: {rollup['trace_records_total']} sampled")
        if rollup.get("trace_queue_p99_s") is not None:
            line += (
                f"  p99 split queue {_ms(rollup['trace_queue_p99_s'])}"
                f" / prefill {_ms(rollup.get('trace_prefill_p99_s'))}"
                f" / first-decode "
                f"{_ms(rollup.get('trace_first_decode_p99_s'))} ms")
        out.append(line)
        for t in rollup.get("trace_slow", [])[:5]:
            out.append("  " + _trace_exemplar_row(t))
    if len(out) <= 3:
        out.append("waiting for records...")
    return "\n".join(out)


def _trace_exemplar_row(t: dict, bar_width: int = 24) -> str:
    """One slow-trace exemplar line: trace_id (the obs_timeline
    lookup key), e2e, and a phase bar splitting it into
    q(ueue)/p(refill)/d(ecode) shares."""
    e2e = t.get("e2e_s") or 0.0
    q = t.get("queue_s") or 0.0
    p = t.get("prefill_s") or 0.0
    d = max(0.0, e2e - q - p)
    bar = ""
    if e2e > 0:
        nq = int(round(bar_width * q / e2e))
        np_ = int(round(bar_width * p / e2e))
        nd = max(0, bar_width - nq - np_) if d > 0 else 0
        bar = "[" + "q" * nq + "p" * np_ + "d" * nd + "]"
    extra = ""
    if t.get("failover_count"):
        extra += f"  failovers {t['failover_count']}"
    if t.get("preemptions"):
        extra += f"  preempts {t['preemptions']}"
    return (f"{t.get('trace_id', '?'):<16.16} "
            f"e2e {_ms(e2e):>7}ms  {bar:<{bar_width + 2}} "
            f"q {_ms(q)} p {_ms(p)} ms  "
            f"{t.get('finish_reason', '')}{extra}")


def render_fleet_html(rollup: dict, streams, source: str,
                      alerts=(), regressions=()) -> str:
    """Static fleet report: rollup tiles, per-stream step-time chart,
    regression-compare panel, per-stream table, serve SLO panel,
    fleet alert table."""
    e = html_mod.escape
    tiles = []

    def tile(value, key):
        tiles.append(f'<div class="tile"><div class="v">{e(str(value))}'
                     f'</div><div class="k">{e(key)}</div></div>')

    tile(rollup.get("streams", 0), "streams")
    unit = rollup.get("throughput_unit")
    if unit:
        tile(_fmt_rate(rollup[f"{unit}_per_sec"]),
             "tokens/s total" if unit == "tokens" else "examples/s total")
    if rollup.get("step_time_p50_s") is not None:
        tile(f"{_ms(rollup['step_time_p50_s'])} ms", "fleet step p50")
        tile(f"{_ms(rollup.get('step_time_p99_s'))} ms",
             f"fleet step p99 (±{rollup.get('step_time_rank_err', 0):.3f})")
    if rollup.get("straggler_factor") is not None:
        tile(f"x{rollup['straggler_factor']:.2f}", "straggler factor")
    if rollup.get("step_lag") is not None:
        tile(rollup["step_lag"], "step lag")
    tile(rollup.get("alerts_total", 0) + len(alerts), "alerts")
    if rollup.get("crashes_total"):
        tile(rollup["crashes_total"], "crashes")
    if rollup.get("elastic_events_total"):
        last = rollup.get("elastic_last_event", "")
        tile(rollup["elastic_events_total"],
             f"elastic events{f' (last {last})' if last else ''}")
    if rollup.get("routers"):
        last = rollup.get("router_last_event", "")
        tile(f"{rollup.get('router_replicas_healthy', 0)}"
             f"/{rollup.get('router_replicas', 0)}",
             f"router replicas{f' (last {last})' if last else ''}")
        if rollup.get("router_evictions_total") is not None:
            tile(f"{rollup.get('router_evictions_total', 0)}"
                 f"/{rollup.get('router_respawns_total', 0)}",
                 "router evictions/respawns")

    cards = []
    # Per-stream step-time trend: one line per stream, shared y scale.
    series = []
    legend = []
    for i, s in enumerate(streams):
        pts = [(ep, p * 1e3)
               for ep, p in list(getattr(s, "epoch_p50s", []))]
        if not pts:
            continue
        color = _SERIES[i % len(_SERIES)]
        series.append((color, s.key, pts))
        legend.append(f'<span class="sw" style="background:var({color})">'
                      f"</span>{e(s.key)}")
    if series:
        chart = _svg_line_chart(series, fmt=lambda v: f"{v:.1f}ms")
        cards.append('<div class="card"><h2>Step time p50 per epoch, '
                     'per stream</h2><div class="legend">'
                     + "&nbsp;&nbsp;".join(legend) + "</div>"
                     + chart + "</div>")

    if regressions:
        body = []
        for r in regressions:
            frac = r.get("delta_frac")
            body.append(
                f"<tr><td>{e(str(r['stream']))}</td>"
                f"<td>{e(str(r['base']))}</td>"
                f"<td>{e(str(r.get('fingerprint', '')))}</td>"
                f"<td>{_ms(r['a'])}</td><td>{_ms(r['b'])}</td>"
                f"<td>{'-' if frac is None else f'{100 * frac:+.1f}%'}"
                f"</td><td>{e(r['verdict'])}</td></tr>")
        cards.append(
            '<div class="card"><h2>Regression compare (same config '
            "fingerprint, step-time p50 vs DKW error bars)</h2>"
            "<table><tr><th>stream</th><th>baseline</th>"
            "<th>fingerprint</th><th>base p50 ms</th><th>p50 ms</th>"
            "<th>delta</th><th>verdict</th></tr>"
            + "".join(body) + "</table></div>")

    rows = rollup.get("per_stream", [])
    if rows:
        body = []
        for r in rows:
            t = r.get("tokens_per_sec", r.get("examples_per_sec"))
            mfu = r.get("mfu")
            body.append(
                f"<tr><td>{e(str(r['stream']))}</td>"
                f"<td>{e(str(r.get('host', '-')))}</td>"
                f"<td>{r.get('epoch', '-')}</td>"
                f"<td>{r.get('step', '-')}</td>"
                f"<td>{_ms(r.get('step_time_p50_s'))}</td>"
                f"<td>{'-' if t is None else _fmt_rate(t)}</td>"
                f"<td>{'-' if mfu is None else f'{mfu:.3f}'}</td>"
                f"<td>{r.get('alerts', 0)}</td></tr>")
        cards.append('<div class="card"><h2>Streams</h2><table>'
                     "<tr><th>stream</th><th>host</th><th>ep</th>"
                     "<th>step</th><th>p50 ms</th><th>thruput</th>"
                     "<th>mfu</th><th>alerts</th></tr>"
                     + "".join(body) + "</table></div>")

    if rollup.get("serve_replicas"):
        sv_tiles = []

        def sv_tile(value, key):
            sv_tiles.append(
                f'<div class="tile"><div class="v">{e(str(value))}'
                f'</div><div class="k">{e(key)}</div></div>')

        sv_tile(rollup["serve_replicas"], "replicas")
        sv_tile(rollup.get("serve_queue_depth", 0), "total queue depth")
        sv_tile(f"{rollup.get('serve_active_slots', 0)}"
                f"/{rollup.get('serve_slots', 0)}", "active slots")
        if rollup.get("serve_ttft_p50_s") is not None:
            sv_tile(f"{_ms(rollup['serve_ttft_p50_s'])} ms", "fleet TTFT p50")
            sv_tile(f"{_ms(rollup.get('serve_ttft_p99_s'))} ms",
                    f"fleet TTFT p99 "
                    f"(±{rollup.get('serve_ttft_rank_err', 0):.3f})")
        if rollup.get("serve_e2e_p99_s") is not None:
            sv_tile(f"{_ms(rollup['serve_e2e_p99_s'])} ms", "fleet e2e p99")
        sv_tile(f"{100 * rollup.get('serve_reject_rate', 0.0):.2f}%",
                "reject rate")
        body = []
        for r in rows:
            if r.get("serve_requests_total") is None:
                continue
            body.append(
                f"<tr><td>{e(str(r['stream']))}</td>"
                f"<td>{r.get('serve_queue_depth', 0)}</td>"
                f"<td>{r.get('serve_active_slots', 0)}"
                f"/{r.get('serve_slots', 0)}</td>"
                f"<td>{r.get('serve_requests_total', 0)}</td>"
                f"<td>{100 * r.get('serve_reject_rate', 0.0):.2f}%</td>"
                f"<td>{_ms(r.get('serve_ttft_p50_s'))}</td>"
                f"<td>{_ms(r.get('serve_e2e_p99_s'))}</td></tr>")
        table = ""
        if body:
            table = ("<table><tr><th>replica</th><th>queue</th>"
                     "<th>slots</th><th>requests</th><th>reject</th>"
                     "<th>ttft p50 ms</th><th>e2e p99 ms</th></tr>"
                     + "".join(body) + "</table>")
        cards.append('<div class="card"><h2>Serve SLO (fleet)</h2>'
                     f'<div class="tiles">{"".join(sv_tiles)}</div>'
                     + table + "</div>")

    if rollup.get("slo_table"):
        slo_tiles = []

        def slo_tile(value, key):
            slo_tiles.append(
                f'<div class="tile"><div class="v">{e(str(value))}'
                f'</div><div class="k">{e(key)}</div></div>')

        worst = rollup.get("fleet_slo_worst_budget_remaining")
        if worst is not None:
            slo_tile(f"{100 * worst:.1f}%",
                     f"worst budget "
                     f"({rollup.get('fleet_slo_worst_slo', '?')})")
        if rollup.get("fleet_slo_max_page_burn") is not None:
            slo_tile(f"x{rollup['fleet_slo_max_page_burn']:.2f}",
                     "max page burn")
        slo_tile(rollup.get("fleet_slo_firing", 0), "SLOs firing")
        slo_tile(f"{rollup.get('fleet_slo_pages_total', 0)}"
                 f"/{rollup.get('fleet_slo_tickets_total', 0)}",
                 "pages/tickets")
        if rollup.get("fleet_slo_probe_requests_total"):
            slo_tile(f"{rollup.get('fleet_slo_probe_failures_total', 0)}"
                     f"+{rollup.get('fleet_slo_probe_mismatches_total', 0)}"
                     f"/{rollup['fleet_slo_probe_requests_total']}",
                     "probe fails+mismatches/total")
        body = []
        for r in rollup["slo_table"]:
            b = r.get("budget_remaining")
            burn = r.get("page_burn_long")
            firing = ("page" if r.get("page_firing")
                      else "ticket" if r.get("ticket_firing") else "")
            cls = ' class="alert"' if firing else ""
            body.append(
                f"<tr{cls}><td>{e(str(r.get('stream', '')))}</td>"
                f"<td>{e(str(r.get('name', '?')))}</td>"
                f"<td>{e(str(r.get('sli', '')))}</td>"
                f"<td>{r.get('objective', '-')}</td>"
                f"<td>{'-' if b is None else f'{100 * b:.1f}%'}</td>"
                f"<td>{'-' if burn is None else f'x{burn:.2f}'}</td>"
                f"<td>{r.get('pages_total', 0)}"
                f"/{r.get('tickets_total', 0)}</td>"
                f"<td>{e(firing)}</td></tr>")
        extras = ""
        spark = sparkline(rollup.get("slo_burn_spark", []), width=48)
        if spark:
            extras += (f'<p class="legend">page-burn trend '
                       f"(worst stream): <code>{e(spark)}</code></p>")
        if rollup.get("fleet_slo_last_failed_trace"):
            tid = rollup["fleet_slo_last_failed_trace"]
            extras += (f'<p class="legend">last failed probe trace: '
                       f"<code>{e(str(tid))}</code> (join with "
                       "scripts/obs_timeline.py)</p>")
        cards.append(
            '<div class="card"><h2>Error budget (SLOs, '
            "tpunet/obs/slo.py)</h2>"
            f'<div class="tiles">{"".join(slo_tiles)}</div>'
            + extras
            + "<table><tr><th>stream</th><th>slo</th><th>sli</th>"
              "<th>objective</th><th>budget left</th>"
              "<th>page burn</th><th>pages/tickets</th>"
              "<th>firing</th></tr>"
            + "".join(body) + "</table></div>")

    if rollup.get("trace_slow"):
        tr_tiles = []

        def tr_tile(value, key):
            tr_tiles.append(
                f'<div class="tile"><div class="v">{e(str(value))}'
                f'</div><div class="k">{e(key)}</div></div>')

        tr_tile(rollup.get("trace_records_total", 0), "traces sampled")
        if rollup.get("trace_queue_p99_s") is not None:
            tr_tile(f"{_ms(rollup['trace_queue_p99_s'])} ms",
                    "queue p99")
            tr_tile(f"{_ms(rollup.get('trace_prefill_p99_s'))} ms",
                    "prefill p99")
            tr_tile(f"{_ms(rollup.get('trace_first_decode_p99_s'))} ms",
                    "first-decode p99")
        body = []
        for t in rollup["trace_slow"]:
            e2e = t.get("e2e_s") or 0.0
            q = t.get("queue_s") or 0.0
            p = t.get("prefill_s") or 0.0
            d = max(0.0, e2e - q - p)
            bar = ""
            if e2e > 0:
                segs = (("#e0a030", q), ("#4090e0", p), ("#40c070", d))
                bar = "".join(
                    f'<span style="display:inline-block;height:10px;'
                    f"background:{c};width:{max(1, round(120 * v / e2e))}px"
                    '"></span>' for c, v in segs if v > 0)
            body.append(
                f"<tr><td><code>{e(str(t.get('trace_id', '?')))}</code></td>"
                f"<td>{_ms(e2e)}</td>"
                f'<td style="text-align:left">{bar}</td>'
                f"<td>{_ms(q)}</td><td>{_ms(p)}</td>"
                f"<td>{e(str(t.get('finish_reason', '')))}</td>"
                f"<td>{t.get('failover_count', 0)}</td></tr>")
        cards.append(
            '<div class="card"><h2>Slow-request exemplars '
            "(top traces by e2e — join the full span tree with "
            "scripts/obs_timeline.py)</h2>"
            f'<div class="tiles">{"".join(tr_tiles)}</div>'
            "<table><tr><th>trace_id</th><th>e2e ms</th>"
            '<th style="text-align:left">queue / prefill / decode</th>'
            "<th>queue ms</th><th>prefill ms</th><th>finish</th>"
            "<th>failovers</th></tr>"
            + "".join(body) + "</table></div>")

    if alerts:
        body = "".join(
            f'<tr class="alert"><td>{e(str(a.get("reason", "?")))}</td>'
            f'<td>{e(str(a.get("scope", "?")))}</td>'
            f'<td>{e(str(a.get("stream", "")))}</td>'
            f'<td style="text-align:left">'
            f'{e(json.dumps({k: v for k, v in a.items() if k not in ("kind", "reason", "scope", "stream", "severity", "step", "run_id", "process_index", "host")}))}'
            f"</td></tr>" for a in alerts)
        cards.append('<div class="card"><h2>Fleet alerts</h2><table>'
                     "<tr><th>reason</th><th>scope</th><th>stream</th>"
                     '<th style="text-align:left">detail</th></tr>'
                     + body + "</table></div>")

    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<meta name='viewport' content='width=device-width,"
            "initial-scale=1'>"
            f"<title>tpunet fleet — {e(source)}</title>"
            f"<style>{_CSS}</style></head><body>"
            f"<h1>tpunet fleet observability report</h1>"
            f'<p class="sub">{e(source)} — generated '
            f"{time.strftime('%Y-%m-%d %H:%M:%S')}</p>"
            f'<div class="tiles">{"".join(tiles)}</div>'
            + "".join(cards) + "</body></html>")


# ---------------------------------------------------------------------------
# record sources: file tail / HTTP listener
# ---------------------------------------------------------------------------


class RecordBuffer:
    """Thread-safe accumulator both sources feed.

    Bounded: a multi-day run with --obs-step-every 1 would otherwise
    grow (and re-summarize) an unbounded list. Epoch-grained records
    and alerts are small and all kept; high-volume ``obs_step``
    records are compacted to the most recent ``max_steps`` — exactly
    what the trend view renders anyway."""

    def __init__(self, max_steps: int = 20_000):
        self._records: list = []
        self._max_steps = max_steps
        self._lock = threading.Lock()

    def feed(self, records) -> None:
        with self._lock:
            self._records.extend(records)
            n_steps = sum(1 for r in self._records
                          if r.get("kind") == "obs_step")
            if n_steps > 2 * self._max_steps:
                drop = n_steps - self._max_steps
                kept = []
                for r in self._records:
                    if drop > 0 and r.get("kind") == "obs_step":
                        drop -= 1
                        continue
                    kept.append(r)
                self._records = kept

    def clear(self) -> None:
        """Forget everything — the tailed file was truncated by a
        fresh run; merging two runs' records would corrupt every
        aggregate."""
        with self._lock:
            self._records = []

    def snapshot(self) -> list:
        with self._lock:
            return list(self._records)


def serve_http(port: int, buf: RecordBuffer, source_name: str,
               agg=None):
    """Line-JSON ingest endpoint matching HttpLineTransport: POST
    bodies are newline-delimited records; GET returns the current
    text render. With ``agg`` (fleet mode) each record is also routed
    into the aggregator by its identity stamp — N runs posting
    concurrently become N streams (handler threads ingest
    concurrently; the aggregator is thread-safe)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from tpunet.obs.summary import summarize

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            body = self.rfile.read(n)
            records = []
            for line in body.splitlines():
                if not line.strip():
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    pass    # one bad line must not poison the stream
            if agg is not None:
                # Fleet mode renders from the aggregator only; also
                # filling the buffer would grow an unrendered list of
                # non-step records without bound.
                agg.ingest_many(
                    records, source=self.client_address[0])
            else:
                buf.feed(records)
            self.send_response(204)
            self.end_headers()

        def do_GET(self):
            if agg is not None:
                text = render_fleet_terminal(
                    agg.rollup(), agg.heartbeat_ages(), source_name,
                    alerts=_fleet_alerts(agg),
                    regressions=_regression_rows(agg))
            else:
                text = render_terminal(summarize(buf.snapshot()),
                                       source_name)
            data = (text + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("", port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    return server


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="*",
                    help="metrics.jsonl (or a directory containing "
                         "one); several paths = fleet mode; omit with "
                         "--listen")
    ap.add_argument("--listen", type=int, metavar="PORT",
                    help="receive line-JSON POSTs (train.py "
                         "--obs-http http://HOST:PORT/) instead of "
                         "tailing a file")
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit (no follow loop)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh/poll period in seconds (default 2)")
    ap.add_argument("--html", metavar="OUT",
                    help="write a static self-contained HTML report "
                         "(re-written every refresh in follow mode)")
    ap.add_argument("--last", type=int, default=10,
                    help="epochs shown in the terminal table")
    ap.add_argument("--fleet", action="store_true",
                    help="aggregate --listen streams by run identity "
                         "(automatic when several paths are given)")
    ap.add_argument("--straggler-factor", type=float, default=2.0,
                    help="fleet straggler alert: slowest stream's step "
                         "time above FACTOR x the median of the rest")
    ap.add_argument("--stale-after", type=float, default=0.0,
                    metavar="SECONDS",
                    help="fleet stream_stale alert when a live stream "
                         "stops posting for this long (0 = off)")
    ap.add_argument("--mem-growth", type=float, default=0.0,
                    metavar="BYTES_PER_EPOCH",
                    help="fleet mem_growth alert when any stream's "
                         "peak device bytes grow faster than this per "
                         "epoch (0 = off)")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="RULE",
                    help="GaugePredicate rule evaluated fleet-wide AND "
                         "per stream (e.g. 'serve_queue_depth > 10'); "
                         "repeatable")
    ap.add_argument("--webhook", metavar="URL",
                    help="fleet mode: POST one templated JSON payload "
                         "per fired alert (straggler/crash/stale/"
                         "mem_growth/--rule) to this URL — wire "
                         "format in docs/metrics_schema.md")
    args = ap.parse_args(argv)

    if bool(args.path) == (args.listen is not None):
        ap.error("give metrics.jsonl path(s) OR --listen PORT")

    from tpunet.obs.summary import summarize
    from tpunet.utils.logging import MetricsLogger

    paths = []
    for p in args.path:
        if os.path.isdir(p):
            p = os.path.join(p, "metrics.jsonl")
        paths.append(p)
    fleet = args.fleet or len(paths) > 1

    agg = None
    webhook = None
    if fleet:
        from tpunet.obs.agg import Aggregator
        agg = Aggregator(straggler_factor=args.straggler_factor,
                         stream_stale_s=args.stale_after,
                         mem_growth_bytes_per_epoch=args.mem_growth,
                         rules=tuple(args.rule))
        if args.webhook:
            # The bridge emits its obs_alert records through the
            # aggregator's registry; attaching the webhook sink there
            # turns every fired fleet alert into one POST.
            from tpunet.obs.export import AlertWebhook
            webhook = AlertWebhook(args.webhook, registry=agg.registry)
            agg.registry.add_sink(webhook)
    elif args.webhook:
        ap.error("--webhook needs fleet mode (several paths or "
                 "--fleet): only the fleet aggregator emits alerts "
                 "from the dashboard process")

    buf = RecordBuffer()
    offsets = {p: 0 for p in paths}
    if args.listen is not None:
        source = f"http://:{args.listen}"
        serve_http(args.listen, buf, source, agg=agg)
    else:
        source = paths[0] if len(paths) == 1 else f"{len(paths)} streams"
        if args.once:
            missing = [p for p in paths if not os.path.isfile(p)]
            if missing:
                print(f"no metrics.jsonl at {', '.join(missing)}",
                      file=sys.stderr)
                return 1

    def refresh():
        for p in paths:
            records, offsets[p], reset = MetricsLogger.tail_records(
                p, offsets[p])
            if reset:
                # Fresh run truncated the file underneath us: drop the
                # old run's records (already re-read from the start),
                # or every aggregate would straddle two runs.
                if agg is not None:
                    agg.drop_source(p)
                buf.clear()
            if agg is not None:
                # Follow-mode tailing IS live: stamp arrival so
                # --stale-after can page a silent replica. Only a
                # --once replay skips the clock (so replayed and
                # concurrently-ingested rollups compare equal).
                # Identity-less old files fall back to
                # one-file-one-stream via the source tag.
                agg.ingest_many(records, source=p,
                                stamp_time=not args.once)
            else:
                buf.feed(records)
        if agg is not None:
            rollup = agg.rollup()
            agg.bridge.check(rollup, agg.streams(),
                             now=time.monotonic())
            return rollup
        return summarize(buf.snapshot())

    def render_text(view):
        if agg is not None:
            return render_fleet_terminal(view, agg.heartbeat_ages(),
                                         source,
                                         alerts=_fleet_alerts(agg),
                                         regressions=_regression_rows(agg))
        return render_terminal(view, source, last=args.last)

    def render_page(view):
        if agg is not None:
            return render_fleet_html(view, agg.streams(), source,
                                     alerts=_fleet_alerts(agg),
                                     regressions=_regression_rows(agg))
        return render_html(view, source)

    def close_webhook() -> None:
        # Flush queued/backing-off pages before exit: without the
        # close, a page mid-retry dies with the daemon thread —
        # neither delivered, dead-lettered, NOR counted dropped.
        if webhook is None:
            return
        webhook.close()
        st = webhook.stats()
        if st["send_errors"] or st["dropped"]:
            print(f"webhook delivery incomplete: {st}",
                  file=sys.stderr)

    view = refresh()
    if args.html:
        with open(args.html, "w") as f:
            f.write(render_page(view))
    if args.once:
        print(render_text(view))
        close_webhook()
        return 0

    try:
        while True:
            # Full-frame redraw: clear + home, like top(1).
            sys.stdout.write("\x1b[2J\x1b[H")
            sys.stdout.write(render_text(view) + "\n")
            sys.stdout.flush()
            if args.html:
                tmp = args.html + ".tmp"
                with open(tmp, "w") as f:
                    f.write(render_page(view))
                os.replace(tmp, args.html)
            time.sleep(args.interval)
            view = refresh()
    except KeyboardInterrupt:
        return 0
    finally:
        close_webhook()


if __name__ == "__main__":
    sys.exit(main())
