#!/usr/bin/env python
"""Summarize a run's ``metrics.jsonl`` (tpunet/obs/ record schema).

Usage:
    python scripts/obs_report.py checkpoints/metrics.jsonl
    python scripts/obs_report.py checkpoints/          # finds metrics.jsonl
    python scripts/obs_report.py checkpoints/ --json   # machine-readable
    python scripts/obs_report.py checkpoints/ --trace checkpoints/profile

Prints the per-epoch training table, the step-time percentile /
input-stall summary from the ``obs_epoch`` records, the per-window
``obs_step`` step-time trend, any ``obs_alert`` records, and
device-memory high-water marks. ``--json`` emits the same summary as
one JSON object (the ``tpunet.obs.summary.summarize`` schema — the
exact structure the live dashboard renders, so the two views cannot
drift). Tolerates a truncated trailing line (a crashed or preempted
run's artifact) via ``MetricsLogger.read_records``.

``--trace DIR`` additionally attributes MEASURED device time to
training phases (fwd / bwd / optimizer / ema / eval) from the
windowed profiler's xplane under DIR (``--profile-dir``, or
``<checkpoint-dir>/profile``) — so a step-time regression names the
phase that moved instead of one opaque host lap. Needs the ``xprof``
package (TPU toolchain); without it the section degrades to a note.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fmt_s(v, digits=4):
    return "-" if v is None else f"{v:.{digits}f}"


def _fmt_ms(v):
    return "-" if v is None else f"{v * 1e3:.1f}"


def render(summary: dict) -> list:
    """Text report lines from a ``summarize()`` dict."""
    epochs = summary["epochs"]
    obs = summary["obs_epochs"]
    windows = summary["step_windows"]
    alerts = summary["alerts"]
    totals = summary["totals"]
    lines = []

    if epochs:
        lines.append("== epochs ==")
        lines.append(f"{'ep':>4} {'secs':>8} {'train_loss':>10} "
                     f"{'train_acc':>9} {'test_loss':>9} {'test_acc':>8} "
                     f"{'thruput':>10}")
        for r in epochs:
            thr = r.get("examples_per_sec", r.get("tokens_per_sec"))
            lines.append(
                f"{r['epoch']:>4} {_fmt_s(r.get('seconds'), 2):>8} "
                f"{_fmt_s(r.get('train_loss')):>10} "
                f"{_fmt_s(r.get('train_accuracy')):>9} "
                f"{_fmt_s(r.get('test_loss')):>9} "
                f"{_fmt_s(r.get('test_accuracy')):>8} "
                f"{_fmt_s(thr, 1):>10}"
                + ("  [partial]" if r.get("partial") else ""))

    if obs:
        lines.append("")
        lines.append("== step time / stalls (obs_epoch) ==")
        lines.append(f"{'ep':>4} {'steps':>6} {'p50ms':>8} {'p90ms':>8} "
                     f"{'p99ms':>8} {'stall_s':>8} {'stall%':>7} "
                     f"{'mfu':>6} {'procs':>6}")
        for r in obs:
            lines.append(
                f"{r['epoch']:>4} {r.get('steps', 0):>6} "
                f"{_fmt_ms(r.get('step_time_p50_s')):>8} "
                f"{_fmt_ms(r.get('step_time_p90_s')):>8} "
                f"{_fmt_ms(r.get('step_time_p99_s')):>8} "
                f"{_fmt_s(r.get('input_stall_s'), 2):>8} "
                f"{100 * r.get('stall_frac', 0.0):>6.1f}% "
                f"{_fmt_s(r.get('mfu'), 3):>6} "
                f"{r.get('live_processes', 1):>6}")
        frac = totals.get("stall_frac", 0.0)
        lines.append(f"run input-stall: "
                     f"{totals.get('input_stall_s', 0.0):.2f}s of "
                     f"{totals.get('train_seconds', 0.0):.2f}s train "
                     f"time ({100 * frac:.1f}%)")
        peak = totals.get("peak_bytes_in_use")
        if peak is not None:
            lines.append(f"device memory high-water: "
                         f"{peak / 2**30:.2f} GiB")
        else:
            lines.append("device memory: backend reports no allocator "
                         "stats (CPU)")

    if windows:
        lines.append("")
        lines.append("== step-time trend (obs_step windows) ==")
        lines.append(f"{'steps':>15} {'n':>5} {'mean_ms':>8} "
                     f"{'p50ms':>8} {'p99ms':>8} {'wait_ms':>8}")
        for w in windows:
            span = f"{w['step_lo']}-{w['step_hi']}"
            lines.append(
                f"{span:>15} {w['samples']:>5} "
                f"{_fmt_ms(w['step_time_mean_s']):>8} "
                f"{_fmt_ms(w['step_time_p50_s']):>8} "
                f"{_fmt_ms(w['step_time_p99_s']):>8} "
                f"{_fmt_ms(w['data_wait_mean_s']):>8}")

    if alerts:
        lines.append("")
        lines.append(f"== alerts ({len(alerts)}) ==")
        for a in alerts:
            extras = {k: v for k, v in a.items()
                      if k not in ("kind", "reason", "step", "severity")}
            lines.append(f"  step {a.get('step', '?'):>8} "
                         f"[{a.get('severity', 'warn')}] "
                         f"{a.get('reason', '?')} {extras}")

    if not lines:
        lines.append("no records found")
    return lines


def render_phases(phases: dict) -> list:
    """Text lines for a ``trace_phase.phase_times`` dict."""
    lines = ["", "== device time by phase (profiled window) =="]
    lines.append(f"{'phase':>10} {'ms/window':>12} {'share':>7}")
    for ph, row in phases.items():
        lines.append(f"{ph:>10} {row['us'] / 1e3:>12.2f} "
                     f"{row['pct']:>6.1f}%")
    return lines


def device_phases(trace_dir: str):
    """-> (phases dict or None, note lines). Degrades to a note when
    xprof or the trace is unavailable."""
    from tpunet.obs.trace_phase import hlo_stats_rows, phase_times
    try:
        return phase_times(hlo_stats_rows(trace_dir)), []
    except Exception as e:  # missing xprof / empty trace / bad xplane
        return None, ["", f"device-phase attribution unavailable: {e}"]


def report(records: list, trace_dir: str = None) -> list:
    """Build the report lines from parsed metrics.jsonl records."""
    from tpunet.obs.summary import summarize
    lines = render(summarize(records))
    if trace_dir:
        phases, notes = device_phases(trace_dir)
        lines += render_phases(phases) if phases else notes
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="metrics.jsonl, or a directory "
                                 "containing one (e.g. checkpoints/)")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable summary (the "
                         "tpunet.obs.summary.summarize schema) instead "
                         "of the text tables")
    ap.add_argument("--trace", default=None, metavar="DIR",
                    help="profiler trace dir (--profile-dir or "
                         "<checkpoint-dir>/profile): adds measured "
                         "device time by phase (fwd/bwd/optimizer/"
                         "ema/eval); needs the xprof package")
    args = ap.parse_args(argv)
    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
        if args.trace is None:
            # Convention: the windowed profiler writes under
            # <checkpoint-dir>/profile when --profile-dir is unset.
            cand = os.path.join(os.path.dirname(path), "profile")
            if os.path.isdir(cand):
                args.trace = cand
    if not os.path.isfile(path):
        print(f"no metrics.jsonl at {path}", file=sys.stderr)
        return 1
    from tpunet.utils.logging import MetricsLogger
    records = MetricsLogger.read_records(path)
    if args.json:
        from tpunet.obs.summary import summarize
        out = summarize(records)
        if args.trace:
            phases, _notes = device_phases(args.trace)
            out["device_phases"] = phases
        print(json.dumps(out, indent=2))
        return 0
    for line in report(records, trace_dir=args.trace):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
