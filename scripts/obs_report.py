#!/usr/bin/env python
"""Summarize a run's ``metrics.jsonl`` (tpunet/obs/ record schema).

Usage:
    python scripts/obs_report.py checkpoints/metrics.jsonl
    python scripts/obs_report.py checkpoints/          # finds metrics.jsonl

Prints the per-epoch training table, the step-time percentile /
input-stall summary from the ``obs_epoch`` records, and device-memory
high-water marks. Tolerates a truncated trailing line (a crashed or
preempted run's artifact) via ``MetricsLogger.read_records``.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _fmt_s(v, digits=4):
    return "-" if v is None else f"{v:.{digits}f}"


def _fmt_ms(v):
    return "-" if v is None else f"{v * 1e3:.1f}"


def report(records: list) -> list:
    """Build the report lines from parsed metrics.jsonl records."""
    epochs = [r for r in records if "kind" not in r and "epoch" in r]
    obs = [r for r in records if r.get("kind") == "obs_epoch"]
    steps = [r for r in records if r.get("kind") == "obs_step"]
    lines = []

    if epochs:
        lines.append("== epochs ==")
        lines.append(f"{'ep':>4} {'secs':>8} {'train_loss':>10} "
                     f"{'train_acc':>9} {'test_loss':>9} {'test_acc':>8} "
                     f"{'thruput':>10}")
        for r in epochs:
            thr = r.get("examples_per_sec", r.get("tokens_per_sec"))
            lines.append(
                f"{r['epoch']:>4} {_fmt_s(r.get('seconds'), 2):>8} "
                f"{_fmt_s(r.get('train_loss')):>10} "
                f"{_fmt_s(r.get('train_accuracy')):>9} "
                f"{_fmt_s(r.get('test_loss')):>9} "
                f"{_fmt_s(r.get('test_accuracy')):>8} "
                f"{_fmt_s(thr, 1):>10}"
                + ("  [partial]" if r.get("partial") else ""))

    if obs:
        lines.append("")
        lines.append("== step time / stalls (obs_epoch) ==")
        lines.append(f"{'ep':>4} {'steps':>6} {'p50ms':>8} {'p90ms':>8} "
                     f"{'p99ms':>8} {'stall_s':>8} {'stall%':>7} "
                     f"{'mfu':>6} {'procs':>6}")
        for r in obs:
            mfu = r.get("mfu")
            lines.append(
                f"{r['epoch']:>4} {r.get('steps', 0):>6} "
                f"{_fmt_ms(r.get('step_time_p50_s')):>8} "
                f"{_fmt_ms(r.get('step_time_p90_s')):>8} "
                f"{_fmt_ms(r.get('step_time_p99_s')):>8} "
                f"{_fmt_s(r.get('input_stall_s'), 2):>8} "
                f"{100 * r.get('stall_frac', 0.0):>6.1f}% "
                f"{_fmt_s(mfu, 3):>6} "
                f"{r.get('live_processes', 1):>6}")
        total_stall = sum(r.get("input_stall_s", 0.0) for r in obs)
        total_train = sum(r.get("train_seconds", 0.0) for r in obs)
        frac = total_stall / total_train if total_train else 0.0
        lines.append(f"run input-stall: {total_stall:.2f}s of "
                     f"{total_train:.2f}s train time ({100 * frac:.1f}%)")
        peaks = [m.get("peak_bytes_in_use")
                 for r in obs for m in r.get("device_memory", [])
                 if m.get("peak_bytes_in_use") is not None]
        if peaks:
            lines.append(f"device memory high-water: "
                         f"{max(peaks) / 2**30:.2f} GiB")
        else:
            lines.append("device memory: backend reports no allocator "
                         "stats (CPU)")

    if steps:
        lines.append("")
        times = sorted(r["step_time_s"] for r in steps
                       if "step_time_s" in r)
        mid = times[len(times) // 2]
        lines.append(f"== obs_step samples: {len(steps)} "
                     f"(median {mid * 1e3:.1f}ms) ==")

    if not lines:
        lines.append("no records found")
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="metrics.jsonl, or a directory "
                                 "containing one (e.g. checkpoints/)")
    args = ap.parse_args(argv)
    path = args.path
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    if not os.path.isfile(path):
        print(f"no metrics.jsonl at {path}", file=sys.stderr)
        return 1
    from tpunet.utils.logging import MetricsLogger
    for line in report(MetricsLogger.read_records(path)):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
