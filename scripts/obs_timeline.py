#!/usr/bin/env python
"""Unified Perfetto timeline from one or more runs' flight recorders.

    # one training run -> trace.json (load at ui.perfetto.dev)
    python scripts/obs_timeline.py checkpoints/ -o trace.json

    # trainer + serve replica on one clock
    python scripts/obs_timeline.py train-run/ serve-run/ -o trace.json

    # router + N replicas, traces joined on trace_id into one track
    python scripts/obs_timeline.py --metrics-dir router-run/ \\
        --metrics-dir rep-a/ --metrics-dir rep-b/ -o trace.json

Converts the crash-durable flightrec event rings (recorded by default
in every run: span begin/end pairs, host-thread busy/idle flips,
serve request lifecycles, alerts, epoch marks) into chrome-trace JSON
— host threads, device phases, and requests on one wall clock. Wire
details in docs/metrics_schema.md "Timeline export".

Exit: 0 written, 1 no rings found, 2 usage.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from _gate_cli import split_flags  # noqa: E402


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    # --metrics-dir is repeatable (router dir + N replica dirs in one
    # invocation -> single merged chrome-trace); split_flags is
    # last-wins so collect the repeats by hand first.
    dirs = []
    i = 0
    while i < len(args):
        if args[i] == "--metrics-dir":
            if i + 1 >= len(args):
                print("--metrics-dir needs a value", file=sys.stderr)
                return 2
            dirs.append(args[i + 1])
            del args[i:i + 2]
            continue
        i += 1
    parsed = split_flags(args, ("-o", "--out"))
    if isinstance(parsed, int):
        return parsed
    flags, paths = parsed
    paths = dirs + paths
    if not paths:
        print("usage: obs_timeline.py [--metrics-dir DIR]... RUN_DIR... "
              "[-o trace.json]", file=sys.stderr)
        return 2
    out = str(flags.get("o") or flags.get("out") or "trace.json")

    from tpunet.obs.history import write_trace
    try:
        trace = write_trace(paths, out)
    except FileNotFoundError as e:
        print(f"obs_timeline: {e}", file=sys.stderr)
        return 1
    events = trace["traceEvents"]
    kinds = {"B": 0, "X": 0, "i": 0, "M": 0}
    for e in events:
        kinds[e["ph"]] = kinds.get(e["ph"], 0) + 1
    span_ms = 0.0
    ts = [e["ts"] for e in events if e["ph"] != "M"]
    if ts:
        span_ms = (max(ts) - min(ts)) / 1e3
    print(f"obs_timeline: wrote {out}: {len(events)} events "
          f"({kinds.get('B', 0)} span pairs, {kinds.get('X', 0)} "
          f"complete, {kinds.get('i', 0)} instants) spanning "
          f"{span_ms:.1f} ms — open at ui.perfetto.dev")
    return 0


if __name__ == "__main__":
    sys.exit(main())
