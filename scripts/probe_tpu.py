"""Stagewise TPU compile/runtime probe (diagnostic; not part of bench)."""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def stamp(msg):
    print(f"[{time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    stamp(f"devices: {jax.devices()} batch={batch}")

    from tpunet.config import DataConfig, ModelConfig, OptimConfig
    from tpunet.data.augment import make_eval_preprocess, make_train_augment
    from tpunet.models import create_model, init_variables

    x8 = np.random.default_rng(0).integers(
        0, 256, size=(batch, 32, 32, 3), dtype=np.uint8)
    dcfg = DataConfig(batch_size=batch)

    # Stage 1: eval preprocess (static resize matmuls)
    pre = jax.jit(make_eval_preprocess(dcfg))
    t = time.perf_counter()
    out = pre(x8)
    jax.block_until_ready(out)
    stamp(f"eval preprocess compile+run: {time.perf_counter()-t:.1f}s")
    t = time.perf_counter()
    jax.block_until_ready(pre(x8))
    stamp(f"eval preprocess steady: {(time.perf_counter()-t)*1e3:.1f}ms")

    # Stage 2: train augmentation (rotate gather + dynamic matrices)
    aug = jax.jit(make_train_augment(dcfg))
    key = jax.random.PRNGKey(0)
    t = time.perf_counter()
    out = aug(key, x8)
    jax.block_until_ready(out)
    stamp(f"train augment compile+run: {time.perf_counter()-t:.1f}s")
    t = time.perf_counter()
    jax.block_until_ready(aug(key, x8))
    stamp(f"train augment steady: {(time.perf_counter()-t)*1e3:.1f}ms")

    # Stage 3: model forward (inference)
    mcfg = ModelConfig()
    model = create_model(mcfg)
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=224)
    fwd = jax.jit(lambda v, x: model.apply(v, x, train=False))
    xi = jnp.asarray(out)
    t = time.perf_counter()
    logits = fwd(variables, xi)
    jax.block_until_ready(logits)
    stamp(f"fwd compile+run: {time.perf_counter()-t:.1f}s")
    t = time.perf_counter()
    jax.block_until_ready(fwd(variables, xi))
    stamp(f"fwd steady: {(time.perf_counter()-t)*1e3:.1f}ms")

    # Stage 4: full train step (no mesh; single chip)
    from tpunet.train.state import create_train_state
    from tpunet.train.steps import make_train_step
    state = create_train_state(mcfg, OptimConfig(), jax.random.PRNGKey(0),
                               image_size=224, steps_per_epoch=100, epochs=20)
    step = jax.jit(make_train_step(dcfg, OptimConfig()), donate_argnums=0)
    y = np.zeros((batch,), np.int32)
    t = time.perf_counter()
    state, m = step(state, x8, y, key)
    jax.block_until_ready(m)
    stamp(f"train step compile+run: {time.perf_counter()-t:.1f}s")
    for i in range(3):
        t = time.perf_counter()
        state, m = step(state, x8, y, jax.random.PRNGKey(i))
        jax.block_until_ready(m)
        stamp(f"train step steady: {(time.perf_counter()-t)*1e3:.1f}ms "
              f"({batch/(time.perf_counter()-t):.0f} img/s)")


if __name__ == "__main__":
    main()
