#!/usr/bin/env bash
# Reproduce the reference's headline deliverable: ImageNet-pretrained
# MobileNetV2 fine-tuned on real CIFAR-10 @ 224px, 20 epochs, batch 128
# (reference cifar10_128batch.py; published record
# cifar10_128_gpu_27326.out:30-52 — epoch-1 acc 0.9027, best 0.9603,
# total 10,698 s on one V100).
#
# Turnkey when the machine has egress (CIFAR-10 tarball and torchvision
# weights are fetched, checksum-verified, into data/ and
# ~/.cache/tpunet). Offline: stage the two artifacts per the printed
# drop-in instructions, then rerun.
#
#   bash scripts/reproduce_reference.sh [extra train.py flags...]
#
# Artifacts land in runs/real-single/: epoch log (train.log),
# metrics.jsonl, best + last checkpoints. Expected on one TPU chip:
# epoch-1 test acc ~0.89-0.91, best >= 0.95, wall-clock far under the
# V100's 10,698 s (bench.py measures ~39x the V100's throughput).
set -euo pipefail
cd "$(dirname "$0")/.."

OUT=runs/real-single
mkdir -p "$OUT"

# metrics.jsonl is written into the checkpoint dir by the trainer.
python -u train.py --preset single \
  --dataset cifar10 \
  --pretrained auto \
  --checkpoint-dir "$OUT/ckpt" \
  "$@" 2>&1 | tee "$OUT/train.log"
