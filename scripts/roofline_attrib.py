#!/usr/bin/env python
"""Per-op time attribution for the flagship bench step (VERDICT r4 #3).

Traces the exact bench.py workload (MobileNetV2 @224, bf16, full train
step: augment + fwd + bwd + Adam + metrics) with the JAX profiler on the
real chip, converts the xplane with xprof's hlo_stats tool, and writes a
measured per-op/per-category breakdown of where the step time goes —
turning the round-4 "residual is unfused BN/elementwise traffic,
sub-peak bandwidth, depthwise VPU time" *guess* into numbers.

Usage: python scripts/roofline_attrib.py [--batch 512] [--out PATH]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpunet.utils.cache import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache(os.path.join(REPO, ".jax_cache"))


def build_step(per_chip_batch: int, image_size: int = 224):
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import shard_host_batch
    from tpunet.train.loop import Trainer

    # GLOBAL batch = per-chip x n_chips, matching bench.py's per-chip
    # convention so the attribution and bench records compare 1:1 on
    # any chip count.
    batch = per_chip_batch * jax.device_count()
    cfg = TrainConfig(
        data=DataConfig(dataset="synthetic", batch_size=batch,
                        image_size=image_size),
        model=ModelConfig(),
        optim=OptimConfig(),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    ds = synthetic_cifar10(n_train=2 * batch, n_test=batch)
    trainer = Trainer(cfg, dataset=ds)
    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(batch, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, size=batch).astype(np.int32)
    gx, gy = shard_host_batch(trainer.mesh, x, y)
    return trainer, gx, gy


def sync(state):
    jax.block_until_ready(state)
    leaf = jax.tree_util.tree_leaves(state.params)[0]
    return float(np.asarray(leaf.ravel()[0]))


def trace_step(trainer, gx, gy, steps: int, trace_dir: str) -> float:
    from tpunet.utils.prng import step_key

    state = trainer.state
    for i in range(3):
        state, _ = trainer.train_step(state, gx, gy, step_key(0, i))
    sync(state)
    t0 = time.perf_counter()
    with jax.profiler.trace(trace_dir):
        for i in range(steps):
            state, _ = trainer.train_step(state, gx, gy, step_key(0, 3 + i))
        sync(state)
    return time.perf_counter() - t0


def hlo_stats(trace_dir: str):
    """Per-HLO-op row dicts from the captured xplane (shared parser:
    tpunet/obs/trace_phase.py, also behind obs_report.py --trace)."""
    from tpunet.obs.trace_phase import hlo_stats_rows

    return hlo_stats_rows(trace_dir)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--out", default=os.path.join(
        REPO, "runs", "bench-roofline", "ATTRIB_r05.json"))
    ap.add_argument("--keep-trace", action="store_true")
    ap.add_argument("--from-trace", default=None,
                    help="parse an existing trace dir instead of "
                         "re-tracing (batch/steps must match how it "
                         "was captured for the throughput numbers)")
    args = ap.parse_args()

    bytes_breakdown = None
    if args.from_trace:
        trace_dir, wall, trainer = args.from_trace, None, None
    else:
        trainer, gx, gy = build_step(args.batch, args.image_size)
        # Byte attribution from the optimized module text (same
        # decomposition bench.py ships as bytes_per_image_breakdown);
        # AOT-compiling here warms the executable the trace reuses.
        try:
            from tpunet.obs import hlo_bytes
            from tpunet.utils.prng import step_key
            compiled = trainer.train_step.lower(
                trainer.state, gx, gy, step_key(0, 0)).compile()
            bytes_breakdown = hlo_bytes.per_image_breakdown(
                compiled.as_text(), args.batch)
        except Exception as e:
            print(f"# byte attribution unavailable: {e}", file=sys.stderr)
        trace_dir = tempfile.mkdtemp(prefix="tpunet-roofline-trace-")
        wall = trace_step(trainer, gx, gy, args.steps, trace_dir)
        print(f"# traced {args.steps} steps in {wall:.2f}s "
              f"({args.steps * args.batch / wall:.0f} img/s/chip, incl. "
              "profiler overhead)", file=sys.stderr)

    # Everything past the trace runs under try/finally: hlo_stats
    # parses xprof columns by exact label (version-fragile) and the
    # output write can fail too — neither may leak the mkdtemp trace
    # dir this run created, or skip closing the trainer's
    # checkpointer/threads.
    try:
        _attrib_and_write(args, trace_dir, wall, bytes_breakdown)
    finally:
        if args.from_trace or args.keep_trace:
            # Never delete a trace the CALLER owns (--from-trace) or
            # asked to keep; only the tempdir this run created is
            # cleaned up.
            print(f"# trace kept at {trace_dir}", file=sys.stderr)
        else:
            import shutil
            shutil.rmtree(trace_dir, ignore_errors=True)
        if trainer is not None:
            trainer.close()


def _attrib_and_write(args, trace_dir: str, wall,
                      bytes_breakdown=None) -> None:
    from tpunet.obs.hlo_bytes import phase_of

    rows = hlo_stats(trace_dir)

    def f(row, name, default=0.0):
        v = row.get(name)
        try:
            return float(v)
        except (TypeError, ValueError):
            return default

    by_cat = {}
    by_src = {}
    by_phase = {}
    bw_weighted = 0.0
    hbm_time = 0.0
    ops = []
    for r in rows:
        t = f(r, "Total self time (us)")
        cat = r.get("HLO op category") or "?"
        by_cat[cat] = by_cat.get(cat, 0.0) + t
        # attribute to framework source (module/op) for actionability
        src = (r.get("Framework op name") or "?").split("/")
        src = "/".join(src[1:3]) if len(src) > 2 else "/".join(src)
        by_src[src] = by_src.get(src, 0.0) + t
        # and to the training phase (fwd / bwd / optimizer / ema) —
        # the same classifier scripts/obs_report.py --trace uses, so
        # the time and bytes tables split the step identically.
        ph = phase_of(r.get("Framework op name") or "")
        by_phase[ph] = by_phase.get(ph, 0.0) + t
        bw = f(r, "Measured memory BW (GiB/s)")
        if r.get("Bound by") == "HBM":
            hbm_time += t
            bw_weighted += t * bw
        ops.append((t, r))
    total = sum(by_cat.values()) or 1.0
    ops.sort(key=lambda x: -x[0])

    def top(n):
        return [
            {"pct": round(100.0 * t / total, 2),
             "us_per_step": round(t / args.steps, 1),
             "category": r.get("HLO op category"),
             "bound_by": r.get("Bound by"),
             "measured_bw_gibs": round(f(r, "Measured memory BW (GiB/s)"), 1),
             "gflops": round(f(r, "Model GFLOP/s"), 1),
             "op": r.get("HLO op name"),
             "source": (r.get("Framework op name") or "")[:140]}
            for t, r in ops[:n]]

    out = {
        "batch_per_chip": args.batch,
        "n_chips": jax.device_count(),
        "steps_traced": args.steps,
        "wall_seconds": wall and round(wall, 3),
        "img_per_sec_per_chip_traced": wall and round(
            args.steps * args.batch / wall, 1),
        "device_kind": jax.devices()[0].device_kind,
        "total_profiled_us_per_step": round(total / args.steps, 1),
        "hbm_bound_time_pct": round(100.0 * hbm_time / total, 2),
        "hbm_bound_mean_achieved_bw_gibs": round(
            bw_weighted / hbm_time, 1) if hbm_time else None,
        "by_phase_pct": {
            k: round(100.0 * v / total, 2)
            for k, v in sorted(by_phase.items(), key=lambda kv: -kv[1])},
        "bytes_per_image_breakdown": bytes_breakdown,
        "by_category_pct": {
            k: round(100.0 * v / total, 2)
            for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1])},
        "by_source_pct_top": {
            k: round(100.0 * v / total, 2)
            for k, v in sorted(by_src.items(), key=lambda kv: -kv[1])[:25]},
        "top_ops": top(40),
    }
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fp:
        json.dump(out, fp, indent=1)
    print(json.dumps({k: out[k] for k in
                      ("img_per_sec_per_chip_traced",
                       "total_profiled_us_per_step",
                       "hbm_bound_time_pct",
                       "hbm_bound_mean_achieved_bw_gibs",
                       "by_category_pct")}, indent=1))
    print(f"# wrote {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
