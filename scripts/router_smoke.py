#!/usr/bin/env python
"""Fast router smoke: the control plane against stub replicas.

Exercises the routing tier with NO engine, NO model, NO device —
stdlib HTTP stubs play the replicas — so the gate runs in seconds
and failures point at router logic, not at jax. Five legs:

1. least-loaded routing spreads requests by probed load;
2. a dead replica is re-routed around (no client-visible failure)
   and evicted after its failure budget;
3. a draining replica's 503 + Retry-After is honored (backed off,
   traffic lands elsewhere, zero drops);
4. an AlertWebhook page (straggler) POSTed to /webhook evicts the
   named replica;
5. the obs_router window record reconciles with what was routed.

Wired into scripts/run_checks.sh (fast set). Exit 0 = all legs pass.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


class StubReplica:
    """Stdlib stand-in for one tpunet.serve replica."""

    def __init__(self, run_id: str, *, slots: int = 4):
        self.run_id = run_id
        self.slots = slots
        self.queue_depth = 0
        self.requests = 0
        self.draining = False
        self.retry_after = 5
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102
                pass

            def _json(self, code, obj, headers=()):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/healthz":
                    if stub.draining:
                        self._json(503, {"status": "draining",
                                         "run_id": stub.run_id},
                                   [("Retry-After",
                                     str(stub.retry_after))])
                    else:
                        self._json(200, {
                            "status": "ok", "run_id": stub.run_id,
                            "slots": stub.slots,
                            "queue_depth": stub.queue_depth,
                            "active_slots": 0})
                elif self.path == "/metrics":
                    self._json(200, {
                        "serve_queue_depth": stub.queue_depth,
                        "serve_active_slots": 0,
                        "serve_requests_total": stub.requests})

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                if stub.draining:
                    self._json(503, {"error": "draining"},
                               [("Retry-After",
                                 str(stub.retry_after))])
                    return
                stub.requests += 1
                self._json(200, {"tokens": [1, 2],
                                 "finish_reason": "length",
                                 "served_by": stub.run_id})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def post(base, path, obj, timeout=10):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def get(base, path, timeout=10):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def wait_for(pred, timeout=15.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def main() -> int:
    from tpunet.config import RouterConfig
    from tpunet.obs.registry import MemorySink
    from tpunet.router import Router, RouterServer

    stubs = [StubReplica(f"stub-{i}") for i in range(3)]
    cfg = RouterConfig(probe_interval_s=0.1, probe_timeout_s=1.0,
                       unhealthy_after=2, emit_every_s=0.0,
                       boot_timeout_s=2.0, affinity_prefix=0)
    router = Router(cfg, replica_urls=[s.url for s in stubs])
    sink = MemorySink()
    router.registry.add_sink(sink)
    server = RouterServer(router, port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    failures = []

    def leg(name, fn):
        try:
            fn()
            print(f"[PASS] {name}")
        except Exception as e:  # noqa: BLE001
            failures.append(name)
            print(f"[FAIL] {name}: {type(e).__name__}: {e}")

    def leg1():
        wait_for(lambda: router.healthy_count() == 3, what="3 healthy")
        stubs[0].queue_depth = 8      # heavily loaded
        wait_for(lambda: next(r for r in router.replicas
                              if r.run_id == "stub-0").queue_depth == 8,
                 what="probe to see load")
        for _ in range(6):
            code, out = post(base, "/v1/generate", {"tokens": [1]})
            assert code == 200
            assert out["served_by"] != "stub-0", \
                "routed to the loaded replica"
        stubs[0].queue_depth = 0

    def leg2():
        stubs[1].close()              # hard-dead replica
        for _ in range(4):
            code, out = post(base, "/v1/generate", {"tokens": [2]})
            assert code == 200, "re-route must hide the dead replica"
        wait_for(lambda: any(r.state in ("dead", "evicted")
                             for r in router.replicas),
                 what="eviction of the dead replica")

    def leg3():
        stubs[2].draining = True
        for _ in range(4):
            code, out = post(base, "/v1/generate", {"tokens": [3]})
            assert code == 200
            assert out["served_by"] == "stub-0", \
                f"expected stub-0, got {out['served_by']}"
        target = next(r for r in router.replicas
                      if r.run_id == "stub-2")
        wait_for(lambda: target.backoff_until > 0,
                 what="Retry-After backoff recorded")
        stubs[2].draining = False

    def leg4():
        code, out = post(base, "/webhook", {
            "source": "tpunet", "kind": "obs_alert",
            "reason": "straggler", "severity": "warn",
            "run_id": "stub-0", "detail": {}})
        assert code == 200 and out["accepted"], out
        target = next(r for r in router.replicas
                      if r.run_id == "stub-0")
        assert target.state == "evicted", target.state
        # An unrelated page is acknowledged without action.
        code, out = post(base, "/webhook", {
            "kind": "obs_alert", "reason": "loss_spike",
            "run_id": "stub-2"})
        assert code == 200 and not out["accepted"]

    def leg5():
        router.emit_record(final=True)
        windows = [r for r in sink.records
                   if r.get("kind") == "obs_router"
                   and not r.get("event")]
        assert windows, "no obs_router window record"
        win = windows[-1]
        routed = sum(row["requests_routed"]
                     for row in win["per_replica"])
        assert routed >= 14, f"routed {routed} < 14"
        assert win["requests_total"] >= 14
        events = {r.get("event") for r in sink.records
                  if r.get("kind") == "obs_router" and r.get("event")}
        assert "evict" in events, events

    leg("least-loaded routing", leg1)
    leg("dead-replica re-route + evict", leg2)
    leg("drain Retry-After honored", leg3)
    leg("webhook page evicts", leg4)
    leg("obs_router record reconciles", leg5)
    server.drain()
    for s in stubs:
        try:
            s.close()
        except Exception:  # noqa: BLE001
            pass
    if failures:
        print(f"router_smoke: FAILED ({', '.join(failures)})")
        return 1
    print("router_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
