#!/usr/bin/env bash
# Pre-merge gate aggregator: every repo-native check behind one exit
# code (docs/static_analysis.md "Pre-merge command"). Fast set by
# default; --slow adds the measured gates (obs overhead A/B, the full
# sanitizer matrix). A gate whose input artifact does not exist on
# this tree SKIPs with a note — a skip is printed, never silent.
#
# Usage:
#   scripts/run_checks.sh            # fast: tpucheck, types, schema,
#                                    # budgets (artifact-gated), spec
#                                    # bench A/B, sanitizer smoke
#   scripts/run_checks.sh --slow     # + obs overhead, full asan/ubsan/
#                                    # tsan stress matrix
#
# Exit: 0 = every gate PASS or SKIP, 1 = any gate FAILED.

set -u
cd "$(dirname "$0")/.."

SLOW=0
for arg in "$@"; do
  case "$arg" in
    --slow) SLOW=1 ;;
    *) echo "usage: scripts/run_checks.sh [--slow]" >&2; exit 2 ;;
  esac
done

FAILED=0
SUMMARY=""

run_gate() {       # run_gate <name> <cmd...>
  local name="$1"; shift
  echo "=== [$name] $*"
  if "$@"; then
    SUMMARY="$SUMMARY
[PASS] $name"
  else
    local rc=$?
    SUMMARY="$SUMMARY
[FAIL] $name (exit $rc)"
    FAILED=1
  fi
}

skip_gate() {      # skip_gate <name> <why>
  echo "=== [$1] SKIP: $2"
  SUMMARY="$SUMMARY
[SKIP] $1 — $2"
}

run_gate "tpucheck" python -m tpunet.analysis --strict-baseline
run_gate "types" python scripts/check_types.py
run_gate "metrics-schema" python scripts/check_metrics_schema.py

# Bytes budget gates the newest BENCH artifact measured AFTER the
# budget's as_of_round (the same eligibility rule as
# tests/test_hbm_bytes.py::test_budget_vs_latest_bench_artifact).
BENCH_ARTIFACT=$(python - <<'EOF'
import glob, json, os, re
budget = json.load(open(os.path.join("docs", "bytes_budget.json")))
as_of = max(int(b.get("as_of_round", 0))
            for b in budget.get("budgets", {}).values())
best = None
for path in glob.glob("BENCH_r*.json"):
    m = re.match(r"BENCH_r(\d+)\.json$", os.path.basename(path))
    if m and int(m.group(1)) > as_of:
        best = max(best or "", path)
print(best or "")
EOF
)
if [ -n "$BENCH_ARTIFACT" ]; then
  run_gate "bytes-budget" python scripts/check_bytes_budget.py "$BENCH_ARTIFACT"
else
  skip_gate "bytes-budget" "no BENCH_rN artifact newer than the budget's as_of_round (the tier-1 drift test enforces reconciliation when one lands)"
fi

if ls SERVE_BENCH*.json >/dev/null 2>&1; then
  run_gate "serve-budget" python scripts/check_serve_budget.py SERVE_BENCH*.json
else
  skip_gate "serve-budget" "no SERVE_BENCH*.json artifact (run scripts/bench_serve.py --enforce-budget to gate in-process)"
fi

# Speculative-decoding A/B on the bench workload: fits the default
# width_mult-0.25 drafter, serves the identical closed-loop traffic
# spec-on vs spec-off, and gates in-process (check_serve_budget
# check_spec: spec-on tokens/s strictly above spec-off
# unconditionally, plus the per-slot spec floor).
run_gate "spec-bench" python scripts/bench_serve.py --spec --enforce-budget

# Router control plane against stdlib stub replicas (no devices, no
# model): least-loaded routing, dead-replica re-route + evict,
# drain Retry-After, webhook eviction, obs_router reconciliation.
run_gate "router-smoke" python scripts/router_smoke.py

# Serve-tier chaos matrix against stdlib stub replicas: mid-stream
# failover (kill/wedge/prefill-death), the journal-cap degradation,
# and the SLO closed loop (prober-detected stall -> exactly one
# fast-burn webhook page -> recovery re-arms the latch).
# --slow adds the real-engine leg (SIGKILL of a real serve child).
run_gate "serve-chaos-smoke" python scripts/serve_chaos_smoke.py

run_gate "sanitizer-smoke" python scripts/check_sanitizers.py --smoke

if [ "$SLOW" = 1 ]; then
  run_gate "sanitizers-full" python scripts/check_sanitizers.py
  run_gate "obs-overhead" python scripts/check_obs_overhead.py
  run_gate "chaos-smoke" python scripts/chaos_smoke.py
  run_gate "serve-chaos-real" python scripts/serve_chaos_smoke.py --real
fi

echo
echo "=== run_checks summary ==="
echo "$SUMMARY" | sed '/^$/d'
if [ "$FAILED" = 1 ]; then
  echo "run_checks: FAILED"
  exit 1
fi
echo "run_checks: OK"
