#!/usr/bin/env python
"""Serve-tier chaos matrix: mid-stream failover, end-to-end.

The fast set (default) drives the ROUTER's failover machinery against
stdlib stub replicas — no engine, no model, no device — so the gate
runs in seconds and failures point at router logic, not at jax. The
stubs speak the real replica stream contract (ndjson token events
with ``i`` indices, ``resume_tokens`` continuation, the done frame)
with scripted deaths. Six legs:

1. **kill mid-stream** — the stream's replica dies after first bytes
   reached the client (re-emitting its last token at the seam): the
   client stream continues seamlessly on the survivor, every index
   exactly once, NO error frame, ``failover_count`` stamped on done;
2. **kill during prefill** — the replica dies before any response
   byte: the pre-first-byte re-route hides it entirely (no failover,
   no error);
3. **wedge -> stall-evict -> failover** — the replica stops producing
   AND stops answering probes: the control loop evicts it, the relay
   notices mid-poll, and the stream resumes on the survivor;
4. **journal cap exceeded** — a stream past ``--failover-journal-
   tokens`` loses protection: replica death yields the HONEST error
   frame (the documented degradation), never a silent truncation;
5. **trace propagation** — a client-supplied ``X-Trace-Id`` is
   stamped on every replica hop across a mid-stream failover with an
   incrementing ``X-Trace-Hop`` (docs/metrics_schema.md "Request
   tracing wire format");
6. **SLO closed loop** — the synthetic prober + burn-rate engine
   (tpunet/obs/slo.py): a fleet-wide stall that healthz cannot see
   burns the fast window and lands EXACTLY ONE page (carrying the
   failing probe's trace id) on a stdlib webhook receiver; recovery
   clears the latch with no second page and the budget stops
   draining. Golden outputs stay bitwise-identical across replicas
   and across a mid-probe failover.

``--real`` adds the slow legs: (a) a supervised fleet of two real
``python -m tpunet.serve`` children with ``--chaos
kill@tokens=N:replica=0`` (tpunet/serve/chaos.py) — SIGKILL of a real
engine mid-stream, resumed through the real bucketed-prefill path;
(b) the fleet-wide prefix warm start (PR 18): a shared-prefix request
spills cached pages to a shared ``--prefix-store``, the serving
replica is SIGKILLed by pid, and its RESPAWN adopts the fleet's
prefix set at boot — the first shared-prefix request on the fresh
process prefills only the suffix; (c) speculative decoding under
SIGKILL: a ``--spec-decode`` replica dies MID-VERIFY-WINDOW (the
kill counter lands inside a burst's emit loop) and the survivor —
also spec-on — resumes from the journal; because the engine only
ever journals VERIFIED tokens, the stitched stream must be
token-identical to an uninterrupted stream of the same request on
the other replica.

Wired into scripts/run_checks.sh (fast set; --slow adds --real).
Exit 0 = all legs pass.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def stream_token(prompt0: int, i: int) -> int:
    """The stubs' shared 'model': token ``i`` of a stream is a pure
    function of the prompt (like two real replicas sharing weights),
    so a resumed stub continues the same logical stream."""
    return (prompt0 + 7 * (i + 1)) % 256


class StubReplica:
    """Stdlib stand-in for one tpunet.serve replica speaking the
    streaming + resume contract. ``behavior`` keys:

    - ``die_after_tokens``: close the socket abruptly after emitting
      that many token lines (once; cleared after firing);
    - ``dup_at_seam``: re-emit the last token line before dying (the
      'replica emitted token N as it died' seam);
    - ``die_at_prefill``: close the socket before any response byte
      (once);
    - ``wedge_after_tokens``: emit that many lines then hang — and
      hang /healthz too (the wedged-process shape);
    - ``resume_delay_s``: sleep before answering a resume (widens the
      failover window for the drain-coordination test);
    - ``line_delay_s``: sleep before each token line (a slow stream).

    ``headers_seen`` records each generate request's headers (the
    deadline-propagation test reads ``X-Deadline-Ms`` back).
    """

    def __init__(self, run_id: str, behavior=None):
        self.run_id = run_id
        self.behavior = dict(behavior or {})
        self.requests = 0
        self.resumes = 0
        self.headers_seen = []
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102
                pass

            def _json(self, code, obj, headers=()):
                b = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(b)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(b)

            def do_GET(self):  # noqa: N802
                if stub.behavior.get("wedged"):
                    time.sleep(30.0)      # probe times out -> evict
                if self.path == "/healthz":
                    self._json(200, {"status": "ok",
                                     "run_id": stub.run_id,
                                     "slots": 4, "queue_depth": 0,
                                     "active_slots": 0})
                else:
                    self._json(200, {"serve_requests_total":
                                     stub.requests})

            def _chunk(self, obj):
                line = (json.dumps(obj) + "\n").encode()
                self.wfile.write(f"{len(line):x}\r\n".encode()
                                 + line + b"\r\n")
                self.wfile.flush()

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = json.loads(self.rfile.read(n) or b"{}")
                stub.requests += 1
                stub.headers_seen.append(dict(self.headers))
                if stub.behavior.pop("die_at_prefill", None):
                    self.connection.close()
                    return
                prompt0 = int((body.get("tokens") or [0])[0])
                resume = body.get("resume_tokens") or []
                if resume:
                    stub.resumes += 1
                    delay = stub.behavior.get("resume_delay_s")
                    if delay:
                        time.sleep(delay)
                budget = int(body.get("max_new_tokens", 8))
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                die_after = stub.behavior.get("die_after_tokens")
                wedge_after = stub.behavior.get("wedge_after_tokens")
                emitted = 0
                for i in range(len(resume), budget):
                    line_delay = stub.behavior.get("line_delay_s")
                    if line_delay:
                        time.sleep(line_delay)
                    ev = {"token": stream_token(prompt0, i), "i": i}
                    self._chunk(ev)
                    emitted += 1
                    if die_after is not None and emitted >= die_after:
                        if stub.behavior.get("dup_at_seam"):
                            self._chunk(ev)       # the seam duplicate
                        stub.behavior.pop("die_after_tokens", None)
                        self.connection.close()   # no done frame
                        return
                    if wedge_after is not None \
                            and emitted >= wedge_after:
                        stub.behavior["wedged"] = True
                        time.sleep(60.0)          # never finishes
                        return
                self._chunk({"done": True, "finish_reason": "length",
                             "n_tokens": budget})
                self.wfile.write(b"0\r\n\r\n")

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def read_stream(base, body, timeout=30, headers=()):
    """POST a streaming generate and return the parsed ndjson lines."""
    req = urllib.request.Request(
        base + "/v1/generate", json.dumps(body).encode(),
        {"Content-Type": "application/json", **dict(headers)})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return [json.loads(line) for line in resp]


def wait_for(pred, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {what}")


def make_router(stub_urls, **cfg_kw):
    from tpunet.config import RouterConfig
    from tpunet.router import Router, RouterServer
    cfg_kw.setdefault("probe_interval_s", 0.1)
    cfg_kw.setdefault("probe_timeout_s", 0.5)
    cfg_kw.setdefault("unhealthy_after", 2)
    cfg_kw.setdefault("boot_timeout_s", 2.0)
    cfg_kw.setdefault("emit_every_s", 0.0)
    cfg_kw.setdefault("affinity_prefix", 0)
    router = Router(RouterConfig(**cfg_kw), replica_urls=stub_urls)
    server = RouterServer(router, port=0).start()
    return router, server


def expected_tokens(prompt0, n):
    return [stream_token(prompt0, i) for i in range(n)]


def leg_kill_mid_stream():
    """Leg 1: SIGKILL-shaped death after first bytes (with the seam
    duplicate) -> seamless continuation, every index exactly once."""
    stubs = [StubReplica("c0", {"die_after_tokens": 3,
                                "dup_at_seam": True}),
             StubReplica("c1")]
    router, server = make_router([s.url for s in stubs])
    try:
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")
        lines = read_stream(f"http://127.0.0.1:{server.port}",
                            {"tokens": [10], "max_new_tokens": 8,
                             "stream": True})
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            f"expected clean done frame, got {done}"
        assert "error" not in done, done
        assert toks == expected_tokens(10, 8), \
            f"stream diverged: {toks}"
        assert [ev["i"] for ev in lines if "token" in ev] \
            == list(range(8)), "indices not exactly-once"
        assert done.get("failover_count") == 1, done
        assert stubs[1].resumes == 1, "survivor never saw the resume"
        snap = router.registry.snapshot()
        assert snap.get("router_failovers_total", 0) >= 1, snap
    finally:
        server.drain()
        for s in stubs:
            s.close()


def leg_kill_at_prefill():
    """Leg 2: death before any response byte -> pre-first-byte
    re-route, no failover machinery involved."""
    stubs = [StubReplica("p0", {"die_at_prefill": True}),
             StubReplica("p1")]
    router, server = make_router([s.url for s in stubs])
    try:
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")
        lines = read_stream(f"http://127.0.0.1:{server.port}",
                            {"tokens": [20], "max_new_tokens": 6,
                             "stream": True})
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length"
        assert toks == expected_tokens(20, 6)
        assert "failover_count" not in done, \
            "prefill death must re-route, not failover"
        snap = router.registry.snapshot()
        assert snap.get("router_rerouted_total", 0) >= 1
    finally:
        server.drain()
        for s in stubs:
            s.close()


def leg_wedge_stall_evict():
    """Leg 3: the replica wedges (stream AND probes stall) -> the
    control loop evicts it, the relay's poll notices, the stream
    resumes on the survivor."""
    stubs = [StubReplica("w0", {"wedge_after_tokens": 2}),
             StubReplica("w1")]
    router, server = make_router([s.url for s in stubs])
    try:
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")
        lines = read_stream(f"http://127.0.0.1:{server.port}",
                            {"tokens": [30], "max_new_tokens": 6,
                             "stream": True}, timeout=30)
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            done
        assert toks == expected_tokens(30, 6), toks
        assert done.get("failover_count") == 1, done
        assert any(r.state in ("dead", "evicted")
                   for r in router.replicas), \
            "wedged replica was never evicted"
    finally:
        server.drain()
        for s in stubs:
            s.close()


def leg_journal_cap():
    """Leg 4: past the journal cap the stream loses protection —
    replica death gets the HONEST error frame (the documented
    degradation), never a silent truncation."""
    stubs = [StubReplica("j0", {"die_after_tokens": 8}),
             StubReplica("j1")]
    router, server = make_router([s.url for s in stubs],
                                 failover_journal_tokens=4)
    try:
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")
        lines = read_stream(f"http://127.0.0.1:{server.port}",
                            {"tokens": [40], "max_new_tokens": 16,
                             "stream": True})
        done = lines[-1]
        assert done.get("done") and done["finish_reason"] == "error", \
            f"over-cap death must be an honest error frame: {done}"
        assert "journal cap" in done.get("error", ""), done
        assert done["n_tokens"] == 4, done
        assert stubs[1].resumes == 0, \
            "over-cap stream must not attempt a resume"
    finally:
        server.drain()
        for s in stubs:
            s.close()


def leg_trace_propagation():
    """Trace leg: a client-supplied ``X-Trace-Id`` survives a
    kill@tokens-shaped failover — the SAME id is stamped on the dying
    hop and on the survivor's resume re-submit, with an incrementing
    ``X-Trace-Hop``, and the router records the span."""
    stubs = [StubReplica("t0", {"die_after_tokens": 3}),
             StubReplica("t1")]
    router, server = make_router([s.url for s in stubs])
    try:
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")
        tid = "feedc0dedeadbeef"
        lines = read_stream(f"http://127.0.0.1:{server.port}",
                            {"tokens": [50], "max_new_tokens": 8,
                             "stream": True},
                            headers=[("X-Trace-Id", tid)])
        done = lines[-1]
        assert done.get("done") and done["finish_reason"] == "length", \
            done
        assert done.get("failover_count") == 1, done
        assert [ev["i"] for ev in lines if "token" in ev] \
            == list(range(8)), "indices not exactly-once"
        hops = []
        for stub in stubs:
            for h in stub.headers_seen:
                low = {k.lower(): v for k, v in h.items()}
                assert low.get("x-trace-id") == tid, \
                    f"trace id lost on hop: {low}"
                assert low.get("x-trace-sampled") == "1", low
                hops.append(int(low["x-trace-hop"]))
        assert sorted(hops) == [1, 2], \
            f"expected hop 1 (dying) + hop 2 (resume), got {hops}"
        # The router closes the span AFTER the terminating chunk the
        # client already saw — poll, don't race the handler thread.
        wait_for(lambda: router.registry.snapshot()
                 .get("trace_requests_total", 0) >= 1,
                 what="obs_trace span recorded")
    finally:
        server.drain()
        for s in stubs:
            s.close()


def leg_slo_closed_loop():
    """SLO leg: the full error-budget paging loop, end to end. The
    router runs its synthetic prober (``--probe-every-s``) against a
    short-window availability SLO (``--slo-policy``):

    - golden phase: probes spread over BOTH replicas and the golden
      matches the stubs' pure token function (bitwise-stable across
      replicas);
    - failover phase: a replica dies mid-PROBE — the resume continues
      the stream on the survivor and the tokens still match the
      golden (zero mismatches), with no page;
    - stall phase: both replicas go slow (healthz stays green — the
      failure only the prober can see): probes time out, the fast
      window burns, and EXACTLY ONE page — carrying the failing
      probe's trace id — reaches a stdlib webhook receiver;
    - recovery: probes pass again, the latch clears with no second
      page, and the error budget stops draining.
    """
    import tempfile

    from tpunet.obs import tracing
    from tpunet.obs.export.webhook import AlertWebhook
    from tpunet.router.prober import PROBE_NEW_TOKENS, PROBE_PROMPT

    pages = []

    class Hook(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, *a):  # noqa: D102
            pass

        def do_POST(self):  # noqa: N802
            n = int(self.headers.get("Content-Length") or 0)
            pages.append(json.loads(self.rfile.read(n)))
            self.send_response(204)
            self.send_header("Content-Length", "0")
            self.end_headers()

    receiver = ThreadingHTTPServer(("127.0.0.1", 0), Hook)
    receiver.daemon_threads = True
    threading.Thread(target=receiver.serve_forever,
                     daemon=True).start()
    hook_url = f"http://127.0.0.1:{receiver.server_address[1]}"

    # Page-only, availability-only policy with seconds-scale windows
    # (production uses hours — docs/slos.json): "exactly one webhook
    # POST" is then the whole green condition. short_s stays above
    # the worst-case failed-probe interval (timeout 0.5s + cadence)
    # so the short window is never empty mid-burn.
    policy = {"slos": [{"name": "availability",
                        "sli": "availability", "objective": 0.9,
                        "compliance_window_s": 60,
                        "page": {"long_s": 4.0, "short_s": 1.5,
                                 "burn": 2.0}}]}
    fd, policy_path = tempfile.mkstemp(suffix=".json",
                                       prefix="slo-smoke-")
    with os.fdopen(fd, "w") as f:
        f.write("// chaos-smoke SLO policy (short windows)\n"
                + json.dumps(policy))

    stubs = [StubReplica("s0"), StubReplica("s1")]
    router, server = make_router([s.url for s in stubs],
                                 probe_every_s=0.05,
                                 slo_policy=policy_path,
                                 emit_every_s=0.2)
    hook = AlertWebhook(hook_url, kinds=("obs_alert",),
                        registry=router.registry, name="slo-smoke")
    router.registry.add_sink(hook)
    slo_records = []

    class SloTap:
        def write(self, record):
            if record.get("kind") == "obs_slo":
                slo_records.append(record)

    router.registry.add_sink(SloTap())
    try:
        engine, prober = router.slo, server.prober
        assert engine is not None and prober is not None, \
            "probe_every_s + slo_policy must arm engine and prober"
        wait_for(lambda: router.healthy_count() == 2, what="2 healthy")

        # -- golden phase: bitwise-stable across replicas ----------
        wait_for(lambda: prober.golden is not None
                 and engine.probe_requests >= 10
                 and stubs[0].requests > 0 and stubs[1].requests > 0,
                 what="golden established across both replicas")
        assert prober.golden \
            == expected_tokens(PROBE_PROMPT[0], PROBE_NEW_TOKENS), \
            f"golden diverged from the pure stream: {prober.golden}"
        assert engine.probe_mismatches == 0, "golden unstable"

        # -- mid-probe failover: golden survives the seam ----------
        stubs[0].behavior["die_after_tokens"] = 3
        wait_for(lambda: "die_after_tokens" not in stubs[0].behavior,
                 what="a probe to hit the armed replica")
        n0 = engine.probe_requests
        wait_for(lambda: engine.probe_requests >= n0 + 3,
                 what="post-failover probes")
        assert engine.probe_mismatches == 0, \
            "failover resume diverged from the golden"
        assert router.registry.snapshot() \
            .get("router_failovers_total", 0) >= 1
        assert pages == [], f"paged during clean failover: {pages}"

        # -- stall phase: burn the fast window -> exactly one page -
        for s in stubs:
            s.behavior["line_delay_s"] = 2.0
        wait_for(lambda: len(pages) >= 1, timeout=30,
                 what="fast-burn page at the webhook")
        assert router.healthy_count() == 2, \
            "stall must be invisible to healthz (prober-only signal)"
        page = pages[0]
        assert page["kind"] == "obs_alert" \
            and page["reason"] == "slo_fast_burn" \
            and page["severity"] == "page", page
        detail = page["detail"]
        assert detail["slo"] == "availability", detail
        assert tracing.valid_trace_id(detail.get("trace_id", "")), \
            f"page must carry the failing probe's trace id: {detail}"
        time.sleep(1.5)         # burn continues; the latch must hold
        assert len(pages) == 1, \
            f"edge latch failed: {len(pages)} pages for one burst"

        # -- recovery: latch clears, budget stops draining ---------
        for s in stubs:
            s.behavior.pop("line_delay_s", None)
        wait_for(lambda: not any(r.get("page_firing")
                                 for r in engine.evaluate()),
                 timeout=30, what="page latch to clear")
        rec = next(r for r in engine.evaluate()
                   if r["name"] == "availability")
        budget_at_clear = rec["budget_remaining"]
        time.sleep(1.0)
        rec = next(r for r in engine.evaluate()
                   if r["name"] == "availability")
        assert rec["budget_remaining"] >= budget_at_clear - 1e-9, \
            (rec["budget_remaining"], budget_at_clear)
        assert len(pages) == 1, \
            f"re-paged after recovery: {len(pages)}"
        wait_for(lambda: any(r.get("name") == "availability"
                             and "budget_remaining" in r
                             for r in slo_records),
                 what="obs_slo records on the emit cadence")
    finally:
        server.drain()
        hook.close()
        receiver.shutdown()
        receiver.server_close()
        for s in stubs:
            s.close()
        os.unlink(policy_path)


def leg_real_engine():
    """Slow leg (--real): two real serve children, --chaos
    kill@tokens=N:replica=0 — a real SIGKILL of a real engine
    mid-stream, resumed through the real bucketed-prefill path with
    no error frame."""
    import tempfile

    from tpunet.router.__main__ import build_argparser, build_server
    from tpunet.router.balance import preferred_replica
    from tpunet.router.replica import ReplicaHandle

    tmp = tempfile.mkdtemp(prefix="serve-chaos-")
    argv = ["--spawn", "2", "--port", "0",
            "--probe-interval-s", "0.2", "--probe-timeout-s", "2",
            "--unhealthy-after", "2", "--boot-timeout-s", "240",
            "--respawn-backoff-s", "60",   # victim stays down: the
            #                               survivor must carry alone
            "--emit-every-s", "0.5", "--min-replicas", "2",
            "--max-replicas", "2", "--metrics-dir", tmp,
            "--chaos", "kill@tokens=12:replica=0", "--",
            "--checkpoint-dir", "", "--slots", "2",
            "--prefill-buckets", "64", "--queue-max", "16",
            "--max-new-tokens", "64", "--vit-hidden", "32",
            "--vit-depth", "2", "--vit-heads", "2",
            "--vocab-size", "256", "--max-seq-len", "256"]
    server = build_server(build_argparser().parse_args(argv)).start()
    router = server.router
    base = f"http://127.0.0.1:{server.port}"
    try:
        wait_for(lambda: router.healthy_count() == 2, timeout=240,
                 what="both replicas healthy (cold boot)")
        # Pin the stream to the chaos-armed child via session
        # affinity (rendezvous over replica names is pure).
        fakes = [ReplicaHandle("r0", "http://x"),
                 ReplicaHandle("r1", "http://x")]
        session = next(s for s in (f"s{i}" for i in range(64))
                       if preferred_replica(fakes, f"s:{s}").name
                       == "r0")
        lines = read_stream(base, {"tokens": [7, 3, 9],
                                   "max_new_tokens": 24,
                                   "stream": True,
                                   "session": session}, timeout=240)
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            done
        assert "error" not in done, done
        assert len(toks) == 24, f"{len(toks)} tokens"
        assert done.get("failover_count", 0) >= 1, done
        snap = json.loads(urllib.request.urlopen(
            base + "/metrics", timeout=10).read())
        assert snap.get("router_failovers_total", 0) >= 1
    finally:
        server.drain()


def leg_spec_kill_mid_verify():
    """Slow leg (--real): SIGKILL of a SPECULATIVE-DECODING replica
    mid-verify-window. kill@tokens=14 with K=3 self-speculation
    (4 verified tokens per burst) fires inside the 4th window's emit
    loop — the dying replica has streamed a partial verify window.
    The survivor resumes spec-on from the journal; the stitched
    stream must equal an UNINTERRUPTED run of the same request pinned
    to the other replica, which is only true if every journaled token
    was a verified one (a draft leaking into the stream would fork
    the two runs at the seam)."""
    import tempfile

    from tpunet.router.__main__ import build_argparser, build_server
    from tpunet.router.balance import preferred_replica
    from tpunet.router.replica import ReplicaHandle

    tmp = tempfile.mkdtemp(prefix="serve-chaos-spec-")
    argv = ["--spawn", "2", "--port", "0",
            "--probe-interval-s", "0.2", "--probe-timeout-s", "2",
            "--unhealthy-after", "2", "--boot-timeout-s", "240",
            "--respawn-backoff-s", "60",
            "--emit-every-s", "0.5", "--min-replicas", "2",
            "--max-replicas", "2", "--metrics-dir", tmp,
            "--chaos", "kill@tokens=14:replica=0", "--",
            "--checkpoint-dir", "", "--slots", "2",
            "--prefill-buckets", "64", "--queue-max", "16",
            "--max-new-tokens", "64", "--vit-hidden", "32",
            "--vit-depth", "2", "--vit-heads", "2",
            "--vocab-size", "256", "--max-seq-len", "256",
            "--spec-decode", "--spec-k", "3",
            "--spec-draft-width-mult", "1.0"]
    server = build_server(build_argparser().parse_args(argv)).start()
    router = server.router
    base = f"http://127.0.0.1:{server.port}"
    try:
        wait_for(lambda: router.healthy_count() == 2, timeout=240,
                 what="both spec replicas healthy (cold boot)")
        fakes = [ReplicaHandle("r0", "http://x"),
                 ReplicaHandle("r1", "http://x")]

        def session_for(name):
            return next(s for s in (f"s{i}" for i in range(64))
                        if preferred_replica(fakes, f"s:{s}").name
                        == name)

        body = {"tokens": [7, 3, 9], "max_new_tokens": 24,
                "stream": True}
        # Uninterrupted reference on r1 FIRST (r0's chaos counter
        # must not see these tokens).
        ref = read_stream(base, dict(body,
                                     session=session_for("r1")),
                          timeout=240)
        ref_toks = [ev["token"] for ev in ref if "token" in ev]
        assert len(ref_toks) == 24, f"{len(ref_toks)} ref tokens"
        lines = read_stream(base, dict(body,
                                       session=session_for("r0")),
                            timeout=240)
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            done
        assert "error" not in done, done
        assert done.get("failover_count", 0) >= 1, done
        assert toks == ref_toks, \
            "stitched spec stream != uninterrupted stream"
    finally:
        server.drain()


def leg_prefix_warm_start():
    """Slow leg (--real): fleet-wide prefix warm start across a
    SIGKILL. Two real serve children share a ``--prefix-store``
    directory; a shared-prefix request through replica r0 spills its
    cached pages to the store; r0 is SIGKILLed by pid (from
    ``GET /replicas``); the supervisor's respawn warm-loads the
    fleet's prefix set at boot, so the FIRST shared-prefix request on
    the fresh process prefills only the suffix."""
    import signal
    import tempfile

    from tpunet.router.__main__ import build_argparser, build_server

    def get_json(url, timeout=10):
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())

    tmp = tempfile.mkdtemp(prefix="serve-chaos-")
    store = tempfile.mkdtemp(prefix="serve-prefix-")
    argv = ["--spawn", "2", "--port", "0",
            "--probe-interval-s", "0.2", "--probe-timeout-s", "2",
            "--unhealthy-after", "2", "--boot-timeout-s", "240",
            "--respawn-backoff-s", "0.5",  # we WANT the respawn here
            "--emit-every-s", "0.5", "--min-replicas", "2",
            "--max-replicas", "2", "--metrics-dir", tmp, "--",
            "--checkpoint-dir", "", "--slots", "2",
            "--prefill-buckets", "64", "--queue-max", "16",
            "--max-new-tokens", "64", "--vit-hidden", "32",
            "--vit-depth", "2", "--vit-heads", "2",
            "--vocab-size", "256", "--max-seq-len", "256",
            "--kv-page-tokens", "16", "--prefix-store", store]
    server = build_server(build_argparser().parse_args(argv)).start()
    router = server.router
    base = f"http://127.0.0.1:{server.port}"
    try:
        wait_for(lambda: router.healthy_count() == 2, timeout=240,
                 what="both replicas healthy (cold boot)")
        rows = get_json(base + "/replicas")["replicas"]
        r0 = next(r for r in rows if r["name"] == "r0")
        old_pid = r0["pid"]

        # Shared prefix = 2 full 16-token pages; hit r0 DIRECTLY so
        # we know exactly which process cached + spilled the pages.
        shared = [(i * 11 + 3) % 256 for i in range(32)]
        lines = read_stream(r0["url"], {"tokens": shared + [5],
                                        "max_new_tokens": 4,
                                        "stream": True}, timeout=240)
        assert lines[-1].get("done"), lines[-1]
        wait_for(lambda: any(f.endswith(".pfx")
                             for f in os.listdir(store)),
                 timeout=30, what="prefix pages spilled to the store")
        m0 = get_json(r0["url"] + "/metrics")
        assert m0.get("serve_prefix_spills_total", 0) >= 2, m0

        # SIGKILL the process that owns the cache; the probe loop
        # evicts it and the supervisor respawns after the backoff.
        os.kill(old_pid, signal.SIGKILL)

        def respawned():
            for r in get_json(base + "/replicas")["replicas"]:
                if r["name"] == "r0":
                    return (r["state"] == "healthy"
                            and r.get("alive")
                            and r.get("pid") not in (None, old_pid))
            return False
        wait_for(respawned, timeout=240,
                 what="r0 respawned + healthy after SIGKILL")
        rows = get_json(base + "/replicas")["replicas"]
        r0 = next(r for r in rows if r["name"] == "r0")

        # The fresh process adopted the fleet's prefix set at boot...
        m1 = get_json(r0["url"] + "/metrics")
        assert m1.get("serve_prefix_warm_loads_total", 0) >= 2, \
            f"respawn did not warm-load the shared store: {m1}"
        # ...so its FIRST shared-prefix request prefills suffix only.
        before = m1.get("serve_prefill_tokens_total", 0)
        lines = read_stream(r0["url"], {"tokens": shared + [9],
                                        "max_new_tokens": 4,
                                        "stream": True}, timeout=240)
        assert lines[-1].get("done"), lines[-1]
        m2 = get_json(r0["url"] + "/metrics")
        delta = m2.get("serve_prefill_tokens_total", 0) - before
        assert 0 < delta < len(shared), \
            f"warm replica prefilled {delta} tokens for a " \
            f"{len(shared)}-token cached prefix"
        assert m2.get("serve_prefix_hits_total", 0) >= 1, m2
    finally:
        server.drain()


def main() -> int:
    real = "--real" in sys.argv[1:]
    unknown = [a for a in sys.argv[1:] if a != "--real"]
    if unknown:
        print(f"usage: serve_chaos_smoke.py [--real] "
              f"(unknown: {unknown})", file=sys.stderr)
        return 2
    legs = [("kill mid-stream -> seamless continuation",
             leg_kill_mid_stream),
            ("kill during prefill -> pre-first-byte re-route",
             leg_kill_at_prefill),
            ("wedge -> stall-evict -> failover",
             leg_wedge_stall_evict),
            ("journal cap exceeded -> honest error frame",
             leg_journal_cap),
            ("trace context propagated across failover",
             leg_trace_propagation),
            ("slo closed loop: stall -> one page -> recovery",
             leg_slo_closed_loop)]
    if real:
        legs.append(("real engine: SIGKILL mid-stream, no error "
                     "frame", leg_real_engine))
        legs.append(("prefix warm start: SIGKILL -> respawn adopts "
                     "shared store, suffix-only prefill",
                     leg_prefix_warm_start))
        legs.append(("spec decode: SIGKILL mid-verify -> survivor "
                     "resumes verified-only journal",
                     leg_spec_kill_mid_verify))
    failures = []
    for name, fn in legs:
        try:
            fn()
            print(f"[PASS] {name}")
        except Exception as e:  # noqa: BLE001
            failures.append(name)
            print(f"[FAIL] {name}: {type(e).__name__}: {e}")
    if failures:
        print(f"serve_chaos_smoke: FAILED ({', '.join(failures)})")
        return 1
    print("serve_chaos_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
