#!/usr/bin/env python
"""tpucheck: repo-native JAX/TPU static analysis (thin wrapper around
``python -m tpunet.analysis`` for people who tab-complete scripts/).

Rule catalog, baseline semantics, and suppression syntax:
docs/static_analysis.md. Part of the pre-merge gate
(scripts/run_checks.sh).
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tpunet.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
