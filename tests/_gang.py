"""Shared 2-controller gang launcher (no import-time side effects).

ONE home for the launch/drain protocol used by both
tests/test_multiprocess.py and the driver dryrun's leg 8
(__graft_entry__._dryrun_two_process) — this very protocol needed a
lockstep fix once (the stderr-pipe gang stall below), which is exactly
why it must not be duplicated.

Protocol invariants:
- fresh coordinator port per gang;
- env scrubbed of the parent's single-process platform pins
  (JAX_PLATFORMS / XLA_FLAGS / PALLAS_AXON_POOL_IPS) so the workers
  pick their own 4-device CPU config;
- stderr goes to FILES, not pipes: the parent drains the workers
  SEQUENTIALLY, so a chatty worker 1 (orbax/XLA warnings) can fill its
  64 KB stderr pipe while worker 0 is being read, block mid-step, and
  stall the whole gang at the next collective until the coordination
  barrier times out. stdout stays a pipe — it is one JSON line;
- workers are killed on ANY failure (a rendezvous deadlock must not
  outlive the caller).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import tempfile


def launch_gang(argv_tail, timeout: float = 600.0):
    """Spawn 2 worker controllers (tests/_mp_worker.py) with the given
    extra argv and return both parsed JSON outputs."""
    here = os.path.dirname(os.path.abspath(__file__))
    repo = os.path.dirname(here)
    worker = os.path.join(here, "_mp_worker.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS",
                        "PALLAS_AXON_POOL_IPS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    errs = [tempfile.NamedTemporaryFile("w+", suffix=f"-w{pid}.err",
                                        delete=False)
            for pid in range(2)]
    procs = [subprocess.Popen(
        [sys.executable, worker, f"127.0.0.1:{port}", "2", str(pid)]
        + [str(a) for a in argv_tail],
        stdout=subprocess.PIPE, stderr=errs[pid], text=True,
        env=env, cwd=repo) for pid in range(2)]
    outs = []
    try:
        for p, ef in zip(procs, errs):
            out, _ = p.communicate(timeout=timeout)
            if p.returncode != 0:
                ef.seek(0)
                raise AssertionError(
                    f"worker failed:\n{ef.read()[-3000:]}")
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for ef in errs:
            ef.close()
            try:
                os.unlink(ef.name)
            except OSError:
                pass
    return outs
