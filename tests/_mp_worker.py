"""Worker for the 2-process multi-controller test (run via subprocess).

Boots jax.distributed against a localhost coordinator (the analogue of
the reference's mpirun + localhost:29500 rendezvous,
cifar10_mpi_mobilenet_224.py:28-35), builds the global mesh spanning both
processes' virtual CPU devices, trains one epoch of the tiny synthetic
workload, and prints metrics as JSON for the parent to compare.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def fsdp_lm_case():
    """(cfg, dataset) for the FSDP+grad-accum LM case — the ONE source
    of truth shared by the worker and the test's single-process
    reference (FSDP: params + Adam moments sharded over the
    cross-process 'data' axis)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import synthetic_lm

    cfg = TrainConfig(
        epochs=1, seed=42,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        seq_len=32, vocab_size=32),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=32),
        optim=OptimConfig(learning_rate=3e-3, grad_accum=2),
        mesh=MeshConfig(fsdp=True),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    return cfg, synthetic_lm(64, 32, seq_len=32, vocab=32, seed=7)


def packed_lm_case(tmp_dir=None):
    """(cfg, dataset) for the packed-sequence LM case: both controllers
    train on packed documents with [B, T] segment-id labels crossing
    the process boundary — exercises 2-D label sharding, the
    segment-masked step, and count-weighted metrics multi-controller.
    Each process writes its OWN copy of the (deterministic, identical)
    corpus — a shared path would race: the workers reach this right
    after the rendezvous, and one could read the file mid-truncation.
    """
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import text_lm_packed

    tmp_dir = tmp_dir or f"/tmp/tpunet-mp-packed-{os.getpid()}"
    os.makedirs(tmp_dir, exist_ok=True)
    path = os.path.join(tmp_dir, "docs.txt")
    docs = ([b"alpha beta gamma delta"] * 30 + [b"tiny"] * 60) * 2
    with open(path, "wb") as f:
        f.write(b"\n".join(docs))
    cfg = TrainConfig(
        epochs=1, seed=42,
        data=DataConfig(dataset="text_lm", text_path=path,
                        batch_size=16, seq_len=32, vocab_size=256,
                        pack_docs=True),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=256,
                          max_seq_len=32),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    return cfg, text_lm_packed(path, seq_len=32)


def main():
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "dp"
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=pid,
    )
    assert jax.process_count() == num_procs
    assert jax.device_count() == 4 * num_procs

    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import sync_hosts
    from tpunet.train.loop import Trainer

    if mode == "fsdp_lm":
        cfg, ds = fsdp_lm_case()
    elif mode == "packed_lm":
        cfg, ds = packed_lm_case()
    else:
        cfg = TrainConfig(
            epochs=1, seed=42,
            data=DataConfig(dataset="synthetic", image_size=32, batch_size=16,
                            rrc_scale=(1.0, 1.0), rrc_ratio=(1.0, 1.0),
                            jitter_brightness=0.0, jitter_contrast=0.0,
                            jitter_saturation=0.0, jitter_hue=0.0,
                            rotation_degrees=0.0),
            model=ModelConfig(dtype="float32", width_mult=0.5),
            optim=OptimConfig(learning_rate=1e-3),
            mesh=MeshConfig(),  # all 8 global devices on the data axis
            checkpoint=CheckpointConfig(save_best=False, save_last=False),
        )
        ds = synthetic_cifar10(n_train=64, n_test=32, seed=7)
    trainer = Trainer(cfg, dataset=ds)
    sync_hosts("start")
    eval0 = trainer.evaluate()
    train1 = trainer.train_one_epoch(0)
    print(json.dumps({
        "process": pid,
        "world": jax.process_count(),
        "devices": jax.device_count(),
        "eval0": eval0,
        "train1": train1,
    }), flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
