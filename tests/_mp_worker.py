"""Worker for the 2-process multi-controller test (run via subprocess).

Boots jax.distributed against a localhost coordinator (the analogue of
the reference's mpirun + localhost:29500 rendezvous,
cifar10_mpi_mobilenet_224.py:28-35), builds the global mesh spanning both
processes' virtual CPU devices, trains one epoch of the tiny synthetic
workload, and prints metrics as JSON for the parent to compare.
"""

import json
import os
import sys

# Worker-process environment ONLY: tests import this module for its
# *_case() config factories, and mutating XLA_FLAGS at import time
# would silently re-initialize the IMPORTING process's backend with 4
# devices (a solo `pytest tests/test_multiprocess.py::<one test>` hit
# exactly that).
if __name__ == "__main__":
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

if __name__ == "__main__":
    jax.config.update("jax_platforms", "cpu")

    # Persistent compile cache, shared with tests/conftest.py and the
    # dryrun: the two controllers compile IDENTICAL programs, so
    # whichever wins the race warms the other (and any prior test run
    # warms both).
    from tpunet.utils.cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()


def fsdp_lm_case():
    """(cfg, dataset) for the FSDP+grad-accum LM case — the ONE source
    of truth shared by the worker and the test's single-process
    reference (FSDP: params + Adam moments sharded over the
    cross-process 'data' axis)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import synthetic_lm

    cfg = TrainConfig(
        epochs=1, seed=42,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        seq_len=32, vocab_size=32),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=32),
        optim=OptimConfig(learning_rate=3e-3, grad_accum=2),
        mesh=MeshConfig(fsdp=True),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    return cfg, synthetic_lm(64, 32, seq_len=32, vocab=32, seed=7)


def pp_lm_case():
    """(cfg, dataset) for the PIPELINED LM case under multi-controller:
    the 1F1B executor's shard_map (activation ppermutes over 'pipe',
    microbatch scheduling, the manual VJP) spans a mesh whose 'data'
    axis crosses the process boundary — the closest analogue of the
    reference's multi-node pipeline story (its DDP is single-axis;
    this is schedule + cross-process sharding together)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import synthetic_lm

    cfg = TrainConfig(
        epochs=1, seed=42,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        seq_len=32, vocab_size=32),
        model=ModelConfig(name="lm_pp", vit_hidden=64, vit_depth=4,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=32, pp_microbatches=2,
                          pp_schedule="1f1b"),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(data=4, pipe=2),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    return cfg, synthetic_lm(64, 32, seq_len=32, vocab=32, seed=7)


def packed_lm_case(tmp_dir=None):
    """(cfg, dataset) for the packed-sequence LM case: both controllers
    train on packed documents with [B, T] segment-id labels crossing
    the process boundary — exercises 2-D label sharding, the
    segment-masked step, and count-weighted metrics multi-controller.
    Each process writes its OWN copy of the (deterministic, identical)
    corpus — a shared path would race: the workers reach this right
    after the rendezvous, and one could read the file mid-truncation.
    """
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import text_lm_packed

    tmp_dir = tmp_dir or f"/tmp/tpunet-mp-packed-{os.getpid()}"
    os.makedirs(tmp_dir, exist_ok=True)
    path = os.path.join(tmp_dir, "docs.txt")
    docs = ([b"alpha beta gamma delta"] * 30 + [b"tiny"] * 60) * 2
    with open(path, "wb") as f:
        f.write(b"\n".join(docs))
    cfg = TrainConfig(
        epochs=1, seed=42,
        data=DataConfig(dataset="text_lm", text_path=path,
                        batch_size=16, seq_len=32, vocab_size=256,
                        pack_docs=True),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=256,
                          max_seq_len=32),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    return cfg, text_lm_packed(path, seq_len=32)


def _tree_equal(a, b):
    """Bit-exact pytree equality, computed as a global computation (works
    on cross-process sharded leaves: every controller runs the same
    array_equal, whose scalar result is replicated)."""
    import jax
    import jax.numpy as jnp

    la, ta = jax.tree_util.tree_flatten(a)
    lb, tb = jax.tree_util.tree_flatten(b)
    if ta != tb:
        return False
    return all(bool(jnp.array_equal(x, y)) for x, y in zip(la, lb))


def _state_data(state):
    """The ARRAY fields of a TrainState: its own treedef also carries
    apply_fn/tx as static aux data, which are different function objects
    in different Trainer instances — comparing those would always
    report inequality."""
    return {"params": state.params, "batch_stats": state.batch_stats,
            "opt_state": state.opt_state, "step": state.step,
            "ema_params": state.ema_params,
            "ema_batch_stats": state.ema_batch_stats}


def _ckpt_roundtrip(trainer, cfg, ds, train1):
    """Multi-host orbax checkpointing under TRUE multi-controller: both
    processes participate in one best-params save + one full-state save
    into a SHARED directory, then a fresh Trainer resumes from it and
    must match bit-exactly. The reference saves from rank 0 only
    (cifar10_mpi_mobilenet_224.py:243-250); orbax instead coordinates
    every host through the same save — the coordination (barrier
    pairing, one consistent directory, no deadlock) is exactly what
    this exercises."""
    import dataclasses

    from tpunet.train.loop import Trainer

    trainer.best_acc = float(train1["accuracy"])
    lay = trainer._pp_layout()
    trainer.ckpt.save_best(
        {"params": trainer.state.params,
         "batch_stats": trainer.state.batch_stats},
        meta={"model": cfg.model.name,
              "pp_schedule": cfg.model.pp_schedule,
              "pp_layout_pipe": int(lay[0]),
              "pp_layout_virtual": int(lay[1])})
    trainer.ckpt.save_state(1, trainer._payload())
    trainer.ckpt.wait()

    cfg2 = cfg.replace(checkpoint=dataclasses.replace(
        cfg.checkpoint, resume=True))
    t2 = Trainer(cfg2, dataset=ds)
    try:
        state_equal = _tree_equal(_state_data(trainer.state),
                                  _state_data(t2.state))
        best = t2.ckpt.restore_best({
            "params": t2.state.params,
            "batch_stats": t2.state.batch_stats})
        best_equal = best is not None and _tree_equal(
            trainer.state.params, best["params"])
        meta = t2.ckpt.best_meta()
        return {
            "resume_epoch": t2.start_epoch,
            "resume_best_acc": t2.best_acc,
            "state_equal": state_equal,
            "best_equal": best_equal,
            "meta_model": meta["model"] if meta else None,
        }
    finally:
        t2.close()


def main():
    coordinator, num_procs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "dp"
    ckpt_dir = sys.argv[5] if len(sys.argv) > 5 else None
    # Cross-process CPU collectives need an explicit implementation on
    # this jax (same fix as tpunet/parallel/dist.py): without gloo the
    # first cross-controller psum raises "Multiprocess computations
    # aren't implemented on the CPU backend".
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_procs,
        process_id=pid,
    )
    assert jax.process_count() == num_procs
    assert jax.device_count() == 4 * num_procs

    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import sync_hosts
    from tpunet.train.loop import Trainer

    if mode == "fsdp_lm":
        cfg, ds = fsdp_lm_case()
    elif mode == "pp_lm":
        cfg, ds = pp_lm_case()
    elif mode == "packed_lm":
        cfg, ds = packed_lm_case()
    else:
        cfg = TrainConfig(
            epochs=1, seed=42,
            data=DataConfig(dataset="synthetic", image_size=32, batch_size=16,
                            rrc_scale=(1.0, 1.0), rrc_ratio=(1.0, 1.0),
                            jitter_brightness=0.0, jitter_contrast=0.0,
                            jitter_saturation=0.0, jitter_hue=0.0,
                            rotation_degrees=0.0),
            model=ModelConfig(dtype="float32", width_mult=0.5),
            optim=OptimConfig(learning_rate=1e-3),
            mesh=MeshConfig(),  # all 8 global devices on the data axis
            checkpoint=CheckpointConfig(save_best=False, save_last=False),
        )
        ds = synthetic_cifar10(n_train=64, n_test=32, seed=7)
    if ckpt_dir:
        # Shared directory from the parent: all controllers join the
        # same multi-host orbax saves (and the round-trip below).
        cfg = cfg.replace(checkpoint=CheckpointConfig(
            directory=ckpt_dir, save_best=True, save_last=True))
    trainer = Trainer(cfg, dataset=ds)
    sync_hosts("start")
    eval0 = trainer.evaluate()
    train1 = trainer.train_one_epoch(0)
    out = {
        "process": pid,
        "world": jax.process_count(),
        "devices": jax.device_count(),
        "eval0": eval0,
        "train1": train1,
    }
    if ckpt_dir:
        out["ckpt"] = _ckpt_roundtrip(trainer, cfg, ds, train1)
    trainer.close()
    print(json.dumps(out), flush=True)
    jax.distributed.shutdown()


if __name__ == "__main__":
    main()
