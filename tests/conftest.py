"""Test harness: force an 8-device virtual CPU mesh BEFORE jax import.

This is the TPU-native analogue of the reference's gloo/CPU fallback path
(cifar10_mpi_mobilenet_224.py:34,41-43) — multi-device sharding logic is
exercised on any machine with no TPU attached (SURVEY.md section 4).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Some environments force a TPU platform via sitecustomize *after* env
# vars are read; override at the config level too (must happen before
# the first backend use).
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: repeated Trainer/jit builds across test
# files reuse compiled executables instead of re-tracing XLA each time.
# Shared convention (path + thresholds) lives in tpunet.utils.cache.
from tpunet.utils.cache import enable_persistent_compile_cache  # noqa: E402

enable_persistent_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng_np():
    return np.random.default_rng(42)
