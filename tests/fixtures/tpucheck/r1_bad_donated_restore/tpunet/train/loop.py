# tpucheck R1 regression fixture: the PR-7 resume heap-corruption
# pattern — orbax-restored state donated into the jitted train step
# without re-materialization. Parsed only, never imported.
import jax


class Trainer:
    def __init__(self, cfg, ckpt, train_fn):
        self.ckpt = ckpt
        self.train_step = jax.jit(train_fn, donate_argnums=0)
        self.state = None
        if cfg.resume:
            self._try_resume()

    def _try_resume(self):
        restored = self.ckpt.restore_state(self._payload())
        if restored is None:
            return
        self.state = restored["state"]

    def _payload(self):
        return {"state": self.state}

    def train(self, batches):
        for batch, labels, rng in batches:
            # BUG (by construction): self.state still aliases the
            # restore's host buffers on the first post-resume step.
            self.state, metrics = self.train_step(self.state, batch,
                                                  labels, rng)
        return self.state
