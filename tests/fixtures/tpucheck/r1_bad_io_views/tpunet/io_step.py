# tpucheck R1 fixture: module-level IO-origin views (np.load /
# np.asarray over a foreign buffer) into donated jit args, positional
# and by-name. Parsed only, never imported.
import jax
import numpy as np


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))
named_step = jax.jit(_step, donate_argnames=("state",))

weights = np.load("weights.npy")
step(weights, None)

view = np.asarray(memoryview(b"romp"))
named_step(state=view, batch=None)
