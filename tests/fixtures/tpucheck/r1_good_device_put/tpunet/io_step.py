# tpucheck R1 good fixture: device_put re-materializes before
# donation; reassignment from a fresh producer clears taint.
import jax
import numpy as np


def _step(state, batch):
    return state


def fresh_state():
    return {"w": 0}


step = jax.jit(_step, donate_argnums=(0,))

weights = jax.device_put(np.load("weights.npy"))
step(weights, None)

state = np.load("ckpt.npy")
state = fresh_state()
step(state, None)
