# tpucheck R1 good fixture: the PR-7 FIX — restored state is
# re-materialized (tree_map(jnp.copy)) before the donated call.
import jax
import jax.numpy as jnp


class Trainer:
    def __init__(self, cfg, ckpt, train_fn):
        self.ckpt = ckpt
        self.train_step = jax.jit(train_fn, donate_argnums=0)
        self.state = None

    def _try_resume(self):
        restored = self.ckpt.restore_state({"state": self.state})
        if restored is None:
            return
        self.state = jax.tree_util.tree_map(jnp.copy, restored["state"])

    def train(self, batches):
        for batch, labels, rng in batches:
            self.state, metrics = self.train_step(self.state, batch,
                                                  labels, rng)
        return self.state
