# tpucheck R2 regression fixture: the PR-6 pattern — a custom_vjp'd
# Pallas kernel whose fwd/bwd carry NO tpunet_* named scope, so its
# custom calls attribute to 'elementwise' and the backward to the fwd
# phase. Parsed only, never imported.
import functools

import jax
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _invoke(x):
    return pl.pallas_call(_kernel, out_shape=x)(x)


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_op(x):
    return _invoke(x)


def _fwd(x):
    return _invoke(x), (x,)


def _bwd(res, g):
    (x,) = res
    return (_invoke(g),)


fused_op.defvjp(_fwd, _bwd)
