# tpucheck R2 fixture: scoped, but with a marker hlo_bytes'
# KERNEL_SCOPES does not know — attribution would silently bucket it
# into 'elementwise'. Parsed only, never imported.
import jax
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def mystery_op(x):
    with jax.named_scope("tpunet_mystery_fwd"):
        return pl.pallas_call(_kernel, out_shape=x)(x)
