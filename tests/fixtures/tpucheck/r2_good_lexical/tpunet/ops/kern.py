# tpucheck R2 good fixture: kernel calls and custom_vjp fwd/bwd all
# lexically under registered tpunet_* scopes (the flash layout).
import functools

import jax
from jax.experimental import pallas as pl


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def fused_op(x):
    with jax.named_scope("tpunet_fused_ir_fwd"):
        return pl.pallas_call(_kernel, out_shape=x)(x)


def _fwd(x):
    with jax.named_scope("tpunet_fused_ir_fwd"):
        y = pl.pallas_call(_kernel, out_shape=x)(x)
    return y, (x,)


def _bwd(res, g):
    (x,) = res
    with jax.named_scope("tpunet_fused_ir_bwd"):
        return (pl.pallas_call(_kernel, out_shape=g)(g),)


fused_op.defvjp(_fwd, _bwd)
