# tpucheck R2 good fixture: the depthwise layout — the pallas_call
# lives in a wrapper (here additionally hidden behind a
# custom_partitioning alias) whose every live call site is scoped;
# the bwd body carries its own scope.
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.custom_partitioning import custom_partitioning


def _kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...]


def _pallas_forward(x):
    return pl.pallas_call(_kernel, out_shape=x)(x)


_partitioned = custom_partitioning(_pallas_forward)


def _partition(mesh, arg_shapes, result_shape):
    # Partitioner callback: never called in-module; its unscoped use
    # of the wrapper must not count against coverage.
    def lower_fn(x):
        return _pallas_forward(x)

    return mesh, lower_fn, result_shape, arg_shapes


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def depthwise_op(x):
    with jax.named_scope("tpunet_depthwise_fwd"):
        return _partitioned(x)


def _fwd(x):
    return depthwise_op(x), (x,)


def _bwd(res, g):
    (x,) = res
    with jax.named_scope("tpunet_depthwise_bwd"):
        return (_pallas_forward(g),)


depthwise_op.defvjp(_fwd, _bwd)
