# tpucheck R3 fixture: numpy on a traced value and global mutation
# inside jit/shard_map bodies.
import functools

import jax
import numpy as np

_STEPS = 0


@functools.partial(jax.jit, static_argnums=(1,))
def loss_step(batch, scale):
    global _STEPS
    _STEPS = _STEPS + 1
    return np.mean(batch) * scale


def _shard_body(x):
    return np.sum(x)


def build(mesh):
    from jax.experimental.shard_map import shard_map
    return shard_map(_shard_body, mesh=mesh, in_specs=None,
                     out_specs=None)
