# tpucheck R3 fixture: print and wall-clock reads inside jitted
# bodies — both run once at trace time and never again.
import time

import jax


@jax.jit
def train_step(state, batch):
    print("step!", batch)
    return state


def _timed(state):
    t0 = time.perf_counter()
    return state, t0


timed_step = jax.jit(_timed)
