# tpucheck R3 good fixture: the same side effects OUTSIDE jit are
# host code and perfectly fine; jax.debug.* inside jit is sanctioned.
import time

import jax
import jax.numpy as jnp


@jax.jit
def train_step(state, batch):
    jax.debug.print("loss {l}", l=batch)
    return state, jnp.mean(batch)


def epoch(batches):
    t0 = time.perf_counter()
    for batch in batches:
        print("host-side progress", batch.shape)
    return time.perf_counter() - t0
