# tpucheck R3 good fixture: numpy on STATIC values (shapes, closure
# constants) inside jit is trace-time math by design — only numpy on
# traced parameters is the bug; callbacks are the sanctioned bridge.
import jax
import numpy as np

SHAPE = (8, 128)


def _record(x):
    pass


@jax.jit
def padded_step(batch):
    n = int(np.prod(SHAPE))
    jax.experimental.io_callback(_record, None, batch)
    return batch.reshape(n)


step = jax.jit(padded_step)
