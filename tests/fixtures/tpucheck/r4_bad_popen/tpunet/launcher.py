# tpucheck R4 fixture: a long-lived child process spawned without
# any registry/inventory trace.
import subprocess
import sys


def launch_sidecar(path):
    return subprocess.Popen([sys.executable, path])
