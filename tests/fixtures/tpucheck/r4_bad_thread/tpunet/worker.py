# tpucheck R4 fixture: a background thread invisible to the
# flightrec host-thread registry.
import threading


class Exporter:
    def start(self):
        self._thread = threading.Thread(target=self._drain,
                                        daemon=True,
                                        name="rogue-exporter")
        self._thread.start()

    def _drain(self):
        pass
