# tpucheck R4 good fixture: the sanctioned idiom — register in the
# spawning scope, beat in the worker; synchronous subprocess.run is
# not a spawn (the child is reaped before the call returns).
import subprocess
import threading

from tpunet.obs.flightrec import register_thread


class Exporter:
    def start(self):
        self._handle = register_thread("exporter-drain",
                                       stall_after_s=120.0)
        self._thread = threading.Thread(target=self._drain,
                                        daemon=True,
                                        name="exporter-drain")
        self._thread.start()

    def _drain(self):
        self._handle.beat("busy")
        self._handle.beat("idle")


def build_lib():
    subprocess.run(["make", "-C", "cxx"], check=True)
