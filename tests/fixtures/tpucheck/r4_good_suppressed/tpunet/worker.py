# tpucheck R4 good fixture: inline suppression — the line-level
# escape hatch for a reviewed, justified exception.
import threading


def fire_and_forget(fn):
    # one-shot timer thread, dies in <1ms; registry churn would cost
    # more than the inventory is worth here
    t = threading.Thread(target=fn)  # tpucheck: disable=R4
    t.start()
    return t
