# tpucheck R5 fixture: ServeConfig.queue_max is flagged but
# undocumented — no markdown mentions the field or its flag.
import argparse
from dataclasses import dataclass


@dataclass(frozen=True)
class ServeConfig:
    host: str = "127.0.0.1"
    queue_max: int = 64


def build_argparser():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--queue-max", type=int, default=64)
    return p
