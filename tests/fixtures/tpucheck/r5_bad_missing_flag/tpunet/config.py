# tpucheck R5 fixture: ServeConfig.queue_max has no CLI flag.
import argparse
from dataclasses import dataclass


@dataclass(frozen=True)
class ServeConfig:
    host: str = "127.0.0.1"
    queue_max: int = 64


def build_argparser():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    return p
