# tpucheck R5 good fixture: a boolean field wired through its
# --no-X negation form (the --no-obs idiom).
import argparse
from dataclasses import dataclass


@dataclass(frozen=True)
class ObsConfig:
    enabled: bool = True


def build_argparser():
    p = argparse.ArgumentParser()
    p.add_argument("--no-enabled", action="store_true")
    return p
