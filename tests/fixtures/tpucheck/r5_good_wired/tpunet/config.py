# tpucheck R5 good fixture: every field has a flag and a docs
# mention; nested sub-config fields are their own surface and are
# not judged here.
import argparse
from dataclasses import dataclass, field


@dataclass(frozen=True)
class ExportConfig:
    statsd: str = ""


@dataclass(frozen=True)
class ServeConfig:
    host: str = "127.0.0.1"
    queue_max: int = 64
    export: ExportConfig = field(default_factory=ExportConfig)


def build_argparser():
    p = argparse.ArgumentParser()
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--queue-max", type=int, default=64)
    return p
