# tpucheck R6 bad fixture: a dynamically-named family with NO
# documented placeholder shape — a bare `<name>`-only doc span must
# not act as a match-everything wildcard either.


def account(registry, name):
    registry.counter(f"pool_{name}_dropped").inc()
