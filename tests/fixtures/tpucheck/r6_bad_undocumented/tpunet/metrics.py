# tpucheck R6 bad fixture: the drift class — an instrument created in
# code that the schema doc never heard of. check_metrics_schema only
# catches this at runtime IF some driven path emits a record carrying
# it; the static rule catches the name at creation.


def account(registry):
    registry.counter("widgets_total").inc()         # documented: fine
    registry.gauge("surprise_depth").set(3)         # undocumented
