# tpucheck R6 good fixture: every literal instrument name appears in
# docs/metrics_schema.md.


def account(registry):
    registry.counter("widgets_total").inc()
    registry.gauge("widget_depth").set(3)
    registry.histogram("widget_latency_s").observe(0.01)
