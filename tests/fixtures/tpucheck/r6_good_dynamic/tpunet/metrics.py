# tpucheck R6 good fixture: a dynamically-named instrument family
# whose shape is documented with a <hole> placeholder.


def account(registry, name):
    registry.counter(f"pool_{name}_dropped").inc()
    registry.gauge(f"pool_{name}_depth").set(1)
