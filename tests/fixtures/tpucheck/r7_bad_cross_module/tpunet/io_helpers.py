# tpucheck R7 fixture (bad): the producer's NAME escapes R1's
# restore/load heuristic, but its return is an IO-origin value — only
# the cross-module summary sees it. Parsed only, never imported.
import pickle


def grab_weights(path):
    with open(path, "rb") as f:
        return pickle.load(f)
