# tpucheck R7 fixture (bad): donating the cross-module IO-tainted
# value — the elastic re-mesh restore-path shape with the
# re-materialization missing.
import jax

from tpunet.io_helpers import grab_weights


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))

weights = grab_weights("weights.pkl")
step(weights, None)
