# tpucheck R7 fixture (bad, transitive): the taint crosses TWO
# project functions before reaching the donated call — the fixpoint
# summary pass must propagate it through the wrapper.
import pickle


def grab_weights(path):
    with open(path, "rb") as f:
        return pickle.load(f)


def fetch_bundle(path):
    return grab_weights(path)
