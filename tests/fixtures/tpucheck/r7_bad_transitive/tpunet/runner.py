# tpucheck R7 fixture (bad, transitive): the donated value came
# through a wrapper of a wrapper of pickle.load.
import jax

from tpunet.io_helpers import fetch_bundle


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))

bundle = fetch_bundle("weights.pkl")
step(bundle, None)
