# tpucheck R7 fixture (good, call-site copy): the producer IS
# tainted, but the consumer re-materializes before donating.
import pickle


def grab_weights(path):
    with open(path, "rb") as f:
        return pickle.load(f)
