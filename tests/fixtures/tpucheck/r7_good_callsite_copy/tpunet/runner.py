# tpucheck R7 fixture (good): jnp.copy at the call site clears the
# cross-module taint before the donated position — the established
# PR-7 discipline, applied by the consumer.
import jax
import jax.numpy as jnp

from tpunet.io_helpers import grab_weights


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))

weights = jnp.copy(grab_weights("weights.pkl"))
step(weights, None)
