# tpucheck R7 fixture (good): the producer RE-MATERIALIZES before
# returning — its summary is clean, so donating its result is safe.
# This is the precision R1's name heuristic cannot express (it would
# need a baseline entry); the cross-module summary proves it.
import pickle

import jax
import jax.numpy as jnp


def grab_weights(path):
    with open(path, "rb") as f:
        raw = pickle.load(f)
    return jax.tree_util.tree_map(jnp.copy, raw)
