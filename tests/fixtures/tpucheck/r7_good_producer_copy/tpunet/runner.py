# tpucheck R7 fixture (good): the producer re-materializes, so this
# donated call is clean without any call-site copy.
import jax

from tpunet.io_helpers import grab_weights


def _step(state, batch):
    return state


step = jax.jit(_step, donate_argnums=(0,))

weights = grab_weights("weights.pkl")
step(weights, None)
