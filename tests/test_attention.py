"""Attention ops: dense / blockwise / ring equivalence (fwd + grad).

Ring attention is the sequence-parallel primitive (tpunet/ops/attention.py);
these tests run it over a real multi-device mesh (8 virtual CPU devices,
conftest.py) and check exact agreement with the dense reference.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpunet.ops import (blockwise_attention, dense_attention,
                        ring_attention, ring_self_attention,
                        ulysses_self_attention)

B, T, H, D = 2, 32, 4, 8


def _qkv(seed=0, t=T, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, t, H, D)), dtype)
    return mk(), mk(), mk()


def _naive(q, k, v, causal=False):
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * (D ** -0.5)
    if causal:
        mask = jnp.tril(jnp.ones((q.shape[1], k.shape[1]), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, -1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_dense_matches_naive(causal):
    q, k, v = _qkv()
    np.testing.assert_allclose(dense_attention(q, k, v, causal=causal),
                               _naive(q, k, v, causal), rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("block", [4, 8, 32])
def test_blockwise_matches_dense(causal, block):
    q, k, v = _qkv(1)
    out = blockwise_attention(q, k, v, block_size=block, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)


def test_causal_cross_lengths_fully_masked_rows_zero():
    """tq > tk: top q rows attend to nothing -> zeros from every variant
    (plain softmax would leak a uniform average of all values)."""
    rng = np.random.default_rng(7)
    q = jnp.asarray(rng.normal(size=(B, 8, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, 4, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, 4, H, D)), jnp.float32)
    d = dense_attention(q, k, v, causal=True)
    bw = blockwise_attention(q, k, v, block_size=2, causal=True)
    np.testing.assert_allclose(d, bw, rtol=2e-5, atol=2e-5)
    np.testing.assert_array_equal(np.asarray(d[:, :4]), 0.0)
    assert np.abs(np.asarray(d[:, 4:])).max() > 0


def test_blockwise_rejects_indivisible():
    q, k, v = _qkv()
    with pytest.raises(ValueError):
        blockwise_attention(q, k, v, block_size=5)


def _seq_mesh(seq=4, data=2):
    devs = np.asarray(jax.devices()[:data * seq]).reshape(data, seq)
    return Mesh(devs, ("data", "seq"))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_dense(causal):
    mesh = _seq_mesh()
    q, k, v = _qkv(2)
    out = ring_self_attention(q, k, v, mesh, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_under_jit_with_sharded_inputs():
    mesh = _seq_mesh()
    q, k, v = _qkv(3)
    sh = NamedSharding(mesh, P("data", "seq", None, None))
    qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
    fn = jax.jit(functools.partial(ring_self_attention, mesh=mesh))
    out = fn(qs, ks, vs)
    assert out.sharding.is_equivalent_to(sh, 4)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ring_gradients_match_dense(causal):
    mesh = _seq_mesh()
    q, k, v = _qkv(4)

    def loss_ring(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh,
                                           causal=causal) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gr, gd in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(gr), np.asarray(gd),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_dense(causal):
    mesh = _seq_mesh()  # seq=4; H=4 heads divisible
    q, k, v = _qkv(8)
    out = ulysses_self_attention(q, k, v, mesh, causal=causal)
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_ulysses_gradients_match_dense(causal):
    mesh = _seq_mesh()
    q, k, v = _qkv(9)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_self_attention(q, k, v, mesh,
                                              causal=causal) ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_merge_attention_states_exact():
    """Splitting K/V into two blocks and merging the flash states must
    reproduce whole-sequence attention exactly."""
    from tpunet.ops.flash import (local_flash_attention_state,
                                  merge_attention_states)
    q, k, v = _qkv(12)
    half = k.shape[1] // 2
    sa = local_flash_attention_state(q, k[:, :half], v[:, :half],
                                     interpret=True)
    sb = local_flash_attention_state(q, k[:, half:], v[:, half:],
                                     interpret=True)
    out, lse = merge_attention_states(sa, sb)
    ref = dense_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    # lse is the whole-sequence log-sum-exp
    s = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), np.asarray(k))
    s *= q.shape[-1] ** -0.5
    ref_lse = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) \
        + s.max(-1)
    np.testing.assert_allclose(np.asarray(lse), ref_lse, rtol=1e-5,
                               atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_core_matches_dense(causal):
    """The flash-core ring (fused local kernel + state merging +
    lax.cond step classification) against dense on the 8-device mesh."""
    mesh = _seq_mesh()
    q, k, v = _qkv(13)
    out = ring_self_attention(q, k, v, mesh, causal=causal, core="flash")
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_flash_core_bf16_f32_accumulator():
    """The flash ring's merged-output carry stays f32 across all folds
    (one bf16 cast at the end), so bf16 accuracy matches a single
    bf16 attention, not n accumulated roundings."""
    mesh = _seq_mesh()
    q, k, v = _qkv(15, dtype=jnp.bfloat16)
    out = ring_self_attention(q, k, v, mesh, causal=True, core="flash")
    assert out.dtype == jnp.bfloat16
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32), causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref), rtol=0.02, atol=0.02)


def test_ring_unknown_core_raises():
    mesh = _seq_mesh()
    q, k, v = _qkv(16)
    with pytest.raises(ValueError, match="unknown attention core"):
        ring_self_attention(q, k, v, mesh, core="blokwise")


@pytest.mark.slow
def test_ring_flash_core_gradients():
    mesh = _seq_mesh()
    q, k, v = _qkv(14)

    def loss_r(q, k, v):
        return jnp.sum(ring_self_attention(q, k, v, mesh, causal=True,
                                           core="flash") ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_flash_core_matches_dense(causal):
    """core='flash' runs the Pallas kernel (interpret mode off-TPU)
    inside the shard_map body — the TPU-default composition of
    sequence parallelism with the fused local kernel."""
    mesh = _seq_mesh()
    q, k, v = _qkv(10)
    out = ulysses_self_attention(q, k, v, mesh, causal=causal,
                                 core="flash")
    ref = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_ulysses_flash_core_gradients():
    mesh = _seq_mesh()
    q, k, v = _qkv(11)

    def loss_u(q, k, v):
        return jnp.sum(ulysses_self_attention(q, k, v, mesh, causal=True,
                                              core="flash") ** 2)

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-4)


def test_ulysses_rejects_indivisible_heads():
    devs = np.asarray(jax.devices()[:3]).reshape(1, 3)
    mesh = Mesh(devs, ("data", "seq"))
    rng = np.random.default_rng(0)
    # T=6 divisible by 3, H=4 not divisible by 3
    q = jnp.asarray(rng.normal(size=(2, 6, 4, 8)), jnp.float32)
    with pytest.raises(ValueError):
        ulysses_self_attention(q, q, q, mesh)


@pytest.mark.parametrize("impl", [ring_self_attention,
                                  ulysses_self_attention])
def test_seq_parallel_with_tensor_parallel_heads(impl):
    """dp x sp x tp mesh: the head dim stays sharded over 'model'
    through the sequence-parallel cores (no forced all-gather), and the
    result still matches dense."""
    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "seq", "model"))
    q, k, v = _qkv(11)  # H=4 heads; 2 per model shard, divisible by seq 2
    sh = NamedSharding(mesh, P("data", "seq", "model", None))
    qs, ks, vs = (jax.device_put(a, sh) for a in (q, k, v))
    out = impl(qs, ks, vs, mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_ring_single_device_axis():
    """seq axis of size 1 degrades to plain blockwise == dense."""
    devs = np.asarray(jax.devices()[:1]).reshape(1, 1)
    mesh = Mesh(devs, ("data", "seq"))
    q, k, v = _qkv(5)
    out = ring_self_attention(q, k, v, mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(dense_attention(q, k, v)),
                               rtol=2e-5, atol=2e-5)


def test_bfloat16_path_close_to_f32():
    mesh = _seq_mesh()
    q, k, v = _qkv(6, dtype=jnp.bfloat16)
    out = ring_self_attention(q, k, v, mesh)
    ref = dense_attention(q.astype(jnp.float32), k.astype(jnp.float32),
                          v.astype(jnp.float32))
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


# ---------------------------------------------------------------- flash


class TestFlashAttention:
    """Pallas flash kernel vs the dense/blockwise reference, exercised
    in interpret mode on CPU (same scheme as the depthwise kernel)."""

    def _qkv(self, b=2, t=128, h=4, d=32, tk=None, seed=0):
        rng = np.random.default_rng(seed)
        shape_k = (b, tk or t, h, d)
        q = rng.standard_normal((b, t, h, d)).astype(np.float32)
        k = rng.standard_normal(shape_k).astype(np.float32)
        v = rng.standard_normal(shape_k).astype(np.float32)
        return jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)

    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_dense(self, causal):
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv()
        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32, interpret=True)
        ref = dense_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_odd_lengths_fall_back_to_divisor_blocks(self):
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=65, d=16)  # ViT-like: 65 tokens (cls+8x8)
        out = flash_attention(q, k, v, interpret=True)
        ref = dense_attention(q, k, v)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_cross_length_causal_offset(self):
        """tq < tk (decode window): the tk - tq diagonal offset must
        match dense_attention."""
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=32, tk=128)
        out = flash_attention(q, k, v, causal=True, block_q=16,
                              block_k=32, interpret=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_triangular_grid_many_blocks(self):
        """Causal self-attention takes the fused lower-triangular grid
        (no dead steps); exercise many q blocks so the sqrt-based
        (qi, ki) inversion crosses every triangular-number boundary."""
        from tpunet.ops.flash import _use_tri, flash_attention
        assert _use_tri(True, 256, 256, 16, 16)
        assert not _use_tri(True, 128, 256, 16, 16)   # cross-length
        assert not _use_tri(True, 256, 256, 16, 32)   # unequal blocks
        assert not _use_tri(False, 256, 256, 16, 16)  # non-causal
        # float32 sqrt inversion bound: past ~2**23 linearized steps
        # sqrt's ~2^-24 relative error can exceed the ±1 correction's
        # reach — fall back to the rectangular grid (nq=4096 -> 8.39M
        # steps > 2**23)
        assert _use_tri(True, 2048 * 512, 2048 * 512, 512, 512)
        assert not _use_tri(True, 4096 * 8, 4096 * 8, 8, 8)
        q, k, v = self._qkv(t=256, d=16)
        out = flash_attention(q, k, v, causal=True, block_q=16,
                              block_k=16, interpret=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    def test_tri_qi_ki_inversion_exact(self):
        from tpunet.ops.flash import _tri_qi_ki
        n = 128  # rows; covers t up to 8255
        ts = jnp.arange(n * (n + 1) // 2)
        qi, ki = jax.vmap(_tri_qi_ki)(ts)
        expect = [(i, j) for i in range(n) for j in range(i + 1)]
        np.testing.assert_array_equal(np.asarray(qi),
                                      np.asarray([e[0] for e in expect]))
        np.testing.assert_array_equal(np.asarray(ki),
                                      np.asarray([e[1] for e in expect]))

    def test_tri_ki_qi_upper_inversion_exact(self):
        from tpunet.ops.flash import _tri_ki_qi_upper
        for n in (1, 2, 5, 64):
            ts = jnp.arange(n * (n + 1) // 2)
            ki, qi = jax.vmap(lambda t: _tri_ki_qi_upper(t, n))(ts)
            expect = [(k, q) for k in range(n) for q in range(k, n)]
            np.testing.assert_array_equal(
                np.asarray(ki), np.asarray([e[0] for e in expect]))
            np.testing.assert_array_equal(
                np.asarray(qi), np.asarray([e[1] for e in expect]))

    @pytest.mark.parametrize("causal", [False, True])
    def test_segment_ids_match_dense(self, causal):
        """Packed-sequence masking (VERDICT r1 item 5): queries attend
        only within their own segment; parity vs the dense reference
        with the same mask, forward AND gradients."""
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=128, d=16)
        rng = np.random.default_rng(3)
        # 3 packed docs + trailing padding (id 0 reserved for pad)
        bounds = sorted(rng.choice(np.arange(8, 120), 3, replace=False))
        seg_row = np.zeros(128, np.int32)
        start = 0
        for si, b_ in enumerate([*bounds, 128]):
            seg_row[start:b_] = si + 1
            start = b_
        seg_row[120:] = 0                     # padding
        seg = jnp.asarray(np.stack([seg_row, np.roll(seg_row, 13)]))

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=causal, block_q=32,
                                   block_k=32, interpret=True,
                                   segment_ids=(seg, seg)).sum()

        def f_dense(q, k, v):
            return dense_attention(q, k, v, causal=causal,
                                   segment_ids=(seg, seg)).sum()

        out = flash_attention(q, k, v, causal=causal, block_q=32,
                              block_k=32, interpret=True,
                              segment_ids=(seg, seg))
        ref = dense_attention(q, k, v, causal=causal,
                              segment_ids=(seg, seg))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)
        g_flash = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(f_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b_ in zip(g_flash, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=2e-4, atol=2e-4)

    def test_segment_ids_block_cross_attention(self):
        """No probability mass may leak across segments: with two
        segments holding identical k/v but different v offsets, each
        query's output must equal single-segment attention over its own
        half."""
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=128, d=16)
        seg = jnp.concatenate([jnp.ones((2, 64), jnp.int32),
                               jnp.full((2, 64), 2, jnp.int32)], axis=1)
        out = flash_attention(q, k, v, block_q=32, block_k=32,
                              interpret=True, segment_ids=(seg, seg))
        left = dense_attention(q[:, :64], k[:, :64], v[:, :64])
        right = dense_attention(q[:, 64:], k[:, 64:], v[:, 64:])
        np.testing.assert_allclose(np.asarray(out[:, :64]),
                                   np.asarray(left), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(out[:, 64:]),
                                   np.asarray(right), rtol=1e-5, atol=1e-5)

    def test_gradients_match_dense(self):
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=64, d=16)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=True,
                                           block_q=16, block_k=16,
                                           interpret=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=True) ** 2)

        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g_flash, g_dense):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("causal", [False, True])
    def test_gradients_cross_length_unequal_blocks(self, causal):
        """The hand-written backward kernels' decode-window offset
        ((tk - tq) in both mask and skip condition) and unequal
        block_q/block_k paths, against the dense vjp."""
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=32, tk=128, d=16, seed=3)

        def loss_flash(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal,
                                           block_q=16, block_k=32,
                                           interpret=True) ** 2)

        def loss_dense(q, k, v):
            return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gd):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_bf16_accumulates_in_f32(self):
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=64)
        qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
        out = flash_attention(qb, kb, vb, causal=True, block_q=32,
                              block_k=32, interpret=True)
        assert out.dtype == jnp.bfloat16
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out, np.float32),
                                   np.asarray(ref), rtol=0.05, atol=0.05)

    def test_off_tpu_entry_falls_back_to_dense(self):
        if jax.default_backend() == "tpu":
            pytest.skip("on TPU the entry runs the real kernel")
        from tpunet.ops.flash import flash_attention
        q, k, v = self._qkv(t=32)
        out = flash_attention(q, k, v, causal=True)  # interpret=None
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    def test_degenerate_lengths_fall_back_to_dense(self):
        """A prime length ABOVE the block cap has only tiny divisors
        (bq would be 1); the entry must return the dense path instead of
        building a 1-row-block grid (same policy as _auto_block).
        t <= the cap is NOT degenerate — it runs as one t-row block."""
        from tpunet.ops import flash as F
        assert F._divisor_block(521, 512) == 1          # the trigger
        assert F._divisor_block(97, 512) == 97          # single block
        q, k, v = self._qkv(t=521, d=16)
        # interpret=True would be ignored on the fallback path; leave it
        # unset so this also passes on a TPU host.
        out = F.flash_attention(q, k, v, causal=True)
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_spmd_partitions_over_batch_and_heads(self):
        """The custom_partitioning rule: under a (data, model) mesh with
        batch- and head-sharded inputs the kernel runs per-shard (each
        device's pallas_call sees 1/4 batch x 1/2 heads) and still
        matches dense."""
        from jax.sharding import NamedSharding
        from tpunet.config import MeshConfig
        from tpunet.ops.flash import flash_attention
        from tpunet.parallel import make_mesh

        mesh = make_mesh(MeshConfig(data=4, model=2))
        q, k, v = self._qkv(b=4, t=64, h=4, d=16)
        sh = NamedSharding(mesh, P("data", None, "model", None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        fn = jax.jit(functools.partial(flash_attention, causal=True,
                                       block_q=32, block_k=32,
                                       interpret=True))
        out = fn(qs, ks, vs)
        # Normalize: newer jax trims trailing Nones in PartitionSpec,
        # older jax keeps them — same sharding either way.
        def _trim(spec):
            parts = list(spec)
            while parts and parts[-1] is None:
                parts.pop()
            return tuple(parts)

        assert _trim(out.sharding.spec) == ("data", None, "model")
        ref = dense_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

        # Gradients under the mesh: exercises the res-forward (two
        # outputs, mixed 4-D/3-D shardings) and the 6-operand backward
        # custom_partitioning rules.
        gfn = jax.jit(jax.grad(
            lambda q, k, v: jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32,
                interpret=True) ** 2), argnums=(0, 1, 2)))
        gq, gk, gv = gfn(qs, ks, vs)
        assert gq.sharding.spec == P("data", None, "model")
        dref = jax.grad(
            lambda q, k, v: jnp.sum(dense_attention(
                q, k, v, causal=True) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip((gq, gk, gv), dref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_spmd_partitions_with_segment_ids(self):
        """The segmented custom_partitioning trio (5/8-operand rules):
        batch-sharded q/k/v AND segment ids run per-shard and match
        dense, forward and gradients."""
        from jax.sharding import NamedSharding
        from tpunet.config import MeshConfig
        from tpunet.ops.flash import flash_attention
        from tpunet.parallel import make_mesh

        mesh = make_mesh(MeshConfig(data=4))
        q, k, v = self._qkv(b=4, t=64, h=4, d=16)
        seg = jnp.asarray(
            np.repeat(np.arange(1, 5, dtype=np.int32)[None], 4, 0),
        ).repeat(16, axis=1)                      # [4, 64], 4 docs/row
        sh4 = NamedSharding(mesh, P("data"))
        sh2 = NamedSharding(mesh, P("data"))
        qs, ks, vs = (jax.device_put(x, sh4) for x in (q, k, v))
        segs = jax.device_put(seg, sh2)

        fn = jax.jit(lambda q, k, v, s: flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32,
            interpret=True, segment_ids=(s, s)))
        out = fn(qs, ks, vs, segs)
        ref = dense_attention(q, k, v, causal=True,
                              segment_ids=(seg, seg))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-5)

        gfn = jax.jit(jax.grad(
            lambda q, k, v, s: jnp.sum(flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32,
                interpret=True, segment_ids=(s, s)) ** 2),
            argnums=(0, 1, 2)))
        gq, gk, gv = gfn(qs, ks, vs, segs)
        dref = jax.grad(
            lambda q, k, v: jnp.sum(dense_attention(
                q, k, v, causal=True,
                segment_ids=(seg, seg)) ** 2), argnums=(0, 1, 2))(q, k, v)
        for a, b in zip((gq, gk, gv), dref):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.slow
    def test_lm_trains_with_flash_config(self):
        """attention='flash' wires through the model registry (dense
        fallback on the CPU backend) and trains end-to-end."""
        from tpunet.config import (CheckpointConfig, DataConfig,
                                   MeshConfig, ModelConfig, OptimConfig,
                                   TrainConfig)
        from tpunet.train.loop import Trainer
        cfg = TrainConfig(
            epochs=1,
            data=DataConfig(dataset="synthetic_lm", batch_size=16,
                            synthetic_train_size=32,
                            synthetic_test_size=16, seq_len=64,
                            vocab_size=32),
            model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                              vit_heads=4, dropout_rate=0.0,
                              dtype="float32", vocab_size=32,
                              max_seq_len=64, attention="flash"),
            optim=OptimConfig(learning_rate=3e-3),
            mesh=MeshConfig(),
            checkpoint=CheckpointConfig(save_best=False, save_last=False),
        )
        trainer = Trainer(cfg)
        try:
            m = trainer.train_one_epoch(1)
            assert np.isfinite(m["loss"])
        finally:
            trainer.close()
