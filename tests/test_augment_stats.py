"""Statistical equivalence of the on-device augmentation vs a PIL
reference implementing the torchvision semantics of the reference
pipeline (cifar10_mpi_mobilenet_224.py:72-89).

tpunet's fused augmentation deviates from torchvision pixel-for-pixel
(documented in tpunet/data/augment.py's deviation list: content
rotation at the 32px source before the crop, fixed jitter order,
clamped crop box — the rotation BORDER geometry is torchvision-exact
via the closed-form mask); accuracy parity relies on the two producing
the SAME DISTRIBUTION of training inputs. These tests
quantify that claim: a PIL pipeline written to torchvision's documented
sampling rules (10-attempt RandomResizedCrop, shuffled ColorJitter
order, rotate-after-jitter) must agree with the on-device pipeline on
aggregate statistics — per-channel mean/std, inter-image spread, and
the rotation-induced dark-border mass. The EVAL path (deterministic
Resize + Normalize) is compared directly, image by image.
"""

import math

import numpy as np
import pytest

from PIL import Image, ImageEnhance

from tpunet.config import DataConfig
from tpunet.data.augment import make_eval_preprocess, make_train_augment
from tpunet.data.cifar10 import synthetic_cifar10

CFG = DataConfig()          # reference strengths: 0.3/0.3/0.3/0.1, 15deg
N = 128


def _pil_hue(img, factor):
    """torchvision adjust_hue: shift the HSV hue channel by
    ``factor`` (fraction of the full circle)."""
    h, s, v = img.convert("HSV").split()
    h = h.point(lambda px: (px + int(round(factor * 255))) % 256)
    return Image.merge("HSV", (h, s, v)).convert("RGB")


def _pil_augment_one(rng, img32):
    """One draw of the reference train transform, PIL/torchvision
    semantics (Resize -> RandomResizedCrop -> HFlip -> ColorJitter in
    RANDOM order -> RandomRotation -> [0,1] floats)."""
    size = CFG.image_size
    img = Image.fromarray(img32).resize((size, size), Image.BILINEAR)
    # RandomResizedCrop(scale=(0.7, 1.0), ratio=(3/4, 4/3)): torchvision
    # samples up to 10 candidate boxes, else falls back to center crop.
    for _ in range(10):
        area = size * size * rng.uniform(*CFG.rrc_scale)
        aspect = math.exp(rng.uniform(math.log(CFG.rrc_ratio[0]),
                                      math.log(CFG.rrc_ratio[1])))
        w = int(round(math.sqrt(area * aspect)))
        h = int(round(math.sqrt(area / aspect)))
        if 0 < w <= size and 0 < h <= size:
            top = rng.integers(0, size - h + 1)
            left = rng.integers(0, size - w + 1)
            break
    else:
        top = left = 0
        h = w = size
    img = img.crop((left, top, left + w, top + h)).resize(
        (size, size), Image.BILINEAR)
    if rng.random() < 0.5:
        img = img.transpose(Image.FLIP_LEFT_RIGHT)
    # ColorJitter(0.3, 0.3, 0.3, 0.1), sub-ops in random order.
    ops = [
        lambda im: ImageEnhance.Brightness(im).enhance(
            rng.uniform(1 - CFG.jitter_brightness,
                        1 + CFG.jitter_brightness)),
        lambda im: ImageEnhance.Contrast(im).enhance(
            rng.uniform(1 - CFG.jitter_contrast, 1 + CFG.jitter_contrast)),
        lambda im: ImageEnhance.Color(im).enhance(
            rng.uniform(1 - CFG.jitter_saturation,
                        1 + CFG.jitter_saturation)),
        lambda im: _pil_hue(im, rng.uniform(-CFG.jitter_hue,
                                            CFG.jitter_hue)),
    ]
    for idx in rng.permutation(4):
        img = ops[idx](img)
    angle = rng.uniform(-CFG.rotation_degrees, CFG.rotation_degrees)
    img = img.rotate(angle, Image.BILINEAR)
    return np.asarray(img, np.float32) / 255.0


@pytest.fixture(scope="module")
def images():
    x, _, _, _ = synthetic_cifar10(n_train=N, n_test=1, seed=11)
    return x


@pytest.fixture(scope="module")
def pil_batch(images):
    rng = np.random.default_rng(123)
    return np.stack([_pil_augment_one(rng, im) for im in images])


@pytest.fixture(scope="module")
def device_batch(images):
    import jax

    aug = jax.jit(make_train_augment(CFG))
    out = np.asarray(aug(jax.random.PRNGKey(7), images))
    # De-normalize back to [0, 1] so stats compare on the same scale.
    return out * np.asarray(CFG.std) + np.asarray(CFG.mean)


@pytest.mark.slow
def test_train_augmentation_distribution_matches_pil(pil_batch,
                                                     device_batch):
    """Aggregate distribution parity: channel means/stds over the whole
    augmented batch and the inter-image spread must agree between the
    on-device pipeline and the PIL/torchvision reference (independent
    random draws — tolerances cover sampling noise at N=128)."""
    for c in range(3):
        pm, dm = pil_batch[..., c].mean(), device_batch[..., c].mean()
        # 0.025: the PIL reference itself quantizes to uint8 between
        # every jitter sub-op and round-trips hue through 8-bit HSV,
        # which biases saturated synthetic images by up to ~0.02 —
        # before the fix this test caught a 0.032 shift from rotation-
        # before-crop, well outside this band.
        assert abs(pm - dm) < 0.025, (c, pm, dm)
        ps, ds = pil_batch[..., c].std(), device_batch[..., c].std()
        assert abs(ps - ds) < 0.03, (c, ps, ds)
    # inter-image variability (augmentation strength proxy)
    p_spread = pil_batch.mean(axis=(1, 2, 3)).std()
    d_spread = device_batch.mean(axis=(1, 2, 3)).std()
    assert abs(p_spread - d_spread) < 0.015, (p_spread, d_spread)


@pytest.mark.slow
def test_rotation_border_mass_matches_pil(pil_batch, device_batch):
    """Rotation fills corners with black in both pipelines; the mass of
    near-zero pixels (a geometry statistic, independent of color
    jitter) must agree in distribution."""
    dark = lambda b: (b.max(axis=-1) < 0.02).mean()
    assert abs(dark(pil_batch) - dark(device_batch)) < 0.02, \
        (dark(pil_batch), dark(device_batch))


@pytest.mark.slow
def test_eval_preprocess_matches_pil_exactly(images):
    """The deterministic eval path (Resize(224) bilinear + ImageNet
    normalize) is compared image-by-image: both use half-pixel-center
    bilinear, so the only slack is PIL's uint8 intermediate
    quantization."""
    import jax.numpy as jnp

    pre = make_eval_preprocess(CFG)
    got = np.asarray(pre(jnp.asarray(images[:16])))
    size = CFG.image_size
    ref = np.stack([
        np.asarray(Image.fromarray(im).resize((size, size),
                                              Image.BILINEAR),
                   np.float32) / 255.0
        for im in images[:16]])
    ref = (ref - np.asarray(CFG.mean)) / np.asarray(CFG.std)
    # mean abs diff far below quantization noise; max bounded by a few
    # uint8 steps (normalized by std ~0.22-0.27)
    assert np.abs(got - ref).mean() < 0.01, np.abs(got - ref).mean()
    assert np.abs(got - ref).max() < 0.12, np.abs(got - ref).max()
