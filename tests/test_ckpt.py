"""Checkpoint/resume tests (reference parity: best-by-test-acc saving,
cifar10_mpi_mobilenet_224.py:238-249; upgrade: true resume, which the
reference lacks — it always restarts from epoch 0)."""

import dataclasses

import jax
import numpy as np
import pytest

from tpunet.config import CheckpointConfig
from tpunet.train.loop import Trainer

from test_train import tiny_config, tiny_dataset  # noqa: F401


def _cfg(tmp_path, epochs):
    cfg = tiny_config(tmp_path, epochs=epochs)
    return cfg.replace(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ckpt"), save_best=True, save_last=True))


@pytest.mark.slow
def test_best_and_state_saved(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=2)
    t = Trainer(cfg, dataset=tiny_dataset)
    t.train()
    t.ckpt.close()
    assert t.ckpt.latest_step() == 2
    best = t.ckpt.restore_best({
        "params": t.state.params, "batch_stats": t.state.batch_stats})
    assert best is not None
    chex_shape = jax.tree_util.tree_structure(best["params"])
    assert chex_shape == jax.tree_util.tree_structure(t.state.params)


@pytest.mark.slow
def test_resume_continues_from_epoch(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=2)
    t = Trainer(cfg, dataset=tiny_dataset)
    hist = t.train()
    t.ckpt.close()
    assert len(hist) == 2

    cfg3 = _cfg(tmp_path, epochs=3).replace(
        checkpoint=dataclasses.replace(
            _cfg(tmp_path, 3).checkpoint, resume=True))
    t2 = Trainer(cfg3, dataset=tiny_dataset)
    assert t2.start_epoch == 3          # continues, not restarts
    assert t2.global_step == t.global_step
    assert np.isclose(t2.best_acc, t.best_acc)
    # Restored params equal the saved ones.
    a = jax.tree_util.tree_leaves(t.state.params)[0]
    b = jax.tree_util.tree_leaves(t2.state.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    hist2 = t2.train()
    assert len(hist2) == 1              # only epoch 3 runs
    t2.ckpt.close()


@pytest.mark.slow
def test_fresh_run_ignores_missing_checkpoint(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=1).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "none"),
                                    resume=True))
    t = Trainer(cfg, dataset=tiny_dataset)
    assert t.start_epoch == 1


@pytest.mark.slow
def test_resume_from_legacy_checkpoint_without_pp_layout(
        tmp_path, tiny_dataset):  # noqa: F811
    """Pre-round-4 checkpoints have no pp_layout leaf; restore must
    filter the target to the keys the save actually wrote (instead of
    raising an opaque orbax structure error) so _try_resume's lenient
    .get(key, default) path is reachable."""
    from tpunet.ckpt.orbax_io import Checkpointer

    cfg = _cfg(tmp_path, epochs=1)
    t = Trainer(cfg, dataset=tiny_dataset)
    t.train()
    t.ckpt.close()

    legacy_dir = str(tmp_path / "legacy")
    ck = Checkpointer(CheckpointConfig(
        directory=legacy_dir, save_best=False, save_last=True))
    payload = t._payload()
    del payload["pp_layout"]        # what an old save looked like
    ck.save_state(1, payload)
    ck.close()

    cfg2 = cfg.replace(checkpoint=CheckpointConfig(
        directory=legacy_dir, save_best=False, save_last=True,
        resume=True))
    t2 = Trainer(cfg2, dataset=tiny_dataset)
    assert t2.start_epoch == 2      # resumed, defaulting pp_layout
    a = jax.tree_util.tree_leaves(t.state.params)[0]
    b = jax.tree_util.tree_leaves(t2.state.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_best_meta_reads_latest_after_async_save(tmp_path):
    """best_meta() must drain queued background saves first — a caller
    invoking it right after save_best() gets THAT save's sidecar, never
    the previous one."""
    import jax.numpy as jnp

    from tpunet.ckpt.orbax_io import Checkpointer

    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                         save_best=True, save_last=False))
    try:
        w = {"params": {"w": jnp.ones((4,))}}
        ckpt.save_best(w, meta={"v": 1})
        ckpt.save_best(w, meta={"v": 2})
        assert ckpt.best_meta()["v"] == 2
    finally:
        ckpt.close()


def test_restore_survives_metadata_probe_failure(tmp_path, caplog):
    """If the tree-metadata probe fails, restore proceeds with the FULL
    target (correct for non-legacy checkpoints) and logs the swallowed
    error — on multi-host, one controller probing differently from the
    others is only diagnosable from that breadcrumb."""
    import logging

    import jax.numpy as jnp

    from tpunet.ckpt.orbax_io import Checkpointer

    payload = {"state": {"w": jnp.arange(4.0)},
               "epoch": np.asarray(1, np.int32)}
    ck = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                       save_best=False, save_last=True))
    ck2 = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                        save_best=False, save_last=True))
    try:
        ck.save_state(1, payload)
        ck.wait()
        ck2.manager.item_metadata = lambda step: (_ for _ in ()).throw(
            RuntimeError("probe boom"))
        with caplog.at_level(logging.WARNING,
                             logger="tpunet.ckpt.orbax_io"):
            restored = ck2.restore_state(
                {"state": {"w": jnp.zeros(4)},
                 "epoch": np.asarray(0, np.int32)})
        assert restored is not None
        np.testing.assert_array_equal(np.asarray(restored["state"]["w"]),
                                      np.arange(4.0))
        assert any("metadata probe failed" in r.message
                   for r in caplog.records)
    finally:
        ck.close()
        ck2.close()


def test_cache_dir_honors_jax_env_var(monkeypatch):
    """The shared compile-cache convention: JAX's own env var wins;
    otherwise the per-user tempdir path."""
    from tpunet.utils.cache import cache_dir

    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", "/elsewhere/cache")
    assert cache_dir() == "/elsewhere/cache"
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR")
    assert "tpunet-jax-cache-" in cache_dir()


def test_failed_best_save_rolls_back_sidecar(tmp_path):
    """The sidecar commits before the orbax save (multi-host ordering);
    if the save then FAILS, the sidecar must roll back — a new layout
    sidecar durably paired with the old best/ params would make
    serving mis-permute the old stack."""
    import jax.numpy as jnp

    from tpunet.ckpt.orbax_io import Checkpointer

    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                         save_best=True, save_last=False))
    w = {"params": {"w": jnp.ones((4,))}}
    ckpt.save_best(w, meta={"v": 1})
    ckpt.wait()

    def boom(*a, **k):
        raise RuntimeError("disk full")

    ckpt._best.save = boom
    ckpt.save_best(w, meta={"v": 2})
    with pytest.raises(RuntimeError, match="disk full"):
        ckpt.wait()
    assert ckpt.best_meta()["v"] == 1   # rolled back, not orphaned
    ckpt.close()


def test_failed_async_phase_best_save_rolls_back_sidecar(tmp_path):
    """StandardCheckpointer is an AsyncCheckpointer: save() can return
    having only dispatched the write, with the failure surfacing later
    at wait_until_finished(). The rollback must cover THAT phase too
    (ADVICE r5): here save() succeeds synchronously and only the join
    raises — the sidecar must still roll back, and the error must
    still surface at the durability barrier."""
    import jax.numpy as jnp

    from tpunet.ckpt.orbax_io import Checkpointer

    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                         save_best=True, save_last=False))
    w = {"params": {"w": jnp.ones((4,))}}
    ckpt.save_best(w, meta={"v": 1})
    ckpt.wait()

    real_wait = ckpt._best.wait_until_finished
    fired = []

    def async_boom():
        # The dispatch (save()) already succeeded; the async
        # write/commit fails exactly once, at the first join.
        if not fired:
            fired.append(True)
            raise RuntimeError("async disk full")
        return real_wait()

    ckpt._best.wait_until_finished = async_boom
    ckpt.save_best(w, meta={"v": 2})
    with pytest.raises(RuntimeError, match="async disk full"):
        ckpt.wait()
    assert fired, "async phase was never joined inside the save"
    assert ckpt.best_meta()["v"] == 1   # rolled back, not orphaned
    ckpt.close()


def test_async_save_overlaps_training(tmp_path):
    """The epoch-boundary save must NOT block the step loop: the
    dispatch returns while the write is still in progress (a ~200 MB
    payload makes the IO window observable), host work proceeds during
    the write, and wait() is the durability barrier after which the
    checkpoint restores bit-exactly."""
    import time

    import jax.numpy as jnp

    from tpunet.ckpt.orbax_io import Checkpointer

    big = {f"w{i}": jnp.arange(6_000_000, dtype=jnp.float32) + i
           for i in range(8)}                      # ~192 MB
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path),
                                         save_best=False))
    try:
        t0 = time.perf_counter()
        ckpt.save_state(1, big)
        dispatch = time.perf_counter() - t0
        overlapped = ckpt.saving_in_progress()
        # work the chip/host can do while the write is in flight
        y = float(jnp.sum(jnp.ones((512, 512)) @ jnp.ones((512, 512))))
        ckpt.wait()
        total = time.perf_counter() - t0
        assert y == 512.0 * 512 * 512
        # Either we caught the write in flight, or the dispatch was
        # clearly cheaper than the durable write (slack for fast tmpfs).
        assert overlapped or dispatch < 0.5 * total, (
            f"save_state blocked: dispatch {dispatch:.3f}s of "
            f"{total:.3f}s total, in_progress={overlapped}")
        restored = ckpt.restore_state(
            {k: jnp.zeros_like(v) for k, v in big.items()})
        for k in big:
            np.testing.assert_array_equal(np.asarray(restored[k]),
                                          np.asarray(big[k]))
    finally:
        ckpt.close()
