"""Checkpoint/resume tests (reference parity: best-by-test-acc saving,
cifar10_mpi_mobilenet_224.py:238-249; upgrade: true resume, which the
reference lacks — it always restarts from epoch 0)."""

import dataclasses

import jax
import numpy as np
import pytest

from tpunet.config import CheckpointConfig
from tpunet.train.loop import Trainer

from test_train import tiny_config, tiny_dataset  # noqa: F401


def _cfg(tmp_path, epochs):
    cfg = tiny_config(tmp_path, epochs=epochs)
    return cfg.replace(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ckpt"), save_best=True, save_last=True))


@pytest.mark.slow
def test_best_and_state_saved(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=2)
    t = Trainer(cfg, dataset=tiny_dataset)
    t.train()
    t.ckpt.close()
    assert t.ckpt.latest_step() == 2
    best = t.ckpt.restore_best({
        "params": t.state.params, "batch_stats": t.state.batch_stats})
    assert best is not None
    chex_shape = jax.tree_util.tree_structure(best["params"])
    assert chex_shape == jax.tree_util.tree_structure(t.state.params)


@pytest.mark.slow
def test_resume_continues_from_epoch(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=2)
    t = Trainer(cfg, dataset=tiny_dataset)
    hist = t.train()
    t.ckpt.close()
    assert len(hist) == 2

    cfg3 = _cfg(tmp_path, epochs=3).replace(
        checkpoint=dataclasses.replace(
            _cfg(tmp_path, 3).checkpoint, resume=True))
    t2 = Trainer(cfg3, dataset=tiny_dataset)
    assert t2.start_epoch == 3          # continues, not restarts
    assert t2.global_step == t.global_step
    assert np.isclose(t2.best_acc, t.best_acc)
    # Restored params equal the saved ones.
    a = jax.tree_util.tree_leaves(t.state.params)[0]
    b = jax.tree_util.tree_leaves(t2.state.params)[0]
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    hist2 = t2.train()
    assert len(hist2) == 1              # only epoch 3 runs
    t2.ckpt.close()


@pytest.mark.slow
def test_fresh_run_ignores_missing_checkpoint(tmp_path, tiny_dataset):  # noqa: F811
    cfg = _cfg(tmp_path, epochs=1).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "none"),
                                    resume=True))
    t = Trainer(cfg, dataset=tiny_dataset)
    assert t.start_epoch == 1
