"""End-to-end CLI tests: config parsing and a full train.py run."""

import json
import os
import subprocess
import sys

import pytest

from tpunet.config import config_from_args

REPO = os.path.dirname(os.path.dirname(__file__))


def test_presets_match_reference_batch_sizes():
    assert config_from_args(["--preset", "serial"]).data.batch_size == 64
    assert config_from_args(["--preset", "single"]).data.batch_size == 128
    cfg = config_from_args([])
    assert cfg.epochs == 20 and cfg.seed == 42
    assert cfg.optim.learning_rate == 1e-4
    assert cfg.optim.step_size_epochs == 10 and cfg.optim.gamma == 0.1
    assert cfg.data.image_size == 224


def test_attention_defaults_to_measured_policy():
    """Defaults encode the measured policy (VERDICT round-2 item 8):
    'auto' — the flash kernel on TPU (fastest in every measured regime,
    README long-context table), dense semantics elsewhere. Dense stays
    selectable as the cross-backend reference."""
    assert config_from_args([]).model.attention == "auto"
    assert config_from_args(
        ["--attention", "dense"]).model.attention == "dense"


def test_arg_overrides():
    cfg = config_from_args([
        "--preset", "serial", "--epochs", "2", "--batch-size", "32",
        "--image-size", "64", "--lr", "0.01", "--dataset", "synthetic",
        "--mesh-data", "4", "--dtype", "float32", "--resume",
        "--checkpoint-dir", "/tmp/x"])
    assert cfg.epochs == 2
    assert cfg.data.batch_size == 32 and cfg.data.image_size == 64
    assert cfg.optim.learning_rate == 0.01
    assert cfg.mesh.data == 4
    assert cfg.model.dtype == "float32"
    assert cfg.checkpoint.resume and cfg.checkpoint.directory == "/tmp/x"


def test_round4_flags_parse_and_default():
    cfg = config_from_args([
        "--preset", "serial", "--model", "lm_pp", "--dataset",
        "synthetic_lm", "--moe-experts", "4", "--moe-dispatch",
        "alltoall", "--vocab-ce", "sharded", "--pp-schedule",
        "interleaved", "--pp-virtual", "4"])
    assert cfg.model.moe_dispatch == "alltoall"
    assert cfg.model.vocab_ce == "sharded"
    assert cfg.model.pp_schedule == "interleaved"
    assert cfg.model.pp_virtual == 4
    dflt = config_from_args(["--preset", "serial"])
    assert dflt.model.moe_dispatch == "auto"
    assert dflt.model.vocab_ce == "auto"
    assert dflt.model.pp_virtual == 2


@pytest.mark.slow
def test_train_cli_end_to_end(tmp_path):
    """python train.py on synthetic data: epoch lines in the reference
    format, checkpoints written, exit code 0."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "PALLAS_AXON_POOL_IPS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, "train.py", "--preset", "distributed",
         "--dataset", "synthetic", "--synthetic-size", "128",
         "--epochs", "2", "--batch-size", "32", "--image-size", "32",
         "--dtype", "float32", "--width-mult", "0.5",
         "--checkpoint-dir", str(tmp_path / "ck")],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=570)
    assert out.returncode == 0, out.stderr[-3000:]
    lines = out.stdout.splitlines()
    epoch_lines = [l for l in lines if l.startswith("Epoch ")]
    assert len(epoch_lines) == 2
    assert "Train Loss:" in epoch_lines[0] and "Test Acc:" in epoch_lines[0]
    assert any(l.startswith("Best test accuracy:") for l in lines)
    assert any(l.startswith("Total training time:") for l in lines)
    assert (tmp_path / "ck" / "state").is_dir()


def test_eval_only_flag_parses():
    cfg = config_from_args(["--eval-only"])
    assert cfg.eval_only


@pytest.mark.slow
def test_eval_only_evaluates_best_checkpoint(tmp_path):
    """--eval-only on a trained dir reproduces the best test accuracy
    without training; on an empty dir it raises cleanly."""
    import dataclasses

    import pytest as _pytest

    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.train.loop import Trainer

    def cfg(**kw):
        return TrainConfig(
            epochs=1,
            data=DataConfig(dataset="synthetic_lm", batch_size=16,
                            synthetic_train_size=32,
                            synthetic_test_size=16, seq_len=32,
                            vocab_size=32),
            model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                              vit_heads=4, dropout_rate=0.0,
                              dtype="float32", vocab_size=32,
                              max_seq_len=32),
            optim=OptimConfig(learning_rate=3e-3),
            mesh=MeshConfig(),
            checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                        save_last=False),
            **kw,
        )

    trainer = Trainer(cfg())
    try:
        history = trainer.train()
        trained_acc = history[-1]["test_accuracy"]
    finally:
        trainer.close()

    ev = Trainer(cfg(eval_only=True))
    try:
        m = ev.evaluate_checkpoint()
        assert m["accuracy"] == _pytest.approx(trained_acc, abs=1e-6)
    finally:
        ev.close()

    empty = Trainer(dataclasses.replace(
        cfg(eval_only=True),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "nope"),
                                    save_last=False)))
    try:
        with _pytest.raises(FileNotFoundError, match="no checkpoint"):
            empty.evaluate_checkpoint()
    finally:
        empty.close()
