"""Converter golden-parity tests: torch MobileNetV2 -> Flax.

The torch model here is a test oracle reproducing torchvision's module
nesting / state_dict keys (tests/torch_ref_mobilenetv2.py). Parity of
converted weights is checked end-to-end on logits, including BatchNorm
running statistics updated by real train-mode passes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tpunet.config import ModelConfig
from tpunet.models.convert import convert_torch_state_dict, merge_pretrained
from tpunet.models import create_model, init_variables

from torch_ref_mobilenetv2 import TorchMobileNetV2


@pytest.fixture(scope="module")
def torch_model():
    torch.manual_seed(0)
    m = TorchMobileNetV2(num_classes=10)
    # Update BN running stats away from the (0, 1) init so the stats
    # conversion is actually exercised.
    m.train()
    with torch.no_grad():
        for _ in range(3):
            m(torch.randn(8, 3, 64, 64))
    m.eval()
    return m


def _flax_from_torch(torch_model, num_classes=10):
    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=64)
    p, s, head_ok = convert_torch_state_dict(
        torch_model.state_dict(), num_classes=num_classes)
    return model, merge_pretrained(variables, p, s, head_ok), head_ok


@pytest.mark.slow
def test_logit_parity(torch_model):
    model, variables, head_ok = _flax_from_torch(torch_model)
    assert head_ok
    x = np.random.default_rng(1).normal(size=(2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        ref = torch_model(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=1e-3)


@pytest.mark.slow
def test_head_swap_on_class_mismatch(torch_model):
    # ImageNet checkpoints have a 1000-way head; the converter must keep
    # the fresh 10-way head (reference head swap, :138-139).
    sd = dict(torch_model.state_dict())
    sd["classifier.1.weight"] = torch.randn(1000, 1280)
    sd["classifier.1.bias"] = torch.randn(1000)
    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=64)
    p, s, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert not head_ok
    merged = merge_pretrained(variables, p, s, head_ok)
    np.testing.assert_array_equal(
        np.asarray(merged["params"]["classifier"]["kernel"]),
        np.asarray(variables["params"]["classifier"]["kernel"]))
    # Backbone still converted and usable.
    x = jnp.zeros((1, 64, 64, 3))
    assert model.apply(merged, x, train=False).shape == (1, 10)


def test_ddp_module_prefix_stripped(torch_model):
    sd = {f"module.{k}": v for k, v in torch_model.state_dict().items()}
    p, _s, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert head_ok
    assert p["stem"]["conv"]["kernel"].shape == (3, 3, 3, 32)


@pytest.mark.slow
def test_export_round_trips_and_loads_into_torch_strict():
    """export_torch_state_dict is the exact inverse of the importer, and
    the exported dict satisfies torch load_state_dict(strict=True) with
    matching logits — tpunet-trained weights serve on the reference's
    torch stack."""
    import jax
    import torch

    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.models.convert import (convert_torch_state_dict,
                                       export_torch_state_dict,
                                       merge_pretrained)

    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(7), image_size=32)
    sd = export_torch_state_dict(variables["params"],
                                 variables["batch_stats"])

    # 1. bit-exact round trip through the importer
    params, stats, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert head_ok
    back = merge_pretrained(variables, params, stats, head_ok)
    for a, b in zip(jax.tree_util.tree_leaves(variables),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # 2. strict load into the torch reference + logit parity
    tmodel = TorchMobileNetV2(num_classes=10)
    tmodel.load_state_dict({k: torch.tensor(np.asarray(v))
                            for k, v in sd.items()}, strict=True)
    tmodel.eval()
    x = np.random.default_rng(0).standard_normal((2, 32, 32, 3)).astype(
        np.float32)
    flax_logits = np.asarray(model.apply(variables, jnp.asarray(x),
                                         train=False))
    with torch.no_grad():
        torch_logits = tmodel(torch.from_numpy(
            x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(flax_logits, torch_logits, rtol=1e-4,
                               atol=1e-4)


# ---------------------------------------------------------------------------
# Validation against GENUINE torchvision (VERDICT round-1 item 3): the
# local TorchMobileNetV2 oracle above shares an author with the
# converter, so it cannot catch a key-scheme divergence from real
# torchvision. tests/data/torchvision_mobilenet_v2_manifest.json is a
# vendored (key -> shape) census of torchvision's mobilenet_v2
# state_dict, hand-derived from torchvision/models/mobilenetv2.py's
# module structure — NOT generated by this repo's converter. Its own
# consistency witness: summed trainable shapes give 3,504,872 params
# (torchvision's published count) and 2,236,682 with the 10-class head
# (the reference's logged count, cifar_mpi_gpu128_26188.out:30).
# ---------------------------------------------------------------------------

import json
import math
import os

_MANIFEST_PATH = os.path.join(os.path.dirname(__file__), "data",
                              "torchvision_mobilenet_v2_manifest.json")


@pytest.fixture(scope="module")
def manifest():
    with open(_MANIFEST_PATH) as f:
        return {k: tuple(v) for k, v in json.load(f).items()}


def _trainable(manifest):
    return {k: s for k, s in manifest.items()
            if "running_" not in k and "num_batches" not in k}


def test_manifest_self_witness(manifest):
    n = sum(math.prod(s) for s in _trainable(manifest).values())
    assert n == 3_504_872                      # torchvision mobilenet_v2
    swapped = n - 1000 * 1280 - 1000 + 10 * 1280 + 10
    assert swapped == 2_236_682                # reference :30


@pytest.mark.slow
def test_export_matches_torchvision_manifest(manifest):
    """The exporter emits EXACTLY torchvision's key set and shapes (10-way
    head aside) — fails if the converter's key scheme ever diverges from
    genuine torchvision."""
    from tpunet.models.convert import export_torch_state_dict

    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=32)
    sd = {k: tuple(np.asarray(v).shape)
          for k, v in export_torch_state_dict(
              variables["params"], variables["batch_stats"]).items()}
    expected = dict(manifest)
    expected["classifier.1.weight"] = (10, 1280)
    expected["classifier.1.bias"] = (10,)
    assert set(sd) == set(expected)
    mismatched = {k: (sd[k], expected[k]) for k in expected
                  if sd[k] != expected[k]}
    assert not mismatched, mismatched


def test_import_consumes_full_manifest(manifest):
    """The importer consumes every torchvision tensor (so no weight is
    silently dropped) and yields the reference's 2,236,682-param model
    after the head swap. Consumption witness: each input tensor is
    filled with a unique constant; every constant (head/bookkeeping
    aside) must resurface in the converted tree."""
    keys = sorted(manifest)
    sd = {k: np.full(manifest[k], float(i + 1), np.float32)
          for i, k in enumerate(keys)}
    params, stats, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert not head_ok                      # 1000-way head -> swap
    out_consts = set()
    for leaf in (jax.tree_util.tree_leaves(params)
                 + jax.tree_util.tree_leaves(stats)):
        out_consts.update(np.unique(np.asarray(leaf)).tolist())
    unread = {k for i, k in enumerate(keys)
              if float(i + 1) not in out_consts
              and "num_batches" not in k
              and not k.startswith("classifier")}
    assert not unread, f"weights never consumed: {sorted(unread)[:8]}"
    n_converted = sum(np.asarray(x).size
                      for x in jax.tree_util.tree_leaves(params))
    n_stats = sum(np.asarray(x).size
                  for x in jax.tree_util.tree_leaves(stats))
    # converted trainables + the fresh 10-way head == reference count
    assert n_converted + 10 * 1280 + 10 == 2_236_682
    assert n_stats == sum(
        math.prod(s) for k, s in manifest.items() if "running_" in k)


def _real_weights_path():
    """The staged-checkpoint path, via the download module's own
    resolution (download=False only resolves, never fetches) so the
    skipif below can't silently go stale against a cache-layout change."""
    from tpunet.data.download import (DownloadError,
                                      ensure_mobilenet_v2_weights)
    try:
        return ensure_mobilenet_v2_weights(download=False)
    except DownloadError:
        return ""


@pytest.mark.skipif(not _real_weights_path(),
                    reason="real torchvision checkpoint not staged "
                           "(~/.cache/tpunet/mobilenet_v2-b0353104.pth)")
def test_real_checkpoint_matches_manifest_and_converts(manifest):
    """With the genuine torchvision .pth staged: its keys/shapes must
    equal the vendored manifest, and the converter must consume it."""
    sd = torch.load(_real_weights_path(), map_location="cpu",
                    weights_only=True)
    got = {k: tuple(v.shape) for k, v in sd.items()}
    assert got == manifest
    params, stats, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert not head_ok
    # ImageNet BN statistics are far from the (0, 1) init.
    assert float(np.abs(np.asarray(
        stats["stem"]["bn"]["mean"])).max()) > 0.1


def test_real_torchvision_golden_logits():
    """Full end-to-end check against actual torchvision: convert its
    mobilenet_v2 and assert logit parity (catches any divergence between
    the local oracle and the real model)."""
    torchvision = pytest.importorskip("torchvision")

    tm = torchvision.models.mobilenet_v2(weights=None, num_classes=10)
    tm.eval()
    model, merged, head_ok = _flax_from_torch(tm)
    assert head_ok
    x = np.random.default_rng(3).normal(size=(2, 64, 64, 3)).astype(
        np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(model.apply(merged, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=1e-3)
