"""Converter golden-parity tests: torch MobileNetV2 -> Flax.

The torch model here is a test oracle reproducing torchvision's module
nesting / state_dict keys (tests/torch_ref_mobilenetv2.py). Parity of
converted weights is checked end-to-end on logits, including BatchNorm
running statistics updated by real train-mode passes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from tpunet.config import ModelConfig
from tpunet.models.convert import convert_torch_state_dict, merge_pretrained
from tpunet.models import create_model, init_variables

from torch_ref_mobilenetv2 import TorchMobileNetV2


@pytest.fixture(scope="module")
def torch_model():
    torch.manual_seed(0)
    m = TorchMobileNetV2(num_classes=10)
    # Update BN running stats away from the (0, 1) init so the stats
    # conversion is actually exercised.
    m.train()
    with torch.no_grad():
        for _ in range(3):
            m(torch.randn(8, 3, 64, 64))
    m.eval()
    return m


def _flax_from_torch(torch_model, num_classes=10):
    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=64)
    p, s, head_ok = convert_torch_state_dict(
        torch_model.state_dict(), num_classes=num_classes)
    return model, merge_pretrained(variables, p, s, head_ok), head_ok


def test_logit_parity(torch_model):
    model, variables, head_ok = _flax_from_torch(torch_model)
    assert head_ok
    x = np.random.default_rng(1).normal(size=(2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        ref = torch_model(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    got = np.asarray(model.apply(variables, jnp.asarray(x), train=False))
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=1e-3)


def test_head_swap_on_class_mismatch(torch_model):
    # ImageNet checkpoints have a 1000-way head; the converter must keep
    # the fresh 10-way head (reference head swap, :138-139).
    sd = dict(torch_model.state_dict())
    sd["classifier.1.weight"] = torch.randn(1000, 1280)
    sd["classifier.1.bias"] = torch.randn(1000)
    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=64)
    p, s, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert not head_ok
    merged = merge_pretrained(variables, p, s, head_ok)
    np.testing.assert_array_equal(
        np.asarray(merged["params"]["classifier"]["kernel"]),
        np.asarray(variables["params"]["classifier"]["kernel"]))
    # Backbone still converted and usable.
    x = jnp.zeros((1, 64, 64, 3))
    assert model.apply(merged, x, train=False).shape == (1, 10)


def test_ddp_module_prefix_stripped(torch_model):
    sd = {f"module.{k}": v for k, v in torch_model.state_dict().items()}
    p, _s, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert head_ok
    assert p["stem"]["conv"]["kernel"].shape == (3, 3, 3, 32)


def test_export_round_trips_and_loads_into_torch_strict():
    """export_torch_state_dict is the exact inverse of the importer, and
    the exported dict satisfies torch load_state_dict(strict=True) with
    matching logits — tpunet-trained weights serve on the reference's
    torch stack."""
    import jax
    import torch

    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.models.convert import (convert_torch_state_dict,
                                       export_torch_state_dict,
                                       merge_pretrained)

    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(7), image_size=32)
    sd = export_torch_state_dict(variables["params"],
                                 variables["batch_stats"])

    # 1. bit-exact round trip through the importer
    params, stats, head_ok = convert_torch_state_dict(sd, num_classes=10)
    assert head_ok
    back = merge_pretrained(variables, params, stats, head_ok)
    for a, b in zip(jax.tree_util.tree_leaves(variables),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # 2. strict load into the torch reference + logit parity
    tmodel = TorchMobileNetV2(num_classes=10)
    tmodel.load_state_dict({k: torch.tensor(np.asarray(v))
                            for k, v in sd.items()}, strict=True)
    tmodel.eval()
    x = np.random.default_rng(0).standard_normal((2, 32, 32, 3)).astype(
        np.float32)
    flax_logits = np.asarray(model.apply(variables, jnp.asarray(x),
                                         train=False))
    with torch.no_grad():
        torch_logits = tmodel(torch.from_numpy(
            x.transpose(0, 3, 1, 2))).numpy()
    np.testing.assert_allclose(flax_logits, torch_logits, rtol=1e-4,
                               atol=1e-4)
