"""Data pipeline tests: loader formats, sharding semantics, augmentation."""

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import DataConfig
from tpunet.data.augment import (make_eval_preprocess, make_train_augment,
                                 resize_matrix_np)
from tpunet.data.cifar10 import load_cifar10, synthetic_cifar10
from tpunet.data.pipeline import eval_batches, steps_per_epoch, train_batches

SMALL = DataConfig(image_size=64, batch_size=16)


def _write_fake_cifar(tmp_path):
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir()
    rng = np.random.default_rng(0)
    for name, n in [(f"data_batch_{i}", 20) for i in range(1, 6)] + [("test_batch", 30)]:
        data = rng.integers(0, 256, size=(n, 3072), dtype=np.uint8)
        labels = rng.integers(0, 10, size=n).tolist()
        with open(d / name, "wb") as f:
            pickle.dump({b"data": data, b"labels": labels}, f)
    return tmp_path


def test_load_cifar10_pickle_layout(tmp_path):
    root = _write_fake_cifar(tmp_path)
    tx, ty, ex, ey = load_cifar10(str(root))
    assert tx.shape == (100, 32, 32, 3) and tx.dtype == np.uint8
    assert ex.shape == (30, 32, 32, 3)
    assert ty.shape == (100,) and ey.dtype == np.int32


def test_load_cifar10_missing_raises(tmp_path):
    from tpunet.data.download import DownloadError
    with pytest.raises(DownloadError, match="synthetic"):
        load_cifar10(str(tmp_path / "nope"), download=False)


def test_synthetic_separable():
    tx, ty, _, _ = synthetic_cifar10(n_train=500, n_test=10)
    assert tx.shape == (500, 32, 32, 3) and tx.dtype == np.uint8
    # Same-class images are more alike than cross-class ones.
    c0 = tx[ty == ty[0]].astype(np.float32)
    c1 = tx[ty != ty[0]].astype(np.float32)
    within = np.abs(c0[0] - c0[1]).mean()
    across = np.abs(c0[0] - c1[0]).mean()
    assert within < across


def test_train_batches_disjoint_cover():
    x = np.arange(100, dtype=np.uint8).reshape(100, 1, 1, 1) * np.ones(
        (1, 32, 32, 3), np.uint8)
    y = np.arange(100, dtype=np.int32)
    seen = []
    for pi in range(4):  # 4 simulated hosts
        for bx, by in train_batches(x, y, global_batch=32, seed=1, epoch=0,
                                    process_index=pi, process_count=4):
            assert bx.shape == (8, 32, 32, 3)
            seen.extend(by.tolist())
    assert len(seen) == 96  # 3 steps * 32, remainder dropped
    assert len(set(seen)) == 96  # disjoint across hosts and steps


def test_train_batches_reshuffle_per_epoch():
    x = np.zeros((64, 32, 32, 3), np.uint8)
    y = np.arange(64, dtype=np.int32)
    e0 = np.concatenate([b for _, b in train_batches(
        x, y, global_batch=32, seed=1, epoch=0)])
    e1 = np.concatenate([b for _, b in train_batches(
        x, y, global_batch=32, seed=1, epoch=1)])
    e0_again = np.concatenate([b for _, b in train_batches(
        x, y, global_batch=32, seed=1, epoch=0)])
    assert not np.array_equal(e0, e1)       # set_epoch-style reshuffle
    assert np.array_equal(e0, e0_again)     # deterministic


def test_eval_batches_exact_mask():
    x = np.zeros((70, 32, 32, 3), np.uint8)
    y = np.arange(70, dtype=np.int32)
    total = 0.0
    ids = []
    for pi in range(2):
        for bx, by, m in eval_batches(x, y, global_batch=32,
                                      process_index=pi, process_count=2):
            assert bx.shape == (16, 32, 32, 3)
            total += m.sum()
            ids.extend(by[m > 0].tolist())
    assert total == 70  # exact coverage despite padding
    assert sorted(ids) == list(range(70))


def test_resize_matrix_identity():
    # Resizing to the same size must be the identity map.
    m = resize_matrix_np(32, 32)
    np.testing.assert_allclose(m, np.eye(32), atol=1e-6)


def test_eval_preprocess_shapes_and_stats():
    pre = jax.jit(make_eval_preprocess(SMALL))
    imgs = np.full((4, 32, 32, 3), 128, np.uint8)
    out = pre(jnp.asarray(imgs))
    assert out.shape == (4, 64, 64, 3)
    # A constant gray image maps to (0.5 - mean) / std everywhere.
    expect = (128 / 255 - np.asarray(SMALL.mean)) / np.asarray(SMALL.std)
    np.testing.assert_allclose(np.asarray(out[0, 0, 0]), expect, atol=1e-2)


def test_train_augment_shapes_determinism_and_randomness():
    aug = jax.jit(make_train_augment(SMALL))
    imgs = jnp.asarray(np.random.default_rng(0).integers(
        0, 256, size=(8, 32, 32, 3), dtype=np.uint8))
    a = aug(jax.random.PRNGKey(0), imgs)
    b = aug(jax.random.PRNGKey(0), imgs)
    c = aug(jax.random.PRNGKey(1), imgs)
    assert a.shape == (8, 64, 64, 3) and a.dtype == jnp.float32
    assert jnp.allclose(a, b)                    # same key -> same batch
    assert not jnp.allclose(a, c)                # different key -> different
    assert bool(jnp.all(jnp.isfinite(a)))
    # Per-example independence: example 0 augmented differently than 1
    # even though the raw images could be equal.
    same = jnp.asarray(np.tile(imgs[:1], (2, 1, 1, 1)))
    out = aug(jax.random.PRNGKey(2), same)
    assert not jnp.allclose(out[0], out[1])


def test_shear_rotation_matches_gather_rotation():
    """The 3-shear (Paeth) matmul rotation must reproduce the direct
    4-tap bilinear gather rotation: identical at angle 0, and close on
    smooth content at the pipeline's +-15 degrees (3 successive 1-D
    interps blur marginally more than one 2-D bilinear, so the band is
    loose on noise but tight on smooth images; geometry must agree —
    that's what a wrong shear convention would break)."""
    from tpunet.data.augment import _rotate_bilinear, _rotate_shear

    yy, xx = np.meshgrid(np.linspace(0, 1, 32), np.linspace(0, 1, 32),
                         indexing="ij")
    smooth = np.stack([yy, xx, (yy + xx) / 2], -1).astype(np.float32)

    out0 = _rotate_shear(jnp.asarray(smooth), jnp.float32(0.0))
    np.testing.assert_allclose(np.asarray(out0), smooth, atol=1e-5)

    for deg in (-15.0, 7.5, 15.0):
        a = jnp.float32(np.deg2rad(deg))
        ref = np.asarray(_rotate_bilinear(jnp.asarray(smooth), a,
                                          fill="edge"))
        got = np.asarray(_rotate_shear(jnp.asarray(smooth), a))
        # interior only: the edge-clamp order differs in the corners
        err = np.abs(ref - got)[4:-4, 4:-4]
        assert err.max() < 0.02, (deg, err.max())
        assert err.mean() < 0.003, (deg, err.mean())

    # The dispatch boundary (ADVICE r5): the shear path serves every
    # config up to rotation_degrees == 30, where the y-shear shifts
    # edge columns by up to sin(30 deg) * 16 = 8 px — so the
    # intermediate edge-clamp smearing penetrates deeper than at 15
    # deg. Calibrated: with a 6 px interior margin the two rotations
    # still agree tightly on smooth content at +-(25, 30) deg
    # (measured interior max < 1e-4 here; band leaves headroom), which
    # pins the geometry across the whole dispatched range.
    for deg in (-30.0, -25.0, 25.0, 30.0):
        a = jnp.float32(np.deg2rad(deg))
        ref = np.asarray(_rotate_bilinear(jnp.asarray(smooth), a,
                                          fill="edge"))
        got = np.asarray(_rotate_shear(jnp.asarray(smooth), a))
        err = np.abs(ref - got)[6:-6, 6:-6]
        assert err.max() < 0.01, (deg, err.max())
        assert err.mean() < 0.001, (deg, err.mean())


def test_augment_large_rotation_uses_exact_path(monkeypatch):
    """rotation_degrees > 30 must dispatch the direct 4-tap gather
    rotation (the shear decomposition's edge clamps smear content
    there), <= 30 the shear path — asserted by counting which
    implementation each config actually traces."""
    import dataclasses

    from tpunet.data import augment as A

    calls = {"shear": 0, "gather": 0}
    real_shear, real_gather = A._rotate_shear, A._rotate_bilinear
    monkeypatch.setattr(A, "_rotate_shear", lambda *a, **k: (
        calls.__setitem__("shear", calls["shear"] + 1),
        real_shear(*a, **k))[1])
    monkeypatch.setattr(A, "_rotate_bilinear", lambda *a, **k: (
        calls.__setitem__("gather", calls["gather"] + 1),
        real_gather(*a, **k))[1])

    imgs = jnp.asarray(np.random.default_rng(2).integers(
        0, 256, size=(4, 32, 32, 3), dtype=np.uint8))

    big = dataclasses.replace(SMALL, rotation_degrees=60.0)
    out = jax.jit(A.make_train_augment(big))(jax.random.PRNGKey(5), imgs)
    assert calls == {"shear": 0, "gather": 1}, calls
    assert out.shape == (4, 64, 64, 3)
    assert bool(jnp.all(jnp.isfinite(out)))

    small = dataclasses.replace(SMALL, rotation_degrees=15.0)
    jax.jit(A.make_train_augment(small))(jax.random.PRNGKey(5), imgs)
    assert calls == {"shear": 1, "gather": 1}, calls


def test_augment_values_in_normalized_range():
    aug = jax.jit(make_train_augment(SMALL))
    imgs = jnp.asarray(np.random.default_rng(1).integers(
        0, 256, size=(4, 32, 32, 3), dtype=np.uint8))
    out = aug(jax.random.PRNGKey(3), imgs)
    # Normalized pixel values from [0,1] inputs stay within the stats range.
    lo = (0.0 - max(SMALL.mean)) / min(SMALL.std)
    hi = (1.0 - min(SMALL.mean)) / min(SMALL.std)
    assert float(out.min()) >= lo - 1e-3
    assert float(out.max()) <= hi + 1e-3
