"""Dataset/weight acquisition (tpunet/data/download.py).

The reference's download path is torchvision ``download=True`` plus a
rank-0 barrier (cifar10_mpi_mobilenet_224.py:93-102); these tests drive
tpunet's checksum-verified equivalent against a loopback HTTP server
(hermetic — no egress required).
"""

import hashlib
import http.server
import os
import threading

import pytest

from tpunet.data.download import (CIFAR10_MD5, DownloadError, ensure_cifar10,
                                  ensure_mobilenet_v2_weights, fetch)

PAYLOAD = b"tpunet-test-payload" * 100


@pytest.fixture()
def http_url():
    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            body = PAYLOAD if self.path == "/file.bin" else b""
            self.send_response(200 if body else 404)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_address[1]}"
    srv.shutdown()


def test_fetch_verifies_checksums(http_url, tmp_path):
    dest = str(tmp_path / "out.bin")
    md5 = hashlib.md5(PAYLOAD).hexdigest()
    sha8 = hashlib.sha256(PAYLOAD).hexdigest()[:8]
    assert fetch(f"{http_url}/file.bin", dest, md5=md5,
                 sha256_prefix=sha8) == dest
    assert open(dest, "rb").read() == PAYLOAD


def test_fetch_rejects_corruption_and_cleans_up(http_url, tmp_path):
    dest = str(tmp_path / "out.bin")
    with pytest.raises(DownloadError, match="md5"):
        fetch(f"{http_url}/file.bin", dest, md5="0" * 32)
    # neither the dest nor any .part temp file survives a failed fetch
    assert os.listdir(tmp_path) == []
    with pytest.raises(DownloadError, match="sha256"):
        fetch(f"{http_url}/file.bin", dest, sha256_prefix="ffffffff")
    assert os.listdir(tmp_path) == []


def test_fetch_network_failure(tmp_path):
    with pytest.raises(DownloadError, match="failed"):
        fetch("http://127.0.0.1:9/nope", str(tmp_path / "x"), timeout=0.5)
    assert os.listdir(tmp_path) == []


def test_ensure_cifar10_disabled_documents_drop_in(tmp_path):
    with pytest.raises(DownloadError) as e:
        ensure_cifar10(str(tmp_path), download=False)
    msg = str(e.value)
    assert "cifar-10-python.tar.gz" in msg
    assert CIFAR10_MD5 in msg           # drop-in checksum is actionable
    assert str(tmp_path) in msg


def test_ensure_cifar10_present_skips_download(tmp_path):
    # an extracted dir short-circuits entirely; a staged tarball is
    # md5-verified (drop-in integrity) but touches no network
    (tmp_path / "d" / "cifar-10-batches-py").mkdir(parents=True)
    assert ensure_cifar10(str(tmp_path / "d"), download=True)
    (tmp_path / "cifar-10-python.tar.gz").write_bytes(b"truncated junk")
    with pytest.raises(DownloadError, match="corrupt"):
        ensure_cifar10(str(tmp_path), download=True)


def test_ensure_weights_present_and_disabled(tmp_path):
    p = tmp_path / "mobilenet_v2-b0353104.pth"
    p.write_bytes(b"weights")
    assert ensure_mobilenet_v2_weights(str(p)) == str(p)
    with pytest.raises(DownloadError, match="b0353104"):
        ensure_mobilenet_v2_weights(str(tmp_path / "absent.pth"),
                                    download=False)


def test_no_download_flag_plumbs_through():
    from tpunet.config import config_from_args
    assert config_from_args([]).data.download is True
    assert config_from_args(["--no-download"]).data.download is False


@pytest.mark.slow
def test_pretrained_auto_resolves_in_trainer(tmp_path, monkeypatch):
    """--pretrained auto resolves through ensure_mobilenet_v2_weights
    inside the Trainer (process-0-gated); with downloads disabled and no
    cached file it fails actionably instead of training silently
    from-scratch."""
    from tpunet.config import config_from_args
    from tpunet.train.loop import Trainer

    monkeypatch.setenv("HOME", str(tmp_path))  # empty ~/.cache/tpunet
    cfg = config_from_args(
        ["--dataset", "synthetic", "--synthetic-size", "64",
         "--batch-size", "32", "--image-size", "32", "--epochs", "1",
         "--pretrained", "auto", "--no-download"])
    with pytest.raises(DownloadError, match="drop-in|Drop-in"):
        Trainer(cfg)
