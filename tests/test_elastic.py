"""Elastic grow/shrink training (tpunet/elastic/): chaos spec +
injection hooks, filesystem rendezvous, checkpoint IO retry, agent
supervision — and the tier-1 end-to-end scenarios the ROADMAP asked
for: a 2-process gang loses one host to injected SIGKILL mid-epoch,
the survivor re-meshes dp 2->1 and finishes under the original
run_id; and a kill mid-checkpoint-write restarts from the previous
INTACT checkpoint (no torn-state acceptance)."""

import json
import os
import signal
import sys
import threading
import time

import numpy as np
import pytest

from tpunet.elastic import chaos as chaos_mod
from tpunet.elastic import events
from tpunet.elastic.agent import (EXIT_DONE, EXIT_QUORUM,
                                  EXIT_RESTARTS, AgentConfig,
                                  ElasticAgent)
from tpunet.elastic.chaos import Chaos, ChaosSpecError
from tpunet.elastic.rendezvous import QuorumError, Rendezvous
from tpunet.utils.logging import MetricsLogger

# The e2e legs share ONE set of child-env/train-argv helpers with the
# slow chaos matrix (scripts/chaos_smoke.py) so the tier-1 legs can
# never drift from the matrix they mirror.
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "scripts"))
try:
    import chaos_smoke as _smoke
finally:
    sys.path.pop(0)


@pytest.fixture(autouse=True)
def _clear_chaos():
    yield
    chaos_mod.clear()


# ---------------------------------------------------------------- chaos


def test_chaos_parse_and_render():
    c = Chaos.parse("kill@step=5; slow@step=3:delay=0.5:steps=2 ;"
                    "ioerr@save=1:fails=2:host=1")
    assert len(c.events) == 3
    assert "kill@step=5" in c.render()


@pytest.mark.parametrize("bad", [
    "", "kill", "kill@banana=1", "slow@step=3", "kill@step=x",
    "slow@prob=0.5:delay=1", "slow@prob=2:delay=1:seed=1",
    "ioerr@save=1:bogus=2", "sigterm@step",
])
def test_chaos_parse_errors(bad):
    with pytest.raises(ChaosSpecError):
        Chaos.parse(bad)


def test_chaos_kill_fires_once_on_addressed_step_and_host():
    calls = []
    c = Chaos.parse("kill@step=3:host=1", process_index=1,
                    kill=lambda pid, sig: calls.append(sig))
    for s in range(6):
        c.step(s)
    assert calls == [signal.SIGKILL]  # step 3 only, once
    other = Chaos.parse("kill@step=3:host=1", process_index=0,
                        kill=lambda pid, sig: calls.append(sig))
    for s in range(6):
        other.step(s)
    assert calls == [signal.SIGKILL]  # host filter: nothing new


def test_chaos_generation_scope():
    calls = []
    fired = Chaos.parse("kill@step=1:gen=1", generation=1,
                        kill=lambda pid, sig: calls.append(sig))
    fired.step(1)
    assert calls == [signal.SIGKILL]
    held = Chaos.parse("kill@step=1:gen=0", generation=1,
                       kill=lambda pid, sig: calls.append(sig))
    held.step(1)
    assert calls == [signal.SIGKILL]  # gen filter: nothing new


def test_chaos_slow_window_and_seeded_prob():
    sleeps = []
    c = Chaos.parse("slow@step=4:delay=0.25:steps=3",
                    sleep=lambda s: sleeps.append(s))
    for s in range(10):
        c.step(s)
    assert sleeps == [0.25, 0.25, 0.25]  # steps 4, 5, 6

    def fired_steps(seed):
        out, slept = [], []
        c = Chaos.parse(f"slow@prob=0.5:delay=0.1:seed={seed}",
                        sleep=lambda s: slept.append(s))
        for s in range(32):
            before = len(slept)
            c.step(s)
            if len(slept) > before:
                out.append(s)
        return out

    a, b = fired_steps(7), fired_steps(7)
    assert a == b and 0 < len(a) < 32  # seeded => reproducible
    assert fired_steps(8) != a


def test_chaos_sigterm_escalation_second_signal():
    got = []
    seen_two = threading.Event()

    def rec(pid, sig):
        got.append(sig)
        if len(got) >= 2:
            seen_two.set()

    c = Chaos.parse("sigterm@step=2:again=0.01", kill=rec)
    c.step(2)
    assert got[0] == signal.SIGTERM
    assert seen_two.wait(timeout=5.0), "second SIGTERM never fired"
    assert got[1] == signal.SIGTERM


def test_chaos_ioerr_save_and_restore_attempts():
    c = Chaos.parse("ioerr@save=2:fails=2;ioerr@restore=1")
    c.save_attempt(1, 0)                       # other ordinal: clean
    with pytest.raises(OSError):
        c.save_attempt(2, 0)
    with pytest.raises(OSError):
        c.save_attempt(2, 1)
    c.save_attempt(2, 2)                       # past fails: clean
    with pytest.raises(OSError):
        c.restore_attempt(1, 0)
    c.restore_attempt(1, 1)


def test_elastic_data_axis_and_mesh_dict():
    from tpunet.config import MeshConfig
    from tpunet.parallel.mesh import (elastic_data_axis, make_mesh,
                                      mesh_shape_dict)
    assert elastic_data_axis(MeshConfig(), 4) == 4
    assert elastic_data_axis(MeshConfig(model=2), 4) == 2
    assert elastic_data_axis(None, 1) == 1
    with pytest.raises(ValueError, match="cannot shrink"):
        # seq/pipe/model are workload topology: a world below the
        # model-parallel footprint is a quorum failure, not a mesh.
        elastic_data_axis(MeshConfig(model=2, pipe=2), 2)
    mesh = make_mesh(MeshConfig(data=2))
    assert mesh_shape_dict(mesh) == {"data": 2, "seq": 1, "pipe": 1,
                                     "model": 1}


# ----------------------------------------------------------- rendezvous


def test_rendezvous_gather_ranks_and_departure(tmp_path):
    a = Rendezvous(str(tmp_path), "a", settle_s=0.1, timeout_s=5.0)
    b = Rendezvous(str(tmp_path), "b", settle_s=0.1, timeout_s=5.0)
    a.announce(0, {"port": 1, "ckpt_step": None})
    b.announce(0, {"port": 2})
    members = a.gather(0)
    assert [h for h, _ in members] == ["a", "b"]  # deterministic rank
    assert members[0][1]["port"] == 1
    assert a.latest_generation() == 0
    b.mark_gone()
    assert set(a.members(0)) == {"a"}
    b2 = Rendezvous(str(tmp_path), "c", settle_s=0.1, timeout_s=5.0)
    b2.announce(4, {})
    assert a.latest_generation() == 4


def test_rendezvous_quorum_timeout(tmp_path):
    solo = Rendezvous(str(tmp_path), "a", min_hosts=2, settle_s=0.05,
                      timeout_s=0.3)
    solo.announce(0, {})
    with pytest.raises(QuorumError, match="cannot form quorum"):
        solo.gather(0)


def test_rendezvous_heartbeats_and_join(tmp_path):
    a = Rendezvous(str(tmp_path), "a")
    b = Rendezvous(str(tmp_path), "b")
    a.heartbeat()
    b.heartbeat()
    assert a.stale_peers(["a", "b"], dead_after_s=60.0) == set()
    old = time.time() - 120.0
    os.utime(os.path.join(str(tmp_path), "hb", "b"), (old, old))
    assert a.stale_peers(["a", "b"], dead_after_s=60.0) == {"b"}
    assert a.stale_peers(["a", "ghost"], dead_after_s=60.0) == {"ghost"}
    b.request_join()
    assert a.join_requests() == {"b"}
    a.clear_join("b")
    assert a.join_requests() == set()


# --------------------------------------------------------------- events


def test_elastic_records_and_markers(tmp_path):
    run_dir = str(tmp_path)
    with open(os.path.join(run_dir, "run_id"), "w") as f:
        f.write("run-xyz\n")
    rec = events.append_elastic_record(run_dir, events.build_elastic_record(
        "shrink", cause="host_lost", generation=2, old_world=2,
        new_world=1, hosts=["h0"], lost=["h1"], recovery_s=1.25))
    assert rec["kind"] == "obs_elastic" and rec["run_id"] == "run-xyz"
    parsed = MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    assert parsed[0]["event"] == "shrink"
    assert parsed[0]["recovery_s"] == 1.25
    with pytest.raises(ValueError, match="unknown elastic event"):
        events.build_elastic_record("explode")
    assert events.build_elastic_record(
        "quorum_failed")["severity"] == "fatal"

    assert not events.is_done(run_dir)
    events.mark_done(run_dir)
    assert events.is_done(run_dir)
    assert events.read_evict_marker(run_dir) is None
    events.write_evict_marker(run_dir, process_index=1, host="h1",
                              reason="step_stall", detail={"x": 1})
    marker = events.read_evict_marker(run_dir)
    assert marker["host"] == "h1" and marker["process_index"] == 1
    events.clear_evict_marker(run_dir)
    assert events.read_evict_marker(run_dir) is None
    events.write_mesh(run_dir, {"data": 2, "seq": 1})
    assert events.read_mesh(run_dir) == {"data": 2, "seq": 1}


# ------------------------------------------------- checkpoint IO retry


def _obs_with_sink(tmp_path):
    from tpunet.config import ObsConfig
    from tpunet.obs import Observability
    from tpunet.obs.registry import MemorySink
    obs = Observability(ObsConfig(flightrec=False),
                        checkpoint_dir=str(tmp_path))
    sink = MemorySink()
    obs.add_sink(sink)
    return obs, sink


def test_ckpt_transient_save_error_retried_with_one_alert(tmp_path):
    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig
    obs, sink = _obs_with_sink(tmp_path)
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path)),
                        obs=obs)
    chaos_mod._CURRENT = Chaos.parse("ioerr@save=1:fails=2")
    try:
        ckpt.save_state(1, {"x": np.arange(8, dtype=np.int32)})
        assert ckpt.wait() is True
    finally:
        ckpt.close()
        obs.close()
    assert obs.registry.counter("ckpt_io_retries").value == 2
    bursts = [r for r in sink.records
              if r.get("kind") == "obs_alert"
              and r.get("reason") == "ckpt_io_retry"]
    assert len(bursts) == 1          # one loud alert per burst
    assert bursts[0]["what"] == "save"
    # ... and the save actually landed despite the two failures.
    restored = Checkpointer(
        CheckpointConfig(directory=str(tmp_path))).restore_state(
        {"x": np.zeros(8, dtype=np.int32)})
    assert restored is not None
    np.testing.assert_array_equal(np.asarray(restored["x"]),
                                  np.arange(8))


def test_ckpt_exhausted_retries_propagate(tmp_path):
    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path)))
    chaos_mod._CURRENT = Chaos.parse("ioerr@save=1:fails=9")
    ckpt.save_state(1, {"x": np.arange(4, dtype=np.int32)})
    with pytest.raises(OSError, match="chaos"):
        ckpt.wait()
    ckpt.abandon()   # unblock close on the failed worker


def test_ckpt_transient_restore_error_retried(tmp_path):
    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig
    obs, sink = _obs_with_sink(tmp_path)
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path)),
                        obs=obs)
    try:
        ckpt.save_state(1, {"x": np.arange(4, dtype=np.int32)})
        ckpt.wait()
        chaos_mod._CURRENT = Chaos.parse("ioerr@restore=1:fails=1")
        restored = ckpt.restore_state(
            {"x": np.zeros(4, dtype=np.int32)})
        assert restored is not None
        assert obs.registry.counter("ckpt_io_retries").value == 1
    finally:
        ckpt.close()
        obs.close()


def test_ckpt_grace_timeout_goes_permanently_nonblocking(tmp_path):
    """A timed-out bounded wait must not be followed by an unbounded
    one: main's finally runs close(), and blocking there holds the
    process past the platform's SIGKILL (the grace window's whole
    point)."""
    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path)))
    # Three injected failures keep the worker busy in retry/backoff
    # (~0.7s) — far longer than the 50ms grace budget below.
    chaos_mod._CURRENT = Chaos.parse("ioerr@save=1:fails=3")
    ckpt.save_state(1, {"x": np.arange(4, dtype=np.int32)})
    assert ckpt.wait(timeout=0.05) is False
    t0 = time.monotonic()
    assert ckpt.wait() is False     # abandoned: no unbounded re-wait
    ckpt.close()                    # ... and close() is a no-op too
    assert time.monotonic() - t0 < 0.5


def test_ckpt_abandon_makes_wait_and_close_nonblocking(tmp_path):
    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig
    ckpt = Checkpointer(CheckpointConfig(directory=str(tmp_path)))
    ckpt.save_state(1, {"x": np.arange(4, dtype=np.int32)})
    ckpt.abandon()
    t0 = time.monotonic()
    assert ckpt.wait() is False
    ckpt.close()
    assert time.monotonic() - t0 < 1.0


# ------------------------------------------------------- agent (dummy)


def _agent(tmp_path, script_body, host="h0", **kw):
    run_dir = os.path.join(str(tmp_path), "run")
    os.makedirs(run_dir, exist_ok=True)
    cmd = [sys.executable, "-c", script_body, run_dir]
    cfg = AgentConfig(
        run_dir=run_dir, rdzv_dir=os.path.join(str(tmp_path), "rdzv"),
        host_id=host, command=cmd, settle_s=0.05, timeout_s=5.0,
        beat_s=0.05, grace_s=1.0, **kw)
    return ElasticAgent(cfg), run_dir


DONE_CHILD = """
import os, sys
d = os.path.join(sys.argv[-1], "elastic")
os.makedirs(d, exist_ok=True)
open(os.path.join(d, "done"), "w").write("x")
"""

ARGV_CHILD = """
import json, os, sys
run = [a for a in sys.argv[1:] if a != "--resume"][-1]
with open(os.path.join(run, "argv.json"), "w") as f:
    json.dump(sys.argv[1:], f)
d = os.path.join(run, "elastic")
os.makedirs(d, exist_ok=True)
open(os.path.join(d, "done"), "w").write("x")
"""


def test_agent_done_marker_stops_relaunching(tmp_path):
    agent, run_dir = _agent(tmp_path, DONE_CHILD)
    assert agent.run() == EXIT_DONE
    # One generation, no membership-change records.
    assert not os.path.exists(os.path.join(run_dir, "metrics.jsonl"))


def test_agent_restarts_then_gives_up_and_marks_gone(tmp_path):
    agent, run_dir = _agent(tmp_path, "import sys; sys.exit(1)",
                            max_restarts=1)
    assert agent.run() == EXIT_RESTARTS
    records = MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    restarts = [r for r in records if r.get("event") == "restart"]
    assert len(restarts) == 1
    assert restarts[0]["cause"] == "failed"
    assert restarts[0]["old_world"] == restarts[0]["new_world"] == 1
    assert restarts[0]["recovery_s"] >= 0
    assert "h0" in agent.rdzv.gone()


def test_agent_quorum_failure_degrades_cleanly(tmp_path):
    agent, run_dir = _agent(tmp_path, DONE_CHILD, min_hosts=2)
    agent.rdzv.timeout_s = 0.3
    assert agent.run() == EXIT_QUORUM
    records = MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    assert [r["event"] for r in records] == ["quorum_failed"]
    assert records[0]["severity"] == "fatal"


def test_agent_appends_resume_once_state_exists(tmp_path):
    agent, run_dir = _agent(tmp_path, ARGV_CHILD)
    assert agent.run() == EXIT_DONE
    with open(os.path.join(run_dir, "argv.json")) as f:
        assert "--resume" not in json.load(f)
    # A prior incarnation's run_id makes every later launch a resume.
    with open(os.path.join(run_dir, "run_id"), "w") as f:
        f.write("abc\n")
    os.unlink(os.path.join(run_dir, "elastic", "done"))
    agent2, _ = _agent(tmp_path, ARGV_CHILD, host="h0")
    assert agent2.run() == EXIT_DONE
    with open(os.path.join(run_dir, "argv.json")) as f:
        assert "--resume" in json.load(f)


# ------------------------------------------------------ e2e (tier-1)


_child_env = _smoke._child_env
_train_cmd = _smoke._train_cmd


def _read_run(run_dir):
    records = MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))
    with open(os.path.join(run_dir, "run_id")) as f:
        run_id = f.read().strip()
    return records, run_id


def test_elastic_shrink_on_sigkill_mid_step(tmp_path):
    """THE acceptance scenario: a 2-process CPU gang loses host 1 to
    an injected SIGKILL mid-epoch-2; the survivor re-meshes dp 2->1,
    restores the epoch-1 checkpoint, finishes training, and the
    metrics stream carries obs_elastic shrink + recovered records
    under the original run_id."""
    run_dir = str(tmp_path / "run")
    rdzv_dir = str(tmp_path / "rdzv")
    # slow@step=2 (both hosts, 2s) gives the async epoch-1 save time
    # to COMMIT before host 1 dies entering step 3 (epoch 2's second
    # step); gen=0 keeps the faults out of the resumed incarnation.
    cmd = _train_cmd(
        run_dir, "slow@step=2:delay=2:gen=0;kill@step=3:host=1:gen=0")
    agents = {
        # Survivor: absorbs its own wedged-child kill via the peer
        # path (no restart budget consumed) — budget is for failures.
        "h0": AgentConfig(run_dir=run_dir, rdzv_dir=rdzv_dir,
                          host_id="h0", command=cmd, max_restarts=2,
                          settle_s=0.4, timeout_s=120.0, beat_s=0.1,
                          dead_after_s=10.0, grace_s=3.0,
                          env=_child_env()),
        # Doomed host: any child failure is host death.
        "h1": AgentConfig(run_dir=run_dir, rdzv_dir=rdzv_dir,
                          host_id="h1", command=cmd, max_restarts=0,
                          settle_s=0.4, timeout_s=120.0, beat_s=0.1,
                          dead_after_s=10.0, grace_s=3.0,
                          env=_child_env()),
    }
    rcs = {}
    threads = []
    for host, cfg in agents.items():
        t = threading.Thread(
            target=lambda h=host, c=cfg: rcs.__setitem__(
                h, ElasticAgent(c).run()),
            name=f"agent-{host}", daemon=True)
        t.start()
        threads.append(t)
    for t in threads:
        t.join(timeout=420.0)
        assert not t.is_alive(), "elastic gang did not converge"
    assert rcs["h1"] == EXIT_RESTARTS      # host death, left the pod
    assert rcs["h0"] == EXIT_DONE          # survivor finished the run
    assert events.is_done(run_dir)

    records, run_id = _read_run(run_dir)
    assert run_id
    # ONE stream: every identity-stamped record carries the original
    # run_id (training rows, obs rows, and the agent's elastic rows).
    for r in records:
        if "run_id" in r:
            assert r["run_id"] == run_id
    elastic = [r for r in records if r.get("kind") == "obs_elastic"]
    shrinks = [r for r in elastic if r["event"] == "shrink"]
    assert len(shrinks) == 1
    assert shrinks[0]["old_world"] == 2
    assert shrinks[0]["new_world"] == 1
    assert shrinks[0]["lost"] == ["h1"]
    assert shrinks[0]["recovery_s"] > 0
    recovered = [r for r in elastic if r["event"] == "recovered"]
    assert recovered, "re-meshed trainer never stamped its recovery"
    rec = recovered[-1]
    assert rec["new_mesh"]["data"] == 1          # dp 2 -> 1
    assert rec["old_mesh"]["data"] == 2
    assert rec["generation"] >= 1
    # Restored from the last checkpoint (epoch 1 complete -> resumes
    # at epoch 2), not from scratch.
    assert rec["epoch"] == 2
    # The injected SIGKILL left complete flight-recorder forensics
    # for the dead host (process 1): the watcher survived the kill
    # and assembled a full report. (No p1 successor ever runs, so
    # this is the artifact, not an obs_crash record — the survivor's
    # own child died CLEANLY: gloo surfaces the dead peer as an
    # error, and the clean close leaves no p0 report.)
    import glob
    reports = glob.glob(os.path.join(run_dir, "flightrec",
                                     "crash_report.p1*"))
    assert reports, "no crash report for the SIGKILLed host"
    with open(reports[0]) as f:
        report = json.load(f)
    assert report["cause"] == "died-without-fatal-signal"  # SIGKILL
    assert report["events"] and report["stacks"]
    # Training finished: the final epoch's plain record exists.
    plain = [r for r in records if "kind" not in r]
    epochs_seen = [r["epoch"] for r in plain if "epoch" in r]
    assert max(epochs_seen) == 3
    assert set(epochs_seen) >= {1, 2, 3}


@pytest.mark.slow
@pytest.mark.parametrize("leg", ["sigterm_grace", "slow_host_evict"])
def test_chaos_matrix_slow_legs(tmp_path, leg):
    """The two chaos-matrix legs tier-1 does not cover: SIGTERM with
    a grace window (partial save + resumed relaunch) and the
    proactive slow-host checkpoint-and-evict (scripts/chaos_smoke.py
    runs all four under run_checks.sh --slow)."""
    _smoke.LEGS[leg](str(tmp_path))


def test_elastic_restart_after_kill_mid_ckpt_write(tmp_path):
    """Kill mid-checkpoint-write: the epoch-2 save's orbax write is
    dispatched and then SIGKILLed before commit. The relaunched run
    must restore the PREVIOUS intact checkpoint (epoch 1) — a torn,
    uncommitted step directory is never accepted — and finish."""
    run_dir = str(tmp_path / "run")
    # slow@step=8 pins epoch 3 (steps 8-11 at 4 steps/epoch) while the
    # background writer reaches save #2 and the injected SIGKILL lands
    # — the child deterministically dies MID-RUN with the epoch-2
    # write in flight, not after a too-fast run already finished.
    agent = ElasticAgent(AgentConfig(
        run_dir=run_dir, rdzv_dir=str(tmp_path / "rdzv"),
        host_id="h0",
        command=_train_cmd(
            run_dir,
            "kill@ckpt=2:gen=0;slow@step=8:delay=3:steps=4:gen=0"),
        max_restarts=1, settle_s=0.2, timeout_s=60.0, beat_s=0.1,
        grace_s=2.0, env=_child_env()))
    assert agent.run() == EXIT_DONE
    assert events.is_done(run_dir)

    records, run_id = _read_run(run_dir)
    elastic = [r for r in records if r.get("kind") == "obs_elastic"]
    restarts = [r for r in elastic if r["event"] == "restart"]
    assert len(restarts) == 1 and restarts[0]["cause"] == "failed"
    recovered = [r for r in elastic if r["event"] == "recovered"]
    assert recovered
    # epoch-2's save was torn: the resume restored epoch 1 and
    # re-ran epoch 2 (no torn-state acceptance).
    assert recovered[-1]["epoch"] == 2
    plain = [r for r in records if "kind" not in r]
    epochs_seen = [r["epoch"] for r in plain if "epoch" in r]
    # gen0 wrote [1, 2] (the epoch-2 row lands before its save),
    # gen1 re-ran 2 and finished 3.
    assert sorted(epochs_seen) == [1, 2, 2, 3]
    assert any(r.get("kind") == "obs_crash" for r in records)
    for r in records:
        if "run_id" in r:
            assert r["run_id"] == run_id
