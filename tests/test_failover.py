"""Mid-stream request failover + serve-tier chaos harness.

Three layers, cheapest first: pure-logic units (chaos grammar +
hooks, the request journal, resume-request semantics, supervisor
chaos forwarding, the flock-deduped AOT store), stub-replica
integration (duplicate-at-the-seam suppression, journal-cap
degradation, deadline propagation, drain-during-failover), and THE
acceptance test: two real ``python -m tpunet.serve`` children behind
an in-process router with ``--chaos kill@tokens=N:replica=0`` — a
real SIGKILL of the serving replica after first bytes reached the
client, with the completed stream asserted bitwise against solo
generate (greedy) and against an uninterrupted engine (sampled).
"""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from tpunet.config import RouterConfig, ServeConfig
from tpunet.router.journal import JournalEntry, RequestJournal
from tpunet.serve.chaos import (ServeChaos, ServeChaosError,
                                split_by_replica, spec_for_replica)
from tpunet.serve.scheduler import GenerateRequest

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _smoke():
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__("serve_chaos_smoke")
    finally:
        sys.path.pop(0)


# ---------------------------------------------------------------------------
# chaos grammar + hooks (no processes, injected kill/sleep)
# ---------------------------------------------------------------------------


def test_chaos_parse_good_and_bad():
    ch = ServeChaos.parse("kill@tokens=5;stall@tokens=3:ms=100;"
                          "drop-probe@prob=0.5:seed=7;"
                          "slow-stream@ms=2;kill@prefill")
    assert len(ch.events) == 5
    assert ch.render().startswith("kill@tokens=5")
    for bad in ("boom@tokens=1", "kill@step=1", "kill@tokens",
                "stall@tokens=3", "drop-probe@prob=0.5",
                "drop-probe@prob=2:seed=1", "kill@tokens=x",
                "kill@tokens=1:wat=2", ""):
        with pytest.raises(ServeChaosError):
            ServeChaos.parse(bad)


def test_chaos_replica_scoping():
    spec = "kill@tokens=5:replica=0;slow-stream@ms=10;" \
           "stall@tokens=2:ms=50:replica=1"
    assert split_by_replica(spec) == {
        0: "kill@tokens=5", None: "slow-stream@ms=10",
        1: "stall@tokens=2:ms=50"}
    assert spec_for_replica(spec, 0) == \
        "kill@tokens=5;slow-stream@ms=10"
    assert spec_for_replica(spec, 1) == \
        "slow-stream@ms=10;stall@tokens=2:ms=50"
    assert spec_for_replica(spec, 2) == "slow-stream@ms=10"
    assert spec_for_replica("", 0) == ""
    with pytest.raises(ServeChaosError):
        split_by_replica("kill@tokens=bad:replica=0")


def test_chaos_hooks_fire_deterministically():
    kills = []
    sleeps = []
    ch = ServeChaos.parse(
        "kill@tokens=3;kill@prefill=2;stall@tokens=2:ms=40",
        kill=lambda pid, sig: kills.append((pid, sig)),
        sleep=sleeps.append)
    ch.on_token()                      # 1: nothing
    assert not kills and not ch.stalled
    ch.on_token()                      # 2: stall arms
    assert ch.stalled and ch.stall_ms == 40.0
    ch.maybe_stall()
    assert sleeps == [0.04]
    ch.on_token()                      # 3: kill fires ONCE
    ch.on_token()
    assert len(kills) == 1
    ch.on_prefill()                    # ordinal 1: below the =2 mark
    assert len(kills) == 1
    ch.on_prefill()                    # ordinal 2: fires
    assert len(kills) == 2
    # drop-probe: same seed => same afflicted probes.
    runs = []
    for _ in range(2):
        probe = ServeChaos.parse("drop-probe@prob=0.5:seed=11",
                                 kill=lambda *a: None,
                                 sleep=lambda s: None)
        runs.append([probe.on_probe() for _ in range(16)])
    assert runs[0] == runs[1] and any(runs[0]) and not all(runs[0])


# ---------------------------------------------------------------------------
# request journal
# ---------------------------------------------------------------------------


def test_journal_roundtrip_cap_and_failover_accounting():
    journal = RequestJournal(max_tokens=3)
    entry = journal.open({"tokens": [1], "max_new_tokens": 8},
                         deadline_t=None)
    assert journal.active() == 1 and journal.active_failovers() == 0
    assert journal.note_token(entry, 5)
    assert journal.note_token(entry, 6)
    assert journal.note_token(entry, 7)
    assert not entry.over_cap
    assert not journal.note_token(entry, 8)   # cap: NOT recorded
    assert entry.over_cap and entry.tokens == [5, 6, 7]
    body = entry.resume_body()
    assert body["resume_tokens"] == [5, 6, 7] and body["stream"]
    assert entry.body.get("resume_tokens") is None  # original intact
    journal.begin_failover(entry)
    assert entry.failover_count == 1
    assert journal.active_failovers() == 1
    journal.end_failover(entry)
    assert journal.active_failovers() == 0
    journal.close(entry)
    assert journal.active() == 0
    journal.close(entry)                      # idempotent
    with pytest.raises(ValueError):
        RequestJournal(max_tokens=0)


def test_journal_entry_deadline_budget():
    entry = JournalEntry({}, deadline_t=time.monotonic() + 1.0)
    remaining = entry.remaining_ms()
    assert 0 < remaining <= 1000
    assert JournalEntry({}).remaining_ms() is None
    expired = JournalEntry({}, deadline_t=time.monotonic() - 0.1)
    assert expired.remaining_ms() <= 0


# ---------------------------------------------------------------------------
# resume-request semantics (no engine)
# ---------------------------------------------------------------------------


def test_generate_request_resume_tokens():
    req = GenerateRequest([1, 2], max_new_tokens=8,
                          resume_tokens=[7, 9, 11])
    assert req.tokens == [7, 9, 11] and req.resume_offset == 3
    # Journaled tokens are NOT re-emitted as events; a new push is.
    req.push_token(13)
    req.finish("length")
    events = list(req.events(timeout=1.0))
    assert events == [("token", 13), ("done", "length")]
    assert req.tokens == [7, 9, 11, 13]
    # A journal larger than the budget is a client error, not a hang.
    with pytest.raises(ValueError):
        GenerateRequest([1], max_new_tokens=2,
                        resume_tokens=[1, 2, 3])
    plain = GenerateRequest([1], max_new_tokens=2)
    assert plain.resume_offset == 0


def test_supervisor_forwards_scoped_chaos():
    from tpunet.router.supervisor import Supervisor
    sup = Supervisor(["--slots", "2"],
                     chaos="kill@tokens=5:replica=0;slow-stream@ms=9")
    argv0 = sup.child_argv(0, 8001, "r-0")
    argv1 = sup.child_argv(1, 8002, "r-1")
    assert argv0[argv0.index("--chaos") + 1] == \
        "kill@tokens=5;slow-stream@ms=9"
    assert argv1[argv1.index("--chaos") + 1] == "slow-stream@ms=9"
    # Caller-pinned --chaos in serve_args wins (not duplicated).
    sup2 = Supervisor(["--chaos", "kill@prefill"],
                      chaos="kill@tokens=5")
    assert sup2.child_argv(0, 1, "x").count("--chaos") == 1
    # Unscoped-empty: no flag at all.
    sup3 = Supervisor([], chaos="kill@tokens=5:replica=3")
    assert "--chaos" not in sup3.child_argv(0, 1, "x")


# ---------------------------------------------------------------------------
# AOT store: shared-filesystem dedup (flock-guarded commit)
# ---------------------------------------------------------------------------


def test_aot_store_concurrent_writers_dedup(tmp_path):
    """N concurrent writers of one entry key (the multi-host fleet
    sharing one --aot-cache dir): exactly one committed file, no tmp
    litter, every save reports success, and the committed entry
    load-verifies."""
    import jax
    import jax.numpy as jnp

    from tpunet.utils.cache import AotProgramStore, \
        serializable_compile

    with serializable_compile():
        compiled = jax.jit(lambda x: x + 1).lower(
            jax.ShapeDtypeStruct((4,), jnp.float32)).compile()
    store = AotProgramStore(str(tmp_path), "dedup-test")
    results = [None] * 6
    threads = [threading.Thread(
        target=lambda i=i: results.__setitem__(
            i, store.save("prog", "w1", compiled)))
        for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert all(results), results
    entries = [f for f in os.listdir(tmp_path)
               if f.endswith(AotProgramStore.SUFFIX)]
    assert len(entries) == 1, entries
    assert not [f for f in os.listdir(tmp_path)
                if ".tmp" in f], "tmp litter left behind"
    loaded = store.load("prog", "w1")
    assert loaded is not None
    out = np.asarray(loaded(jnp.zeros((4,), jnp.float32)))
    np.testing.assert_array_equal(out, np.ones(4, np.float32))
    # A later save of a committed key is a dedup no-op, not a rewrite.
    path = os.path.join(tmp_path, entries[0])
    before = os.stat(path).st_mtime_ns
    assert store.save("prog", "w1", compiled)
    assert os.stat(path).st_mtime_ns == before


# ---------------------------------------------------------------------------
# stub-replica integration (stdlib stubs, no engine)
# ---------------------------------------------------------------------------


def _stub_fleet(behaviors, **cfg_kw):
    smoke = _smoke()
    stubs = [smoke.StubReplica(f"fs{i}", b)
             for i, b in enumerate(behaviors)]
    router, server = smoke.make_router([s.url for s in stubs],
                                       **cfg_kw)
    smoke.wait_for(lambda: router.healthy_count() == len(stubs),
                   what="stubs healthy")
    return smoke, stubs, router, server


def test_duplicate_token_seam_suppressed():
    """The dying replica re-emits its last token at the seam AND the
    resumed stream is index-stamped: the client sees every index
    exactly once, greedy-identical to an uninterrupted stream."""
    smoke, stubs, router, server = _stub_fleet(
        [{"die_after_tokens": 4, "dup_at_seam": True}, {}])
    try:
        lines = smoke.read_stream(
            f"http://127.0.0.1:{server.port}",
            {"tokens": [10], "max_new_tokens": 10, "stream": True})
        toks = [ev["token"] for ev in lines if "token" in ev]
        idxs = [ev["i"] for ev in lines if "token" in ev]
        assert toks == smoke.expected_tokens(10, 10)
        assert idxs == list(range(10)), "indices not exactly-once"
        done = lines[-1]
        assert done["finish_reason"] == "length" \
            and "error" not in done
        assert done["failover_count"] == 1
    finally:
        server.drain()
        for s in stubs:
            s.close()


def test_journal_cap_honest_error_frame():
    """Past the cap, replica death degrades to the HONEST error frame
    (documented), never a silent truncation or a wrong resume."""
    smoke, stubs, router, server = _stub_fleet(
        [{"die_after_tokens": 6}, {}], failover_journal_tokens=3)
    try:
        lines = smoke.read_stream(
            f"http://127.0.0.1:{server.port}",
            {"tokens": [9], "max_new_tokens": 12, "stream": True})
        done = lines[-1]
        assert done["finish_reason"] == "error"
        assert "journal cap" in done["error"]
        assert done["n_tokens"] == 3     # what the journal still holds
        assert stubs[1].resumes == 0
    finally:
        server.drain()
        for s in stubs:
            s.close()


def test_deadline_header_propagates_and_expires():
    """X-Deadline-Ms: forwarded to the replica with the REMAINING
    budget (never more than the client sent), and an expired budget
    is a 504 carrying the partial token count."""
    smoke, stubs, router, server = _stub_fleet([{}, {}])
    base = f"http://127.0.0.1:{server.port}"
    try:
        lines = smoke.read_stream(
            base, {"tokens": [5], "max_new_tokens": 4,
                   "stream": True},
            headers=[("X-Deadline-Ms", "30000")])
        assert lines[-1]["finish_reason"] == "length"
        seen = [h for s in stubs for h in s.headers_seen
                if "X-Deadline-Ms" in h]
        assert seen, "deadline header not forwarded"
        assert all(0 < float(h["X-Deadline-Ms"]) <= 30000
                   for h in seen)
        # Pre-expired budget: 504 + partial count, replica untouched.
        before = sum(s.requests for s in stubs)
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"tokens": [5], "stream": True}).encode(),
            {"Content-Type": "application/json",
             "X-Deadline-Ms": "0.001"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 504
        payload = json.loads(exc.value.read())
        assert payload == {"error": "deadline", "n_tokens": 0}
        assert sum(s.requests for s in stubs) == before
        # Garbage header: loud 400.
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"tokens": [5]}).encode(),
            {"Content-Type": "application/json",
             "X-Deadline-Ms": "soon"})
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=10)
        assert exc.value.code == 400
    finally:
        server.drain()
        for s in stubs:
            s.close()


def test_drain_waits_for_inflight_failover():
    """A drain issued while a failover is in flight must not orphan
    the journaled request: drain blocks (against the shared grace
    budget) until the resume is re-homed, and the client stream still
    completes with no error frame."""
    smoke, stubs, router, server = _stub_fleet(
        [{"die_after_tokens": 2},
         {"resume_delay_s": 1.0, "line_delay_s": 0.05}],
        drain_grace_s=15.0)
    result = {}

    def client():
        try:
            result["lines"] = smoke.read_stream(
                f"http://127.0.0.1:{server.port}",
                {"tokens": [3], "max_new_tokens": 8, "stream": True},
                timeout=30)
        except Exception as e:  # noqa: BLE001
            result["error"] = e

    t = threading.Thread(target=client)
    t.start()
    try:
        smoke.wait_for(lambda: router.journal.active_failovers() > 0,
                       timeout=10, what="failover to begin")
        server.drain()                  # must block past the window
        assert router.journal.active_failovers() == 0
        t.join(timeout=30)
        assert not t.is_alive()
        assert "error" not in result, result.get("error")
        lines = result["lines"]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert toks == smoke.expected_tokens(3, 8)
        done = lines[-1]
        assert done["finish_reason"] == "length" \
            and "error" not in done
        assert done["failover_count"] == 1
    finally:
        for s in stubs:
            s.close()


# ---------------------------------------------------------------------------
# serve-side: X-Deadline-Ms through a real engine
# ---------------------------------------------------------------------------


def test_serve_honors_deadline_header(tmp_path):
    """The serve frontend maps X-Deadline-Ms into the engine
    scheduler's deadline: an exhausted budget finishes 'deadline'
    with the partial tokens it produced."""
    sys.path.insert(0, os.path.dirname(__file__))
    try:
        http_helpers = __import__("test_serve_http")
    finally:
        sys.path.pop(0)
    srv = http_helpers.make_server(default_max_new_tokens=64)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"tokens": [1, 2, 3]}).encode(),
            {"Content-Type": "application/json",
             "X-Deadline-Ms": "1"})
        with urllib.request.urlopen(req, timeout=120) as r:
            out = json.loads(r.read())
        assert out["finish_reason"] == "deadline"
        assert len(out["tokens"]) < 64
        # The tighter of header and body wins.
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"tokens": [1, 2, 3], "max_new_tokens": 4,
                        "deadline_s": 600.0}).encode(),
            {"Content-Type": "application/json",
             "X-Deadline-Ms": "1"})
        with urllib.request.urlopen(req, timeout=120) as r:
            assert json.loads(r.read())["finish_reason"] == "deadline"
    finally:
        srv.drain(5.0)


def _post(base, path, obj, timeout=120):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_resume_stop_token_and_host_sampling_guards(tmp_path):
    """Two resume seams the engine must close: a journal already
    ending in the stop token finishes 'stop' immediately (never
    generates past the stop an uninterrupted run honored), and a
    host-sampling replica rejects sampled resumes (its stateful
    generator cannot fast-forward — continuing would diverge)."""
    sys.path.insert(0, os.path.dirname(__file__))
    try:
        http_helpers = __import__("test_serve_http")
    finally:
        sys.path.pop(0)
    srv = http_helpers.make_server()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # Journal ends in the stop token -> immediate 'stop', no
        # generation.
        code, out = _post(base, "/v1/generate",
                          {"tokens": [1, 2], "max_new_tokens": 8,
                           "stop_token": 42,
                           "resume_tokens": [7, 42]})
        assert code == 200 and out["finish_reason"] == "stop"
        assert out["tokens"] == [7, 42]
        # A greedy resume continues to the total budget.
        code, out = _post(base, "/v1/generate",
                          {"tokens": [1, 2], "max_new_tokens": 6,
                           "resume_tokens": [7, 9]})
        assert code == 200 and out["finish_reason"] == "length"
        assert len(out["tokens"]) == 6 and out["tokens"][:2] == [7, 9]
        # Journal already meets the budget -> immediate 'length'.
        code, out = _post(base, "/v1/generate",
                          {"tokens": [1, 2], "max_new_tokens": 2,
                           "resume_tokens": [7, 9]})
        assert code == 200 and out["finish_reason"] == "length"
        assert out["tokens"] == [7, 9]
    finally:
        srv.drain(5.0)
    srv = http_helpers.make_server(device_sampling=False)
    base = f"http://127.0.0.1:{srv.port}"
    try:
        code, out = _post(base, "/v1/generate",
                          {"tokens": [1, 2], "max_new_tokens": 8,
                           "temperature": 0.9, "seed": 3,
                           "resume_tokens": [7, 9]})
        assert code == 400 and "device-side sampling" in out["error"]
        # Greedy resumes work on either sampler.
        code, out = _post(base, "/v1/generate",
                          {"tokens": [1, 2], "max_new_tokens": 6,
                           "resume_tokens": [7, 9]})
        assert code == 200 and len(out["tokens"]) == 6
    finally:
        srv.drain(5.0)


# ---------------------------------------------------------------------------
# THE acceptance test: real SIGKILL mid-stream through real HTTP
# ---------------------------------------------------------------------------

TINY_ARGS = ["--vit-hidden", "32", "--vit-depth", "2",
             "--vit-heads", "2", "--vocab-size", "256",
             "--max-seq-len", "256"]


def _pin_session_to(name: str) -> str:
    """A session string whose rendezvous-preferred replica (over the
    supervised fleet's stable names r0/r1) is ``name`` — routes the
    test stream onto the chaos-armed child deterministically."""
    from tpunet.router.balance import preferred_replica
    from tpunet.router.replica import ReplicaHandle
    fakes = [ReplicaHandle("r0", "http://x"),
             ReplicaHandle("r1", "http://x")]
    return next(s for s in (f"sess{i}" for i in range(256))
                if preferred_replica(fakes, f"s:{s}").name == name)


def _stream(base, body, timeout=240, headers=()):
    req = urllib.request.Request(
        base + "/v1/generate", json.dumps(body).encode(),
        {"Content-Type": "application/json", **dict(headers)})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return [json.loads(line) for line in resp]


def test_midstream_sigkill_failover_real_http(tmp_path):
    """SIGKILL of the serving replica mid-stream (after first bytes
    reached the client) produces a COMPLETE client stream with no
    error frame — greedy token-identical to an uninterrupted solo
    run, and a sampled stream deterministic across the failover
    (the (seed, step) counter-based sampling keys)."""
    import jax

    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.models.lm import generate
    from tpunet.router.__main__ import build_argparser, build_server
    from tpunet.serve.engine import Engine

    argv = ["--spawn", "2", "--port", "0",
            "--probe-interval-s", "0.2", "--probe-timeout-s", "2",
            "--unhealthy-after", "2", "--boot-timeout-s", "240",
            "--respawn-backoff-s", "0.2", "--emit-every-s", "0.5",
            "--min-replicas", "2", "--max-replicas", "2",
            "--metrics-dir", str(tmp_path),
            "--aot-cache", str(tmp_path / "aot"),
            "--chaos", "kill@tokens=12:replica=0", "--",
            "--checkpoint-dir", "", "--slots", "2",
            "--prefill-buckets", "64", "--queue-max", "16",
            "--max-new-tokens", "64"] + TINY_ARGS
    server = build_server(build_argparser().parse_args(argv)).start()
    router = server.router
    base = f"http://127.0.0.1:{server.port}"
    session = _pin_session_to("r0")
    try:
        _wait(lambda: router.healthy_count() == 2, timeout=240,
              what="both replicas healthy (cold boot)")

        # -- greedy: bitwise parity with an uninterrupted solo run ----
        model_cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                                vit_heads=2, vocab_size=256,
                                max_seq_len=256, dropout_rate=0.0)
        model = create_model(model_cfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=16)
        prompt = np.asarray([17, 5, 211, 42, 9], np.int32)
        trace_id = "abad1deafee1900d"   # client-supplied: always sampled
        lines = _stream(base, {"tokens": prompt.tolist(),
                               "max_new_tokens": 24, "stream": True,
                               "session": session},
                        headers=[("X-Trace-Id", trace_id)])
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            f"stream must end cleanly across the SIGKILL: {done}"
        assert "error" not in done, done
        assert done.get("failover_count", 0) >= 1, \
            f"the kill never triggered a failover: {done}"
        solo = np.asarray(generate(model, variables, prompt[None],
                                   n_new=24))[0, prompt.size:]
        assert toks == solo.tolist(), \
            "failover stream diverged from uninterrupted solo generate"
        assert [ev["i"] for ev in lines if "token" in ev] \
            == list(range(24)), "token indices not exactly-once"

        # -- sampled: deterministic continuation across the failover --
        _wait(lambda: router.healthy_count() == 2, timeout=240,
              what="victim respawned healthy (AOT warm boot)")
        ref_engine = Engine(model, variables, ServeConfig(
            slots=2, prefill_buckets=(64,), emit_every_s=0.0)).start()
        try:
            ref = ref_engine.submit(prompt, max_new_tokens=24,
                                    temperature=0.9, seed=1234)
            ref_tokens = ref.result(timeout=120)
        finally:
            ref_engine.stop()
        lines = _stream(base, {"tokens": prompt.tolist(),
                               "max_new_tokens": 24, "stream": True,
                               "temperature": 0.9, "seed": 1234,
                               "session": session})
        done = lines[-1]
        toks = [ev["token"] for ev in lines if "token" in ev]
        assert done.get("done") and done["finish_reason"] == "length", \
            done
        assert "error" not in done, done
        assert done.get("failover_count", 0) >= 1, \
            "respawned replica's re-armed chaos never fired"
        assert toks == ref_tokens, \
            "sampled continuation diverged across the failover"

        snap = json.loads(urllib.request.urlopen(
            base + "/metrics", timeout=10).read())
        assert snap["router_failovers_total"] >= 2
    finally:
        server.drain()

    # -- failover events + counters in metrics.jsonl -------------------
    recs = [json.loads(line) for line in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    events = [r for r in recs if r.get("kind") == "obs_router"
              and r.get("event") == "failover"]
    assert len(events) >= 2
    assert all(e["cause"] == "replica_failed_mid_stream"
               for e in events)
    windows = [r for r in recs if r.get("kind") == "obs_router"
               and not r.get("event")]
    assert windows[-1]["failovers_total"] >= 2

    # -- ONE trace_id spans both replicas, seam recorded ---------------
    # Router-role span: the client-supplied id, closed with the
    # failover seam accounting (docs/metrics_schema.md "obs_trace").
    spans = [r for r in recs if r.get("kind") == "obs_trace"
             and r.get("trace_id") == trace_id]
    assert len(spans) == 1 and spans[0]["role"] == "router", spans
    assert spans[0]["hop"] == 0
    assert spans[0]["finish_reason"] == "length"
    assert spans[0]["tokens"] == 24
    assert spans[0]["failover_count"] >= 1
    assert spans[0].get("tokens_relayed") is not None
    # Replica-role span: the SIGKILLed first hop never finishes (its
    # breadcrumbs survive in the crash-durable ring); the survivor's
    # resumed hop emits its span with the resume offset.
    rep_spans = []
    for rep_dir in sorted(tmp_path.glob("replica-*")):
        mfile = rep_dir / "metrics.jsonl"
        if not mfile.exists():
            continue
        rep_spans += [json.loads(line) for line
                      in mfile.read_text().splitlines()
                      if '"obs_trace"' in line]
    rep_spans = [r for r in rep_spans if r.get("trace_id") == trace_id]
    assert rep_spans, "no surviving replica emitted the resumed span"
    resumed = next(r for r in rep_spans if r.get("resume_offset"))
    assert resumed["role"] == "replica" and resumed["hop"] >= 2
    assert resumed["resume_offset"] + resumed["tokens"] == 24
    assert resumed["finish_reason"] == "length"

    # -- the timeline join renders one causal track --------------------
    from tpunet.obs.history.timeline import build_timeline
    trace = build_timeline(
        [str(tmp_path)] + [str(d) for d
                           in sorted(tmp_path.glob("replica-*"))])
    joined = [e for e in trace["traceEvents"]
              if e.get("args", {}).get("trace_id") == trace_id
              and e["pid"] == 1]
    names = {e["name"] for e in joined}
    assert "relay" in names, "router relay span missing from the join"
    assert "seam" in names, "failover seam missing from the join"
    # The dying hop's orphaned lifecycle is force-closed at the seam.
    assert any(e.get("args", {}).get("force_closed") == "failover_seam"
               for e in joined), "first hop never force-closed"
    # The track spans BOTH replicas: the router's open crumbs name a
    # different serving replica per hop (the victim's own ring was
    # recycled by its respawn — the router's record is what survives).
    reps = {e["args"]["replica"] for e in joined
            if e.get("args", {}).get("replica")}
    assert len(reps) >= 2, \
        f"trace does not span both replicas: {reps}"
    # The survivor's own breadcrumbs joined the track too.
    assert any(e.get("args", {}).get("process") for e in joined), \
        "no replica-side crumbs joined the track"


def _wait(pred, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {what}")


# ---------------------------------------------------------------------------
# speculative decoding x failover: verified-only journal resume
# ---------------------------------------------------------------------------


def test_spec_midverify_stop_resumes_from_verified_journal():
    """A spec replica that stops mid-verify-window leaves a journal of
    VERIFIED tokens only (the engine never push_token()s a draft), so
    a survivor seeded with that journal continues the exact canonical
    stream. Modeled in-process: replica A's budget cuts its last burst
    in the middle of an accepted verify window (a full-accept
    self-speculating drafter guarantees the window overshoots), then
    replica B resumes with ``resume_tokens`` — greedy and sampled, the
    stitched stream must be bitwise an uninterrupted spec-off run."""
    import jax

    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.serve import Engine

    cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=31, max_seq_len=48)
    model = create_model(cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)

    def make(spec):
        return Engine(model, variables, ServeConfig(
            slots=2, queue_max=8, prefill_buckets=(8, 16),
            emit_every_s=0.0, spec_decode=spec, spec_k=3,
            spec_draft_width_mult=1.0)).start()

    rng = np.random.default_rng(31)
    prompt = rng.integers(0, 31, size=6).astype(np.int32)
    # Sampling params are per-request, so ONE spec-off and ONE spec-on
    # engine serve both the greedy and the sampled arm (compile once).
    eng_off, eng_on = make(False), make(True)
    try:
        for samp in (dict(),
                     dict(temperature=0.9, top_k=5, seed=77)):
            canonical = eng_off.submit(
                prompt, max_new_tokens=10, **samp).result(timeout=120)
            # Replica A: K=3 self-spec emits 4 verified tokens per
            # cycle; a budget of 6 stops it 2 tokens INTO the second
            # verify window. Its stream is the journal.
            journal = eng_on.submit(
                prompt, max_new_tokens=6, **samp).result(timeout=120)
            assert journal == canonical[:6], \
                f"journal is not a verified-only prefix ({samp})"
            # Replica B: resume from the journal, finish the budget
            # (counter-based keys make the resumed rows land on the
            # same (seed, step) stream the canonical run sampled).
            resumed = eng_on.submit(
                prompt, max_new_tokens=10, resume_tokens=journal,
                **samp).result(timeout=120)
            assert resumed == canonical, \
                f"survivor diverged after mid-verify resume ({samp})"
    finally:
        eng_off.stop()
        eng_on.stop()
