"""Flight recorder (tpunet/obs/flightrec/): ring semantics under
concurrency, the host-thread registry + thread_stalled watchdog path,
and the acceptance test — a child process driven to SIGSEGV/SIGABRT
leaves a complete, parseable crash_report.json (ring tail, per-thread
Python stacks, native batcher journal)."""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import pytest

from tpunet.obs.flightrec.ring import (EventRing, read_ring_file,
                                       read_slots)
from tpunet.obs.flightrec.threads import ThreadRegistry

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


# ---------------------------------------------------------------------------
# event ring
# ---------------------------------------------------------------------------


def test_ring_roundtrip_and_order(tmp_path):
    path = str(tmp_path / "events.ring")
    ring = EventRing(path, n_slots=16)
    for i in range(5):
        ring.record("kind", f"msg {i}")
    tail = ring.tail()
    assert [e["msg"] for e in tail] == [f"msg {i}" for i in range(5)]
    assert [e["seq"] for e in tail] == [1, 2, 3, 4, 5]
    assert all(e["kind"] == "kind" for e in tail)
    assert tail[0]["tid"] == threading.get_ident()
    # Bounded tail request.
    assert [e["seq"] for e in ring.tail(2)] == [4, 5]
    ring.close()


def test_ring_wraparound_keeps_newest(tmp_path):
    ring = EventRing(str(tmp_path / "r.ring"), n_slots=8)
    for i in range(20):
        ring.record("k", f"m{i}")
    tail = ring.tail()
    assert len(tail) == 8
    assert [e["seq"] for e in tail] == list(range(13, 21))
    assert tail[-1]["msg"] == "m19"
    ring.close()


def test_ring_survives_without_close(tmp_path):
    """The crash property: slots are durable in the file the moment
    record() returns — a reader parses them with no shutdown step."""
    path = str(tmp_path / "r.ring")
    ring = EventRing(path, n_slots=8)
    ring.record("span", "step 1")
    ring.record("alert", "nan_loss step=3")
    events = read_ring_file(path)          # file read, not the mmap
    assert [e["kind"] for e in events] == ["span", "alert"]
    ring.close()


def test_ring_anonymous_mode_and_long_payload_truncation():
    ring = EventRing(None, n_slots=4)
    ring.record("k" * 40, "x" * 500)       # over the 16/80-byte slots
    (e,) = ring.tail()
    assert e["kind"] == "k" * 16
    assert e["msg"] == "x" * 80
    ring.close()


def test_ring_rejects_garbage_buffers():
    assert read_slots(b"") == []
    assert read_slots(b"not a ring at all" * 10) == []
    assert read_ring_file("/nonexistent/path.ring") == []


def test_ring_concurrent_writers_lose_nothing(tmp_path):
    """8 threads hammer one ring: every write claims a distinct seq
    (the itertools.count cursor is atomic under the GIL) and the final
    tail parses with the highest seqs intact."""
    ring = EventRing(str(tmp_path / "c.ring"), n_slots=256)
    n_threads, per = 8, 500

    def writer(t):
        for i in range(per):
            ring.record("conc", f"t{t} i{i}")

    threads = [threading.Thread(target=writer, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    tail = ring.tail()
    assert len(tail) == 256
    seqs = [e["seq"] for e in tail]
    assert seqs == sorted(seqs) and len(set(seqs)) == 256
    assert max(seqs) == n_threads * per
    # Every surviving slot parses back to a well-formed payload.
    assert all(e["kind"] == "conc" and e["msg"].startswith("t")
               for e in tail)
    ring.close()


def test_record_after_close_is_silent(tmp_path):
    ring = EventRing(str(tmp_path / "r.ring"), n_slots=4)
    ring.close()
    ring.record("k", "never raises")       # must not throw


# ---------------------------------------------------------------------------
# host-thread registry + thread_stalled
# ---------------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def test_thread_registry_beat_state_and_stall():
    clock = FakeClock()
    reg = ThreadRegistry()
    h = reg.register("worker", stall_after_s=5.0, clock=clock)
    h.beat("busy")
    clock.t += 3.0
    assert not h.stalled()                 # within budget
    clock.t += 3.0
    assert h.stalled()                     # busy past budget
    h.beat("idle")
    clock.t += 100.0
    assert not h.stalled()                 # idle never stalls
    assert reg.stalled() == []
    h.beat("busy")
    clock.t += 6.0
    assert [(x.name, round(a)) for x, a in reg.stalled()] \
        == [("worker", 6)]


def test_thread_registry_gauges_and_snapshot():
    from tpunet.obs.registry import Registry
    clock = FakeClock()
    treg = ThreadRegistry()
    h = treg.register("ckpt-writer", stall_after_s=600.0, clock=clock)
    h.beat("busy")
    clock.t += 2.0
    reg = Registry()
    treg.export_gauges(reg)
    snap = reg.snapshot()
    assert snap["thread_count"] == 1
    assert snap["thread_ckpt_writer_age_s"] == pytest.approx(2.0)
    assert snap["thread_ckpt_writer_beats"] == 1
    (row,) = treg.snapshot()
    assert row["name"] == "ckpt-writer" and row["state"] == "busy"
    # Re-registration replaces (thread restart), unregister removes.
    treg.register("ckpt-writer", clock=clock)
    assert treg.handles()[0].beats == 0
    treg.unregister("ckpt-writer")
    assert treg.handles() == []


def test_watchdog_thread_stalled_per_thread_cooldown(monkeypatch):
    """Two stalled threads page separately (per-thread cooldown
    keys); a repeat within the cooldown is suppressed; the alert
    reaches the registry sinks like every other watchdog page."""
    import dataclasses

    from tpunet.config import ObsConfig
    from tpunet.obs import flightrec
    from tpunet.obs.health import Watchdog
    from tpunet.obs.registry import MemorySink, Registry

    clock = FakeClock()
    treg = ThreadRegistry()
    monkeypatch.setattr(
        "tpunet.obs.flightrec.threads.THREADS", treg)
    assert flightrec  # the watchdog resolves THREADS through here
    a = treg.register("writer-a", stall_after_s=1.0, clock=clock)
    b = treg.register("writer-b", stall_after_s=1.0, clock=clock)
    cfg = dataclasses.replace(ObsConfig(), alert_cooldown_steps=10)
    reg = Registry()
    sink = MemorySink()
    reg.add_sink(sink)
    wd = Watchdog(cfg, reg, clock=clock)
    a.beat("busy")
    b.beat("busy")
    clock.t += 5.0
    wd.check_threads(step=100)
    alerts = sink.by_kind("obs_alert")
    assert {al["thread"] for al in alerts} == {"writer-a", "writer-b"}
    assert all(al["reason"] == "thread_stalled"
               and al["severity"] == "warn" for al in alerts)
    assert alerts[0]["age_s"] == pytest.approx(5.0)
    # Inside the cooldown window: suppressed, counted.
    wd.check_threads(step=105)
    assert len(sink.by_kind("obs_alert")) == 2
    assert reg.counter("obs_alerts_suppressed").value == 2
    # Past the cooldown: pages again.
    wd.check_threads(step=111)
    assert len(sink.by_kind("obs_alert")) == 4


def test_watchdog_checks_threads_from_observe_step(monkeypatch):
    import dataclasses

    from tpunet.config import ObsConfig
    from tpunet.obs.health import Watchdog
    from tpunet.obs.registry import MemorySink, Registry

    clock = FakeClock()
    treg = ThreadRegistry()
    monkeypatch.setattr("tpunet.obs.flightrec.threads.THREADS", treg)
    h = treg.register("wedged", stall_after_s=1.0, clock=clock)
    h.beat("busy")
    clock.t += 10.0
    reg = Registry()
    sink = MemorySink()
    reg.add_sink(sink)
    wd = Watchdog(dataclasses.replace(ObsConfig(), stall_factor=0.0),
                  reg, clock=clock)
    # observe_step piggybacks the check every THREAD_CHECK_STEPS.
    wd.observe_step(Watchdog.THREAD_CHECK_STEPS, 0.01)
    assert [a["reason"] for a in sink.by_kind("obs_alert")] \
        == ["thread_stalled"]


# ---------------------------------------------------------------------------
# crash capture end-to-end (the acceptance test)
# ---------------------------------------------------------------------------


_CHILD = r"""
import os, sys
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
from tpunet.obs import flightrec

rec = flightrec.install({workdir!r})
handle = flightrec.register_thread("child-worker", stall_after_s=60.0)
handle.beat("busy")
rec.refresh_threads()
for i in range(5):
    flightrec.record("span", f"step {{i}}")

native_ok = False
try:
    from tpunet.data import native
    if native.available():
        rows = np.arange(64 * 12, dtype=np.uint8).reshape(64, 12)
        pf = native.NativePrefetcher(rows,
                                     np.arange(64, dtype=np.int32), 8)
        next(pf.iter_epoch(np.arange(64)))
        native_ok = True
except Exception:
    pass
print("NATIVE_OK" if native_ok else "NATIVE_MISSING", flush=True)
flightrec.record("test", "about to die: {mode}")
{die}
"""

_DIE = {
    "sigsegv": "import ctypes; ctypes.string_at(0)",
    "sigabrt": "os.abort()",
}


def _run_crash_child(tmp_path, mode):
    workdir = str(tmp_path / mode)
    code = _CHILD.format(repo=REPO, workdir=workdir,
                         die=_DIE[mode], mode=mode)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          timeout=180)
    report_path = os.path.join(workdir, "flightrec",
                               "crash_report.json")
    # The watcher outlives the child; give it a moment to assemble.
    deadline = time.monotonic() + 20.0
    while not os.path.exists(report_path) \
            and time.monotonic() < deadline:
        time.sleep(0.1)
    assert os.path.exists(report_path), (
        f"no crash report after {mode} child "
        f"(rc={proc.returncode})\nstdout: {proc.stdout}\n"
        f"stderr: {proc.stderr}")
    with open(report_path) as f:
        return proc, json.load(f)


@pytest.mark.parametrize("mode,signo", [("sigsegv", signal.SIGSEGV),
                                        ("sigabrt", signal.SIGABRT)])
def test_induced_crash_produces_complete_report(tmp_path, mode, signo):
    proc, rep = _run_crash_child(tmp_path, mode)
    assert proc.returncode != 0            # the child really died
    native_built = "NATIVE_OK" in proc.stdout
    # Ring tail: the events recorded before death, in order, ending
    # with the last breath.
    msgs = [e["msg"] for e in rep["events"]]
    assert f"about to die: {mode}" in msgs[-1]
    assert sum(m.startswith("step ") for m in msgs) == 5
    # Per-thread Python stacks from faulthandler.
    assert rep["stacks"]["fatal"]
    assert len(rep["stacks"]["threads"]) >= 1
    frames = [f for t in rep["stacks"]["threads"]
              for f in t["frames"]]
    assert any("File" in f for f in frames)
    # Host-thread registry snapshot.
    assert any(t["name"] == "child-worker" for t in rep["threads"])
    # Native journal: present whenever the extension was loadable —
    # including the signal the C handler saw.
    if native_built:
        nj = rep["native_journal"]
        assert nj is not None and nj["signal"] == int(signo)
        ops = [o["op"] for o in nj["ops"]]
        assert "create" in ops and "batch_alloc" in ops
        assert rep["cause"] == signal.Signals(signo).name
    # Meta identifies the dead incarnation.
    assert isinstance(rep["meta"]["pid"], int) and rep["meta"]["pid"] > 0


def test_clean_close_leaves_no_crash_report(tmp_path):
    """A clean shutdown must not fabricate a crash."""
    code = (f"import sys; sys.path.insert(0, {REPO!r})\n"
            "import os; os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
            "from tpunet.obs import flightrec\n"
            f"rec = flightrec.install({str(tmp_path / 'clean')!r})\n"
            "flightrec.record('k', 'fine')\n"
            "flightrec.close()\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, env=env,
                          timeout=180)
    assert proc.returncode == 0, proc.stderr
    time.sleep(1.0)                        # watcher shutdown window
    flightdir = tmp_path / "clean" / "flightrec"
    assert (flightdir / "clean").exists()
    assert not (flightdir / "crash_report.json").exists()


def test_watcher_ownership_and_clean_protocol(tmp_path):
    """watch.main directly: EOF after CLEAN assembles nothing; EOF on
    a dir whose meta.json names a NEWER pid assembles nothing (run
    dirs are reused — a lingering predecessor watcher must not write
    over the successor's artifacts); matching pid assembles."""
    import io

    from tpunet.obs.flightrec import report as frreport
    from tpunet.obs.flightrec import watch

    # A dir with a space exercises the remainder-of-line path field.
    d = str(tmp_path / "my runs")
    os.makedirs(d)
    with open(frreport.artifact(d, frreport.META_JSON), "w") as f:
        json.dump({"pid": 999}, f)
    report = frreport.artifact(d, frreport.REPORT_NAME)
    # Stale watcher (pid 123) vs newer incarnation (meta pid 999).
    assert watch.main(io.StringIO(f"DIR 0 123 {d}\n")) == 0
    assert not os.path.exists(report)
    # CLEAN clears the dir: nothing assembled even for the owner.
    assert watch.main(io.StringIO(f"DIR 0 999 {d}\nCLEAN\n")) == 0
    assert not os.path.exists(report)
    # A malformed line is skipped, not fatal; the owning
    # incarnation's watcher then assembles on EOF.
    assert watch.main(io.StringIO(
        f"DIR not-an-int x {d}\nDIR 0 999 {d}\n")) == 0
    assert os.path.exists(report)
    with open(report) as f:
        assert json.load(f)["meta"]["pid"] == 999


def test_native_journal_live_snapshot():
    """tn_journal_read: the in-process view of the native op ring
    (the crash handler's spill is the post-mortem view of the same
    ring, exercised by the crash children above)."""
    from tpunet.data import native
    if not native.available():
        pytest.skip("native toolchain unavailable")
    import numpy as np
    native.gather_rows(np.zeros((4, 4), np.uint8), np.arange(4))
    entries = native.journal_entries()
    assert any(e["op"] == "gather" for e in entries)
    seqs = [e["seq"] for e in entries]
    assert seqs == sorted(seqs) and all(s > 0 for s in seqs)


# ---------------------------------------------------------------------------
# prior-crash detection -> obs_crash emission
# ---------------------------------------------------------------------------


def test_prior_crash_emits_obs_crash_once(tmp_path):
    """A restart over a crashed run dir emits exactly one obs_crash
    (and archives the report so the next restart emits none)."""
    from tpunet.config import ObsConfig
    from tpunet.obs import Observability, flightrec
    from tpunet.obs.registry import MemorySink

    workdir = str(tmp_path)
    flightdir = tmp_path / "flightrec"
    flightdir.mkdir()
    with open(flightdir / "crash_report.json", "w") as f:
        json.dump({"version": 1, "cause": "SIGSEGV", "signal": 11,
                   "meta": {"pid": 1234},
                   "events": [{"seq": 1, "kind": "k", "msg": "m"}],
                   "stacks": {"threads": [{"frames": []}]},
                   "native_journal": {"ops": [{"seq": 1}]}}, f)
    obs = Observability(ObsConfig(), checkpoint_dir=workdir)
    try:
        sink = MemorySink()
        obs.add_sink(sink)
        obs.begin_epoch(1)
        (rec,) = sink.by_kind("obs_crash")
        assert rec["cause"] == "SIGSEGV" and rec["signal"] == 11
        assert rec["crashed_pid"] == 1234
        assert rec["events"] == 1 and rec["stack_threads"] == 1
        assert rec["native_ops"] == 1
        assert os.path.exists(rec["report_path"])
        assert not (flightdir / "crash_report.json").exists()
        obs.begin_epoch(2)                 # no double emission
        assert len(sink.by_kind("obs_crash")) == 1
    finally:
        obs.close()
    # A fresh incarnation over the ARCHIVED report emits nothing.
    obs2 = Observability(ObsConfig(), checkpoint_dir=workdir)
    try:
        sink2 = MemorySink()
        obs2.add_sink(sink2)
        obs2.begin_epoch(1)
        assert sink2.by_kind("obs_crash") == []
    finally:
        obs2.close()


def test_crash_report_renderer(tmp_path):
    """scripts/obs_crash_report.py resolves run dirs and renders the
    sections a post-mortem needs."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        occ = __import__("obs_crash_report")
    finally:
        sys.path.pop(0)
    flightdir = tmp_path / "flightrec"
    flightdir.mkdir()
    rep = {"version": 1, "cause": "SIGABRT", "signal": 6,
           "assembled_t": 1e9,
           "meta": {"pid": 7, "argv": ["train.py"], "run_id": "r1",
                    "started_t": 1e9},
           "events": [{"seq": 1, "t": 1e9, "kind": "span",
                       "msg": "step 1"}],
           "threads": [{"name": "ckpt-writer", "state": "busy",
                        "age_s": 2.0, "beats": 3,
                        "stall_after_s": 600.0}],
           "stacks": {"fatal": "Aborted", "threads": [
               {"ident": "0x1", "current": True,
                "frames": ['File "x.py", line 1 in f']}]},
           "native_journal": {"signal": 6, "ops": [
               {"seq": 1, "op": "create", "tid": 1, "a": 8, "b": 4}]},
           "device_memory": {"sampled_t": 1e9, "devices": [
               {"device": 0, "bytes_in_use": 2 ** 20,
                "peak_bytes_in_use": 2 ** 21}]}}
    with open(flightdir / "crash_report.json", "w") as f:
        json.dump(rep, f)
    path = occ.find_report(str(tmp_path))
    text = occ.render(rep, path)
    for needle in ("SIGABRT", "ckpt-writer", "PYTHON STACKS",
                   "EVENT RING TAIL", "NATIVE BATCHER JOURNAL",
                   "DEVICE MEMORY", "run_id: r1"):
        assert needle in text, needle
    # Archived-only dirs resolve to the newest archive.
    os.rename(flightdir / "crash_report.json",
              flightdir / "crash_report.123.json")
    assert occ.find_report(str(tmp_path)).endswith(
        "crash_report.123.json")
