"""FSDP (ZeRO-3) parameter sharding and gradient accumulation.

Both are beyond-parity upgrades over the reference's replicated-DDP
layout (README.md:77 "Model parameters remain consistent across all
GPUs"): FSDP shards params + Adam moments over 'data' with GSPMD
inserting just-in-time all-gathers; grad accumulation scans equal
microbatches in time inside one jitted step. Each must leave the
training math unchanged — that is what these tests pin down on the
8-device CPU mesh.
"""

import dataclasses
import re

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.parallel import make_mesh
from tpunet.parallel.tp import (FSDP, FSDP_RULES, _fsdp_spec, _spec_for,
                                rules_for)
from tpunet.train.loop import Trainer

VIT_CFG = ModelConfig(name="vit", vit_patch=4, vit_hidden=64, vit_depth=2,
                      vit_heads=4, dropout_rate=0.0, dtype="float32")
LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=32,
                     max_seq_len=64)


def _vit_cfg(mesh_cfg, grad_accum=1, batch=32, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=batch,
                        synthetic_train_size=128, synthetic_test_size=32),
        model=dataclasses.replace(VIT_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3, grad_accum=grad_accum),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


def _lm_cfg(mesh_cfg, grad_accum=1, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=64, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=dataclasses.replace(LM_CFG, **model_kw),
        optim=OptimConfig(learning_rate=3e-3, grad_accum=grad_accum),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


def _run(cfg):
    trainer = Trainer(cfg)
    try:
        train_m = trainer.train_one_epoch(1)
        eval_m = trainer.evaluate()
        params = trainer.state.params
    finally:
        trainer.close()
    return train_m, eval_m, params


# ---------------------------------------------------------------- rules


def test_fsdp_spec_picks_largest_divisible_dim():
    mesh = make_mesh(MeshConfig(data=8))
    assert _fsdp_spec(np.zeros((64, 192)), mesh) == P(None, "data")
    assert _fsdp_spec(np.zeros((192, 64)), mesh) == P("data")
    # dim0 indivisible, dim2 divisible
    assert _fsdp_spec(np.zeros((1, 65, 64)), mesh) == P(None, None, "data")
    # nothing divisible -> replicate
    assert _fsdp_spec(np.zeros((7, 3)), mesh) == P()
    assert _fsdp_spec(np.zeros(()), mesh) == P()
    # data axis of size 1 -> replicate
    assert _fsdp_spec(np.zeros((64,)), make_mesh(MeshConfig(data=1))) == P()


def test_fsdp_rules_appended_and_subsume_zero1():
    rules = rules_for(ModelConfig(name="mobilenet_v2"), fsdp=True)
    assert rules == FSDP_RULES
    # fsdp wins over zero1 (moments covered by the FSDP moment rule)
    rules = rules_for(ModelConfig(name="mobilenet_v2"), zero1=True,
                      fsdp=True)
    assert rules == FSDP_RULES


def test_fsdp_sentinel_resolved_per_leaf():
    mesh = make_mesh(MeshConfig(data=8))
    spec = _spec_for("params/dense/kernel", np.zeros((64, 192)), mesh,
                     [(re.compile(r"^params/"), FSDP)])
    assert spec == P(None, "data")


def test_unfit_rule_falls_through_to_fsdp():
    """A TP rule that matches the path but cannot shard the leaf (expert
    dim 3 indivisible by model=2) must not terminate the search: the
    FSDP catch-all after it still shards a divisible dim."""
    mesh = make_mesh(MeshConfig(data=4, model=2))
    rules = [(re.compile(r"moe/wi$"), P("model", None, None)),
             (re.compile(r"^params/"), FSDP)]
    spec = _spec_for("params/block00/moe/wi", np.zeros((3, 64, 128)),
                     mesh, rules)
    assert spec == P(None, None, "data")  # largest divisible dim (128 % 4)
    # with no catch-all the unfit rule still replicates
    assert _spec_for("params/block00/moe/wi", np.zeros((3, 64, 128)),
                     mesh, rules[:1]) == P()


def test_fsdp_gather_layout_preserves_tp_compute_sharding():
    """The FSDP step-start gather target is the TP/PP compute layout,
    not blanket replication: model-axis leaves keep their Megatron
    sharding for compute; FSDP-only leaves gather to replicated."""
    from tpunet.parallel.tp import tree_shardings
    mesh = make_mesh(MeshConfig(data=4, model=2))
    params = {"block00": {"attn": {"qkv": {"kernel": np.zeros((64, 192))}},
                          "ln1": {"scale": np.zeros((64,))}}}
    gather = tree_shardings(params, mesh, rules_for(VIT_CFG, mesh=mesh))
    assert gather["block00"]["attn"]["qkv"]["kernel"].spec \
        == P(None, "model")
    assert gather["block00"]["ln1"]["scale"].spec == P()


# ----------------------------------------------------------- end-to-end


@pytest.mark.slow
def test_fsdp_shards_params_and_moments_and_keeps_parity():
    base_t, base_e, base_p = _run(_vit_cfg(MeshConfig(data=8)))

    trainer = Trainer(_vit_cfg(MeshConfig(data=8, fsdp=True)))
    try:
        f_t = trainer.train_one_epoch(1)
        f_e = trainer.evaluate()
        params = trainer.state.params
        qkv = params["block00"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == P(None, "data")
        # each device holds 1/8 of the weight
        assert qkv.addressable_shards[0].data.shape == (64, 192 // 8)
        mu = trainer.state.opt_state[0].mu
        assert mu["block00"]["attn"]["qkv"]["kernel"].sharding.spec \
            == P(None, "data")
        # the math is unchanged
        assert abs(base_t["loss"] - f_t["loss"]) < 1e-4
        assert abs(base_e["accuracy"] - f_e["accuracy"]) < 1e-6
        np.testing.assert_allclose(
            np.asarray(base_p["block00"]["attn"]["qkv"]["kernel"]),
            np.asarray(params["block00"]["attn"]["qkv"]["kernel"]),
            rtol=2e-4, atol=2e-5)
    finally:
        trainer.close()


@pytest.mark.slow
def test_fsdp_composes_with_tp():
    """TP rules win for matched leaves; FSDP takes the rest."""
    trainer = Trainer(_vit_cfg(MeshConfig(data=4, model=2, fsdp=True)))
    try:
        params = trainer.state.params
        assert params["block00"]["attn"]["qkv"]["kernel"].sharding.spec \
            == P(None, "model")
        assert params["block00"]["mlp"]["fc1"]["kernel"].sharding.spec \
            == P(None, "model")
        # not TP-matched -> FSDP over data (64 % 4 == 0)
        assert params["block00"]["ln1"]["scale"].sharding.spec == P("data")
        m = trainer.train_one_epoch(1)
        assert np.isfinite(m["loss"])
    finally:
        trainer.close()


@pytest.mark.slow
def test_fsdp_mobilenet_smoke():
    """Conv kernels are HWIO: FSDP shards a channel dim, not dim 0."""
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16),
        model=ModelConfig(width_mult=0.5, dtype="float32"),
        optim=OptimConfig(),
        mesh=MeshConfig(data=8, fsdp=True),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        specs = {
            "/".join(str(getattr(e, "key", e)) for e in path):
                leaf.sharding.spec
            for path, leaf in jax.tree_util.tree_flatten_with_path(
                trainer.state.params)[0]}
        assert any("data" in str(s) for s in specs.values()), specs
        m = trainer.train_one_epoch(1)
        assert np.isfinite(m["loss"])
    finally:
        trainer.close()


# ------------------------------------------------ elastic re-mesh restore


def test_fsdp_restore_onto_smaller_mesh_bit_parity(tmp_path):
    """The elastic shrink contract (docs/elasticity.md): an FSDP
    checkpoint saved on a dp=8 mesh restores onto a dp=4 mesh with
    every leaf — params, BOTH Adam moments, the step counter —
    BIT-equal to the uninterrupted same-seed run's state at the save
    point, re-sharded to the new data axis; and the restored state is
    donation-safe (the R1/R7 jnp.copy re-materialization), proven by
    running the donated train step on it."""
    import numpy as np
    from jax.sharding import PartitionSpec as P

    ckpt = CheckpointConfig(directory=str(tmp_path), save_best=False,
                            save_last=True)
    big = _lm_cfg(MeshConfig(data=8, fsdp=True)).replace(checkpoint=ckpt)
    source = Trainer(big)
    try:
        source.train_one_epoch(1)
        source.start_epoch = 1
        source.ckpt.save_state(1, source._payload())
        source.ckpt.wait()

        small = _lm_cfg(MeshConfig(data=4, fsdp=True)).replace(
            checkpoint=dataclasses.replace(ckpt, resume=True))
        restored = Trainer(small)
        try:
            # Resume bookkeeping carried over...
            assert restored.start_epoch == 2
            assert restored.global_step == source.global_step
            # ...every leaf bit-equal to the uninterrupted run's state
            # (params, Adam mu/nu, step — sharding-independent values)...
            src_leaves = jax.tree_util.tree_leaves(
                {"params": source.state.params,
                 "opt": source.state.opt_state,
                 "step": source.state.step})
            got_leaves = jax.tree_util.tree_leaves(
                {"params": restored.state.params,
                 "opt": restored.state.opt_state,
                 "step": restored.state.step})
            assert len(src_leaves) == len(got_leaves)
            for a, b in zip(src_leaves, got_leaves):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            # ...and actually RE-SHARDED onto the smaller data axis
            # (1/4 per device, not 1/8).
            qkv = restored.state.params["block00"]["attn"]["qkv"]["kernel"]
            assert qkv.sharding.spec == P(None, "data")
            assert qkv.addressable_shards[0].data.shape == (64, 192 // 4)
            mu = restored.state.opt_state[0].mu
            assert mu["block00"]["attn"]["qkv"]["kernel"] \
                .sharding.spec == P(None, "data")
            # Donation-safe: the restored (re-materialized) state
            # survives the donated first step — the PR-7 crash shape
            # on the elastic restore path.
            m = restored.train_one_epoch(2)
            assert np.isfinite(m["loss"])
        finally:
            restored.close()
    finally:
        source.close()


# ---------------------------------------------------- grad accumulation


@pytest.mark.slow
def test_grad_accum_matches_full_batch_lm():
    """No augmentation and no dropout in the LM path -> accumulated
    microbatch gradients must reproduce the full-batch update exactly
    (up to float32 reassociation)."""
    base_t, base_e, base_p = _run(_lm_cfg(MeshConfig(data=8)))
    acc_t, acc_e, acc_p = _run(_lm_cfg(MeshConfig(data=8), grad_accum=2))
    assert base_t["count"] == acc_t["count"]
    assert abs(base_t["loss"] - acc_t["loss"]) < 1e-4
    assert abs(base_e["loss"] - acc_e["loss"]) < 1e-4
    np.testing.assert_allclose(
        np.asarray(base_p["embed"]["embedding"]),
        np.asarray(acc_p["embed"]["embedding"]),
        rtol=2e-4, atol=2e-5)


def test_grad_accum_image_model_smoke():
    """Image steps draw fresh augmentation RNG per microbatch, so exact
    parity is not expected — the step must still run, count every
    example once, and stay finite (BN stats threaded through the scan)."""
    t, e, _ = _run(_vit_cfg(MeshConfig(data=4), grad_accum=4, batch=32))
    assert t["count"] == 128.0  # 4 batches/epoch x 32
    assert np.isfinite(t["loss"]) and np.isfinite(e["loss"])


def test_grad_accum_composes_with_fsdp():
    t, _, _ = _run(_lm_cfg(MeshConfig(data=8, fsdp=True), grad_accum=2))
    assert np.isfinite(t["loss"])


def test_grad_accum_validation():
    with pytest.raises(ValueError, match="not divisible"):
        Trainer(_vit_cfg(MeshConfig(data=8), grad_accum=3, batch=32))
    with pytest.raises(ValueError, match="data-axis"):
        Trainer(_vit_cfg(MeshConfig(data=8), grad_accum=8, batch=32))
    with pytest.raises(ValueError, match=">= 1"):
        Trainer(_vit_cfg(MeshConfig(data=8), grad_accum=0, batch=32))


def test_cli_flags():
    from tpunet.config import config_from_args
    cfg = config_from_args(["--fsdp", "--grad-accum", "4"])
    assert cfg.mesh.fsdp and cfg.optim.grad_accum == 4
