"""Fused conv-BN-ReLU6 epilogue + block-remat policy tests
(tpunet/models/mobilenetv2.py).

The fused path must be a drop-in for the nn.BatchNorm path: identical
variable trees (checkpoints/converted torch weights interchangeable),
matching outputs and running-stat updates up to FP reassociation, bf16
residency on the written activation, and gradients that flow through
the saved-residual (remat) policy end to end.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from tpunet.config import ModelConfig
from tpunet.models import create_model, init_variables
from tpunet.models.mobilenetv2 import FusedBNAct, InvertedResidual


def _bn_pair(dtype):
    """(FusedBNAct with clamp, nn.BatchNorm + clamp) sharing params."""
    fused = FusedBNAct(act=True, dtype=dtype)

    class Legacy(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=dtype, name="bn")(x)
            return jnp.minimum(jnp.maximum(x, 0.0), 6.0)

    return fused, Legacy()


@pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 1e-5),
                                        (jnp.bfloat16, 2e-2)])
def test_fused_bn_matches_flax_batchnorm(dtype, rtol):
    fused, legacy = _bn_pair(dtype)
    x = jax.random.normal(jax.random.PRNGKey(0), (4, 8, 8, 16), dtype)
    vf = fused.init(jax.random.PRNGKey(1), x)
    vl = legacy.init(jax.random.PRNGKey(1), x)
    # Same variable layout under the 'bn' name (fused is itself the
    # module here, so lift its tree under 'bn' for comparison).
    assert set(vf["params"]) == {"scale", "bias"}
    assert set(vf["batch_stats"]) == {"mean", "var"}
    # Seed non-trivial affine params + stats so eval mode is exercised.
    key = jax.random.PRNGKey(2)
    scale = 0.5 + jax.random.uniform(key, (16,))
    vf = {"params": {"scale": scale, "bias": scale * 0.1},
          "batch_stats": {"mean": scale * 0.2, "var": scale}}
    vl = {"params": {"bn": vf["params"]},
          "batch_stats": {"bn": vf["batch_stats"]}}

    # Train mode: outputs and the mutated running stats must agree.
    yf, mf = fused.apply(vf, x, True, mutable=["batch_stats"])
    yl, ml = legacy.apply(vl, x, True, mutable=["batch_stats"])
    assert yf.dtype == jnp.dtype(dtype)  # bf16 residency
    np.testing.assert_allclose(np.asarray(yf, np.float32),
                               np.asarray(yl, np.float32),
                               rtol=rtol, atol=rtol)
    for k in ("mean", "var"):
        np.testing.assert_allclose(
            np.asarray(mf["batch_stats"][k]),
            np.asarray(ml["batch_stats"]["bn"][k]), rtol=1e-5, atol=1e-6)

    # Eval mode: running-stat normalization parity.
    yf = fused.apply(vf, x, False)
    yl = legacy.apply(vl, x, False)
    np.testing.assert_allclose(np.asarray(yf, np.float32),
                               np.asarray(yl, np.float32),
                               rtol=rtol, atol=rtol)


def test_fused_bn_output_clamped():
    fused = FusedBNAct(act=True, dtype=jnp.float32)
    x = 100.0 * jax.random.normal(jax.random.PRNGKey(0), (2, 4, 4, 8))
    v = fused.init(jax.random.PRNGKey(1), x)
    y = np.asarray(fused.apply(v, x, True, mutable=["batch_stats"])[0])
    assert y.min() >= 0.0 and y.max() <= 6.0


def test_model_variable_tree_invariant_under_flags():
    """fused_bn/block_remat must not change the checkpoint contract."""
    base = ModelConfig(dtype="float32", width_mult=0.5,
                       fused_bn=False, block_remat=False)
    ref = init_variables(create_model(base), jax.random.PRNGKey(0),
                         image_size=32)
    for flags in ({"fused_bn": True},
                  {"block_remat": True},
                  {"fused_bn": True, "block_remat": True}):
        cfg = dataclasses.replace(base, **flags)
        v = init_variables(create_model(cfg), jax.random.PRNGKey(0),
                           image_size=32)
        assert (jax.tree_util.tree_structure(ref)
                == jax.tree_util.tree_structure(v)), flags


def test_model_logits_parity_across_flags():
    base = ModelConfig(dtype="float32", width_mult=0.5,
                       fused_bn=False, block_remat=False)
    ref_model = create_model(base)
    v = init_variables(ref_model, jax.random.PRNGKey(0), image_size=32)
    # Batch 8, not 2: at 32px the late blocks have 1x1 spatial maps,
    # so a batch-2 BN reduces over TWO samples — near-zero variances
    # make rsqrt amplify reassociation-level noise chaotically.
    x = jax.random.normal(jax.random.PRNGKey(1), (8, 32, 32, 3))
    want_eval = ref_model.apply(v, x, train=False)
    want_train, want_stats = ref_model.apply(
        v, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)},
        mutable=["batch_stats"])
    for flags in ({"fused_bn": True},
                  {"fused_bn": True, "block_remat": True}):
        model = create_model(dataclasses.replace(base, **flags))
        got = model.apply(v, x, train=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want_eval),
                                   rtol=1e-4, atol=1e-4, err_msg=str(flags))
        got, stats = model.apply(
            v, x, train=True, rngs={"dropout": jax.random.PRNGKey(2)},
            mutable=["batch_stats"])
        # FP reassociation through 35 stacked BN layers: ~1e-3 drift
        # in float32 is expected, structural divergence is not.
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(want_train),
                                   rtol=2e-2, atol=2e-3, err_msg=str(flags))
        for p, q in zip(jax.tree_util.tree_leaves(want_stats),
                        jax.tree_util.tree_leaves(stats)):
            np.testing.assert_allclose(np.asarray(p), np.asarray(q),
                                       rtol=1e-3, atol=1e-4)


def test_gradient_flow_through_rematted_inverted_residual():
    """End-to-end gradient parity through a full inverted-residual
    block: fused epilogue + saved-residual policy vs the reference
    path, including the residual add (stride 1, equal channels)."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 8, 16))

    def grads(fused_bn, remat):
        block = InvertedResidual(features=16, stride=1, expand_ratio=6,
                                 fused_bn=fused_bn, dtype=jnp.float32)
        if remat:
            block = nn.remat(
                InvertedResidual, static_argnums=(2,),
                policy=jax.checkpoint_policies.save_only_these_names(
                    "tpunet_convout", "tpunet_bn_stats"))(
                features=16, stride=1, expand_ratio=6,
                fused_bn=fused_bn, dtype=jnp.float32)
        v = block.init(jax.random.PRNGKey(1), x, True)

        def loss(params):
            y, _ = block.apply(
                {"params": params, "batch_stats": v["batch_stats"]},
                x, True, mutable=["batch_stats"])
            return jnp.mean(y ** 2)

        return v, jax.grad(loss)(v["params"])

    v_ref, g_ref = grads(fused_bn=False, remat=False)
    v_new, g_new = grads(fused_bn=True, remat=True)
    assert (jax.tree_util.tree_structure(g_ref)
            == jax.tree_util.tree_structure(g_new))
    gmax = max(float(jnp.max(jnp.abs(p)))
               for p in jax.tree_util.tree_leaves(g_ref)) or 1.0
    for p, q in zip(jax.tree_util.tree_leaves(g_ref),
                    jax.tree_util.tree_leaves(g_new)):
        # normalized by the global gradient scale: near-zero leaves
        # must not inflate a pure-reassociation difference
        assert float(jnp.max(jnp.abs(p - q))) / gmax < 1e-3


def test_remat_policy_saves_only_named_residuals():
    """The block-remat jaxpr must not carry activation-sized autodiff
    residuals besides the named conv outputs: differentiate a
    two-block stack and check the saved values crossing the remat
    boundary are only conv outputs / (C,)-stats / block inputs."""
    cfg = ModelConfig(dtype="float32", width_mult=0.5,
                      fused_bn=True, block_remat=True)
    model = create_model(cfg)
    v = init_variables(model, jax.random.PRNGKey(0), image_size=32)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 32, 32, 3))

    def loss(params):
        y, _ = model.apply(
            {"params": params, "batch_stats": v["batch_stats"]},
            x, train=True, rngs={"dropout": jax.random.PRNGKey(2)},
            mutable=["batch_stats"])
        return jnp.sum(y ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(loss))(v["params"])
    text = str(jaxpr)
    # the policy is active: remat equations carry the checkpoint names
    assert "checkpoint_name" in text or "remat" in text
