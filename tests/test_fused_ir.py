"""Fused inverted-residual 1x1 kernel pair tests (tpunet/ops/fused_ir.py
+ its model integration behind ModelConfig.fused_ir).

The contract under test:

- the Pallas forward/backward pair (exercised via ``interpret=True`` on
  CPU) is numerically identical to ``jax.vjp`` of the XLA reference
  composition — logits AND gradients — across stride-1 / stride-2
  blocks, odd H/W, channel counts off the 128-lane multiple, bf16,
  residual-add and no-residual blocks;
- dispatch is per-shape and per-backend with the ``TPUNET_FUSED_IR_REF``
  escape hatch, and off-TPU the reference path makes ``fused_ir``
  on/off numerically indistinguishable;
- the variable tree is bit-compatible across the flag (checkpoints flip
  freely) and eval logits are bit-identical (eval never takes the
  fused path).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import ModelConfig
from tpunet.models import create_model
from tpunet.models.mobilenetv2 import InvertedResidual
from tpunet.ops import fused_ir


def _rand(key, shape, dtype, scale=1.0):
    return (scale * jax.random.normal(jax.random.PRNGKey(key), shape)
            ).astype(dtype)


def _rel_err(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return np.max(np.abs(a - b)) / (np.max(np.abs(a)) + 1e-6)


# ------------------------------------------------------- kernel parity

@pytest.mark.parametrize("act", [True, False])
@pytest.mark.parametrize(
    "shape,dtype,tol",
    [((2, 8, 8, 16, 24), jnp.float32, 1e-5),
     ((2, 7, 9, 13, 24), jnp.float32, 1e-5),    # odd H/W, off-lane Ci
     ((1, 5, 5, 8, 10), jnp.float32, 1e-5),     # off-lane Co
     ((2, 8, 8, 16, 24), jnp.bfloat16, 2e-2),
     ((2, 7, 7, 24, 16), jnp.bfloat16, 2e-2)])
def test_kernel_parity_fwd_and_grad(shape, dtype, tol, act):
    """Interpret-mode kernel pair vs jax.vjp of the XLA reference:
    outputs, batch stats, and all four input cotangents."""
    n, h, w, ci, co = shape
    x = _rand(0, (n, h, w, ci), dtype)
    wgt = _rand(1, (ci, co), dtype, scale=0.1)
    scale = 1.0 + 0.5 * _rand(2, (co,), jnp.float32)
    bias = 0.1 * _rand(3, (co,), jnp.float32)
    # Deterministic non-uniform cotangent; the loss reads only `out`
    # (the mean/var outputs feed the non-differentiated running-stat
    # update in the model — their cotangents are zero by contract).
    ct = jnp.cos(jnp.arange(n * h * w * co, dtype=jnp.float32)
                 ).reshape(n, h, w, co)

    def run(fn):
        def loss(x, wgt, scale, bias):
            out, mean, var = fn(x, wgt, scale, bias, act, 1e-5)
            return jnp.sum(out.astype(jnp.float32) * ct), (out, mean, var)
        (_, aux), grads = jax.value_and_grad(
            loss, argnums=(0, 1, 2, 3), has_aux=True)(x, wgt, scale, bias)
        return aux + grads

    ref = run(fused_ir.conv1x1_bn_act_reference)
    ker = run(functools.partial(fused_ir.conv1x1_bn_act, interpret=True))
    names = ("out", "mean", "var", "dx", "dw", "dscale", "dbias")
    for name, a, b in zip(names, ref, ker):
        assert _rel_err(a, b) < tol, (name, shape, _rel_err(a, b))


def test_kernel_output_dtype_and_shapes():
    x = _rand(0, (2, 8, 8, 16), jnp.bfloat16)
    w = _rand(1, (16, 24), jnp.bfloat16)
    out, mean, var = fused_ir.conv1x1_bn_act(
        x, w, jnp.ones(24), jnp.zeros(24), interpret=True)
    assert out.shape == (2, 8, 8, 24) and out.dtype == jnp.bfloat16
    assert mean.shape == (24,) and mean.dtype == jnp.float32
    assert var.shape == (24,) and var.dtype == jnp.float32
    assert bool(jnp.all(var >= 0.0))
    assert bool(jnp.all(out >= 0.0)) and bool(jnp.all(out <= 6.0))  # ReLU6


# ------------------------------------------------- block-level parity

def _block_pair(features, stride, in_features, dtype):
    mk = functools.partial(InvertedResidual, features, stride=stride,
                           expand_ratio=6, dtype=dtype)
    return mk(fused_ir=False), mk(fused_ir=True)


@pytest.mark.parametrize(
    "in_features,features,stride,hw,dtype,tol,floor",
    [(16, 16, 1, (8, 8), jnp.float32, 1e-3, 5e-4),  # residual add
     (16, 24, 1, (8, 8), jnp.float32, 1e-3, 5e-4),  # no residual
     (16, 24, 2, (9, 7), jnp.float32, 1e-3, 5e-4),  # stride-2, odd H/W
     (16, 16, 1, (8, 8), jnp.bfloat16, 3e-2, 5e-1)])
def test_block_parity_through_interpret_kernels(monkeypatch, in_features,
                                                features, stride, hw,
                                                dtype, tol, floor):
    """A full inverted-residual block (expand -> depthwise -> project,
    plus the residual add where shapes allow) run through the Pallas
    pair in interpret mode must match the fused_ir=False block — value
    and gradients wrt params and input. Gradient comparisons are
    normalized by each leaf's own scale with an absolute floor: at
    init several leaves (depthwise kernel, project bn bias, the input
    cotangent) are near zero BY CANCELLATION, where FP reassociation
    noise dominates any relative metric."""
    orig = fused_ir.conv1x1_bn_act
    monkeypatch.setattr(fused_ir, "conv1x1_bn_act",
                        functools.partial(orig, interpret=True))
    ref_blk, fused_blk = _block_pair(features, stride, in_features, dtype)
    x = _rand(0, (2, *hw, in_features), dtype)
    vs = ref_blk.init(jax.random.PRNGKey(1), x, True)

    def loss(blk, params, x):
        y, _ = blk.apply({"params": params,
                          "batch_stats": vs["batch_stats"]}, x, True,
                         mutable=["batch_stats"])
        return jnp.sum(y.astype(jnp.float32) ** 2)

    lr, (gr_p, gr_x) = jax.value_and_grad(
        functools.partial(loss, ref_blk), argnums=(0, 1))(vs["params"], x)
    lf, (gf_p, gf_x) = jax.value_and_grad(
        functools.partial(loss, fused_blk), argnums=(0, 1))(vs["params"], x)
    def close(a, b, what):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        atol = max(tol * float(np.max(np.abs(a))), floor)
        assert np.max(np.abs(a - b)) < atol, \
            (what, float(np.max(np.abs(a - b))), float(np.max(np.abs(a))))

    assert _rel_err(lr, lf) < tol
    close(gr_x, gf_x, "d input")
    for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(gr_p),
            jax.tree_util.tree_leaves_with_path(gf_p)):
        close(a, b, jax.tree_util.keystr(path))


def test_running_stats_update_parity():
    """The batch_stats mutation (running mean/var) matches across the
    flag — the kernel's stats feed the same flax update."""
    ref_blk, fused_blk = _block_pair(16, 1, 16, jnp.float32)
    x = _rand(0, (2, 8, 8, 16), jnp.float32)
    vs = ref_blk.init(jax.random.PRNGKey(1), x, True)
    _, mr = ref_blk.apply(vs, x, True, mutable=["batch_stats"])
    _, mf = fused_blk.apply(vs, x, True, mutable=["batch_stats"])
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        mr["batch_stats"], mf["batch_stats"])


# ------------------------------------------------------------ dispatch

def test_dispatch_off_tpu_is_reference(monkeypatch):
    assert jax.default_backend() != "tpu"
    assert not fused_ir.use_fused_ir_kernel((8, 28, 28, 96))


def test_dispatch_per_shape_on_tpu(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.delenv("TPUNET_FUSED_IR_REF", raising=False)
    # 112px..14px expand/project shapes pay (Ci < H*W)...
    assert fused_ir.use_fused_ir_kernel((512, 112, 112, 16))
    assert fused_ir.use_fused_ir_kernel((512, 14, 14, 96))
    # ...the 7px tail and the 320->1280 head keep the XLA emitter.
    assert not fused_ir.use_fused_ir_kernel((512, 7, 7, 160))
    assert not fused_ir.use_fused_ir_kernel((512, 7, 7, 320))


def test_escape_hatch_env_var(monkeypatch):
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setenv("TPUNET_FUSED_IR_REF", "1")
    assert not fused_ir.use_fused_ir_kernel((512, 112, 112, 16))
    # And the public op still runs (reference path) with the hatch set
    # on a "TPU" backend — no Pallas lowering is attempted.
    x = _rand(0, (1, 8, 8, 16), jnp.float32)
    w = _rand(1, (16, 24), jnp.float32)
    out, _, _ = fused_ir.conv1x1_bn_act(x, w, jnp.ones(24), jnp.zeros(24))
    ref, _, _ = fused_ir.conv1x1_bn_act_reference(
        x, w, jnp.ones(24), jnp.zeros(24), True, 1e-5)
    assert np.array_equal(np.asarray(out), np.asarray(ref))


# ----------------------------------------------- model-level contract

def _model_and_vars(fused_flag, block_remat=False, dtype="float32"):
    cfg = ModelConfig(width_mult=0.5, fused_ir=fused_flag,
                      block_remat=block_remat, dtype=dtype)
    model = create_model(cfg)
    x = _rand(0, (2, 32, 32, 3), jnp.float32)
    vs = model.init({"params": jax.random.PRNGKey(0),
                     "dropout": jax.random.PRNGKey(1)}, x, train=True)
    return model, vs, x


def test_variable_tree_invariant_across_flag():
    _, v_off, _ = _model_and_vars(False)
    _, v_on, _ = _model_and_vars(True)
    assert jax.tree_util.tree_structure(v_off) == \
        jax.tree_util.tree_structure(v_on)
    shapes_off = jax.tree_util.tree_map(lambda a: a.shape, v_off)
    shapes_on = jax.tree_util.tree_map(lambda a: a.shape, v_on)
    assert shapes_off == shapes_on


def test_eval_logits_bit_identical_across_flag():
    """Eval mode never takes the fused path, so flipping the flag on a
    checkpoint changes eval logits by ZERO bits."""
    m_off, vs, x = _model_and_vars(False)
    m_on, _, _ = _model_and_vars(True)
    out_off = m_off.apply(vs, x, train=False)
    out_on = m_on.apply(vs, x, train=False)
    assert np.array_equal(np.asarray(out_off), np.asarray(out_on))


def test_train_logits_parity_across_flag_off_tpu():
    """Off-TPU the dispatch runs the reference, whose ops mirror the
    unfused module path — train logits agree to FP-reassociation."""
    m_off, vs, x = _model_and_vars(False)
    m_on, _, _ = _model_and_vars(True)
    rngs = {"dropout": jax.random.PRNGKey(2)}
    out_off, _ = m_off.apply(vs, x, train=True, rngs=rngs,
                             mutable=["batch_stats"])
    out_on, _ = m_on.apply(vs, x, train=True, rngs=rngs,
                           mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(out_off), np.asarray(out_on),
                               rtol=1e-4, atol=1e-4)


def test_composes_with_block_remat():
    """fused_ir + block_remat: gradients flow and match the non-remat
    fused model (remat changes scheduling, not math)."""
    m_plain, vs, x = _model_and_vars(True, block_remat=False)
    m_remat, _, _ = _model_and_vars(True, block_remat=True)

    def loss(model, params):
        out, _ = model.apply({"params": params,
                              "batch_stats": vs["batch_stats"]},
                             x, train=True,
                             rngs={"dropout": jax.random.PRNGKey(2)},
                             mutable=["batch_stats"])
        return jnp.sum(out ** 2)

    g_plain = jax.grad(functools.partial(loss, m_plain))(vs["params"])
    g_remat = jax.grad(functools.partial(loss, m_remat))(vs["params"])
    # Remat replays change XLA fusion, hence rounding — reassociation
    # tolerance, amplified through 17 BN blocks.
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-3),
        g_plain, g_remat)
