"""Per-op HBM byte attribution + bytes-budget gate + phase attribution
(tpunet/obs/hlo_bytes.py, tpunet/obs/trace_phase.py,
scripts/check_bytes_budget.py)."""

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.obs import hlo_bytes
from tpunet.obs.trace_phase import phase_times

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

from check_bytes_budget import check_record  # noqa: E402


# ---------------------------------------------------------------- parser

def test_parsed_total_tracks_cost_analysis():
    """The text-parsed byte total must track XLA's own cost analysis
    on a real compiled module (same accounting model)."""

    @jax.jit
    def f(x, w):
        with jax.named_scope("tpunet_fwd_bwd"):
            y = jax.nn.relu(x @ w)
        with jax.named_scope("tpunet_optimizer"):
            return y * 2.0 + 1.0, jnp.sum(y)

    x = jnp.ones((256, 128))
    w = jnp.ones((128, 64))
    compiled = f.lower(x, w).compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    want = float(ca.get("bytes accessed", 0.0))
    got = hlo_bytes.breakdown(compiled.as_text())["total"]
    assert want > 0 and abs(got - want) / want < 0.05


def test_breakdown_categories_and_gauges():
    @jax.jit
    def f(x, w):
        return jnp.sum(x @ w)

    compiled = f.lower(jnp.ones((64, 32)), jnp.ones((32, 16))).compile()
    per_image = hlo_bytes.per_image_breakdown(compiled.as_text(), 64)
    assert per_image["total"] > 0
    assert set(per_image) - {"total"} <= set(hlo_bytes.CATEGORIES)

    from tpunet.obs.registry import Registry
    reg = Registry()
    hlo_bytes.emit_gauges(reg, per_image)
    snap = reg.snapshot()
    assert snap["hbm_bytes_per_image_total"] == float(per_image["total"])


def test_shape_bytes():
    assert hlo_bytes._shape_bytes("f32[8,16,16,32]{3,2,1,0}") \
        == 8 * 16 * 16 * 32 * 4
    assert hlo_bytes._shape_bytes("bf16[4,4]") == 32
    assert hlo_bytes._shape_bytes("f32[]") == 4
    assert hlo_bytes._shape_bytes("(f32[2], u8[3])") == 11
    assert hlo_bytes._shape_bytes("token[]") == 0


def test_categorize_markers():
    fwd = ("jit(train_step)/jit(main)/tpunet_fwd_bwd/jvp(MobileNetV2)/"
           "stem/conv/conv_general_dilated")
    bwd = ("jit(train_step)/jit(main)/tpunet_fwd_bwd/"
           "transpose(tpunet_fwd_bwd)/jvp(MobileNetV2)/stem/conv/"
           "conv_general_dilated")
    bn = ("jit(train_step)/jit(main)/tpunet_fwd_bwd/jvp(MobileNetV2)/"
          "stem/bn/reduce_sum")
    opt = "jit(train_step)/jit(main)/tpunet_optimizer/add"
    assert hlo_bytes.categorize("convolution", fwd) == "conv_fwd"
    assert hlo_bytes.categorize("convolution", bwd) == "conv_bwd"
    assert hlo_bytes.categorize("fusion", bn) == "bn"
    assert hlo_bytes.categorize("fusion", opt) == "optimizer"
    assert hlo_bytes.categorize("copy", "") == "copy_pad"
    assert hlo_bytes.categorize("all-reduce", "x") == "collective"
    assert hlo_bytes.phase_of(fwd) == "fwd"
    assert hlo_bytes.phase_of(bwd) == "bwd"
    assert hlo_bytes.phase_of(opt) == "optimizer"


# ----------------------------------------------------- phase attribution

def test_phase_times_from_hlo_stats_rows():
    rows = [
        {"Framework op name": "jit(s)/tpunet_fwd_bwd/jvp(M)/x",
         "Total self time (us)": "30"},
        {"Framework op name":
         "jit(s)/tpunet_fwd_bwd/transpose(tpunet_fwd_bwd)/jvp(M)/x",
         "Total self time (us)": "50"},
        {"Framework op name": "jit(s)/tpunet_optimizer/add",
         "Total self time (us)": "15"},
        {"Framework op name": "jit(s)/tpunet_ema/mul",
         "Total self time (us)": "5"},
        {"Framework op name": None, "Total self time (us)": "bad"},
    ]
    out = phase_times(rows)
    assert out["fwd"]["us"] == 30 and out["bwd"]["us"] == 50
    assert out["optimizer"]["us"] == 15 and out["ema"]["us"] == 5
    assert abs(sum(r["pct"] for r in out.values()) - 100.0) < 0.1
    assert list(out)[0] == "bwd"  # ordered by time


def test_obs_report_trace_degrades_without_xprof(tmp_path):
    """--trace on a box without xprof (this CI) must degrade to a
    note, not a crash."""
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    import obs_report
    phases, notes = obs_report.device_phases(str(tmp_path))
    assert phases is None and any("unavailable" in n for n in notes)


# ----------------------------------------------------------- budget gate

def _record(measured, kind="TPU v5 lite", breakdown=None):
    return {"device_kind": kind,
            "xla_bytes_accessed_per_image": measured,
            "bytes_per_image_breakdown": breakdown}


def _budget(budgeted, tol=5, breakdown=None):
    entry = {"xla_bytes_accessed_per_image": budgeted}
    if breakdown:
        entry["breakdown"] = breakdown
    return {"tolerance_pct": tol, "budgets": {"TPU v5 lite": entry}}


def test_budget_within_tolerance_passes():
    ok, msgs = check_record(_record(103e6), _budget(100e6))
    assert ok and any("OK" in m for m in msgs)


def test_budget_regression_fails():
    ok, msgs = check_record(_record(106e6), _budget(100e6))
    assert not ok and any("REGRESSION" in m for m in msgs)


def test_budget_unknown_device_passes_with_note():
    ok, msgs = check_record(_record(999e6, kind="cpu"), _budget(100e6))
    assert ok and any("no bytes budget" in m for m in msgs)


def test_budget_missing_measurement_skips():
    ok, msgs = check_record(_record(None), _budget(100e6))
    assert ok and any("no measurement" in m for m in msgs)


def test_budget_breakdown_category_gate():
    rec = _record(100e6, breakdown={"conv_bwd": 50e6})
    ok, _ = check_record(rec, _budget(100e6, breakdown={"conv_bwd": 45e6}))
    assert not ok
    ok, _ = check_record(rec, _budget(100e6, breakdown={"conv_bwd": 49e6}))
    assert ok


def _load_checked_in_budget():
    with open(os.path.join(REPO, "docs", "bytes_budget.json")) as fp:
        return json.load(fp)


def _bench_artifacts():
    """[(round, parsed record)] for every BENCH_r*.json in the repo
    root, oldest first. BENCH_rN measures the tree AFTER PR N-1."""
    import glob
    import re
    out = []
    for path in glob.glob(os.path.join(REPO, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", path)
        if not m:
            continue
        with open(path) as fp:
            data = json.load(fp)
        rec = data.get("parsed") if isinstance(data, dict) else None
        if isinstance(rec, dict):
            out.append((int(m.group(1)), rec))
    return sorted(out, key=lambda t: t[0])


def test_checked_in_budget_file_is_valid():
    """Structural validity: positive totals, real category names, and
    category budgets that sum to no more than the total allows."""
    budget = _load_checked_in_budget()
    tol = budget["tolerance_pct"] / 100.0
    assert tol > 0
    for kind, entry in budget["budgets"].items():
        total = entry["xla_bytes_accessed_per_image"]
        assert total > 0, kind
        bd = {k: v for k, v in (entry.get("breakdown") or {}).items()
              if not k.startswith("_")}
        assert set(bd) <= set(hlo_bytes.CATEGORIES), (kind, set(bd))
        assert all(v > 0 for v in bd.values()), (kind, bd)
        assert sum(bd.values()) <= total * (1 + tol), \
            (kind, sum(bd.values()), total)


def test_budget_vs_latest_bench_artifact():
    """Budget/measurement drift fails tier-1 instead of waiting for a
    slow bench run: every BENCH_r* artifact measuring this-or-newer
    trees (round > the entry's as_of_round; BENCH_rN measures the
    tree after PR N-1) must PASS the checked-in budget, and the budget
    must not sit above the latest matching measurement (a stale or
    wishful budget would mask regressions)."""
    budget = _load_checked_in_budget()
    tol = budget["tolerance_pct"] / 100.0
    arts = _bench_artifacts()
    assert arts, "no BENCH_r*.json artifacts found"
    for kind, entry in budget["budgets"].items():
        matching = [(rnd, rec) for rnd, rec in arts
                    if kind.lower() in (rec.get("device_kind") or "").lower()]
        if not matching:
            continue
        # Drift gate: artifacts measuring the budgeted tree (or newer).
        for rnd, rec in matching:
            if rnd > entry.get("as_of_round", 0):
                ok, msgs = check_record(rec, budget)
                assert ok, (f"BENCH_r{rnd:02d} fails the checked-in "
                            f"budget — ratchet/reconcile "
                            f"docs/bytes_budget.json", msgs)
        # Staleness gate: the budget may anticipate a measured lever
        # (ratchet + as_of_round bump) but never EXCEED the last
        # measured reality by more than tolerance.
        latest_total = matching[-1][1].get("xla_bytes_accessed_per_image")
        if latest_total:
            assert entry["xla_bytes_accessed_per_image"] <= \
                latest_total * (1 + tol), \
                (kind, entry["xla_bytes_accessed_per_image"], latest_total)


def test_bench_model_overrides_last_flag_wins():
    """Repeated lever flags resolve last-wins in argv order, matching
    the train CLI's argparse BooleanOptionalAction — a sweep script
    appending an override to a base command gets the appended state."""
    import bench
    assert bench._model_overrides(["--no-fused-ir", "--fused-ir"]) == \
        {"fused_ir": True}
    assert bench._model_overrides(["--fused-ir", "--no-fused-ir"]) == \
        {"fused_ir": False}
    assert bench._model_overrides(["--peak-only"]) == {}
    assert bench._model_overrides(["--block-remat", "--no-fused-bn"]) == \
        {"block_remat": True, "fused_bn": False}


def test_bench_enforce_budget_refuses_lever_overrides(monkeypatch,
                                                      capsys):
    """--enforce-budget gates the default tree; combined with a lever
    override it would gate a deliberately non-default configuration
    against the default budget (false REGRESSION) — bench refuses
    loudly with exit 2 instead."""
    import bench
    monkeypatch.setattr(sys, "argv",
                        ["bench.py", "--peak-only", "--no-fused-ir",
                         "--enforce-budget"])
    with pytest.raises(SystemExit) as ei:
        bench.main()
    assert ei.value.code == 2
    assert "refusing with lever overrides" in capsys.readouterr().err


# ------------------------------------------------------------- end-to-end

@pytest.mark.slow
def test_bench_smoke_emits_breakdown_and_enforces_budget(tmp_path):
    """bench.py --smoke --enforce-budget: the JSON carries the
    bytes_per_image_breakdown field tracking xla_bytes_accessed, and
    the gate exits 0 on CPU (no CPU budget to enforce)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--smoke",
         "--enforce-budget"],
        capture_output=True, text=True, env=env, cwd=REPO, timeout=800)
    assert out.returncode == 0, out.stderr[-2000:]
    rec = json.loads([ln for ln in out.stdout.splitlines()
                      if ln.startswith("{")][-1])
    bd = rec["bytes_per_image_breakdown"]
    assert bd and bd["total"] > 0
    assert abs(bd["total"] - rec["xla_bytes_accessed_per_image"]) \
        / rec["xla_bytes_accessed_per_image"] < 0.05
    assert "nothing to enforce" in out.stderr


def test_async_collectives_counted_once_as_collective():
    assert hlo_bytes.categorize("all-reduce-start", "") == "collective"
    assert hlo_bytes.categorize("collective-permute-start", "") \
        == "collective"
    text = """HloModule m

ENTRY %main.1 (p0: f32[256]) -> f32[256] {
  %p0 = f32[256]{0} parameter(0)
  %ars = f32[256]{0} all-reduce-start(f32[256]{0} %p0), to_apply=%add
  %ard = f32[256]{0} all-reduce-done(f32[256]{0} %ars)
  ROOT %mul = f32[256]{0} multiply(f32[256]{0} %ard, f32[256]{0} %ard)
}
"""
    rows = list(hlo_bytes.instruction_bytes(text))
    cats = {cat for _op, cat, _b, _n in rows}
    assert "collective" in cats
    coll = sum(b for _op, cat, b, _n in rows if cat == "collective")
    assert coll == 2 * 256 * 4  # the -start's operand+output, ONCE


def test_budget_cli_accepts_pretty_printed_artifact(tmp_path, capsys):
    """The documented `check_bytes_budget.py BENCH_r05.json` invocation
    must parse the pretty-printed driver artifact, not crash. (Checked
    against r05's own value, not the checked-in budget — the ratcheted
    budget describes a NEWER tree than the r05 artifact measures.)"""
    from check_bytes_budget import main as budget_main
    b = tmp_path / "budget.json"
    b.write_text(json.dumps(_budget(139e6)))
    rc = budget_main([os.path.join(REPO, "BENCH_r05.json"),
                      "--budget", str(b)])
    out = capsys.readouterr().out
    assert rc == 0 and "xla_bytes_accessed_per_image" in out


def test_budget_cli_flag_order_and_missing_value(tmp_path, capsys):
    """--budget may precede or follow the record path (mirroring
    check_serve_budget); a trailing --budget with no value or a
    missing record path is a usage error, not a crash."""
    from check_bytes_budget import main as budget_main
    b = tmp_path / "budget.json"
    b.write_text(json.dumps(_budget(139e6)))
    art = os.path.join(REPO, "BENCH_r05.json")
    assert budget_main(["--budget", str(b), art]) == 0
    assert budget_main([art, "--budget", str(b)]) == 0
    assert budget_main([art, "--budget"]) == 2
    assert budget_main(["--budget", str(b)]) == 2  # no record path


def test_budget_breakdown_annotation_keys_and_missing_breakdown():
    """'_'-prefixed breakdown keys are annotations (never gated), and
    a record with no breakdown at all passes budgeted categories with
    a note — the r05-style artifact predates the field."""
    bud = _budget(100e6, breakdown={"_source": "estimate",
                                    "conv_bwd": 45e6})
    ok, msgs = check_record(_record(100e6, breakdown=None), bud)
    assert ok and any("no bytes_per_image_breakdown" in m for m in msgs)
    assert not any("_source" in m for m in msgs)
    ok, _ = check_record(_record(100e6, breakdown={"conv_bwd": 44e6}), bud)
    assert ok
    ok, _ = check_record(_record(100e6, breakdown={"conv_bwd": 50e6}), bud)
    assert not ok


def test_augment_scope_gets_its_own_bucket():
    aug = ("jit(train_step)/jit(main)/tpunet_fwd_bwd/tpunet_augment/"
           "dot_general")
    assert hlo_bytes.categorize("dot", aug) == "augment"
    assert hlo_bytes.phase_of(aug) == "augment"
    # ...and it shows up end to end in a real train-step lowering.
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.parallel import shard_host_batch
    from tpunet.train.loop import Trainer
    from tpunet.utils.prng import step_key
    batch = 8
    cfg = TrainConfig(
        data=DataConfig(dataset="synthetic", batch_size=batch,
                        image_size=32),
        model=ModelConfig(width_mult=0.5, dtype="float32"),
        optim=OptimConfig(), mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False))
    t = Trainer(cfg, dataset=synthetic_cifar10(n_train=2 * batch,
                                               n_test=batch))
    try:
        rng = np.random.default_rng(0)
        x = rng.integers(0, 256, size=(batch, 32, 32, 3), dtype=np.uint8)
        y = rng.integers(0, 10, size=batch).astype(np.int32)
        gx, gy = shard_host_batch(t.mesh, x, y)
        compiled = t.train_step.lower(t.state, gx, gy,
                                      step_key(0, 0)).compile()
        bd = hlo_bytes.breakdown(compiled.as_text())
        assert bd.get("augment", 0) > 0
    finally:
        t.close()
