"""Inference tests (reference C13/C14 parity: top-k, confidence
threshold, checkpoint loading, serve-time preprocessing)."""

import numpy as np
import pytest

from tpunet.config import CheckpointConfig, DataConfig, ModelConfig
from tpunet.infer.predict import Predictor
from tpunet.train.loop import Trainer

from test_train import tiny_config, tiny_dataset  # noqa: F401

SMALL_MODEL = ModelConfig(dtype="float32", width_mult=0.5)
SMALL_DATA = DataConfig(image_size=32)


@pytest.fixture(scope="module")
def predictor():
    return Predictor(model_cfg=SMALL_MODEL, data_cfg=SMALL_DATA)


def test_probs_sum_to_one(predictor):
    img = np.random.default_rng(0).integers(
        0, 256, size=(48, 64, 3), dtype=np.uint8)  # arbitrary input size
    probs = predictor.predict_probs(img)
    assert probs.shape == (10,)
    assert np.isclose(probs.sum(), 1.0, atol=1e-5)


def test_topk_ordering_and_threshold(predictor):
    img = np.zeros((32, 32, 3), np.uint8)
    res = predictor.predict(img, topk=3, conf_threshold=0.5)
    assert len(res.topk) == 3
    assert res.topk[0][1] >= res.topk[1][1] >= res.topk[2][1]
    # Untrained model ~ uniform probs (~0.1 each) -> below 0.5 threshold.
    assert res.uncertain and res.predicted == "uncertain"
    # With threshold 0 the argmax class is reported.
    res2 = predictor.predict(img, topk=3, conf_threshold=0.0)
    assert not res2.uncertain
    assert res2.predicted == res2.topk[0][0]


def test_pil_and_float_inputs(predictor):
    from PIL import Image
    arr = np.random.default_rng(1).integers(
        0, 256, size=(32, 32, 3), dtype=np.uint8)
    p1 = predictor.predict_probs(Image.fromarray(arr))
    p2 = predictor.predict_probs(arr)
    p3 = predictor.predict_probs(arr.astype(np.float32) / 255.0)
    np.testing.assert_allclose(p1, p2, atol=1e-6)
    np.testing.assert_allclose(p1, p3, atol=0.02)  # uint8 quantization


@pytest.mark.slow
def test_predictor_loads_best_checkpoint(tmp_path, tiny_dataset):  # noqa: F811
    cfg = tiny_config(tmp_path, epochs=1).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck")))
    t = Trainer(cfg, dataset=tiny_dataset)
    t.train()
    t.ckpt.close()
    pred = Predictor(model_cfg=cfg.model, data_cfg=cfg.data,
                     checkpoint_dir=str(tmp_path / "ck"))
    tp = np.asarray(t.state.params["classifier"]["kernel"])
    pp = np.asarray(pred.variables["params"]["classifier"]["kernel"])
    np.testing.assert_allclose(tp, pp)


def test_missing_checkpoint_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        Predictor(model_cfg=SMALL_MODEL, data_cfg=SMALL_DATA,
                  checkpoint_dir=str(tmp_path / "nope"))


@pytest.mark.slow
def test_web_app_classify_end_to_end(tmp_path, tiny_dataset):  # noqa: F811
    """Drive the EXACT function the web UI serves (app.make_classify,
    what gr.Interface(fn=...) wraps) against a trained checkpoint: PIL
    image in -> {class: prob} dict out, the gr.Label top-3 input format
    (reference app, GROUP03.pdf pp.22-23)."""
    from PIL import Image

    from tpunet.infer import app

    cfg = tiny_config(tmp_path, epochs=1).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck")))
    t = Trainer(cfg, dataset=tiny_dataset)
    t.train()
    t.ckpt.close()
    pred = Predictor(model_cfg=cfg.model, data_cfg=cfg.data,
                     checkpoint_dir=str(tmp_path / "ck"))
    classify = app.make_classify(pred)

    img = Image.fromarray(np.asarray(tiny_dataset[0][0]))
    out = classify(img)
    # gr.Label input contract: full {class name: float prob} mapping.
    assert set(out) == set(pred.class_names)
    assert all(isinstance(v, float) for v in out.values())
    assert np.isclose(sum(out.values()), 1.0, atol=1e-5)
    # and the dict agrees with the Predictor's own top-k path
    res = pred.predict(img, topk=3, conf_threshold=0.0)
    assert res.topk[0][0] == max(out, key=out.get)
    # the cleared-input path the UI also exercises
    assert classify(None) == {}


def test_gradio_gated():
    # gradio isn't installed here: the app module must fail with a clear
    # ImportError, not crash at import time.
    from tpunet.infer import app
    pred = Predictor(model_cfg=SMALL_MODEL, data_cfg=SMALL_DATA)
    try:
        import gradio  # noqa: F401
    except ImportError:
        with pytest.raises(ImportError, match="gradio"):
            app.build_interface(pred)
