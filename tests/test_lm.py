"""LM family: causality, learnability on the synthetic bigram data,
ring-attention sequence parallelism, generation, and MoE composition."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.data.lm import synthetic_lm
from tpunet.models import create_model, init_variables
from tpunet.train.loop import Trainer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=32,
                     max_seq_len=64)


@pytest.mark.slow
def test_forward_shape_and_causality():
    model = create_model(LM_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=16)
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 32, (2, 16)), jnp.int32)
    logits = model.apply(variables, toks, train=False)
    assert logits.shape == (2, 16, 32)
    # Causality: changing a future token must not affect earlier logits.
    toks2 = toks.at[:, 10].set((toks[:, 10] + 1) % 32)
    logits2 = model.apply(variables, toks2, train=False)
    np.testing.assert_allclose(np.asarray(logits[:, :10]),
                               np.asarray(logits2[:, :10]),
                               rtol=1e-5, atol=1e-5)
    assert np.abs(np.asarray(logits[:, 10:] - logits2[:, 10:])).max() > 1e-4


def test_bigram_data_has_learnable_structure():
    tx, _, _, _ = synthetic_lm(64, 8, seq_len=128, vocab=32)
    assert tx.shape == (64, 128) and tx.min() >= 0 and tx.max() < 32
    # the preferred-successor structure: most common bigram per token
    # covers well over uniform probability
    from collections import Counter
    pairs = Counter(zip(tx[:, :-1].ravel(), tx[:, 1:].ravel()))
    tot = Counter()
    for (a, _b), c in pairs.items():
        tot[a] += c
    top_frac = np.mean([max(c for (a, _), c in pairs.items() if a == t)
                        / tot[t] for t in range(32)])
    assert top_frac > 0.5  # ~0.8 by construction


def _cfg(mesh_cfg, epochs=3, **model_kw):
    return TrainConfig(
        epochs=epochs,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=256, synthetic_test_size=32,
                        seq_len=64, vocab_size=32),
        model=dataclasses.replace(LM_CFG, **model_kw),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


@pytest.mark.slow
def test_lm_learns_bigram_structure():
    trainer = Trainer(_cfg(MeshConfig(data=2)))
    try:
        first = trainer.train_one_epoch(1)
        for e in range(2, 4):
            last = trainer.train_one_epoch(e)
        ev = trainer.evaluate()
    finally:
        trainer.close()
    assert last["loss"] < first["loss"]
    # uniform guessing = 1/32 ~ 0.03; bigram ceiling ~0.8
    assert ev["accuracy"] > 0.3
    assert ev["count"] == 32 * 63  # exact token count


@pytest.mark.slow
def test_lm_ring_attention_parity():
    base = Trainer(_cfg(MeshConfig(data=2), epochs=1))
    try:
        base_m = base.train_one_epoch(1)
    finally:
        base.close()
    ring = Trainer(_cfg(MeshConfig(data=2, seq=4), epochs=1,
                        attention="ring"))
    try:
        ring_m = ring.train_one_epoch(1)
    finally:
        ring.close()
    assert abs(base_m["loss"] - ring_m["loss"]) < 1e-4
    assert abs(base_m["accuracy"] - ring_m["accuracy"]) < 1e-6


@pytest.mark.slow
def test_lm_ulysses_attention_parity():
    base = Trainer(_cfg(MeshConfig(data=2), epochs=1))
    try:
        base_m = base.train_one_epoch(1)
    finally:
        base.close()
    uly = Trainer(_cfg(MeshConfig(data=2, seq=4), epochs=1,
                       attention="ulysses"))
    try:
        uly_m = uly.train_one_epoch(1)
    finally:
        uly.close()
    assert abs(base_m["loss"] - uly_m["loss"]) < 1e-4
    assert abs(base_m["accuracy"] - uly_m["accuracy"]) < 1e-6


def test_lm_blockwise_long_sequence():
    cfg = _cfg(MeshConfig(data=2), epochs=1, attention="blockwise")
    cfg = cfg.replace(model=dataclasses.replace(cfg.model,
                                                attention_block=16))
    trainer = Trainer(cfg)
    try:
        m = trainer.train_one_epoch(1)
    finally:
        trainer.close()
    assert np.isfinite(m["loss"])


@pytest.mark.slow
def test_lm_moe_composes():
    trainer = Trainer(_cfg(MeshConfig(data=2, model=2), epochs=1,
                           moe_experts=4))
    try:
        m = trainer.train_one_epoch(1)
    finally:
        trainer.close()
    assert np.isfinite(m["loss"])


@pytest.mark.slow
def test_generation():
    from tpunet.models.lm import generate
    model = create_model(LM_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=8)
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    out = generate(model, variables, prompt, n_new=5)
    assert out.shape == (2, 8)
    assert (np.asarray(out[:, :3]) == np.asarray(prompt)).all()
    assert out.dtype == jnp.int32


@pytest.mark.slow
def test_kv_cache_generation_matches_full_recompute():
    """Incremental decoding (KV cache, O(L)/token) produces exactly the
    same greedy continuation as full-prefix recompute — for the dense
    LM always, and for the MoE LM at this scale (tiny batch -> no
    capacity drops; with drops, per-step routing may legitimately
    differ from whole-prefix routing — see generate()'s docstring)."""
    import dataclasses as dc

    from tpunet.models.lm import generate
    for kw in ({}, {"moe_experts": 4}):
        model = create_model(dc.replace(LM_CFG, **kw))
        variables = init_variables(model, jax.random.PRNGKey(1), seq_len=8)
        variables = {"params": variables["params"]}
        prompt = jnp.asarray([[7, 1, 4], [2, 2, 9]], jnp.int32)
        cached = generate(model, variables, prompt, n_new=5, use_cache=True)
        full = generate(model, variables, prompt, n_new=5, use_cache=False)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))


@pytest.mark.slow
def test_tp_sharded_generation_matches_single_chip():
    """Tensor-parallel serving (VERDICT round-2 item 9): load_lm with a
    'model'-axis mesh shards every block weight by the Megatron path
    rules and the KV cache by head; greedy decode tokens must EXACTLY
    match the unsharded path — for both an lm and an unstacked lm_pp
    checkpoint. Also: the head-divisibility guard fires loudly."""
    import dataclasses as dc

    from tpunet.config import MeshConfig
    from tpunet.infer.generate import load_lm
    from tpunet.models.lm import generate
    from tpunet.parallel import make_mesh

    mesh = make_mesh(MeshConfig(data=1, model=4))
    prompt = jnp.asarray([[7, 1, 4], [2, 2, 9]], jnp.int32)

    for name in ("lm", "lm_pp"):
        cfg = dc.replace(LM_CFG, name=name, vit_heads=4)
        # build the training-layout variables (stacked for lm_pp)
        train_model = create_model(cfg)
        variables = init_variables(train_model, jax.random.PRNGKey(2),
                                   seq_len=8)
        variables = {"params": variables["params"]}
        model, plain_vars = load_lm(cfg, variables=dict(variables))
        model_tp, tp_vars = load_lm(cfg, variables=dict(variables),
                                    mesh=mesh)
        # params really are sharded over 'model'
        qkv = tp_vars["params"]["block00"]["attn"]["qkv"]["kernel"]
        assert "model" in str(qkv.sharding), qkv.sharding
        # Token-exact equality holds at these pinned seeds/shapes; the
        # row-parallel psum reorders float reductions, so a near-tie
        # argmax COULD legitimately flip for other checkpoints — if
        # this ever fires after an unrelated change, compare logits
        # with a tolerance instead of assuming a TP bug.
        ref = generate(model, plain_vars, prompt, n_new=5)
        out = generate(model_tp, tp_vars, prompt, n_new=5, mesh=mesh)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    with pytest.raises(ValueError, match="heads"):
        load_lm(dc.replace(LM_CFG, vit_heads=3),
                variables={"params": {}}, mesh=mesh)


@pytest.mark.slow
def test_tp_serving_restores_directly_into_shardings(tmp_path):
    """The TP-serving load path for 'lm' checkpoints must never
    materialize the full tree on one device: the Orbax restore
    template is built SHARDED from eval_shape, and the restored params
    carry the TP shardings (and produce the same greedy tokens as the
    plain restore)."""
    import dataclasses as dc

    from tpunet.ckpt import Checkpointer
    from tpunet.config import CheckpointConfig, MeshConfig
    from tpunet.infer.generate import load_lm
    from tpunet.models.lm import generate
    from tpunet.parallel import make_mesh

    cfg = dc.replace(LM_CFG, vit_heads=4)
    model = create_model(cfg)
    variables = init_variables(model, jax.random.PRNGKey(3), seq_len=8)
    ck = Checkpointer(CheckpointConfig(directory=str(tmp_path)))
    ck.save_best({"params": variables["params"], "batch_stats": {}})
    ck.close()

    mesh = make_mesh(MeshConfig(data=1, model=4))
    model_tp, tp_vars = load_lm(cfg, checkpoint_dir=str(tmp_path),
                                mesh=mesh)
    qkv = tp_vars["params"]["block00"]["attn"]["qkv"]["kernel"]
    assert "model" in str(qkv.sharding), qkv.sharding
    model_1c, plain_vars = load_lm(cfg, checkpoint_dir=str(tmp_path))
    prompt = jnp.asarray([[7, 1, 4]], jnp.int32)
    ref = generate(model_1c, plain_vars, prompt, n_new=5)
    out = generate(model_tp, tp_vars, prompt, n_new=5, mesh=mesh)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
