"""Pipeline-parallel causal LM ("lm_pp"): parity with TransformerLM,
dp x pp training through the Trainer, pipelined dropout, and grad-accum
composition (VERDICT round-1 item 4)."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables
from tpunet.models.lm_pp import to_transformer_lm_params
from tpunet.parallel import make_mesh
from tpunet.train.loop import Trainer

LMPP_CFG = ModelConfig(name="lm_pp", vit_hidden=64, vit_depth=4,
                       vit_heads=4, dropout_rate=0.0, dtype="float32",
                       vocab_size=32, max_seq_len=32, pp_microbatches=4)


def _tokens(b=8, t=16, seed=0):
    return jnp.asarray(np.random.default_rng(seed).integers(
        0, 32, size=(b, t)), jnp.int32)


@pytest.mark.slow
def test_lmpp_matches_transformer_lm_logits():
    """The stacked/pipelined math == the flax-module TransformerLM with
    params unstacked by to_transformer_lm_params (causal mask, LN
    upcast, tied head — all pinned)."""
    pp_model = create_model(LMPP_CFG)
    variables = init_variables(pp_model, jax.random.PRNGKey(0), seq_len=16)
    lm_cfg = dataclasses.replace(LMPP_CFG, name="lm")
    lm_model = create_model(lm_cfg)
    lm_params = to_transformer_lm_params(variables["params"])
    toks = _tokens()
    a = pp_model.apply(variables, toks, train=False)
    b = lm_model.apply({"params": lm_params}, toks, train=False)
    assert a.shape == (8, 16, 32)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_lmpp_pipelined_matches_sequential():
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    pp_model = create_model(LMPP_CFG, mesh=mesh)
    seq_model = create_model(LMPP_CFG, mesh=None)
    variables = init_variables(seq_model, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    toks = _tokens()
    a = pp_model.apply(variables, toks, train=False)
    b = seq_model.apply(variables, toks, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_lmpp_causality():
    """Changing future tokens must not change past logits."""
    model = create_model(LMPP_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=16)
    toks = _tokens()
    mutated = toks.at[:, 10:].set((toks[:, 10:] + 7) % 32)
    a = model.apply(variables, toks, train=False)
    b = model.apply(variables, mutated, train=False)
    np.testing.assert_allclose(np.asarray(a[:, :10]),
                               np.asarray(b[:, :10]), rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(a[:, 10:]), np.asarray(b[:, 10:]))


@pytest.mark.slow
def test_lmpp_dropout_is_seeded_and_active():
    """train=True dropout: deterministic per rng, different across rngs,
    identity at rate 0 — both sequential and pipelined paths."""
    cfg = dataclasses.replace(LMPP_CFG, dropout_rate=0.3)
    toks = _tokens()
    for mesh in (None, make_mesh(MeshConfig(data=2, pipe=2))):
        model = create_model(cfg, mesh=mesh)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   batch_size=8, seq_len=16)
        run = lambda seed: np.asarray(model.apply(
            variables, toks, train=True,
            rngs={"dropout": jax.random.PRNGKey(seed)}))
        np.testing.assert_array_equal(run(1), run(1))
        assert not np.allclose(run(1), run(2))
        # train=False ignores dropout entirely (no rng needed)
        base = np.asarray(model.apply(variables, toks, train=False))
        assert not np.allclose(run(1), base)


def _cfg(mesh_cfg, accum=1, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        seq_len=32, vocab_size=32),
        model=dataclasses.replace(LMPP_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3, grad_accum=accum),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


def _run(cfg):
    tr = Trainer(cfg)
    try:
        train_m = tr.train_one_epoch(1)
        eval_m = tr.evaluate()
    finally:
        tr.close()
    return train_m, eval_m


@pytest.mark.slow
def test_lmpp_training_parity_with_dp_only():
    base_t, base_e = _run(_cfg(MeshConfig(data=2)))
    pp_t, pp_e = _run(_cfg(MeshConfig(data=2, pipe=4)))
    assert abs(base_t["loss"] - pp_t["loss"]) < 1e-4
    assert abs(base_e["loss"] - pp_e["loss"]) < 1e-4

    # stacked block params and Adam moments sharded over 'pipe'
    from jax.sharding import PartitionSpec as P
    tr = Trainer(_cfg(MeshConfig(data=2, pipe=4)))
    try:
        assert tr.state.params["blocks_qkv_k"].sharding.spec == P("pipe")
        mu = tr.state.opt_state[0].mu["blocks_qkv_k"]
        assert mu.sharding.spec == P("pipe")
    finally:
        tr.close()


@pytest.mark.slow
def test_grad_accum_composes_with_pipeline():
    """accum=2 over a dp x pp mesh gives the same loss/metrics as
    accum=1 (no BatchNorm in the LM -> exact composition), for both
    lm_pp and vit_pp (whose accum rejection this replaces)."""
    base_t, _ = _run(_cfg(MeshConfig(data=2, pipe=2)))
    acc_t, _ = _run(_cfg(MeshConfig(data=2, pipe=2), accum=2))
    assert abs(base_t["loss"] - acc_t["loss"]) < 1e-4

    vit_cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=64, synthetic_test_size=32),
        model=ModelConfig(name="vit_pp", vit_patch=4, vit_hidden=64,
                          vit_depth=4, vit_heads=4, dropout_rate=0.0,
                          dtype="float32", pp_microbatches=2),
        optim=OptimConfig(learning_rate=1e-3, grad_accum=2),
        mesh=MeshConfig(data=2, pipe=2),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    t, _ = _run(vit_cfg)
    assert np.isfinite(t["loss"])


@pytest.mark.slow
def test_grad_accum_pipeline_indivisible_raises():
    with pytest.raises(ValueError, match="pp_microbatches"):
        Trainer(_cfg(MeshConfig(data=2, pipe=2), accum=2,
                     pp_microbatches=8))


@pytest.mark.slow
def test_lmpp_checkpoint_serves_through_generate_cli(tmp_path, capsys):
    """Train pipelined, serve incrementally: an lm_pp best checkpoint
    loads through the generate CLI (--model lm_pp), unstacked into the
    KV-cache TransformerLM."""
    cfg = _cfg(MeshConfig(data=2, pipe=2)).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_last=False))
    tr = Trainer(cfg)
    try:
        tr.train()
    finally:
        tr.close()
    from tpunet.infer import generate as gen
    gen.main(["--checkpoint-dir", str(tmp_path / "ck"), "--model",
              "lm_pp", "--prompt", "5 7 3", "--tokens", "5",
              "--vit-hidden", "64", "--vit-depth", "4", "--vit-heads",
              "4", "--vocab-size", "32", "--max-seq-len", "32"])
    out = capsys.readouterr().out.strip().splitlines()[-1].split()
    assert out[:3] == ["5", "7", "3"] and len(out) == 8
    assert all(0 <= int(t) < 32 for t in out)


def test_attention_auto_resolves_by_backend():
    """attention='auto' picks flash on TPU and dense elsewhere, for the
    dense families; pipeline models accept it (their core is dense by
    construction)."""
    import jax

    from tpunet.models.vit import make_attn_fn

    fn = make_attn_fn(dataclasses.replace(LMPP_CFG, name="lm",
                                          attention="auto"), causal=True)
    expected = ("flash_attention"
                if jax.default_backend() == "tpu" else "dense_attention")
    assert fn.func.__name__ == expected
    create_model(dataclasses.replace(LMPP_CFG, attention="auto"))


def test_lmpp_rejects_unsupported_features():
    with pytest.raises(ValueError, match="dense"):
        create_model(dataclasses.replace(LMPP_CFG, attention="bogus"))
    with pytest.raises(ValueError, match="remat"):
        create_model(dataclasses.replace(LMPP_CFG, remat=True))
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    with pytest.raises(ValueError, match="divisible"):
        create_model(dataclasses.replace(LMPP_CFG, vit_depth=6), mesh=mesh)
    # MoE validation: whole super-layers, divisible across stages
    with pytest.raises(ValueError, match="moe_every"):
        create_model(dataclasses.replace(LMPP_CFG, moe_experts=4,
                                         vit_depth=4, moe_every=3))
    with pytest.raises(ValueError, match="super-layers"):
        create_model(dataclasses.replace(LMPP_CFG, moe_experts=4,
                                         vit_depth=4, moe_every=2),
                     mesh=mesh)  # 2 super-layers over 4 stages
    create_model(dataclasses.replace(LMPP_CFG, moe_experts=4,
                                     vit_depth=8, moe_every=2),
                 mesh=mesh)


# ---------------------------------------------------------------------------
# SP x PP: Ulysses / ring sequence parallelism inside the pipeline
# ---------------------------------------------------------------------------

def test_lmpp_sp_validation():
    for kind in ("ulysses", "ring"):
        with pytest.raises(ValueError, match="requires a mesh"):
            create_model(dataclasses.replace(LMPP_CFG, attention=kind))
    mesh = make_mesh(MeshConfig(data=2, seq=2, pipe=2))
    with pytest.raises(ValueError, match="heads"):
        create_model(dataclasses.replace(LMPP_CFG, attention="ulysses",
                                         vit_heads=3), mesh=mesh)
    # ring shards the sequence only — no head-divisibility constraint
    create_model(dataclasses.replace(LMPP_CFG, attention="ring",
                                     vit_heads=3, vit_hidden=63),
                 mesh=mesh)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ulysses", "ring"])
def test_lmpp_sp_pipelined_matches_dense(kind):
    """dp2 x sp2 x pp2: the SP-in-pipeline forward must equal the
    dense unsharded forward on the same params — the seq collectives
    (Ulysses' all-to-all pair / the ring's K/V rotation) and the
    seq-sharded executor path change the layout, never the math."""
    mesh = make_mesh(MeshConfig(data=2, seq=2, pipe=2))
    ucfg = dataclasses.replace(LMPP_CFG, attention=kind)
    u_model = create_model(ucfg, mesh=mesh)
    d_model = create_model(LMPP_CFG)           # dense, no mesh
    variables = init_variables(d_model, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    toks = _tokens()
    a = u_model.apply(variables, toks, train=False)
    b = d_model.apply(variables, toks, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ulysses", "ring"])
def test_lmpp_sp_matches_unpipelined_sp_lm(kind):
    """VERDICT round-2 item 5's parity target: the pipelined SP LM
    equals the UNPIPELINED SP TransformerLM (params unstacked via
    to_transformer_lm_params) on a dp2 x sp2 (x pp2) mesh."""
    pp_mesh = make_mesh(MeshConfig(data=2, seq=2, pipe=2))
    lm_mesh = make_mesh(MeshConfig(data=2, seq=2))
    ucfg = dataclasses.replace(LMPP_CFG, attention=kind)
    pp_model = create_model(ucfg, mesh=pp_mesh)
    variables = init_variables(pp_model, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    lm_model = create_model(
        dataclasses.replace(ucfg, name="lm"), mesh=lm_mesh)
    lm_params = to_transformer_lm_params(variables["params"])
    toks = _tokens()
    a = pp_model.apply(variables, toks, train=False)
    b = lm_model.apply({"params": lm_params}, toks, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
@pytest.mark.parametrize("schedule,attention",
                         [("gpipe", "ulysses"), ("1f1b", "ulysses"),
                          ("1f1b", "ring")])
def test_lmpp_sp_trains_on_dp_sp_pp(schedule, attention, tmp_path):
    """One training step on dp2 x sp2 x pp2 through the Trainer: step
    metrics must match the same model trained dp-only (the composition
    must not change the math), under both schedules and both SP ops.
    Single-step on purpose: multi-step trajectories amplify
    float-rounding differences between the AD and manual-VJP backwards
    into argmax (accuracy) flips — per-step grad parity is asserted in
    tests/test_pp_1f1b.py, convergence in the dryrun legs."""
    def run(mesh_cfg, attention):
        cfg = TrainConfig(
            epochs=1,
            data=DataConfig(dataset="synthetic_lm", batch_size=16,
                            seq_len=32, vocab_size=32,
                            synthetic_train_size=16,  # exactly 1 step
                            synthetic_test_size=16),
            model=dataclasses.replace(LMPP_CFG, attention=attention,
                                      pp_schedule=schedule,
                                      max_seq_len=32),
            optim=OptimConfig(learning_rate=1e-2, schedule="constant"),
            mesh=mesh_cfg,
            checkpoint=CheckpointConfig(save_best=False,
                                        save_last=False),
        )
        tr = Trainer(cfg)
        try:
            return tr.train_one_epoch(1)
        finally:
            tr.close()

    m_sp = run(MeshConfig(data=2, seq=2, pipe=2), attention)
    m_dp = run(MeshConfig(data=2), "dense")
    assert np.isfinite(m_sp["loss"])
    np.testing.assert_allclose(m_sp["loss"], m_dp["loss"], rtol=2e-4)
    np.testing.assert_allclose(m_sp["accuracy"], m_dp["accuracy"],
                               rtol=2e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# MoE x PP: routed super-layers inside the pipeline (round-3 carve-out)
# ---------------------------------------------------------------------------

MOE_CFG = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=4,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=64, max_seq_len=32, pp_microbatches=1,
                      moe_experts=4, moe_every=2, moe_capacity_factor=2.0)


def _moe_toks(b=4, t=16):
    return jnp.asarray(np.random.default_rng(5).integers(0, 64, (b, t)),
                       jnp.int32)


def _aux_of(mut):
    return sum(jax.tree_util.tree_leaves(mut.get("losses", {})))


@pytest.mark.slow
def test_lmpp_moe_matches_unpipelined_moe_lm():
    """Forward + aux parity: the stacked super-layer MoE (m_every-1
    dense blocks + 1 routed block per scan step) equals the unpipelined
    TransformerLM-with-MoeMlp on unstacked params — sequentially, and
    pipelined at n_micro=1 (full-batch routing per stage) under both
    schedules."""
    pp0 = create_model(MOE_CFG)
    variables = init_variables(pp0, jax.random.PRNGKey(0),
                               batch_size=4, seq_len=16)
    params = {"params": variables["params"]}
    toks = _moe_toks()
    lm = create_model(dataclasses.replace(MOE_CFG, name="lm"))
    lm_params = to_transformer_lm_params(variables["params"])
    ref, mut_ref = lm.apply({"params": lm_params}, toks, train=True,
                            mutable=["losses"])
    aux_ref = _aux_of(mut_ref)

    out, mut = pp0.apply(params, toks, train=True, mutable=["losses"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(float(_aux_of(mut)), float(aux_ref),
                               rtol=1e-6)

    mesh = make_mesh(MeshConfig(data=1, pipe=2))
    for sched in ("gpipe", "1f1b"):
        m = create_model(dataclasses.replace(MOE_CFG, pp_schedule=sched),
                         mesh=mesh)
        with mesh:
            o, mu = m.apply(params, toks, train=True, mutable=["losses"])
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(float(_aux_of(mu)), float(aux_ref),
                                   rtol=1e-5)


@pytest.mark.slow
def test_lmpp_moe_grads_match_unpipelined_truth():
    """Gradient parity incl. the aux cotangent: CE-like loss + weighted
    aux, differentiated through the pipelined MoE (n_micro=1, both
    schedules), must equal the unpipelined TransformerLM-with-MoeMlp
    grads on the same (unstacked) params — router and expert grads
    included (the aux term is what trains the router; a dropped aux
    cotangent would leave router grads near zero, not subtly wrong)."""
    pp0 = create_model(MOE_CFG)
    variables = init_variables(pp0, jax.random.PRNGKey(0),
                               batch_size=4, seq_len=16)
    toks = _moe_toks()

    def loss_of(model, params, mesh=None):
        def loss(p):
            logits, mut = model.apply({"params": p}, toks, train=True,
                                      mutable=["losses"])
            return (jnp.mean((logits - jnp.roll(logits, 1, -1)) ** 2)
                    + 0.01 * _aux_of(mut))
        if mesh is None:
            return jax.grad(loss)(params)
        with mesh:
            return jax.grad(loss)(params)

    lm = create_model(dataclasses.replace(MOE_CFG, name="lm"))
    lm_params = to_transformer_lm_params(variables["params"])
    g_ref = loss_of(lm, lm_params)

    L, m_every = MOE_CFG.vit_depth, MOE_CFG.moe_every
    blocks = [g_ref[f"block{i:02d}"] for i in range(L)]
    ref_stacked = {
        "blocks_qkv_k": jnp.stack([b["attn"]["qkv"]["kernel"]
                                   for b in blocks]),
        "blocks_fc1_k": jnp.stack(
            [blocks[i]["mlp"]["fc1"]["kernel"] for i in range(L)
             if i % m_every != m_every - 1]),
        "blocks_moe_rk": jnp.stack(
            [blocks[i]["moe"]["router"]["kernel"] for i in range(L)
             if i % m_every == m_every - 1]),
        "blocks_moe_wi": jnp.stack(
            [blocks[i]["moe"]["wi"] for i in range(L)
             if i % m_every == m_every - 1]),
        "blocks_moe_bo": jnp.stack(
            [blocks[i]["moe"]["bo"] for i in range(L)
             if i % m_every == m_every - 1]),
    }
    mesh = make_mesh(MeshConfig(data=1, pipe=2))
    for sched in ("gpipe", "1f1b"):
        m = create_model(dataclasses.replace(MOE_CFG, pp_schedule=sched),
                         mesh=mesh)
        g = loss_of(m, variables["params"], mesh)
        for kk, ref in ref_stacked.items():
            np.testing.assert_allclose(
                np.asarray(g[kk]), np.asarray(ref), rtol=1e-4,
                atol=1e-7, err_msg=f"{sched}: grad mismatch at {kk}")
        # router grads must be real, not vanishing (aux actually flows)
        assert float(np.max(np.abs(np.asarray(g["blocks_moe_rk"])))) > 1e-7


@pytest.mark.slow
def test_lmpp_moe_schedules_agree_with_microbatching():
    """n_micro=2 on dp2 x pp2 (per-microbatch-shard routing): gpipe-AD
    and the manual 1F1B backward must produce the same grads — the aux
    reduction (sum over stages, mean over microbatch-shards) and its
    hand-written transpose must agree; also the full dp x sp x pp x moe
    composition under ring attention."""
    cfg = dataclasses.replace(MOE_CFG, pp_microbatches=2,
                              moe_capacity_factor=4.0)
    pp0 = create_model(cfg)
    variables = init_variables(pp0, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    toks = _moe_toks(b=8)

    def grads(mesh, sched, att):
        m = create_model(dataclasses.replace(cfg, pp_schedule=sched,
                                             attention=att), mesh=mesh)
        def loss(p):
            logits, mut = m.apply({"params": p}, toks, train=True,
                                  mutable=["losses"])
            return (jnp.mean((logits - jnp.roll(logits, 1, -1)) ** 2)
                    + 0.01 * _aux_of(mut))
        with mesh:
            return jax.grad(loss)(variables["params"])

    for mesh_cfg, att in ((MeshConfig(data=2, pipe=2), "dense"),
                          (MeshConfig(data=2, seq=2, pipe=2), "ring")):
        mesh = make_mesh(mesh_cfg)
        g1 = grads(mesh, "gpipe", att)
        g2 = grads(mesh, "1f1b", att)
        for (p, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(g1),
                jax.tree_util.tree_leaves_with_path(g2)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"{att}: {jax.tree_util.keystr(p)}")


@pytest.mark.slow
def test_lmpp_moe_trains_and_serves(tmp_path, capsys):
    """End to end: train the MoE pipelined LM (dp2 x pp2, 1f1b) through
    the Trainer, then serve the checkpoint through the generate CLI —
    the MoE stacks unstack into TransformerLM's block/moe layout."""
    cfg = _cfg(MeshConfig(data=2, pipe=2)).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_last=False))
    cfg = cfg.replace(model=dataclasses.replace(
        cfg.model, moe_experts=4, moe_every=2, moe_capacity_factor=2.0,
        pp_schedule="1f1b"))
    tr = Trainer(cfg)
    try:
        tr.train()
    finally:
        tr.close()
    from tpunet.infer import generate as gen
    gen.main(["--checkpoint-dir", str(tmp_path / "ck"), "--model",
              "lm_pp", "--prompt", "5 7 3", "--tokens", "5",
              "--vit-hidden", "64", "--vit-depth", "4", "--vit-heads",
              "4", "--vocab-size", "32", "--max-seq-len", "32",
              "--moe-experts", "4", "--moe-every", "2",
              "--moe-capacity-factor", "2.0"])
    out = capsys.readouterr().out.strip().splitlines()[-1].split()
    assert out[:3] == ["5", "7", "3"] and len(out) == 8
    assert all(0 <= int(t) < 32 for t in out)


@pytest.mark.slow
def test_lmpp_zero1_moment_shardings():
    """ZeRO-1 composes with the pipeline: stacked block moments keep
    their 'pipe' sharding (PP rules precede the ZeRO-1 catch-all),
    while non-stacked leaves' moments (embed/pos/ln) spread over
    'data' where divisible — the composition matrix's lm_pp x zero1
    cell."""
    from jax.sharding import PartitionSpec as P
    cfg = _cfg(MeshConfig(data=2, pipe=2, zero1=True))
    tr = Trainer(cfg)
    try:
        mu = tr.state.opt_state[0].mu
        assert mu["blocks_qkv_k"].sharding.spec == P("pipe")
        # embed [V, C] with V=32 divisible by data=2 -> data-sharded
        assert mu["embed"]["embedding"].sharding.spec == P("data")
        m = tr.train_one_epoch(1)
        assert np.isfinite(m["loss"])
    finally:
        tr.close()


def test_lmpp_ep_validation():
    mesh = make_mesh(MeshConfig(data=1, pipe=2, model=3))
    with pytest.raises(ValueError, match="model"):
        create_model(dataclasses.replace(MOE_CFG, vit_heads=2,
                                         moe_experts=4), mesh=mesh)


@pytest.mark.slow
def test_lmpp_ep_sharded_matches_replicated():
    """True EP x PP: expert stacks sharded P('pipe','model') inside
    the pipeline must produce the same loss gradient as the
    replicated-expert run on the same (data, pipe) routing groups —
    both schedules x both dispatch lowerings (the GShard all_to_all
    capacity-buffer exchange and the replicated-routing psum; ample
    capacity so per-slice routing selects identically). The 1F1B
    cases exercise the unreduced-cotangent convention fix (in-stage
    collective transposes inside jax.vjp complete per-device
    partials; the manual backward divides the entering cotangent by
    the axis size and completes each leaf at the end, except the
    model-sharded ones) — for the a2a lowering that covers the
    all_to_all (self-transposing) and all_gather/dynamic_slice
    (psum-of-shares / zero-padded partial) transposes too."""
    cfg = dataclasses.replace(MOE_CFG, pp_microbatches=2,
                              moe_capacity_factor=4.0)
    pp0 = create_model(cfg)
    variables = init_variables(pp0, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    toks = _moe_toks(b=8)

    def grads(mesh, sched, dispatch="auto"):
        m = create_model(dataclasses.replace(cfg, pp_schedule=sched,
                                             moe_dispatch=dispatch),
                         mesh=mesh)
        def loss(p):
            logits, mut = m.apply({"params": p}, toks, train=True,
                                  mutable=["losses"])
            return (jnp.mean((logits - jnp.roll(logits, 1, -1)) ** 2)
                    + 0.01 * _aux_of(mut))
        with mesh:
            return jax.grad(loss)(variables["params"])

    mesh_ep = make_mesh(MeshConfig(data=2, pipe=2, model=2))
    mesh_rep = make_mesh(MeshConfig(data=2, pipe=2))
    g_rep = grads(mesh_rep, "gpipe")
    for sched in ("gpipe", "1f1b"):
        for dispatch in ("replicated", "alltoall"):
            g = grads(mesh_ep, sched, dispatch)
            for (p, a), (_, b) in zip(
                    jax.tree_util.tree_leaves_with_path(g),
                    jax.tree_util.tree_leaves_with_path(g_rep)):
                np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                    err_msg=f"{sched}/{dispatch}: "
                            f"{jax.tree_util.keystr(p)}")


@pytest.mark.slow
def test_lmpp_ep_trains_with_sharded_storage():
    """dp2 x pp2 x ep2 through the Trainer: expert params AND their
    Adam moments live sharded P('pipe','model') (1/(S*EP) resident
    expert memory per device), and training converges to the same
    loss as the replicated run on identical routing groups — exactly
    (rtol 1e-5 over 4 epochs) with the replicated lowering, whose
    per-device math is identical to the unsharded program, and
    closely (rtol 2%) with the alltoall lowering, whose per-slice
    routing and different reduction order legitimately drift over a
    multi-step trajectory (per-step grad parity is asserted in
    test_lmpp_ep_sharded_matches_replicated)."""
    from jax.sharding import PartitionSpec as P

    from tpunet.data.lm import synthetic_lm

    def run(mesh_cfg, dispatch):
        sb = 8
        cfg = TrainConfig(
            epochs=4,
            data=DataConfig(dataset="synthetic_lm", batch_size=sb,
                            seq_len=64, vocab_size=32),
            model=ModelConfig(name="lm_pp", vit_hidden=64, vit_depth=4,
                              vit_heads=4, dropout_rate=0.0,
                              dtype="float32", vocab_size=32,
                              max_seq_len=64, pp_microbatches=2,
                              moe_experts=4, moe_every=2,
                              moe_capacity_factor=1.5,
                              moe_dispatch=dispatch,
                              pp_schedule="1f1b"),
            optim=OptimConfig(learning_rate=3e-3, schedule="constant"),
            mesh=mesh_cfg,
            checkpoint=CheckpointConfig(save_best=False,
                                        save_last=False),
        )
        tr = Trainer(cfg, dataset=synthetic_lm(2 * sb, sb, seq_len=64,
                                               vocab=32))
        try:
            spec = tr.state.params["blocks_moe_wi"].sharding.spec
            mu_spec = (tr.state.opt_state[0]
                       .mu["blocks_moe_wi"].sharding.spec)
            losses = [tr.train_one_epoch(e)["loss"] for e in range(4)]
        finally:
            tr.close()
        return spec, mu_spec, losses

    ep_mesh = MeshConfig(data=2, pipe=2, model=2)
    spec, mu_spec, ep_losses = run(ep_mesh, "replicated")
    assert spec == P("pipe", "model") and mu_spec == P("pipe", "model")
    _, _, rep_losses = run(MeshConfig(data=2, pipe=2), "auto")
    np.testing.assert_allclose(ep_losses, rep_losses, rtol=1e-5)
    spec, mu_spec, a2a_losses = run(ep_mesh, "alltoall")
    assert spec == P("pipe", "model") and mu_spec == P("pipe", "model")
    np.testing.assert_allclose(a2a_losses, rep_losses, rtol=2e-2)
    assert a2a_losses[-1] < a2a_losses[0]
