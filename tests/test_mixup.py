"""Mixup / CutMix: on-device batch mixing inside the jitted train step
(beyond-parity; the reference's transform stack at :72-82 has neither)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.data.augment import mixup_cutmix
from tpunet.train.loop import Trainer


def _batch(b=8, h=16, w=16):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((b, h, w, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, b), jnp.int32)
    return x, y


def test_disabled_is_identity():
    x, y = _batch()
    out, yb, lam = mixup_cutmix(jax.random.PRNGKey(0), x, y, 0.0, 0.0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    np.testing.assert_array_equal(np.asarray(yb), np.asarray(y))
    assert float(lam) == 1.0


@pytest.mark.slow
def test_mixup_is_convex_combination():
    x, y = _batch()
    out, yb, lam = mixup_cutmix(jax.random.PRNGKey(1), x, y, 0.4, 0.0)
    lam = float(lam)
    assert 0.0 <= lam <= 1.0
    # reconstruct: out = lam*x + (1-lam)*x[perm]; recover the pairing
    # from the labels and verify exactly
    perm_x = (np.asarray(out) - lam * np.asarray(x)) / max(1 - lam, 1e-9)
    # every mixed row must be one of the original rows
    xs = np.asarray(x)
    for i in range(xs.shape[0]):
        dists = np.abs(xs - perm_x[i]).mean(axis=(1, 2, 3))
        assert dists.min() < 1e-4
    # labels_b is a permutation of labels
    assert sorted(np.asarray(yb).tolist()) == sorted(np.asarray(y).tolist())


def test_cutmix_pixels_come_from_two_sources():
    x, y = _batch()
    out, yb, lam = mixup_cutmix(jax.random.PRNGKey(2), x, y, 0.0, 1.0)
    o, xs = np.asarray(out), np.asarray(x)
    lam = float(lam)
    assert 0.0 <= lam <= 1.0
    # every output pixel equals the corresponding pixel of x or of the
    # SAME paired row; the fraction equal to x matches lam
    same = np.isclose(o, xs).all(-1)              # [B, H, W]
    # rows the permutation mapped to themselves are unchanged even
    # inside the box (x_b == x there) — drop them before comparing
    # against lam; which rows those are depends on the jax version's
    # PRNG stream, so the test must not bake in a count
    fixed = same.all(axis=(1, 2))
    assert not fixed.all()   # key 2 must cut at least one real pair
    frac = same[~fixed].mean()
    assert abs(frac - lam) < 0.05  # isclose-coincidence slack
    # and the box is contiguous: per row, the non-same region is a box
    b0 = ~same[~fixed][0]
    if b0.any():
        rows = np.where(b0.any(1))[0]
        cols = np.where(b0.any(0))[0]
        assert b0[rows[0]:rows[-1] + 1, cols[0]:cols[-1] + 1].all()


@pytest.mark.slow
def test_both_alphas_pick_one_per_step():
    """With both alphas set, some steps mix and some cut: CutMix output
    pixels are exact copies of SOME batch row, mixup pixels (lam
    strictly inside (0,1)) generically match none."""
    x, y = _batch()
    kinds = set()
    xs = np.asarray(x)
    for seed in range(10):
        out, _, lam = mixup_cutmix(jax.random.PRNGKey(seed), x, y,
                                   1.0, 1.0)
        o = np.asarray(out)
        # fraction of pixels of image 0 equal to that pixel in any row
        eq_any = np.isclose(o[0][None], xs).all(-1).any(0).mean()
        kinds.add("cutmix" if eq_any > 0.99 else "mixup")
    assert kinds == {"cutmix", "mixup"}, kinds


def test_trainer_with_mixup_and_cutmix():
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        mixup_alpha=0.4, cutmix_alpha=1.0),
        model=ModelConfig(name="vit", vit_patch=4, vit_hidden=64,
                          vit_depth=2, vit_heads=4, dropout_rate=0.0,
                          dtype="float32"),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        m = trainer.train_one_epoch(1)
        assert np.isfinite(m["loss"]) and m["count"] == 32.0
        e = trainer.evaluate()  # eval path is untouched by mixing
        assert np.isfinite(e["loss"])
    finally:
        trainer.close()


def test_cli_flags():
    from tpunet.config import config_from_args
    cfg = config_from_args(["--mixup", "0.4", "--cutmix", "1.0"])
    assert cfg.data.mixup_alpha == 0.4
    assert cfg.data.cutmix_alpha == 1.0


def test_validation():
    import dataclasses
    base = TrainConfig(
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        seq_len=32, vocab_size=32, mixup_alpha=0.4),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0, dtype="float32",
                          vocab_size=32, max_seq_len=32),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    with pytest.raises(ValueError, match="image-family"):
        Trainer(base)
    img = dataclasses.replace(
        base,
        data=DataConfig(dataset="synthetic", image_size=32,
                        batch_size=16, synthetic_train_size=32,
                        synthetic_test_size=16, mixup_alpha=-0.1),
        model=ModelConfig(name="vit", vit_patch=4, vit_hidden=64,
                          vit_depth=2, vit_heads=4, dtype="float32"))
    with pytest.raises(ValueError, match=">= 0"):
        Trainer(img)


@pytest.mark.slow
def test_mixup_composes_with_pipelined_vit(tmp_path):
    """Mixup's convex-label loss runs inside the jitted step for the
    pipelined ViT too (the composition matrix's vit_pp cell)."""
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32,
                        batch_size=16, synthetic_train_size=32,
                        synthetic_test_size=16, mixup_alpha=0.4),
        model=ModelConfig(name="vit_pp", vit_patch=4, vit_hidden=64,
                          vit_depth=4, vit_heads=4, dropout_rate=0.0,
                          dtype="float32", pp_microbatches=2),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=MeshConfig(data=2, pipe=2),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    tr = Trainer(cfg)
    try:
        m = tr.train_one_epoch(1)
    finally:
        tr.close()
    assert np.isfinite(m["loss"])
