"""Model construction / shape / parameter-count tests."""

import jax
import jax.numpy as jnp
import pytest

from tpunet.config import ModelConfig
from tpunet.models import create_model, init_variables, num_params


@pytest.fixture(scope="module")
def model_and_vars():
    model = create_model(ModelConfig(dtype="float32"))
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=64)
    return model, variables


def test_param_count_matches_reference(model_and_vars):
    # Reference logs "Total parameters: 2236682" (cifar_mpi_gpu128_26188.out:30)
    _, variables = model_and_vars
    assert num_params(variables["params"]) == 2_236_682


@pytest.mark.slow
def test_forward_shapes_and_dtype(model_and_vars):
    model, variables = model_and_vars
    x = jnp.zeros((2, 64, 64, 3), jnp.float32)
    logits = model.apply(variables, x, train=False)
    assert logits.shape == (2, 10)
    assert logits.dtype == jnp.float32


@pytest.mark.slow
def test_train_mode_updates_batch_stats(model_and_vars):
    model, variables = model_and_vars
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 64, 64, 3))
    logits, mutated = model.apply(
        variables, x, train=True,
        rngs={"dropout": jax.random.PRNGKey(2)},
        mutable=["batch_stats"])
    assert logits.shape == (4, 10)
    old = variables["batch_stats"]["stem"]["bn"]["mean"]
    new = mutated["batch_stats"]["stem"]["bn"]["mean"]
    assert not jnp.allclose(old, new)


def test_jit_matches_eager(model_and_vars):
    model, variables = model_and_vars
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 64, 64, 3))
    eager = model.apply(variables, x, train=False)
    jitted = jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, x)
    assert jnp.allclose(eager, jitted, atol=1e-5)


def test_width_multiplier_changes_params():
    small = create_model(ModelConfig(width_mult=0.5, dtype="float32"))
    variables = init_variables(small, jax.random.PRNGKey(0), image_size=32)
    assert num_params(variables["params"]) < 2_236_682
    x = jnp.zeros((1, 32, 32, 3))
    assert small.apply(variables, x, train=False).shape == (1, 10)
