"""MoE MLP: routing invariants, aux loss, trainer integration, and
expert-parallel parity on the 8-device CPU mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables
from tpunet.models.moe import MoeMlp
from tpunet.train.loop import Trainer

MOE_CFG = ModelConfig(name="vit", vit_patch=4, vit_hidden=64, vit_depth=2,
                      vit_heads=4, dropout_rate=0.0, dtype="float32",
                      moe_experts=4, moe_every=2)


def _moe(experts=4, top_k=2, cap=1.25, dtype=jnp.float32):
    m = MoeMlp(experts, 128, top_k=top_k, capacity_factor=cap, dtype=dtype)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 32)),
                    dtype)
    variables = m.init(jax.random.PRNGKey(0), x)
    return m, {"params": variables["params"]}, x


@pytest.mark.slow
def test_output_shape_and_dtype():
    m, variables, x = _moe()
    y = m.apply(variables, x)
    assert y.shape == x.shape and y.dtype == x.dtype


@pytest.mark.slow
def test_output_finite_with_ample_capacity():
    m, variables, x = _moe(cap=4.0)
    y = m.apply(variables, x)
    assert np.isfinite(np.asarray(y)).all()


def test_aux_loss_sown_and_bounded():
    m, variables, x = _moe(cap=4.0)
    y, mutated = m.apply(variables, x, mutable=["losses"])
    (aux,) = jax.tree_util.tree_leaves(mutated["losses"])
    # Perfectly balanced routing gives exactly 1.0; anything else > 1.
    assert float(aux) >= 1.0 - 1e-5
    assert float(aux) < m.num_experts + 1e-5


@pytest.mark.slow
def test_single_expert_topk1_is_dense_mlp_through_router():
    """One expert, ample capacity: every token goes to expert 0 with
    gate 1.0, so the MoE output is a plain (batched) MLP of its single
    expert's weights."""
    m, variables, x = _moe(experts=1, top_k=1, cap=8.0)
    y = m.apply(variables, x)
    p = variables["params"]
    h = jax.nn.gelu(x @ p["wi"][0] + p["bi"][0])
    ref = h @ p["wo"][0] + p["bo"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_capacity_drops_tokens_but_stays_finite():
    m, variables, x = _moe(cap=0.1)  # tiny capacity -> heavy drops
    y = m.apply(variables, x)
    assert np.isfinite(np.asarray(y)).all()


def _cfg(mesh_cfg, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=64, synthetic_test_size=32),
        model=dataclasses.replace(MOE_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


@pytest.mark.slow
def test_moe_vit_params_and_trainer():
    model = create_model(MOE_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=32)
    # block00 dense mlp, block01 moe (every 2nd block)
    assert "mlp" in variables["params"]["block00"]
    assert "moe" in variables["params"]["block01"]
    assert variables["params"]["block01"]["moe"]["wi"].shape[0] == 4

    trainer = Trainer(_cfg(MeshConfig(data=2)))
    try:
        m = trainer.train_one_epoch(1)
        e = trainer.evaluate()
    finally:
        trainer.close()
    assert np.isfinite(m["loss"]) and np.isfinite(e["loss"])


@pytest.mark.slow
def test_expert_parallel_training_parity():
    """Experts sharded over 'model' (EP) == unsharded run, same math."""
    def run(mesh_cfg):
        tr = Trainer(_cfg(mesh_cfg))
        try:
            return tr.train_one_epoch(1)
        finally:
            tr.close()

    base = run(MeshConfig(data=2))
    ep = run(MeshConfig(data=2, model=2))
    assert abs(base["loss"] - ep["loss"]) < 1e-4
    assert abs(base["accuracy"] - ep["accuracy"]) < 1e-6


@pytest.mark.slow
def test_ep_shardings_applied():
    from jax.sharding import PartitionSpec as P

    from tpunet.parallel import make_mesh
    mesh = make_mesh(MeshConfig(data=2, model=2))
    tr = Trainer(_cfg(MeshConfig(data=2, model=2)), mesh=mesh)
    try:
        wi = tr.state.params["block01"]["moe"]["wi"]
        assert wi.sharding.spec == P("model", None, None)
        router = tr.state.params["block01"]["moe"]["router"]["kernel"]
        assert router.sharding.spec == P()
    finally:
        tr.close()
