"""MoE MLP: routing invariants, aux loss, trainer integration, and
expert-parallel parity on the 8-device CPU mesh — including the GShard
all_to_all capacity-buffer dispatch vs the replicated-routing psum
lowering."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from tpunet.compat import shard_map
from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables
from tpunet.models.moe import MoeMlp, moe_apply, resolve_moe_dispatch
from tpunet.train.loop import Trainer

MOE_CFG = ModelConfig(name="vit", vit_patch=4, vit_hidden=64, vit_depth=2,
                      vit_heads=4, dropout_rate=0.0, dtype="float32",
                      moe_experts=4, moe_every=2)


def _moe(experts=4, top_k=2, cap=1.25, dtype=jnp.float32):
    m = MoeMlp(experts, 128, top_k=top_k, capacity_factor=cap, dtype=dtype)
    x = jnp.asarray(np.random.default_rng(0).normal(size=(2, 16, 32)),
                    dtype)
    variables = m.init(jax.random.PRNGKey(0), x)
    return m, {"params": variables["params"]}, x


@pytest.mark.slow
def test_output_shape_and_dtype():
    m, variables, x = _moe()
    y = m.apply(variables, x)
    assert y.shape == x.shape and y.dtype == x.dtype


@pytest.mark.slow
def test_output_finite_with_ample_capacity():
    m, variables, x = _moe(cap=4.0)
    y = m.apply(variables, x)
    assert np.isfinite(np.asarray(y)).all()


def test_aux_loss_sown_and_bounded():
    m, variables, x = _moe(cap=4.0)
    y, mutated = m.apply(variables, x, mutable=["losses"])
    (aux,) = jax.tree_util.tree_leaves(mutated["losses"])
    # Perfectly balanced routing gives exactly 1.0; anything else > 1.
    assert float(aux) >= 1.0 - 1e-5
    assert float(aux) < m.num_experts + 1e-5


@pytest.mark.slow
def test_single_expert_topk1_is_dense_mlp_through_router():
    """One expert, ample capacity: every token goes to expert 0 with
    gate 1.0, so the MoE output is a plain (batched) MLP of its single
    expert's weights."""
    m, variables, x = _moe(experts=1, top_k=1, cap=8.0)
    y = m.apply(variables, x)
    p = variables["params"]
    h = jax.nn.gelu(x @ p["wi"][0] + p["bi"][0])
    ref = h @ p["wo"][0] + p["bo"][0]
    np.testing.assert_allclose(np.asarray(y), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_capacity_drops_tokens_but_stays_finite():
    m, variables, x = _moe(cap=0.1)  # tiny capacity -> heavy drops
    y = m.apply(variables, x)
    assert np.isfinite(np.asarray(y)).all()


def _cfg(mesh_cfg, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=64, synthetic_test_size=32),
        model=dataclasses.replace(MOE_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


@pytest.mark.slow
def test_moe_vit_params_and_trainer():
    model = create_model(MOE_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), image_size=32)
    # block00 dense mlp, block01 moe (every 2nd block)
    assert "mlp" in variables["params"]["block00"]
    assert "moe" in variables["params"]["block01"]
    assert variables["params"]["block01"]["moe"]["wi"].shape[0] == 4

    trainer = Trainer(_cfg(MeshConfig(data=2)))
    try:
        m = trainer.train_one_epoch(1)
        e = trainer.evaluate()
    finally:
        trainer.close()
    assert np.isfinite(m["loss"]) and np.isfinite(e["loss"])


@pytest.mark.slow
def test_expert_parallel_training_parity():
    """Experts sharded over 'model' (EP) == unsharded run, same math."""
    def run(mesh_cfg):
        tr = Trainer(_cfg(mesh_cfg))
        try:
            return tr.train_one_epoch(1)
        finally:
            tr.close()

    base = run(MeshConfig(data=2))
    ep = run(MeshConfig(data=2, model=2))
    # 5e-4 abs (~2e-4 relative on a ~2.3 CE): EP's all_to_all dispatch
    # legitimately reorders float32 sums relative to the unsharded
    # einsum, and the reorder differs across jax's shard_map lowerings
    # (measured 1.6e-4 on jax 0.4.37, under 1e-4 on newer jax).
    assert abs(base["loss"] - ep["loss"]) < 5e-4
    # Accuracy at this near-chance, 1-epoch scale is argmax over
    # near-tied logits: bit-stable on modern jax (native jax.shard_map
    # lowering), but the older experimental lowering's float reorder
    # flips a few of the 64 eval ties — there the aligned loss above is
    # the parity evidence and accuracy only gets a coarse bound.
    acc_tol = 1e-6 if hasattr(jax, "shard_map") else 0.1
    assert abs(base["accuracy"] - ep["accuracy"]) < acc_tol


def _ep_args(E=4, D=16, H=32, N=64, seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.normal(size=(N, D)), jnp.float32),
            jnp.asarray(rng.normal(size=(N, E)), jnp.float32),
            jnp.asarray(rng.normal(0, 0.1, (E, D, H)), jnp.float32),
            jnp.zeros((E, H)),
            jnp.asarray(rng.normal(0, 0.1, (E, H, D)), jnp.float32),
            jnp.zeros((E, D)))


def _ep_grads(impl, args, ep, cap=8.0):
    """value+grads of a scalar loss through moe_apply under shard_map
    with an ``ep``-wide expert axis (tokens replicated, experts
    sharded); impl=None runs the unsharded single-device reference."""
    def core(*a):
        return moe_apply(*a, top_k=2, capacity_factor=cap,
                         dtype=jnp.float32,
                         ep_axis="model" if impl else None,
                         ep_impl=impl or "replicated")

    if impl is None:
        fn = core
    else:
        mesh = Mesh(np.array(jax.devices()[:ep]), ("model",))
        fn = shard_map(
            core, mesh=mesh,
            in_specs=(P(), P(), P("model"), P("model"), P("model"),
                      P("model")),
            out_specs=(P(), P()), check_vma=False)

    def loss(a):
        y, aux = fn(*a)
        return jnp.sum(y ** 2) + 0.01 * aux

    return jax.value_and_grad(loss)(args)


@pytest.mark.parametrize("ep", [2, 4])
def test_alltoall_dispatch_matches_replicated_and_unsharded(ep):
    """The GShard a2a capacity-buffer dispatch == the replicated psum
    lowering == the unsharded reference, values AND all six input
    grads (ample capacity, so per-slice routing selects identically
    and only the exchange mechanics differ)."""
    args = _ep_args()
    v_ref, g_ref = _ep_grads(None, args, ep)
    for impl in ("replicated", "alltoall"):
        v, g = _ep_grads(impl, args, ep)
        np.testing.assert_allclose(float(v), float(v_ref), rtol=1e-5,
                                   err_msg=impl)
        for (pth, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(g),
                jax.tree_util.tree_leaves_with_path(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"{impl}: arg {jax.tree_util.keystr(pth)}")


def test_alltoall_overflow_stays_finite():
    """Tiny capacity under the a2a path: per-slice drops, still finite
    output and a bounded aux."""
    args = _ep_args()
    v, g = _ep_grads("alltoall", args, 2, cap=0.25)
    assert np.isfinite(float(v))
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree_util.tree_leaves(g))


def test_resolve_moe_dispatch():
    r = resolve_moe_dispatch
    assert r("auto", ep=1, n_tokens=64, n_experts=4) == "replicated"
    assert r("auto", ep=2, n_tokens=64, n_experts=4) == "alltoall"
    assert r("auto", ep=2, n_tokens=63, n_experts=4) == "replicated"
    assert r("auto", ep=4, n_tokens=64, n_experts=6) == "replicated"
    assert r("replicated", ep=4, n_tokens=64, n_experts=4) == "replicated"
    with pytest.raises(ValueError, match="divisible"):
        r("alltoall", ep=2, n_tokens=63, n_experts=4)
    with pytest.raises(ValueError, match="expert axis"):
        r("alltoall", ep=1, n_tokens=64, n_experts=4)
    with pytest.raises(ValueError, match="unknown"):
        r("nope", ep=2, n_tokens=64, n_experts=4)


@pytest.mark.slow
def test_moemlp_a2a_lowering_matches_gspmd():
    """MoeMlp's shard_map a2a lowering (tokens data/seq-sharded,
    experts 'model'-sharded, GShard exchange between them) matches the
    GSPMD global-routing path — forward, aux, and grads — with ample
    capacity on a dp2 x ep2 mesh."""
    from tpunet.parallel import make_mesh
    mesh = make_mesh(MeshConfig(data=2, model=2))
    x = jnp.asarray(np.random.default_rng(3).normal(size=(4, 8, 32)),
                    jnp.float32)

    def build(dispatch, use_mesh):
        m = MoeMlp(4, 64, capacity_factor=8.0, dtype=jnp.float32,
                   dispatch=dispatch, mesh=mesh if use_mesh else None)
        variables = m.init(jax.random.PRNGKey(0), x)
        return m, {"params": variables["params"]}

    def val_and_grads(m, variables):
        def loss(p):
            y, mut = m.apply({"params": p}, x, mutable=["losses"])
            aux = sum(jax.tree_util.tree_leaves(mut["losses"]))
            return jnp.sum(y ** 2) + 0.01 * aux
        with mesh:
            return jax.value_and_grad(loss)(variables["params"])

    m_ref, v_ref = build("replicated", use_mesh=False)
    m_a2a, v_a2a = build("alltoall", use_mesh=True)
    # identical init: the lowering must not change the param tree
    assert (jax.tree_util.tree_structure(v_ref)
            == jax.tree_util.tree_structure(v_a2a))
    val_ref, g_ref = val_and_grads(m_ref, v_ref)
    val, g = val_and_grads(m_a2a, v_a2a)
    np.testing.assert_allclose(float(val), float(val_ref), rtol=1e-5)
    for (pth, a), (_, b) in zip(jax.tree_util.tree_leaves_with_path(g),
                                jax.tree_util.tree_leaves_with_path(g_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
            err_msg=jax.tree_util.keystr(pth))


@pytest.mark.slow
def test_ep_shardings_applied():
    from jax.sharding import PartitionSpec as P

    from tpunet.parallel import make_mesh
    mesh = make_mesh(MeshConfig(data=2, model=2))
    tr = Trainer(_cfg(MeshConfig(data=2, model=2)), mesh=mesh)
    try:
        wi = tr.state.params["block01"]["moe"]["wi"]
        assert wi.sharding.spec == P("model", None, None)
        router = tr.state.params["block01"]["moe"]["router"]["kernel"]
        assert router.sharding.spec == P()
    finally:
        tr.close()
