"""True 2-process multi-controller training test.

TPU-native equivalent of the reference's no-cluster validation path
(`mpirun -np 2` with the gloo backend, cifar10_mpi_mobilenet_224.py:34,
41-43; SURVEY.md section 4 point 3): two separate JAX processes
rendezvous over a localhost coordinator, form one 8-device global mesh
(4 virtual CPU devices each), and train the same tiny workload. Checks:
both controllers report identical *global* metrics, and those metrics
match a single-process run on the same global mesh.
"""

import os

import numpy as np
import pytest

HERE = os.path.dirname(__file__)
REPO = os.path.dirname(HERE)


def _run_workers(mode=None, timeout=600, ckpt_dir=None):
    """Launch the two worker controllers via the shared gang launcher
    (tests/_gang.py — one home for the launch/drain protocol, shared
    with the driver dryrun's leg 8)."""
    from _gang import launch_gang

    argv_tail = [mode] if mode else []
    if ckpt_dir:
        argv_tail = [mode or "dp", str(ckpt_dir)]
    return launch_gang(argv_tail, timeout=timeout)


@pytest.mark.slow
def test_two_process_training_parity():
    a, b = _run_workers()
    assert a["world"] == b["world"] == 2
    assert a["devices"] == b["devices"] == 8
    # Global metrics identical on both controllers (same psum results).
    for section in ("eval0", "train1"):
        assert np.isclose(a[section]["loss"], b[section]["loss"], rtol=1e-6)
        assert a[section]["count"] == b[section]["count"]
        assert np.isclose(a[section]["accuracy"], b[section]["accuracy"],
                          atol=1e-9)

    # And they match a single-process run of the same global computation
    # (init-time eval is tight; train epoch is loose per Adam noise).
    from tpunet.config import MeshConfig
    from tpunet.data.cifar10 import synthetic_cifar10
    from tpunet.train.loop import Trainer
    from test_train import tiny_config

    cfg = tiny_config(os.path.join(REPO, "/tmp"), batch=16, epochs=1)
    t = Trainer(cfg, dataset=synthetic_cifar10(n_train=64, n_test=32, seed=7))
    try:
        e = t.evaluate()
        assert e["count"] == a["eval0"]["count"]
        assert np.isclose(e["loss"], a["eval0"]["loss"], rtol=1e-4)
        m = t.train_one_epoch(0)
        assert np.isclose(m["loss"], a["train1"]["loss"], rtol=2e-2)
    finally:
        t.close()


@pytest.mark.slow
def test_two_process_packed_lm():
    """Packed-sequence training across a REAL process boundary: [B, T]
    segment-id labels shard over the cross-process data axis, both
    controllers agree on the count-weighted global metrics, and they
    match a single-process run of the same global mesh."""
    a, b = _run_workers(mode="packed_lm")
    assert a["devices"] == b["devices"] == 8
    for section in ("eval0", "train1"):
        assert np.isclose(a[section]["loss"], b[section]["loss"], rtol=1e-6)
        assert a[section]["count"] == b[section]["count"]

    from tpunet.train.loop import Trainer
    from _mp_worker import packed_lm_case
    cfg, ds = packed_lm_case()
    t = Trainer(cfg, dataset=ds)
    try:
        e = t.evaluate()
        assert e["count"] == a["eval0"]["count"]
        assert np.isclose(e["loss"], a["eval0"]["loss"], rtol=1e-4)
        m = t.train_one_epoch(0)
        assert m["count"] == a["train1"]["count"]
        assert np.isclose(m["loss"], a["train1"]["loss"], rtol=2e-2)
    finally:
        t.close()


@pytest.mark.slow
def test_two_process_pipeline_lm():
    """The 1F1B pipeline executor under TRUE multi-controller: its
    shard_map (activation ppermutes over 'pipe', microbatch schedule,
    manual VJP) runs on a dp4 x pp2 mesh whose data axis crosses the
    process boundary. Both controllers must agree on global metrics
    and match a single-process run of the same global mesh."""
    a, b = _run_workers(mode="pp_lm")
    assert a["devices"] == b["devices"] == 8
    for section in ("eval0", "train1"):
        assert np.isclose(a[section]["loss"], b[section]["loss"],
                          rtol=1e-6)
        assert a[section]["count"] == b[section]["count"]

    from tpunet.train.loop import Trainer
    from _mp_worker import pp_lm_case
    cfg, ds = pp_lm_case()
    t = Trainer(cfg, dataset=ds)
    try:
        e = t.evaluate()
        assert e["count"] == a["eval0"]["count"]
        assert np.isclose(e["loss"], a["eval0"]["loss"], rtol=1e-4)
        m = t.train_one_epoch(0)
        assert np.isclose(m["loss"], a["train1"]["loss"], rtol=2e-2)
    finally:
        t.close()


@pytest.mark.slow
def test_two_process_checkpoint_roundtrip(tmp_path):
    """Multi-host orbax checkpointing under TRUE multi-controller, on
    the FSDP case (params + Adam moments sharded over the cross-process
    data axis — each controller holds only half of every leaf): both
    controllers join one best-params save + one full-state save into a
    shared directory, and a fresh Trainer in each process resumes from
    it bit-exactly. The save/restore coordination itself (orbax barrier
    pairing, one consistent directory, no deadlock, no rank-local
    partial write) is what's under test — the reference's rank-0-only
    torch.save has no analogue for sharded state
    (cifar10_mpi_mobilenet_224.py:243-250)."""
    a, b = _run_workers(mode="fsdp_lm", ckpt_dir=tmp_path / "ckpt")
    for o in (a, b):
        assert o["ckpt"]["resume_epoch"] == 2, o["ckpt"]
        assert o["ckpt"]["state_equal"], o["ckpt"]
        assert o["ckpt"]["best_equal"], o["ckpt"]
        assert o["ckpt"]["meta_model"] == "lm", o["ckpt"]
    assert np.isclose(a["ckpt"]["resume_best_acc"],
                      b["ckpt"]["resume_best_acc"])
    assert np.isclose(a["ckpt"]["resume_best_acc"],
                      a["train1"]["accuracy"])


@pytest.mark.slow
def test_two_process_fsdp_grad_accum_lm():
    """FSDP (params + moments sharded over the CROSS-PROCESS data axis)
    + grad accumulation on the LM family: both controllers must agree
    on the global metrics, and match a single-process run of the same
    global mesh to 1e-4 relative in eval (train to Adam tolerance).
    The config comes from _mp_worker.fsdp_lm_case — ONE source of truth
    for the worker and the reference."""
    a, b = _run_workers(mode="fsdp_lm")
    assert a["devices"] == b["devices"] == 8
    for section in ("eval0", "train1"):
        assert np.isclose(a[section]["loss"], b[section]["loss"], rtol=1e-6)
        assert a[section]["count"] == b[section]["count"]

    # single-process reference on the same 8-device global mesh
    from tpunet.train.loop import Trainer
    from _mp_worker import fsdp_lm_case
    cfg, ds = fsdp_lm_case()
    t = Trainer(cfg, dataset=ds)
    try:
        e = t.evaluate()
        assert e["count"] == a["eval0"]["count"]
        assert np.isclose(e["loss"], a["eval0"]["loss"], rtol=1e-4)
        m = t.train_one_epoch(0)
        assert np.isclose(m["loss"], a["train1"]["loss"], rtol=2e-2)
    finally:
        t.close()
