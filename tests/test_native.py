"""Native C++ host-side batcher tests (cxx/batcher.cc via ctypes).

The native path must be bit-identical to the numpy fallback: the gather
is the TPU-native replacement for the reference's DataLoader worker
processes (cifar10_mpi_mobilenet_224.py:126-133) and feeds raw uint8
batches to the on-device augmentation.
"""

import numpy as np
import pytest

from tpunet.data import native
from tpunet.data.pipeline import host_index_sequence, train_batches

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native batcher not built (no g++?)")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    x = rng.integers(0, 256, size=(997, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, size=997).astype(np.int32)
    return x, y


def test_gather_rows_matches_numpy(data):
    x, _ = data
    idx = np.random.default_rng(0).permutation(len(x))[:300]
    np.testing.assert_array_equal(native.gather_rows(x, idx), x[idx])


def test_gather_rows_single_thread(data):
    x, _ = data
    idx = np.asarray([5, 5, 0, 996], dtype=np.int64)
    np.testing.assert_array_equal(
        native.gather_rows(x, idx, n_threads=1), x[idx])


def test_prefetcher_matches_python_pipeline(data):
    x, y = data
    gb = 64
    pf = native.NativePrefetcher(x, y, local_batch=gb, depth=2, n_threads=2)
    for epoch in (0, 1):
        idx = host_index_sequence(len(x), global_batch=gb, seed=42,
                                  epoch=epoch)
        got = list(pf.iter_epoch(idx))
        want = list(train_batches(x, y, global_batch=gb, seed=42,
                                  epoch=epoch))
        assert len(got) == len(want) == len(x) // gb
        for (gx, gy), (wx, wy) in zip(got, want):
            np.testing.assert_array_equal(gx, wx)
            np.testing.assert_array_equal(gy, wy)
    pf.close()


def test_prefetcher_multi_host_slices(data):
    x, y = data
    gb = 32
    seqs = [host_index_sequence(len(x), global_batch=gb, seed=1, epoch=4,
                                process_index=p, process_count=2)
            for p in range(2)]
    pf = native.NativePrefetcher(x, y, local_batch=gb // 2)
    per_host = [list(pf.iter_epoch(s)) for s in seqs]
    pf.close()
    want = list(train_batches(x, y, global_batch=gb, seed=1, epoch=4))
    for s, (wx, wy) in enumerate(want):
        gx = np.concatenate([per_host[p][s][0] for p in range(2)])
        gy = np.concatenate([per_host[p][s][1] for p in range(2)])
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)


def test_gather_rows_int32_tokens():
    """Token rows (int32) ride the same byte-level gather."""
    rng = np.random.default_rng(5)
    toks = rng.integers(0, 256, size=(257, 96), dtype=np.int64).astype(np.int32)
    idx = rng.permutation(len(toks))[:100]
    np.testing.assert_array_equal(native.gather_rows(toks, idx), toks[idx])


def test_prefetcher_int32_tokens_match_python_pipeline():
    rng = np.random.default_rng(6)
    toks = rng.integers(0, 256, size=(200, 64), dtype=np.int64).astype(np.int32)
    y = np.zeros(200, np.int32)
    gb = 32
    pf = native.NativePrefetcher(toks, y, local_batch=gb, depth=2,
                                 n_threads=2)
    idx = host_index_sequence(len(toks), global_batch=gb, seed=7, epoch=2)
    got = list(pf.iter_epoch(idx))
    want = list(train_batches(toks, y, global_batch=gb, seed=7, epoch=2))
    pf.close()
    assert len(got) == len(want) == len(toks) // gb
    for (gx, gy), (wx, wy) in zip(got, want):
        assert gx.dtype == np.int32
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)


def test_lm_trainer_uses_native_loader_with_identical_metrics():
    """The Trainer now routes token datasets through the native
    prefetcher; epoch metrics must be bit-identical to the numpy path."""
    import dataclasses
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.train.loop import Trainer

    def cfg(native_loader):
        return TrainConfig(
            epochs=1,
            data=DataConfig(dataset="synthetic_lm", batch_size=16,
                            synthetic_train_size=64,
                            synthetic_test_size=16, seq_len=64,
                            vocab_size=32, native_loader=native_loader),
            model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                              vit_heads=4, dropout_rate=0.0,
                              dtype="float32", vocab_size=32,
                              max_seq_len=64),
            optim=OptimConfig(learning_rate=3e-3),
            mesh=MeshConfig(),
            checkpoint=CheckpointConfig(save_best=False, save_last=False),
        )

    results = {}
    for use_native in (True, False):
        trainer = Trainer(cfg(use_native))
        try:
            assert (trainer._prefetcher is not None) == use_native
            results[use_native] = trainer.train_one_epoch(1)
        finally:
            trainer.close()
    assert results[True]["loss"] == results[False]["loss"]
    assert results[True]["count"] == results[False]["count"]


def test_resume_keeps_native_loader(tmp_path):
    """The resume heap-corruption bug that used to force a numpy
    fallback here was root-caused to buffer donation of orbax-restored
    state and fixed in Checkpointer.restore_state (re-materializing
    restored arrays — see the flight-recorder A/B in
    runs/flightrec-repro-r7): the prefetcher was innocent, so resumed
    runs keep the native path and the resumed epoch trains."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.train.loop import Trainer

    def cfg(resume):
        return TrainConfig(
            epochs=2,
            data=DataConfig(dataset="synthetic", batch_size=16,
                            synthetic_train_size=32,
                            synthetic_test_size=16, image_size=32,
                            native_loader=True),
            model=ModelConfig(width_mult=0.5, dtype="float32"),
            optim=OptimConfig(),
            mesh=MeshConfig(),
            checkpoint=CheckpointConfig(directory=str(tmp_path),
                                        save_best=False, resume=resume),
        )

    fresh = Trainer(cfg(resume=False))
    try:
        assert fresh._prefetcher is not None  # fresh runs keep native
        fresh.train_one_epoch(1)
        fresh.start_epoch = 1
        fresh.ckpt.save_state(1, fresh._payload())
        fresh.ckpt.wait()
    finally:
        fresh.close()

    resumed = Trainer(cfg(resume=True))
    try:
        assert resumed._prefetcher is not None  # native on resume too
        assert resumed.start_epoch == 2
        # The post-resume epoch — the donated-restored-state window
        # the old bug lived in — trains through the native path.
        m = resumed.train_one_epoch(2)
        assert m["count"] == 32
    finally:
        resumed.close()
