"""Native C++ host-side batcher tests (cxx/batcher.cc via ctypes).

The native path must be bit-identical to the numpy fallback: the gather
is the TPU-native replacement for the reference's DataLoader worker
processes (cifar10_mpi_mobilenet_224.py:126-133) and feeds raw uint8
batches to the on-device augmentation.
"""

import numpy as np
import pytest

from tpunet.data import native
from tpunet.data.pipeline import host_index_sequence, train_batches

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native batcher not built (no g++?)")


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(3)
    x = rng.integers(0, 256, size=(997, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 10, size=997).astype(np.int32)
    return x, y


def test_gather_rows_matches_numpy(data):
    x, _ = data
    idx = np.random.default_rng(0).permutation(len(x))[:300]
    np.testing.assert_array_equal(native.gather_rows(x, idx), x[idx])


def test_gather_rows_single_thread(data):
    x, _ = data
    idx = np.asarray([5, 5, 0, 996], dtype=np.int64)
    np.testing.assert_array_equal(
        native.gather_rows(x, idx, n_threads=1), x[idx])


def test_prefetcher_matches_python_pipeline(data):
    x, y = data
    gb = 64
    pf = native.NativePrefetcher(x, y, local_batch=gb, depth=2, n_threads=2)
    for epoch in (0, 1):
        idx = host_index_sequence(len(x), global_batch=gb, seed=42,
                                  epoch=epoch)
        got = list(pf.iter_epoch(idx))
        want = list(train_batches(x, y, global_batch=gb, seed=42,
                                  epoch=epoch))
        assert len(got) == len(want) == len(x) // gb
        for (gx, gy), (wx, wy) in zip(got, want):
            np.testing.assert_array_equal(gx, wx)
            np.testing.assert_array_equal(gy, wy)
    pf.close()


def test_prefetcher_multi_host_slices(data):
    x, y = data
    gb = 32
    seqs = [host_index_sequence(len(x), global_batch=gb, seed=1, epoch=4,
                                process_index=p, process_count=2)
            for p in range(2)]
    pf = native.NativePrefetcher(x, y, local_batch=gb // 2)
    per_host = [list(pf.iter_epoch(s)) for s in seqs]
    pf.close()
    want = list(train_batches(x, y, global_batch=gb, seed=1, epoch=4))
    for s, (wx, wy) in enumerate(want):
        gx = np.concatenate([per_host[p][s][0] for p in range(2)])
        gy = np.concatenate([per_host[p][s][1] for p in range(2)])
        np.testing.assert_array_equal(gx, wx)
        np.testing.assert_array_equal(gy, wy)
