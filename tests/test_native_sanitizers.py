"""Sanitizer builds of the native batcher (scripts/check_sanitizers.py).

The slow tests build ASan/UBSan/TSan variants of cxx/batcher.cc and
drive the full stress matrix (concurrent journal writers + live
snapshot readers, create/stop/destroy churn, epoch cycling,
concurrent gathers) with the variant loaded via TPUNET_NATIVE_LIB and
the runtime LD_PRELOADed. A host whose toolchain can't run a variant
SKIPS — loudly, because a skip means the batcher's concurrency went
unverified here, not that it is fine.

The seqlock regression matters most: the journal ring used to write
plain fields "racy by design" (a formal C++ data race — the first
TSan run of the old code reported ~50 races in journal_snapshot);
test_tsan_stress is what keeps the ring honest.

Non-slow tests cover the gate's own plumbing (variant parsing, env
wiring, the native-lib override) without compiling anything.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "scripts"))

import check_sanitizers  # noqa: E402


def _stress_env(lib_path):
    env = dict(os.environ)
    env["TPUNET_NATIVE_LIB"] = str(lib_path)
    env.pop("LD_PRELOAD", None)
    return env


# -- non-slow: gate plumbing ------------------------------------------

def test_unknown_variant_is_usage_error():
    assert check_sanitizers.main(["--variants", "msan"]) == 2


def test_variant_table_covers_cli_default():
    defaults = {"asan", "ubsan", "tsan"}
    assert set(check_sanitizers.VARIANTS) == defaults
    for spec in check_sanitizers.VARIANTS.values():
        assert "fsanitize" in spec and "runtime" in spec and "env" in spec


def test_native_lib_override_requires_existing_file(tmp_path):
    """TPUNET_NATIVE_LIB pointing at a missing .so must fail the
    child (exit 3), never silently fall back to the default build —
    a sanitizer gate that tests the wrong library would always pass."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "_native_stress.py"), "restart"],
        env=_stress_env(tmp_path / "nope.so"),
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 3, res.stdout + res.stderr
    assert "unavailable" in res.stderr


def test_stress_driver_unknown_scenario_is_usage_error():
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "_native_stress.py"), "bogus"],
        capture_output=True, text=True, timeout=120)
    assert res.returncode == 2


def test_stress_driver_passes_on_plain_build():
    """The stress scenarios themselves hold on the default (see
    check_sanitizers for the instrumented runs)."""
    from tpunet.data import native
    if not native.available():
        pytest.skip("native batcher unavailable (no C++ toolchain)")
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "_native_stress.py"), "churn",
         "restart"],
        capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "PASS" in res.stdout


# -- slow: the sanitizer matrix ---------------------------------------

def _run(variant):
    result = check_sanitizers.run_variant(variant)
    if result.status == "SKIP":
        pytest.skip(
            f"TOOLCHAIN LIMITATION — {variant} sanitizer cannot run "
            f"here ({result.detail}); the native batcher's "
            f"concurrency is UNVERIFIED by {variant} on this host. "
            f"Run scripts/check_sanitizers.py on a host with g++ + "
            f"{check_sanitizers.VARIANTS[variant]['runtime']}.")
    assert result.status == "PASS", \
        f"{variant} reported findings:\n{result.detail}"


@pytest.mark.slow
def test_asan_stress():
    _run("asan")


@pytest.mark.slow
def test_ubsan_stress():
    _run("ubsan")


@pytest.mark.slow
def test_tsan_stress():
    """TSan over the lock-free journal ring + worker lifecycle — the
    variant the ring's seqlock rewrite exists for."""
    _run("tsan")


@pytest.mark.slow
def test_sanitizer_gate_cli_smoke():
    """The doc'd pre-merge entry point (exit-coded)."""
    res = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts",
                                      "check_sanitizers.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "[PASS] ubsan" in res.stdout or "[SKIP] ubsan" in res.stdout
