"""Fleet aggregation (tpunet/obs/agg/): merge math with its error
bound, live-concurrent vs offline-replay rollup equality, straggler /
stale / growth alerting, and the dashboard's fleet mode end-to-end
(HTTP multi-stream ingest and the two-file --html report)."""

import json
import os
import random
import sys
import threading
import urllib.request

import pytest

from tpunet.obs.agg import Aggregator, merge
from tpunet.obs.registry import Histogram, MemorySink

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _import_dashboard():
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__("obs_dashboard")
    finally:
        sys.path.pop(0)


def _epoch_record(run, epoch, laps, *, unit="tokens", thr=1000.0,
                  peak=2 ** 30, count=None):
    """An obs_epoch record the way the trainer builds one, from raw
    laps through a real Histogram (sample export included)."""
    h = Histogram()
    for v in laps:
        h.observe(v)
    summ = h.summary()
    rec = {
        "kind": "obs_epoch", "run_id": run, "process_index": 0,
        "host": f"host-{run}", "epoch": epoch, "step": 100 * epoch,
        "steps": count if count is not None else summ["count"],
        "train_seconds": 10.0,
        "step_time_mean_s": summ["mean"],
        "step_time_p50_s": summ["p50"],
        "step_time_p90_s": summ["p90"],
        "step_time_p99_s": summ["p99"],
        "step_time_sample": h.export_sample(),
        f"{unit}_per_sec": thr, "mfu": 0.5, "live_processes": 1,
        "input_stall_s": 0.1, "stall_frac": 0.01,
        "device_memory": [{"device": 0, "peak_bytes_in_use": peak}],
    }
    if summ.get("approx"):
        rec["step_time_approx"] = 1
    return rec


def _serve_record(run, *, queue=2, rejected=0, total=100,
                  ttft=0.05, e2e=0.9):
    rng = random.Random(hash(run) & 0xFFFF)
    return {
        "kind": "obs_serve", "run_id": run, "process_index": 0,
        "host": f"host-{run}", "uptime_s": 60.0, "window_s": 10.0,
        "queue_depth": queue, "active_slots": 3, "slots": 8,
        "requests_total": total, "requests_completed": total - rejected,
        "requests_rejected": rejected, "tokens_total": 5000,
        "ttft_count": 50, "ttft_p50_s": ttft,
        "ttft_sample": sorted(ttft + rng.random() * 0.01
                              for _ in range(50)),
        "e2e_count": 50, "e2e_p50_s": e2e,
        "e2e_sample": sorted(e2e + rng.random() * 0.1
                             for _ in range(50)),
    }


# ---------------------------------------------------------------------------
# merge math
# ---------------------------------------------------------------------------


def test_merged_count_and_mean_are_exact():
    # Exactness must hold even when the samples are lossy.
    parts = [(5.0, 1000), (1.0, 3000)]
    assert merge.merged_mean(parts) == pytest.approx(2.0)


def test_merged_quantiles_single_full_stream_match_percentiles():
    # One stream whose sample IS its window: the merge must agree
    # with the histogram's own percentile definition.
    rng = random.Random(7)
    laps = [rng.random() for _ in range(200)]
    h = Histogram()
    for v in laps:
        h.observe(v)
    sample = h.export_sample()
    merged = merge.merged_quantiles([(sample, len(laps), False)],
                                    (50, 90, 99))
    for q in (50, 90, 99):
        assert merged[q] == pytest.approx(h.percentile(q), abs=5e-3)


def test_merged_quantiles_within_documented_rank_bound():
    """The acceptance property: merged quantiles of two lossy streams
    sit within the documented rank-error bound of the ground-truth
    combined distribution."""
    rng = random.Random(42)
    # Unequal sizes and disjoint-ish distributions — the hard case for
    # naive percentile averaging.
    a = [0.010 + rng.random() * 0.002 for _ in range(4000)]
    b = [0.050 + rng.random() * 0.010 for _ in range(1000)]
    parts = []
    for data in (a, b):
        h = Histogram(max_samples=512)      # force reservoir loss
        for v in data:
            h.observe(v)
        parts.append((h.export_sample(), len(data), h.saturated))
    bound = merge.rank_error_bound(parts)
    assert 0 < bound < 0.2
    truth = sorted(a + b)
    n = len(truth)
    merged = merge.merged_quantiles(parts, (50, 90, 99))
    for q in (50, 90, 99):
        est = merged[q]
        # Empirical CDF of the true combined data at the estimate.
        import bisect
        rank = bisect.bisect_right(truth, est) / n
        slack = bound + 1.0 / n   # interpolation half-step
        assert abs(rank - q / 100.0) <= slack, (
            f"p{q}: est {est:.6f} has true rank {rank:.4f}, "
            f"outside ±{slack:.4f}")


def test_rank_bound_tightens_with_sample_size():
    small = merge.part_rank_error(16, True)
    big = merge.part_rank_error(256, True)
    assert big < small
    # Unsaturated windows only pay export striding.
    assert merge.part_rank_error(256, False) == pytest.approx(1 / 512)


def test_histogram_export_sample_is_bounded_and_sorted():
    h = Histogram()
    rng = random.Random(3)
    for _ in range(10_000):
        h.observe(rng.random())
    s = h.export_sample()
    assert len(s) == Histogram.EXPORT_SAMPLE_MAX
    assert s == sorted(s)
    full = h.export_sample(max_n=100_000)
    assert len(full) == len(h.values)


# ---------------------------------------------------------------------------
# aggregator: rollups, live-vs-replay equality, alerts
# ---------------------------------------------------------------------------


def _two_stream_records():
    rng = random.Random(0)
    by_stream = {}
    for run, base in (("run-a", 0.01), ("run-b", 0.08)):
        recs = []
        for ep in range(1, 4):
            laps = [base + rng.random() * 0.002 for _ in range(50)]
            recs.append(_epoch_record(run, ep, laps,
                                      peak=2 ** 30 + ep * 1000))
            for s in range(100 * ep - 3, 100 * ep):
                recs.append({"kind": "obs_step", "run_id": run,
                             "process_index": 0, "step": s,
                             "step_time_s": base})
        recs.append(_serve_record(f"serve-{run}",
                                  rejected=10 if run == "run-b" else 0))
        by_stream[run] = recs
    return by_stream


def test_fleet_rollup_exact_merges_and_straggler_alert():
    by_stream = _two_stream_records()
    agg = Aggregator(straggler_factor=2.0)
    sink = MemorySink()
    agg.registry.add_sink(sink)
    for recs in by_stream.values():
        agg.ingest_many(recs, stamp_time=False)
    rollup = agg.emit_rollup()

    assert rollup["streams"] == 4          # 2 trainers + 2 serve
    # Exact merged count and mean across both trainer streams.
    assert rollup["steps_total"] == 300
    expect_mean = sum(
        r["step_time_mean_s"] * r["steps"]
        for recs in by_stream.values() for r in recs
        if r.get("kind") == "obs_epoch") / 300
    # Exact up to the record's own 6-decimal rounding.
    assert rollup["step_time_mean_s"] == pytest.approx(expect_mean,
                                                       abs=1e-6)
    assert rollup["tokens_per_sec"] == pytest.approx(2000.0)
    # The inflated stream is named and the alert fired.
    assert rollup["slowest_stream"] == "run-b/0"
    assert rollup["straggler_factor"] > 2.0
    reasons = [a["reason"] for a in agg.bridge.alerts]
    assert "straggler" in reasons
    alert = [a for a in agg.bridge.alerts
             if a["reason"] == "straggler"][0]
    assert alert["stream"] == "run-b/0"
    assert alert["scope"] == "fleet"
    # Alert reached the sinks as an obs_alert record.
    assert any(r.get("reason") == "straggler"
               for r in sink.by_kind("obs_alert"))
    # Serve SLO rollup: sums and merged percentiles present.
    assert rollup["serve_replicas"] == 2
    assert rollup["serve_queue_depth"] == 4
    assert rollup["serve_requests_rejected"] == 10
    assert rollup["serve_reject_rate"] == pytest.approx(0.05)
    assert 0.04 < rollup["serve_ttft_p50_s"] < 0.07
    assert rollup["serve_ttft_rank_err"] > 0
    # obs_fleet record emitted with the same content.
    fleet = sink.by_kind("obs_fleet")
    assert fleet and fleet[-1]["steps_total"] == 300


def test_concurrent_ingest_and_offline_replay_agree(tmp_path):
    """The acceptance property: two streams ingested concurrently
    (threads, interleaved arbitrarily) and the same two record files
    replayed offline produce the identical fleet rollup."""
    by_stream = _two_stream_records()

    live = Aggregator(straggler_factor=2.0)
    threads = [threading.Thread(
        target=lambda recs=recs: live.ingest_many(recs))
        for recs in by_stream.values()]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    replay = Aggregator(straggler_factor=2.0)
    for run, recs in by_stream.items():
        path = tmp_path / f"{run}.jsonl"
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        replay.replay_file(str(path))

    assert live.rollup() == replay.rollup()
    # Both fire the same alerts (deterministic, order-independent).
    live.bridge.check(live.rollup(), live.streams())
    replay.bridge.check(replay.rollup(), replay.streams())
    strip = (lambda alerts: sorted(
        (a["reason"], a.get("stream", "")) for a in alerts))
    assert strip(live.bridge.alerts) == strip(replay.bridge.alerts)


def test_alert_latch_fires_once_and_rearms():
    agg = Aggregator(straggler_factor=2.0)
    slow = _epoch_record("b", 1, [0.08] * 20)
    fast = _epoch_record("a", 1, [0.01] * 20)
    agg.ingest_many([fast, slow], stamp_time=False)
    agg.emit_rollup()
    agg.emit_rollup()       # condition persists: no re-page
    assert [a["reason"] for a in agg.bridge.alerts] == ["straggler"]
    # Condition clears (the slow stream recovers), then degrades again
    # -> one new page.
    agg.ingest(_epoch_record("b", 2, [0.011] * 20), stamp_time=False)
    agg.emit_rollup()
    agg.ingest(_epoch_record("b", 3, [0.09] * 20), stamp_time=False)
    agg.emit_rollup()
    assert [a["reason"] for a in agg.bridge.alerts] == ["straggler",
                                                        "straggler"]


def test_stream_stale_alert_uses_injected_clock():
    clock = [100.0]
    agg = Aggregator(clock=lambda: clock[0], stream_stale_s=30.0)
    agg.ingest(_epoch_record("a", 1, [0.01] * 5))
    agg.ingest(_epoch_record("b", 1, [0.01] * 5))
    agg.emit_rollup()
    assert not agg.bridge.alerts
    clock[0] += 31.0
    agg.ingest(_epoch_record("a", 2, [0.01] * 5))   # a stays live
    agg.emit_rollup()
    stale = [a for a in agg.bridge.alerts
             if a["reason"] == "stream_stale"]
    assert [a["stream"] for a in stale] == ["b/0"]


def test_mem_growth_alert_names_the_leaking_stream():
    agg = Aggregator(mem_growth_bytes_per_epoch=10_000.0)
    for ep in range(1, 6):
        agg.ingest(_epoch_record("flat", ep, [0.01] * 5,
                                 peak=2 ** 30), stamp_time=False)
        agg.ingest(_epoch_record("leaky", ep, [0.01] * 5,
                                 peak=2 ** 30 + ep * 10 ** 6),
                   stamp_time=False)
    agg.emit_rollup()
    growth = [a for a in agg.bridge.alerts
              if a["reason"] == "mem_growth"]
    assert growth and growth[0]["stream"] == "leaky/0"
    assert growth[0]["slope_bytes_per_epoch"] > 10_000


def test_operator_rules_fire_per_stream_and_fleet_wide():
    agg = Aggregator(rules=("serve_queue_depth > 5",))
    agg.ingest(_serve_record("r1", queue=2), stamp_time=False)
    agg.ingest(_serve_record("r2", queue=4), stamp_time=False)
    agg.emit_rollup()
    fired = [a for a in agg.bridge.alerts
             if a["reason"] == "gauge_predicate"]
    # Fleet sum (6) breaches; neither replica (2, 4) does.
    assert [a["scope"] for a in fired] == ["fleet"]
    assert fired[0]["value"] == 6


def test_bad_rule_fails_at_construction():
    with pytest.raises(ValueError, match="bad gauge rule"):
        Aggregator(rules=("what is this",))


def test_identityless_records_fall_back_to_source_streams():
    agg = Aggregator()
    agg.ingest({"kind": "obs_step", "step": 1, "step_time_s": 0.01},
               source="old-a.jsonl")
    agg.ingest({"kind": "obs_step", "step": 1, "step_time_s": 0.02},
               source="old-b.jsonl")
    assert [s.key for s in agg.streams()] == ["old-a.jsonl",
                                              "old-b.jsonl"]


def test_drop_source_forgets_only_that_files_streams():
    agg = Aggregator()
    agg.ingest(_epoch_record("a", 1, [0.01] * 5), source="a.jsonl")
    agg.ingest(_epoch_record("b", 1, [0.01] * 5), source="b.jsonl")
    agg.drop_source("a.jsonl")
    assert [s.key for s in agg.streams()] == ["b/0"]


# ---------------------------------------------------------------------------
# dashboard fleet mode
# ---------------------------------------------------------------------------


def _write_stream_files(tmp_path):
    by_stream = _two_stream_records()
    paths = []
    for run, recs in by_stream.items():
        path = tmp_path / f"{run}.jsonl"
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        paths.append(str(path))
    return paths


def test_dashboard_two_files_render_fleet_and_serve_panels(
        tmp_path, capsys):
    """Acceptance: --html renders the fleet + serve SLO panels from
    two metrics.jsonl files without a live run."""
    dash = _import_dashboard()
    paths = _write_stream_files(tmp_path)
    out = tmp_path / "fleet.html"
    rc = dash.main(paths + ["--once", "--html", str(out)])
    assert rc == 0
    text = capsys.readouterr().out
    assert "fleet dashboard" in text
    assert "straggler" in text          # alert line in the frame
    html = out.read_text()
    assert "Serve SLO (fleet)" in html
    assert "fleet TTFT p50" in html
    assert "Fleet alerts" in html
    assert "run-b/0" in html
    assert "straggler factor" in html


def test_dashboard_single_path_keeps_single_run_view(tmp_path, capsys):
    dash = _import_dashboard()
    paths = _write_stream_files(tmp_path)
    rc = dash.main([paths[0], "--once"])
    assert rc == 0
    text = capsys.readouterr().out
    assert "obs dashboard" in text      # not the fleet renderer
    assert "fleet" not in text


def test_dashboard_listen_fleet_routes_concurrent_posts(capsys):
    """Two runs POSTing ndjson concurrently (the real
    HttpLineTransport wire format) become two streams; GET returns
    the fleet frame."""
    dash = _import_dashboard()
    from tpunet.obs.agg import Aggregator
    from tpunet.obs.export.http import HttpLineTransport

    agg = Aggregator(straggler_factor=2.0)
    buf = dash.RecordBuffer()
    server = dash.serve_http(0, buf, "test", agg=agg)
    port = server.server_address[1]
    try:
        by_stream = _two_stream_records()
        url = f"http://127.0.0.1:{port}/"
        threads = [threading.Thread(
            target=lambda recs=recs: HttpLineTransport(url, timeout=5)
            .send_many(recs)) for recs in by_stream.values()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        keys = [s.key for s in agg.streams()]
        assert keys == ["run-a/0", "run-b/0",
                        "serve-run-a/0", "serve-run-b/0"]
        frame = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/", timeout=5).read().decode()
        assert "fleet dashboard" in frame
        assert "run-b/0" in frame
    finally:
        server.shutdown()
        server.server_close()


def test_straggler_latch_hands_off_to_a_new_offender():
    """If replica B recovers while replica C degrades (the fleet
    factor never dipping below threshold), C must still get its own
    page — the latch is per offending stream."""
    agg = Aggregator(straggler_factor=2.0)
    agg.ingest(_epoch_record("a", 1, [0.01] * 20), stamp_time=False)
    agg.ingest(_epoch_record("b", 1, [0.08] * 20), stamp_time=False)
    agg.ingest(_epoch_record("c", 1, [0.012] * 20), stamp_time=False)
    agg.emit_rollup()
    # B recovers, C degrades — factor stays above threshold throughout.
    agg.ingest(_epoch_record("b", 2, [0.011] * 20), stamp_time=False)
    agg.ingest(_epoch_record("c", 2, [0.09] * 20), stamp_time=False)
    agg.emit_rollup()
    named = [(a["reason"], a["stream"]) for a in agg.bridge.alerts]
    assert named == [("straggler", "b/0"), ("straggler", "c/0")]


def test_mixed_unit_fleet_sums_each_unit():
    agg = Aggregator()
    agg.ingest(_epoch_record("lm", 1, [0.01] * 10, unit="tokens",
                             thr=5000.0), stamp_time=False)
    agg.ingest(_epoch_record("img", 1, [0.01] * 10, unit="examples",
                             thr=300.0), stamp_time=False)
    r = agg.rollup()
    assert r["tokens_per_sec"] == pytest.approx(5000.0)
    assert r["examples_per_sec"] == pytest.approx(300.0)
    assert r["throughput_units"] == ["examples", "tokens"]
    assert "throughput_unit" not in r


def test_rule_with_malformed_number_gets_the_rule_diagnostic():
    from tpunet.obs.health import GaugePredicate

    for bad in ("mfu > 1e", "x + ../s", "y < +-3"):
        with pytest.raises(ValueError, match="bad gauge rule"):
            GaugePredicate.parse(bad)
