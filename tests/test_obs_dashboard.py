"""Dashboard / summarizer / tail path: live rendering from an
append-in-progress metrics.jsonl (torn trailing line included), the
HTTP listen mode fed by the real HttpLineTransport, the shared
summarizer's step-window trend, obs_report --json, and the registry
satellites (histogram reservoir bound, snapshot collision rules)."""

import json
import os
import sys

import pytest

from tpunet.obs.registry import Gauge, Histogram, Registry
from tpunet.obs.summary import step_windows, summarize
from tpunet.utils.logging import MetricsLogger

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


def _import_script(name):
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _write_run(path, n_epochs=3, torn=True):
    with open(path, "w") as f:
        for ep in range(1, n_epochs + 1):
            f.write(json.dumps({
                "epoch": ep, "seconds": 2.0, "step": 4 * ep,
                "train_loss": 1.0 / ep, "train_accuracy": 0.5,
                "test_loss": 1.1 / ep, "test_accuracy": 0.6,
                "tokens_per_sec": 1000.0 + ep}) + "\n")
            f.write(json.dumps({
                "kind": "obs_epoch", "epoch": ep, "step": 4 * ep,
                "train_seconds": 1.5, "steps": 4,
                "step_time_p50_s": 0.01, "step_time_p90_s": 0.02,
                "step_time_p99_s": 0.03, "input_stall_s": 0.1,
                "stall_frac": 0.0625, "tokens_per_sec": 1000.0 + ep,
                "mfu": 0.5, "live_processes": 1,
                "device_memory": [{"device": 0,
                                   "peak_bytes_in_use": 2**30}]}) + "\n")
            for s in range(4 * (ep - 1), 4 * ep):
                f.write(json.dumps({
                    "kind": "obs_step", "step": s,
                    "step_time_s": 0.01 + 0.001 * s,
                    "data_wait_s": 0.001}) + "\n")
        f.write(json.dumps({
            "kind": "obs_alert", "reason": "step_stall", "step": 7,
            "severity": "fatal", "step_time_s": 0.9}) + "\n")
        if torn:
            f.write('{"kind": "obs_epoch", "epo')      # write in flight


# ---------------------------------------------------------------------------
# tail_records
# ---------------------------------------------------------------------------


def test_tail_records_incremental_and_torn_line(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        f.write('{"epoch": 1}\n{"epoch": 2}\n{"epoch": 3')   # torn
    recs, off, reset = MetricsLogger.tail_records(p, 0)
    assert [r["epoch"] for r in recs] == [1, 2] and not reset
    # the torn tail was NOT consumed; completing it yields it next poll
    with open(p, "a") as f:
        f.write('}\n{"epoch": 4}\n')
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert [r["epoch"] for r in recs] == [3, 4] and not reset
    recs, off2, reset = MetricsLogger.tail_records(p, off)
    assert recs == [] and off2 == off and not reset


def test_tail_records_signals_reset_on_truncation(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        f.write('{"epoch": 1}\n{"epoch": 2}\n')
    _, off, _ = MetricsLogger.tail_records(p, 0)
    with open(p, "w") as f:                   # fresh run truncates
        f.write('{"epoch": 1}\n')
    recs, _, reset = MetricsLogger.tail_records(p, off)
    # the reset flag is the caller's cue to drop old-run state
    assert [r["epoch"] for r in recs] == [1] and reset


def test_tail_records_missing_file():
    recs, off, reset = MetricsLogger.tail_records("/nonexistent/x.jsonl",
                                                  0)
    assert recs == [] and off == 0 and not reset


def test_tail_records_truncated_midtail_no_double_read(tmp_path):
    """A fresh run truncates the file while we are mid-tail: the
    reader must resync from the start of the NEW run exactly once —
    no crash, no old-run leftovers, no record read twice."""
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        for i in range(5):
            f.write(json.dumps({"kind": "obs_step", "step": i}) + "\n")
    recs, off, reset = MetricsLogger.tail_records(p, 0)
    assert len(recs) == 5 and not reset
    # Fresh run truncates underneath us and starts writing.
    with open(p, "w") as f:
        f.write(json.dumps({"kind": "obs_step", "step": 100}) + "\n")
    seen = []
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert reset
    seen += recs
    with open(p, "a") as f:
        f.write(json.dumps({"kind": "obs_step", "step": 101}) + "\n")
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert not reset
    seen += recs
    assert [r["step"] for r in seen] == [100, 101]   # exactly once each


def test_tail_records_rotation_to_smaller_file_resets(tmp_path):
    """Rotation via os.replace (new inode, smaller file) looks like a
    truncation to the size-based check: reset + reread from start."""
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        for i in range(10):
            f.write(json.dumps({"run": "old", "step": i}) + "\n")
    _, off, _ = MetricsLogger.tail_records(p, 0)
    rot = str(tmp_path / "rotated.jsonl")
    with open(rot, "w") as f:
        f.write(json.dumps({"run": "new", "step": 0}) + "\n")
    os.replace(rot, p)
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert reset
    assert [r["run"] for r in recs] == ["new"]


def test_tail_records_rotation_to_larger_file_resyncs_without_crash(
        tmp_path):
    """Rotation to a LARGER file defeats the size heuristic (no inode
    tracking); the reader must still neither crash nor double-read:
    the stale offset lands mid-record, the chopped line fails to
    parse and is skipped, and the stream resyncs at the next newline
    onto new-run records only."""
    p = str(tmp_path / "metrics.jsonl")
    with open(p, "w") as f:
        f.write(json.dumps({"run": "old", "step": 0}) + "\n")
    _, off, _ = MetricsLogger.tail_records(p, 0)
    rot = str(tmp_path / "rotated.jsonl")
    with open(rot, "w") as f:
        for i in range(50):
            f.write(json.dumps({"run": "new", "step": i,
                                "pad": "x" * 20}) + "\n")
    os.replace(rot, p)
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert not reset                      # undetectable by size alone
    assert all(r["run"] == "new" for r in recs)   # never old-run data
    # Follow-up appends keep flowing normally.
    with open(p, "a") as f:
        f.write(json.dumps({"run": "new", "step": 50}) + "\n")
    recs, off, reset = MetricsLogger.tail_records(p, off)
    assert [r["step"] for r in recs] == [50] and not reset


# ---------------------------------------------------------------------------
# summarizer
# ---------------------------------------------------------------------------


def test_summarize_sections_and_totals(tmp_path):
    p = str(tmp_path / "metrics.jsonl")
    _write_run(p)
    s = summarize(MetricsLogger.read_records(p))
    assert len(s["epochs"]) == 3 and len(s["obs_epochs"]) == 3
    assert s["alerts"][0]["reason"] == "step_stall"
    t = s["totals"]
    assert t["stall_frac"] == pytest.approx(0.3 / 4.5, abs=1e-4)
    assert t["tokens_per_sec"] == 1003.0
    assert t["peak_bytes_in_use"] == 2**30
    assert t["alerts"] == 1


def test_step_windows_show_a_trend():
    steps = [{"kind": "obs_step", "step": s,
              "step_time_s": 0.01 if s < 50 else 0.02}
             for s in range(100)]
    ws = step_windows(steps, n_windows=10)
    assert len(ws) == 10
    assert ws[0]["step_lo"] == 0 and ws[-1]["step_hi"] == 99
    assert sum(w["samples"] for w in ws) == 100
    # the slowdown at step 50 is visible in the window means
    assert ws[0]["step_time_mean_s"] == pytest.approx(0.01)
    assert ws[-1]["step_time_mean_s"] == pytest.approx(0.02)


# ---------------------------------------------------------------------------
# obs_report --json / obs_dashboard
# ---------------------------------------------------------------------------


def test_obs_report_json_output(tmp_path, capsys):
    p = str(tmp_path / "metrics.jsonl")
    _write_run(p)
    obs_report = _import_script("obs_report")
    assert obs_report.main([p, "--json"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert set(out) == {"epochs", "obs_epochs", "step_windows",
                        "alerts", "totals"}
    assert out["totals"]["obs_steps"] == 12


def test_obs_report_text_has_trend_and_alert_sections(tmp_path, capsys):
    p = str(tmp_path / "metrics.jsonl")
    _write_run(p)
    obs_report = _import_script("obs_report")
    assert obs_report.main([p]) == 0
    out = capsys.readouterr().out
    assert "== step-time trend (obs_step windows) ==" in out
    assert "== alerts (1) ==" in out
    assert "step_stall" in out


def test_dashboard_once_renders_live_file(tmp_path, capsys):
    p = str(tmp_path / "metrics.jsonl")
    _write_run(p, torn=True)                  # append in flight
    dash = _import_script("obs_dashboard")
    assert dash.main([str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "tpunet obs dashboard" in out
    assert "ALERTS (1)" in out
    assert "step-time trend" in out
    assert "MFU 0.500" in out


def test_dashboard_html_report(tmp_path, capsys):
    p = str(tmp_path / "metrics.jsonl")
    _write_run(p)
    out_html = str(tmp_path / "report.html")
    dash = _import_script("obs_dashboard")
    assert dash.main([p, "--once", "--html", out_html]) == 0
    html = open(out_html).read()
    assert "<svg" in html and "polyline" in html
    assert "step_stall" in html
    assert "Throughput per epoch" in html
    assert "prefers-color-scheme: dark" in html


def test_record_buffer_bounds_step_records_keeps_the_rest():
    dash = _import_script("obs_dashboard")
    buf = dash.RecordBuffer(max_steps=100)
    buf.feed([{"kind": "obs_epoch", "epoch": 1}])
    buf.feed([{"kind": "obs_step", "step": s, "step_time_s": 0.01}
              for s in range(500)])
    buf.feed([{"kind": "obs_alert", "reason": "nan_loss", "step": 9}])
    records = buf.snapshot()
    steps = [r for r in records if r.get("kind") == "obs_step"]
    # compacted to the most recent window, oldest dropped first
    assert 100 <= len(steps) <= 200
    assert steps[-1]["step"] == 499
    # epoch-grained records and alerts are never compacted away
    assert [r for r in records if r.get("kind") == "obs_epoch"]
    assert [r for r in records if r.get("kind") == "obs_alert"]
    buf.clear()
    assert buf.snapshot() == []


def test_dashboard_listen_mode_roundtrip(tmp_path, capsys):
    """The full live path: HttpLineTransport (the exporter's wire
    format) -> dashboard HTTP listener -> rendered frame."""
    import urllib.request

    dash = _import_script("obs_dashboard")
    buf = dash.RecordBuffer()
    server = dash.serve_http(0, buf, "test")
    port = server.server_address[1]
    try:
        from tpunet.obs.export import HttpLineTransport
        tx = HttpLineTransport(f"http://127.0.0.1:{port}/", timeout=5.0)
        tx.send({"kind": "obs_epoch", "epoch": 1, "step": 4,
                 "steps": 4, "tokens_per_sec": 500.0,
                 "stall_frac": 0.01, "train_seconds": 1.0,
                 "input_stall_s": 0.01, "live_processes": 1})
        tx.send({"kind": "obs_alert", "reason": "nan_loss", "step": 4,
                 "severity": "fatal"})
        assert len(buf.snapshot()) == 2
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=5.0) as r:
            page = r.read().decode()
        assert "tpunet obs dashboard" in page
        assert "nan_loss" in page
    finally:
        server.shutdown()


# ---------------------------------------------------------------------------
# registry satellites: reservoir bound + snapshot collisions
# ---------------------------------------------------------------------------


def test_histogram_exact_below_bound_reservoir_above():
    h = Histogram(max_samples=100)
    for v in range(1, 101):
        h.observe(float(v))
    assert not h.saturated
    s = h.summary()
    assert "approx" not in s
    assert s["p50"] == pytest.approx(50.5)    # exact below the bound
    for v in range(101, 10001):
        h.observe(float(v))
    assert h.saturated and len(h.values) == 100
    s = h.summary()
    assert s["count"] == 10000                # count/mean stay exact
    assert s["mean"] == pytest.approx(5000.5)
    assert h.total == pytest.approx(sum(range(1, 10001)))
    assert s["approx"] == 1
    # the reservoir is a uniform sample: p50 lands near the true median
    assert s["p50"] == pytest.approx(5000.5, rel=0.15)
    h.reset()
    assert len(h) == 0 and h.summary() == {} and not h.saturated


def test_histogram_reservoir_is_deterministic():
    def run():
        h = Histogram(max_samples=10)
        for v in range(1000):
            h.observe(float(v))
        return list(h.values)
    assert run() == run()


def test_registry_rejects_cross_family_name_reuse():
    reg = Registry()
    reg.counter("x")
    with pytest.raises(ValueError, match="already registered as a "
                                         "counter"):
        reg.gauge("x")
    with pytest.raises(ValueError, match="counter"):
        reg.histogram("x")
    reg.counter("x").inc()                    # same family: fine
    reg.gauge("y")
    with pytest.raises(ValueError, match="gauge"):
        reg.counter("y")


def test_snapshot_derived_histogram_key_collision_is_suffixed():
    reg = Registry()
    reg.counter("lap_p50").inc(7.0)           # literal name
    h = reg.histogram("lap")                  # derives lap_p50 etc.
    for v in (1.0, 2.0, 3.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["lap_p50"] == 7.0             # literal key untouched
    assert snap["lap_p50_hist"] == 2.0        # derived key suffixed
    assert snap["lap_p90"] == pytest.approx(2.8)


def test_registry_histogram_honors_max_samples():
    reg = Registry()
    h = reg.histogram("laps", max_samples=4)
    for v in range(100):
        h.observe(float(v))
    assert len(h.values) == 4 and len(h) == 100


def test_histogram_concurrent_observe_loses_nothing():
    """Regression for the serve-path race: HTTP handler threads
    observe serve_* histograms concurrently with the engine thread;
    the unlocked count/total read-modify-writes dropped observations.
    With the lock, accounting is exact under contention."""
    import threading

    h = Histogram(max_samples=200_000)
    n_threads, per = 8, 10_000

    def work():
        for _ in range(per):
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(h) == n_threads * per
    assert h.total == pytest.approx(n_threads * per)
    assert len(h.values) == n_threads * per   # below the bound: exact


def test_histogram_concurrent_observe_in_reservoir_regime():
    """Same race, reservoir path: concurrent replacement must keep
    the sample bounded and the exact tallies exact."""
    import threading

    h = Histogram(max_samples=64)
    n_threads, per = 8, 5_000

    def work():
        for _ in range(per):
            h.observe(1.0)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(h) == n_threads * per
    assert h.total == pytest.approx(n_threads * per)
    assert len(h.values) == 64


def test_gauge_concurrent_set_is_safe():
    import threading

    g = Gauge()

    def work(base):
        for i in range(5_000):
            g.set(base + i)

    threads = [threading.Thread(target=work, args=(k * 10_000,))
               for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Last-write-wins semantics: the final value is SOME thread's
    # final write, never a torn/None value.
    assert g.value is not None
    assert g.value % 10_000 == 4_999
