"""Exporter layer (tpunet/obs/export/): the non-blocking contract.

The promises under test: ``write`` never blocks or raises regardless
of endpoint state; a full queue drops AND counts; close() flushes
in-order with a bounded timeout; and every record that enters write()
is accounted for (enqueued == sent + send_errors + dropped) — plus the
end-to-end smoke: records produced by a real two-step training run
flow through an exporter to its transport.
"""

import json
import socket
import threading
import time

import pytest

from tpunet.config import (CheckpointConfig, DataConfig, ExportConfig,
                           MeshConfig, ModelConfig, ObsConfig,
                           OptimConfig, TrainConfig)
from tpunet.obs import Registry
from tpunet.obs.export import (AsyncExporter, HttpLineTransport,
                               MemoryTransport, StatsdTransport,
                               build_exporters)
from tpunet.obs.export.statsd import record_to_lines


def test_exporter_delivers_in_order_and_flushes_on_close():
    transport = MemoryTransport()
    exp = AsyncExporter(transport, name="mem")
    for i in range(100):
        exp.write({"kind": "obs_step", "step": i})
    exp.close()
    assert [r["step"] for r in transport.records] == list(range(100))
    stats = exp.stats()
    assert stats == {"enqueued": 100, "sent": 100,
                     "send_errors": 0, "dropped": 0}


def test_queue_overflow_drops_and_counts_without_blocking():
    gate = threading.Event()                 # wedged endpoint
    transport = MemoryTransport(gate=gate)
    reg = Registry()
    exp = AsyncExporter(transport, name="mem", queue_size=4,
                        flush_timeout=2.0, registry=reg)
    t0 = time.perf_counter()
    for i in range(50):
        exp.write({"step": i})
    write_time = time.perf_counter() - t0
    # 50 writes against a dead endpoint: pure queue puts, no waiting.
    assert write_time < 0.5
    # 4 queued (+possibly 1 in flight at the gate); the rest dropped.
    assert reg.counter("export_mem_dropped").value >= 45
    gate.set()
    exp.close()
    stats = exp.stats()
    # Total accounting: every one of the 50 writes is either delivered
    # or in the drop counter — nothing silently vanished.
    assert stats["sent"] == stats["enqueued"]
    assert stats["send_errors"] == 0
    assert stats["enqueued"] + stats["dropped"] == 50
    assert len(transport.records) == stats["sent"]


def test_wedged_transport_flush_timeout_accounts_for_leftovers():
    gate = threading.Event()                 # never released: hard wedge
    transport = MemoryTransport(gate=gate)
    reg = Registry()
    exp = AsyncExporter(transport, name="mem", queue_size=4,
                        flush_timeout=0.2, registry=reg)
    for i in range(10):
        exp.write({"step": i})
    t0 = time.perf_counter()
    exp.close()                              # join times out, bounded
    assert time.perf_counter() - t0 < 2.0
    stats = exp.stats()
    # Nothing delivered, yet all 10 writes are in the drop counter:
    # put_nowait overflows plus the flush-timeout leftovers.
    assert stats["sent"] == 0 and stats["send_errors"] == 0
    assert stats["dropped"] == 10
    assert reg.counter("export_mem_dropped").value == 10
    gate.set()                               # un-wedge: the abandoned
    time.sleep(0.2)                          # thread discards the queue;
    # at most the single in-flight send completes, and it stays
    # accounted as dropped (over-delivery, never double-counting).
    assert len(transport.records) <= 1
    assert exp.stats()["sent"] == 0


def test_flaky_transport_errors_are_counted_not_raised():
    transport = MemoryTransport(fail_every=3)
    reg = Registry()
    exp = AsyncExporter(transport, name="mem", registry=reg)
    for i in range(30):
        exp.write({"step": i})
    exp.close()
    stats = exp.stats()
    assert stats["send_errors"] == 10
    assert stats["sent"] == 20
    assert stats["enqueued"] == stats["sent"] + stats["send_errors"]
    assert reg.gauge("export_mem_send_errors").value == 10


def test_dead_http_endpoint_never_blocks_write():
    # A port nothing listens on: connection refused on the drain
    # thread; the training-thread side must stay O(queue put).
    transport = HttpLineTransport("http://127.0.0.1:9/", timeout=0.2)
    reg = Registry()
    exp = AsyncExporter(transport, name="http", queue_size=8,
                        flush_timeout=3.0, registry=reg)
    t0 = time.perf_counter()
    for i in range(200):
        exp.write({"kind": "obs_step", "step": i})
    assert time.perf_counter() - t0 < 0.5
    exp.close()
    stats = exp.stats()
    # Nothing was ever delivered, and every one of the 200 writes is
    # accounted for across the error and drop counters.
    assert stats["sent"] == 0
    assert (stats["sent"] + stats["send_errors"] + stats["dropped"]
            == 200)


def test_statsd_lines_and_datagram_delivery():
    lines = record_to_lines(
        {"kind": "obs_epoch", "epoch": 3, "mfu": 0.5,
         "unit": "tokens", "partial": True, "device_memory": []},
        prefix="tp")
    assert "tp.obs_epoch.epoch:3|g" in lines
    assert "tp.obs_epoch.mfu:0.5|g" in lines
    # strings, bools, and nested fields never become gauges
    assert not any("unit" in l or "partial" in l or "device_memory" in l
                   for l in lines)

    rx = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    rx.bind(("127.0.0.1", 0))
    rx.settimeout(5.0)
    port = rx.getsockname()[1]
    transport = StatsdTransport("127.0.0.1", port)
    exp = AsyncExporter(transport, name="statsd")
    exp.write({"kind": "obs_step", "step": 7, "step_time_s": 0.25})
    exp.close()
    payload = rx.recv(65536).decode()
    rx.close()
    assert "tpunet.obs_step.step:7|g" in payload
    assert "tpunet.obs_step.step_time_s:0.25|g" in payload


def test_build_exporters_validates_endpoints():
    reg = Registry()
    with pytest.raises(ValueError, match="HOST:PORT"):
        build_exporters(ExportConfig(statsd="nonsense"), reg)
    with pytest.raises(ValueError, match="http"):
        build_exporters(ExportConfig(http="ftp://x/"), reg)
    assert build_exporters(ExportConfig(), reg) == []


def test_smoke_two_steps_records_flow_end_to_end(tmp_path):
    """CI smoke: a real (CPU) training run with --obs-step-every 1
    streams obs_step and obs_epoch records through an exporter."""
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0, dtype="float32",
                          vocab_size=32, max_seq_len=64),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmp_path),
                                    save_best=False, save_last=False),
        obs=ObsConfig(step_records_every=1),
    )
    from tpunet.train.loop import Trainer
    trainer = Trainer(cfg)
    transport = MemoryTransport()
    exp = AsyncExporter(transport, name="smoke",
                        registry=trainer.obs.registry)
    trainer.obs.add_sink(exp)
    try:
        trainer.train()                      # 2 steps (32/16)
    finally:
        trainer.close()
    exp.close()
    steps = [r for r in transport.records if r.get("kind") == "obs_step"]
    assert [r["step"] for r in steps] == [0, 1]
    assert all(r["step_time_s"] > 0 for r in steps)
    epoch = [r for r in transport.records
             if r.get("kind") == "obs_epoch"]
    assert len(epoch) == 1 and epoch[0]["steps"] == 2
    assert exp.stats()["dropped"] == 0
    # ... and the same stream landed in metrics.jsonl (shared schema).
    from tpunet.utils.logging import MetricsLogger
    on_disk = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert [r for r in on_disk if r.get("kind") == "obs_step"]


def test_batching_transport_gets_backlogs_in_order():
    """A transport with send_many (the HTTP one) drains the queue in
    batches — order preserved, every record counted exactly once."""
    batches = []
    gate = threading.Event()

    class BatchProbe:
        def send_many(self, records):
            gate.wait()
            batches.append(list(records))

        def send(self, record):
            self.send_many([record])

    exp = AsyncExporter(BatchProbe(), name="batch", queue_size=256)
    for i in range(100):
        exp.write({"step": i})
    gate.set()                                # backlog built up first
    exp.close()
    flat = [r["step"] for b in batches for r in b]
    assert flat == list(range(100))
    assert len(batches) < 100                 # actually batched
    assert max(len(b) for b in batches) <= 64
    assert exp.stats() == {"enqueued": 100, "sent": 100,
                           "send_errors": 0, "dropped": 0}


def test_exported_records_are_json_serializable():
    """The HTTP transport json.dumps every record — the epoch record's
    nested fields must stay plain types."""
    sent = []

    class Probe:
        def send(self, record):
            sent.append(json.loads(json.dumps(record)))

    exp = AsyncExporter(Probe(), name="probe")
    exp.write({"kind": "obs_epoch", "epoch": 1,
               "device_memory": [{"device": 0, "bytes_in_use": 5}],
               "mfu": 0.5})
    exp.close()
    assert sent[0]["device_memory"][0]["bytes_in_use"] == 5
