"""Run-health watchdog (tpunet/obs/health.py): each detector emits an
``obs_alert`` record, rate limiting works, ``--halt-on-unhealthy``
raises after the record lands, and the trainer integration writes
alerts into metrics.jsonl before any hard abort."""

import jax
import jax.numpy as jnp
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, ObsConfig, OptimConfig,
                           TrainConfig)
from tpunet.obs import MemorySink, Registry, RunUnhealthyError, Watchdog
from tpunet.utils.logging import MetricsLogger


def make_watchdog(expected_processes=1, clock=None, **cfg_kw):
    cfg = ObsConfig(**cfg_kw)
    reg = Registry()
    sink = MemorySink()
    reg.add_sink(sink)
    kw = {"expected_processes": expected_processes}
    if clock is not None:
        kw["clock"] = clock
    return Watchdog(cfg, reg, **kw), reg, sink


def feed_baseline(wd, n=16, lap=0.01):
    for i in range(n):
        wd.observe_step(i, lap)


def test_step_stall_alert_with_detail():
    wd, reg, sink = make_watchdog(stall_factor=10.0, stall_min_s=0.0)
    feed_baseline(wd)
    wd.observe_step(16, 0.5)                 # 50x the 10ms baseline
    alerts = sink.by_kind("obs_alert")
    assert len(alerts) == 1
    a = alerts[0]
    assert a["reason"] == "step_stall" and a["step"] == 16
    assert a["severity"] == "fatal"
    assert a["step_time_s"] == 0.5
    assert a["baseline_p50_s"] == pytest.approx(0.01)
    assert reg.counter("obs_alerts").value == 1


def test_stall_needs_absolute_floor():
    # 50x a microsecond baseline is still microseconds — not a page.
    wd, _, sink = make_watchdog(stall_factor=10.0, stall_min_s=1.0)
    feed_baseline(wd, lap=1e-5)
    wd.observe_step(16, 5e-4)
    assert sink.by_kind("obs_alert") == []


def test_no_stall_verdict_before_baseline_warmup():
    wd, _, sink = make_watchdog(stall_factor=2.0, stall_min_s=0.0)
    wd.observe_step(0, 0.01)
    wd.observe_step(1, 10.0)                 # compile-step blip
    assert sink.by_kind("obs_alert") == []


def test_alert_cooldown_suppresses_repeats_but_counts_them():
    wd, reg, sink = make_watchdog(stall_factor=10.0, stall_min_s=0.0,
                                  alert_cooldown_steps=50)
    feed_baseline(wd)
    for step in range(16, 26):
        wd.observe_step(step, 0.5)
    assert len(sink.by_kind("obs_alert")) == 1
    assert reg.counter("obs_alerts_suppressed").value == 9
    # ... and a later recurrence past the cooldown fires again
    wd.observe_step(80, 0.5)
    assert len(sink.by_kind("obs_alert")) == 2


def test_nan_and_inf_loss_alert():
    wd, _, sink = make_watchdog()
    wd.observe_loss(5, float("nan"))
    wd.observe_loss(60, float("inf"))
    alerts = sink.by_kind("obs_alert")
    assert [a["reason"] for a in alerts] == ["nan_loss", "nan_loss"]


def test_loss_spike_alert_after_warmup():
    wd, _, sink = make_watchdog(loss_spike_factor=5.0)
    for i in range(6):
        wd.observe_loss(i, 2.0)
    wd.observe_loss(6, 50.0)                 # 25x the EMA
    alerts = sink.by_kind("obs_alert")
    assert len(alerts) == 1 and alerts[0]["reason"] == "loss_spike"
    # warmup: the same spike in the first observations never fires
    wd2, _, sink2 = make_watchdog(loss_spike_factor=5.0)
    wd2.observe_loss(0, 2.0)
    wd2.observe_loss(1, 50.0)
    assert sink2.by_kind("obs_alert") == []


def test_stale_heartbeat_uses_injected_clock():
    now = [0.0]
    wd, _, sink = make_watchdog(heartbeat_timeout_s=30.0,
                                clock=lambda: now[0])
    wd.observe_heartbeat(live=1, step=0)
    now[0] = 10.0
    wd.check_heartbeat(step=5)
    assert sink.by_kind("obs_alert") == []
    wd.observe_heartbeat(live=1, step=5)     # fresh beat at t=10
    now[0] = 45.0                            # 35s since the last beat
    wd.check_heartbeat(step=9)
    alerts = sink.by_kind("obs_alert")
    assert len(alerts) == 1
    a = alerts[0]
    assert a["reason"] == "stale_heartbeat" and a["severity"] == "warn"
    assert a["age_s"] == pytest.approx(35.0)


def test_missing_processes_alert():
    wd, _, sink = make_watchdog(expected_processes=4)
    wd.observe_heartbeat(live=3, step=100)
    alerts = sink.by_kind("obs_alert")
    assert len(alerts) == 1
    assert alerts[0]["reason"] == "missing_processes"
    assert alerts[0]["live"] == 3 and alerts[0]["expected"] == 4


def test_halt_on_unhealthy_raises_after_emitting():
    wd, _, sink = make_watchdog(halt_on_unhealthy=True)
    with pytest.raises(RunUnhealthyError, match="nan_loss"):
        wd.observe_loss(7, float("nan"))
    # the record landed BEFORE the raise: post-mortems explain themselves
    assert sink.by_kind("obs_alert")[0]["reason"] == "nan_loss"


def test_halt_routes_through_on_fatal_when_set():
    """Multi-host shape: a fatal alert must not raise on one process
    (the others would wedge in their next collective) — with on_fatal
    set, the watchdog invokes it (the trainer wires it to the
    cross-host-agreed preemption stop) instead of raising."""
    wd, _, sink = make_watchdog(halt_on_unhealthy=True)
    halts = []
    wd.on_fatal = halts.append
    wd.observe_loss(7, float("nan"))         # no raise
    assert len(halts) == 1 and halts[0]["reason"] == "nan_loss"
    assert sink.by_kind("obs_alert")[0]["reason"] == "nan_loss"


def test_monitor_thread_pages_on_a_wedged_run():
    """The per-step checks cannot fire when the training thread is
    stuck inside a step — the background monitor emits the
    stale_heartbeat alert anyway (and exactly once, via the cooldown
    on the frozen step counter)."""
    import time as _time
    wd, _, sink = make_watchdog(heartbeat_timeout_s=0.3)
    wd.start_monitor()
    try:
        _time.sleep(1.0)                     # no progress at all
    finally:
        wd.stop_monitor()
    alerts = sink.by_kind("obs_alert")
    assert len(alerts) == 1
    a = alerts[0]
    assert a["reason"] == "stale_heartbeat" and a["source"] == "monitor"
    assert a["severity"] == "warn"


def test_monitor_not_started_without_timeout():
    wd, _, _ = make_watchdog()               # heartbeat_timeout_s == 0
    wd.start_monitor()
    assert wd._monitor is None


# ---------------------------------------------------------------------------
# trainer integration
# ---------------------------------------------------------------------------


def _cfg(tmp_path, **obs_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=64, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0, dtype="float32",
                          vocab_size=32, max_seq_len=64),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmp_path),
                                    save_best=False, save_last=False),
        obs=ObsConfig(**obs_kw),
    )


def _poison(trainer):
    trainer.state = trainer.state.replace(
        params=jax.tree_util.tree_map(
            lambda p: p * jnp.nan, trainer.state.params))


def test_nan_run_writes_obs_alert_before_hard_abort(tmp_path):
    from tpunet.train.loop import Trainer
    trainer = Trainer(_cfg(tmp_path))
    _poison(trainer)
    try:
        with pytest.raises(FloatingPointError):
            trainer.train()
    finally:
        trainer.close()
    records = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    alerts = [r for r in records if r.get("kind") == "obs_alert"]
    assert alerts and alerts[0]["reason"] == "nan_loss"


def test_halt_on_unhealthy_aborts_the_run(tmp_path):
    from tpunet.train.loop import Trainer
    trainer = Trainer(_cfg(tmp_path, halt_on_unhealthy=True))
    _poison(trainer)
    try:
        with pytest.raises(RunUnhealthyError, match="nan_loss"):
            trainer.train()
    finally:
        trainer.close()
    records = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert [r for r in records if r.get("kind") == "obs_alert"]


def test_watchdog_disabled_with_obs(tmp_path):
    from tpunet.train.loop import Trainer
    trainer = Trainer(_cfg(tmp_path, enabled=False))
    try:
        assert trainer.obs.watchdog is None
        trainer.obs.observe_loss(0, float("nan"))   # no-op, no crash
    finally:
        trainer.close()


def test_watchdog_default_run_stays_quiet(tmp_path):
    """A healthy run emits zero alerts at default thresholds (no
    false pages from ordinary CPU-step jitter)."""
    from tpunet.train.loop import Trainer
    trainer = Trainer(_cfg(tmp_path))
    try:
        trainer.train()
    finally:
        trainer.close()
    records = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert not [r for r in records if r.get("kind") == "obs_alert"]


# ---------------------------------------------------------------------------
# GaugePredicate rules (--obs-rule)
# ---------------------------------------------------------------------------


def test_gauge_predicate_parse_forms():
    from tpunet.obs.health import GaugePredicate

    p = GaugePredicate.parse("serve_queue_depth > 10")
    assert p.name == "serve_queue_depth" and p.above == 10.0
    p = GaugePredicate.parse("mfu < 0.3")
    assert p.below == 0.3
    p = GaugePredicate.parse("bytes_in_use + 1e6/s")
    assert p.grow_per_s == 1e6
    for bad in ("", "mfu", "mfu >= 1", "mfu ! 3", "1 > mfu"):
        with pytest.raises(ValueError, match="bad gauge rule"):
            GaugePredicate.parse(bad)


def test_gauge_predicate_threshold_fires_with_detail():
    from tpunet.obs.health import GaugePredicate

    p = GaugePredicate.parse("depth > 5")
    assert p.evaluate({"depth": 5}, 0.0) is None
    d = p.evaluate({"depth": 7}, 0.0)
    assert d == {"rule": "depth > 5", "gauge": "depth", "value": 7,
                 "threshold": 5.0}
    # Missing / non-numeric / non-finite gauges never fire.
    assert p.evaluate({}, 0.0) is None
    assert p.evaluate({"depth": float("nan")}, 0.0) is None
    assert p.evaluate({"depth": True}, 0.0) is None


def test_gauge_predicate_growth_needs_a_trend():
    from tpunet.obs.health import GaugePredicate

    p = GaugePredicate.parse("mem + 10/s")
    # Growing at 100/s: fires once MIN_POINTS samples exist.
    assert p.evaluate({"mem": 0.0}, 0.0) is None
    assert p.evaluate({"mem": 100.0}, 1.0) is None
    d = p.evaluate({"mem": 200.0}, 2.0)
    assert d is not None and d["slope_per_s"] == pytest.approx(100.0)
    # A flat series does not fire.
    q = GaugePredicate.parse("mem + 10/s")
    for i in range(5):
        assert q.evaluate({"mem": 42.0}, float(i)) is None


def test_watchdog_check_gauges_emits_obs_alert_per_rule():
    clock = [0.0]
    wd, reg, sink = make_watchdog(
        clock=lambda: clock[0], alert_cooldown_steps=50,
        gauge_rules=("a > 1", "b > 1"))
    reg.gauge("a").set(5.0)
    reg.gauge("b").set(5.0)
    wd.check_gauges(10, reg.snapshot())
    alerts = sink.by_kind("obs_alert")
    # Per-rule cooldown keys: both rules page in the same window.
    assert len(alerts) == 2
    assert {a["rule"] for a in alerts} == {"a > 1", "b > 1"}
    assert all(a["reason"] == "gauge_predicate" for a in alerts)
    assert all(a["severity"] == "warn" for a in alerts)
    # Same rule inside the cooldown window is suppressed and counted.
    wd.check_gauges(12, reg.snapshot())
    assert len(sink.by_kind("obs_alert")) == 2
    assert reg.counter("obs_alerts_suppressed").value == 2


def test_obs_rule_cli_reaches_config():
    from tpunet.config import config_from_args

    cfg = config_from_args(["--obs-rule", "mfu < 0.3",
                            "--obs-rule", "x + 1/s",
                            "--run-id", "cli-run"])
    assert cfg.obs.gauge_rules == ("mfu < 0.3", "x + 1/s")
    assert cfg.obs.run_id == "cli-run"


def test_bad_obs_rule_fails_at_watchdog_construction():
    with pytest.raises(ValueError, match="bad gauge rule"):
        make_watchdog(gauge_rules=("nope !",))
