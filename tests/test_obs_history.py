"""Run-history store, cross-run regression compare, and the unified
timeline exporter (tpunet/obs/history/).

The properties under test: config fingerprints are stable across
bookkeeping changes and sensitive to compute changes; summaries and
compare verdicts are deterministic functions of the run records (the
checked-in fixture run dirs pin byte-identical CLI output and exit
codes across invocations); a regression verdict requires the two
runs' quantile confidence intervals to be DISJOINT under the DKW
rank-error bounds; and the timeline exporter emits schema-valid
chrome-trace JSON (phase-paired B/E, non-negative X durations,
monotonic timestamps) from both synthetic rings and a real 2-step CPU
run + serve engine.
"""

import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "history")
RUN_A = os.path.join(FIXTURES, "runA")
RUN_B = os.path.join(FIXTURES, "runB")

from tpunet.obs.history import (RunHistory, bench_entry,  # noqa: E402
                                build_timeline, compare_summaries,
                                config_fingerprint, quantile_verdict,
                                stream_regressions, summarize_run,
                                train_fingerprint)
from tpunet.utils.logging import MetricsLogger  # noqa: E402


def _records(run_dir):
    return MetricsLogger.read_records(
        os.path.join(run_dir, "metrics.jsonl"))


# ---------------------------------------------------------------------------
# fingerprint
# ---------------------------------------------------------------------------


def test_fingerprint_stable_and_selective():
    import dataclasses

    from tpunet.config import (CheckpointConfig, ModelConfig,
                               TrainConfig)
    cfg = TrainConfig()
    fp = train_fingerprint(cfg)
    assert fp == train_fingerprint(TrainConfig())
    # Bookkeeping changes must NOT move the fingerprint...
    moved = dataclasses.replace(cfg, checkpoint=CheckpointConfig(
        directory="/somewhere/else"))
    assert train_fingerprint(moved) == fp
    # ...compute changes must.
    wider = dataclasses.replace(cfg, model=ModelConfig(width_mult=0.5))
    assert train_fingerprint(wider) != fp
    assert len(fp) == 12


def test_fingerprint_canonicalizes_dicts():
    assert config_fingerprint({"a": 1, "b": 2}) \
        == config_fingerprint({"b": 2, "a": 1})
    assert config_fingerprint({"a": 1}) != config_fingerprint({"a": 2})


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------


def test_summarize_run_fixture_fields():
    s = summarize_run(_records(RUN_A), source=RUN_A)
    assert s["run_id"] == "runA"
    assert s["config_fingerprint"] == "fixfp0001ab"
    assert s["epochs"] == 3 and s["steps_total"] == 30
    assert s["step_lo"] == 1 and s["step_hi"] == 30
    assert s["throughput"] == 1000.0
    assert s["throughput_unit"] == "examples"
    # Merged p50 sits inside the fixture sample range, with a bound.
    assert 0.010 <= s["step_time_p50_s"] <= 0.0165
    assert s["step_time_rank_err"] > 0


def test_summarize_is_deterministic():
    a1 = summarize_run(_records(RUN_A), source=RUN_A)
    a2 = summarize_run(_records(RUN_A), source=RUN_A)
    assert json.dumps(a1, sort_keys=True) \
        == json.dumps(a2, sort_keys=True)


def test_history_roundtrip_and_latest_wins(tmp_path):
    h = RunHistory(str(tmp_path / "hist"))
    assert h.runs() == []
    h.ingest_run(RUN_A)
    h.ingest_run(RUN_B)
    h.ingest_run(RUN_A)          # re-ingest: supersedes, not duplicates
    runs = h.runs()
    assert sorted(r["run_id"] for r in runs) == ["runA", "runB"]
    assert len(h.entries("run")) == 3          # append-only on disk
    assert h.run("runB")["run_id"] == "runB"
    # fingerprint-scoped view
    assert len(h.runs(fingerprint="fixfp0001ab")) == 2
    assert h.runs(fingerprint="nope") == []


def test_history_rejects_non_run_dir(tmp_path):
    h = RunHistory(str(tmp_path))
    with pytest.raises(FileNotFoundError):
        h.ingest_run(str(tmp_path / "empty"))


def test_bench_join_by_run_id_and_fingerprint(tmp_path):
    h = RunHistory(str(tmp_path))
    h.ingest_run(RUN_A)
    bench = {"parsed": {"metric": "train_images_per_sec_per_chip",
                        "value": 5016.0, "unit": "img/s/chip",
                        "run_id": "runA",
                        "config_fingerprint": "fixfp0001ab",
                        "device_kind": "TPU v5 lite"}}
    p = tmp_path / "BENCH_r99.json"
    p.write_text(json.dumps(bench))
    entry = h.ingest_bench(str(p))
    assert entry["value"] == 5016.0
    joined = h.bench_for(h.run("runA"))
    assert len(joined) == 1 and joined[0]["run_id"] == "runA"
    # A bench row with only the fingerprint still joins.
    bench["parsed"].pop("run_id")
    p2 = tmp_path / "BENCH_r100.json"
    p2.write_text(json.dumps(bench))
    h.ingest_bench(str(p2))
    assert len(h.bench_for(h.run("runA"))) == 2


# ---------------------------------------------------------------------------
# compare
# ---------------------------------------------------------------------------


def test_compare_identical_runs_is_ok():
    a = summarize_run(_records(RUN_A), source=RUN_A)
    cmp = compare_summaries(a, a)
    assert cmp["verdict"] == "ok"
    assert cmp["regressions"] == 0
    assert all(m["verdict"] == "within_error" for m in cmp["metrics"])


def test_compare_flags_clear_regression_with_bounds():
    a = summarize_run(_records(RUN_A), source=RUN_A)
    b = summarize_run(_records(RUN_B), source=RUN_B)
    cmp = compare_summaries(a, b)
    assert cmp["fingerprint_match"] is True
    assert cmp["step_lo"] == 1 and cmp["step_hi"] == 30
    assert cmp["verdict"] == "regression"
    p50 = next(m for m in cmp["metrics"]
               if m["metric"] == "step_time_p50_s")
    # The verdict's definition: disjoint confidence intervals.
    assert p50["b_lo"] > p50["a_hi"]
    assert p50["verdict"] == "regression"
    thr = next(m for m in cmp["metrics"]
               if m["metric"] == "throughput_mean")
    assert thr["verdict"] == "regression" and thr["delta_frac"] < 0


def test_small_shift_stays_within_error_bars():
    """A delta smaller than the combined rank-error bars must NOT be
    called a regression — the bound is the point of the design."""
    base = [0.010 + 0.001 * i for i in range(8)]      # coarse sample
    shifted = [v + 0.0004 for v in base]              # < one rank step
    row = quantile_verdict([(base, 100, False)],
                           [(shifted, 100, False)], 50)
    assert row["verdict"] == "within_error"
    # Same shift against a dense, exact sample IS a verdict.
    dense = [0.010 + 0.00001 * i for i in range(256)]
    dshift = [v + 0.0004 for v in dense]
    row2 = quantile_verdict([(dense, 10000, False)],
                            [(dshift, 10000, False)], 50)
    assert row2["verdict"] == "regression"


def test_saturated_windows_widen_the_bars():
    sample = [0.010 + 0.0001 * i for i in range(64)]
    exact = quantile_verdict([(sample, 64, False)],
                             [(sample, 64, False)], 50)
    saturated = quantile_verdict([(sample, 10 ** 6, True)],
                                 [(sample, 10 ** 6, True)], 50)
    assert saturated["rank_err_a"] > exact["rank_err_a"]


def test_compare_fingerprint_mismatch_is_reported():
    a = summarize_run(_records(RUN_A), source=RUN_A)
    b = dict(summarize_run(_records(RUN_B), source=RUN_B))
    b["config_fingerprint"] = "otherfp00000"
    cmp = compare_summaries(a, b)
    assert cmp["fingerprint_match"] is False


def test_compare_no_data_is_incomparable():
    cmp = compare_summaries({"run_id": "x"}, {"run_id": "y"})
    assert cmp["verdict"] == "incomparable"


# ---------------------------------------------------------------------------
# CLI: deterministic, exit-coded like the budget gates
# ---------------------------------------------------------------------------


def _cli(argv, capsys):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_compare
    finally:
        sys.path.pop(0)
    rc = obs_compare.main(argv)
    out = capsys.readouterr()
    return rc, out.out, out.err


def test_cli_verdict_is_deterministic_and_exit_coded(capsys):
    rc1, out1, _ = _cli([RUN_A, RUN_B], capsys)
    rc2, out2, _ = _cli([RUN_A, RUN_B], capsys)
    assert rc1 == rc2 == 3                  # regression, like exit 3
    assert out1 == out2                     # byte-identical verdict
    assert "REGRESSION" in out1
    rc_ok, out_ok, _ = _cli([RUN_A, RUN_A], capsys)
    assert rc_ok == 0 and "OK" in out_ok


def test_cli_usage_errors_are_loud(capsys):
    rc, _, err = _cli([RUN_A], capsys)
    assert rc == 2 and "usage" in err
    rc, _, err = _cli([RUN_A, RUN_B, "--bogus"], capsys)
    assert rc == 2 and "bogus" in err
    rc, _, err = _cli([RUN_A, str(RUN_B) + "-missing"], capsys)
    assert rc == 2


def test_cli_fingerprint_mismatch_refused_then_allowed(
        tmp_path, capsys):
    # Same records, different stamped fingerprint.
    alt = tmp_path / "runC"
    alt.mkdir()
    with open(alt / "metrics.jsonl", "w") as f:
        for r in _records(RUN_B):
            r = dict(r, config_fingerprint="otherfp00000")
            f.write(json.dumps(r) + "\n")
    rc, _, err = _cli([RUN_A, str(alt)], capsys)
    assert rc == 2 and "fingerprints differ" in err
    rc, out, _ = _cli([RUN_A, str(alt),
                       "--allow-fingerprint-mismatch"], capsys)
    assert rc == 3 and "REGRESSION" in out


def test_cli_json_and_emit(tmp_path, capsys):
    out_path = tmp_path / "metrics.jsonl"
    rc, out, _ = _cli([RUN_A, RUN_B, "--json", "--emit",
                       str(out_path)], capsys)
    assert rc == 3
    parsed = json.loads(out)
    assert parsed["verdict"] == "regression"
    emitted = MetricsLogger.read_records(str(out_path))
    assert len(emitted) == 1
    assert emitted[0]["kind"] == "obs_regression"


# ---------------------------------------------------------------------------
# fleet dashboard panel rows
# ---------------------------------------------------------------------------


def test_stream_regressions_from_aggregator():
    from tpunet.obs.agg import Aggregator
    agg = Aggregator()
    for rec in _records(RUN_A) + _records(RUN_B):
        agg.ingest(rec, stamp_time=False)
    rows = stream_regressions(agg.streams())
    assert len(rows) == 1
    row = rows[0]
    assert row["fingerprint"] == "fixfp0001ab"
    assert row["base"] == "runA/0" and row["stream"] == "runB/0"
    assert row["verdict"] == "regression"
    # ...and the fleet dashboard renders the panel from these rows.
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_dashboard
    finally:
        sys.path.pop(0)
    text = obs_dashboard.render_fleet_terminal(
        agg.rollup(), {}, "test", regressions=rows)
    assert "REGRESSION COMPARE" in text
    html = obs_dashboard.render_fleet_html(
        agg.rollup(), agg.streams(), "test", regressions=rows)
    assert "Regression compare" in html and "fixfp0001ab" in html


# ---------------------------------------------------------------------------
# timeline exporter
# ---------------------------------------------------------------------------


def _validate_chrome_trace(trace):
    """The chrome-trace invariants the acceptance bar names: known
    phases only, monotonic timestamps, stack-paired B/E per
    (pid, tid), non-negative X durations."""
    events = trace["traceEvents"]
    assert events
    stacks = {}
    last_ts = None
    for e in events:
        assert e["ph"] in ("B", "E", "X", "i", "M"), e
        assert "pid" in e and "tid" in e and "ts" in e
        if e["ph"] == "M":
            continue
        if last_ts is not None:
            assert e["ts"] >= last_ts, "timestamps must not go back"
        last_ts = e["ts"]
        key = (e["pid"], e["tid"])
        if e["ph"] == "B":
            stacks.setdefault(key, []).append(e["name"])
        elif e["ph"] == "E":
            assert stacks.get(key), f"E without open B on {key}"
            stacks[key].pop()
        elif e["ph"] == "X":
            assert e["dur"] >= 0
    leftovers = {k: v for k, v in stacks.items() if v}
    assert not leftovers, f"unclosed B events: {leftovers}"


def test_timeline_synthetic_ring(tmp_path):
    from tpunet.obs.flightrec.ring import EventRing
    d = tmp_path / "run" / "flightrec"
    d.mkdir(parents=True)
    ring = EventRing(str(d / "events.ring"), 64)
    ring.record("span", "step 1")
    ring.record("span", "tpunet/data_wait")
    ring.record("span_end", "tpunet/data_wait")
    ring.record("span_end", "step 1")
    ring.record("thread", "busy ckpt-writer")
    ring.record("thread", "idle ckpt-writer")
    ring.record("req", "submit 7 len=5")
    ring.record("req", "prefill 7")
    ring.record("req", "first_token 7")
    ring.record("req", "finish 7 length")
    ring.record("alert", "step_stall step=9")
    ring.record("span", "never closed")
    ring.close()
    trace = build_timeline([str(tmp_path / "run")])
    _validate_chrome_trace(trace)
    events = trace["traceEvents"]
    names = [e["name"] for e in events]
    assert names.count("step 1") == 2          # paired B/E
    assert "never closed" in names             # force-closed at tail
    busy = [e for e in events
            if e["ph"] == "X" and e["name"] == "busy"]
    assert len(busy) == 1
    phases = {e["name"] for e in events
              if e["ph"] == "X" and e.get("args", {}).get("req")}
    assert phases == {"queue", "prefill", "decode"}
    decode = next(e for e in events if e["ph"] == "X"
                  and e["name"] == "decode")
    assert decode["args"]["finish_reason"] == "length"
    assert any(e["ph"] == "i" and "step_stall" in e["name"]
               for e in events)


def test_timeline_requires_a_ring(tmp_path):
    with pytest.raises(FileNotFoundError):
        build_timeline([str(tmp_path)])


def test_timeline_from_real_run_and_serve(tmp_path):
    """The acceptance bar: a real 2-step CPU training run plus a real
    serve engine produce one schema-valid trace containing at least
    one span pair, one host-thread busy track, and one complete serve
    request lifecycle."""
    import jax

    from tpunet.config import (CheckpointConfig, DataConfig,
                               MeshConfig, ModelConfig, ObsConfig,
                               OptimConfig, ServeConfig, TrainConfig)
    from tpunet.train.loop import Trainer

    train_dir = tmp_path / "train"
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=ModelConfig(name="lm", vit_hidden=64, vit_depth=2,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=64),
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(train_dir),
                                    save_best=False, save_last=False),
        obs=ObsConfig(step_records_every=1),
    )
    trainer = Trainer(cfg)
    try:
        trainer.train()                          # 2 steps (32/16)
    finally:
        trainer.close()

    # A real serve engine in its own "replica" dir: the global
    # recorder collects engine thread beats + request lifecycles.
    from tpunet.models import create_model, init_variables
    from tpunet.obs import flightrec
    from tpunet.serve import Engine

    serve_dir = tmp_path / "serve"
    rec = flightrec.install(str(serve_dir), watcher=False,
                            native=False)
    try:
        tiny = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                           vit_heads=2, dropout_rate=0.0,
                           dtype="float32", vocab_size=31,
                           max_seq_len=48)
        model = create_model(tiny)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=8)
        eng = Engine(model, variables,
                     ServeConfig(slots=2, queue_max=4,
                                 prefill_buckets=(8,),
                                 default_max_new_tokens=4,
                                 emit_every_s=0.0)).start()
        try:
            reqs = [eng.submit(np.array([1, 2, 3], np.int32),
                               max_new_tokens=3) for _ in range(2)]
            for r in reqs:
                r.result(timeout=120)
        finally:
            eng.stop()
    finally:
        flightrec.close(rec)

    trace = build_timeline([str(train_dir), str(serve_dir)])
    _validate_chrome_trace(trace)
    events = trace["traceEvents"]
    # >= 1 span pair (the training step spans record B/E into the ring)
    assert any(e["ph"] == "B" for e in events)
    # >= 1 host-thread busy track (serve engine busy/idle flips)
    assert any(e["ph"] == "X" and e["name"] == "busy" for e in events)
    # >= 1 complete serve request lifecycle
    req_events = [e for e in events
                  if e["ph"] == "X" and e.get("args", {}).get("req")]
    assert {"queue", "decode"} <= {e["name"] for e in req_events}
    finished = [e for e in req_events
                if e.get("args", {}).get("finish_reason")]
    assert finished, "no request reached a finish reason"
    # Two trace processes, labeled.
    pids = {e["pid"] for e in events}
    assert len(pids) == 2
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert any("train" in n for n in names)
    assert any("serve" in n for n in names)
    # ...and the CLI writes a loadable file.
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_timeline
    finally:
        sys.path.pop(0)
    out = tmp_path / "trace.json"
    rc = obs_timeline.main([str(train_dir), str(serve_dir),
                            "-o", str(out)])
    assert rc == 0
    with open(out) as f:
        _validate_chrome_trace(json.load(f))
