"""Run-identity stamping end-to-end: run_id/process_index/host on
JsonlSink records, ndjson HTTP exports, and StatsD name tags; run_id
stability across a preemption restore; the serve frontend's replica
default."""

import json
import socket
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from tpunet.config import ObsConfig
from tpunet.obs import JsonlSink, MemorySink, Observability
from tpunet.obs.export import AsyncExporter, HttpLineTransport
from tpunet.obs.export.statsd import record_to_lines
from tpunet.obs.identity import ensure_run_id, run_identity
from tpunet.obs.registry import Registry
from tpunet.utils.logging import MetricsLogger

IDENTITY_KEYS = ("run_id", "process_index", "host")


def _drive(obs, sink):
    obs.add_sink(sink)
    obs.begin_epoch(1)
    obs.observe_step(1, 0.01)
    obs.end_epoch(epoch=1, step=1, units=10.0, train_seconds=0.1)


def test_registry_emit_stamps_identity_and_record_wins():
    reg = Registry()
    sink = MemorySink()
    reg.add_sink(sink)
    reg.set_identity(run_id="r1", process_index=3, host="hostA")
    reg.emit("obs_step", {"step": 7})
    reg.emit("obs_step", {"step": 8, "host": "explicit"})
    assert sink.records[0]["run_id"] == "r1"
    assert sink.records[0]["process_index"] == 3
    assert sink.records[0]["host"] == "hostA"
    # An explicit record field outranks the stamp.
    assert sink.records[1]["host"] == "explicit"


def test_observability_records_carry_identity(tmp_path):
    cfg = ObsConfig(step_records_every=1)
    obs = Observability(cfg, checkpoint_dir=str(tmp_path))
    sink = MemorySink()
    _drive(obs, sink)
    for kind in ("obs_step", "obs_epoch"):
        rec = sink.by_kind(kind)[0]
        for key in IDENTITY_KEYS:
            assert key in rec, (kind, key)
        assert rec["process_index"] == 0
        assert rec["host"] == socket.gethostname()
    # The id was persisted for restores.
    assert (tmp_path / "run_id").read_text().strip() \
        == sink.records[0]["run_id"]


def test_jsonl_sink_records_carry_identity(tmp_path):
    cfg = ObsConfig()
    obs = Observability(cfg, checkpoint_dir=str(tmp_path))
    logger = MetricsLogger(str(tmp_path))
    _drive(obs, JsonlSink(logger))
    records = MetricsLogger.read_records(
        str(tmp_path / "metrics.jsonl"))
    assert records
    for rec in records:
        for key in IDENTITY_KEYS:
            assert key in rec


def test_run_id_stable_across_preemption_restore(tmp_path):
    d = str(tmp_path)
    first = ensure_run_id(d, resume=False)
    # The restore path (--resume) reuses the persisted id...
    assert ensure_run_id(d, resume=True) == first
    assert ensure_run_id(d, resume=True) == first
    # ...and a FRESH run into the same directory gets a new one
    # (mirrors MetricsLogger truncating metrics.jsonl).
    assert ensure_run_id(d, resume=False) != first


def test_observability_resume_continues_the_same_stream(tmp_path):
    cfg = ObsConfig()
    obs1 = Observability(cfg, checkpoint_dir=str(tmp_path))
    rid = obs1.registry.identity()["run_id"]
    obs2 = Observability(cfg, checkpoint_dir=str(tmp_path),
                         resume=True)
    assert obs2.registry.identity()["run_id"] == rid


def test_explicit_run_id_wins_and_is_not_persisted_over(tmp_path):
    cfg = ObsConfig(run_id="my-run")
    obs = Observability(cfg, checkpoint_dir=str(tmp_path))
    assert obs.registry.identity()["run_id"] == "my-run"


def test_non_coordinator_identity_is_ephemeral(tmp_path):
    ident = run_identity(directory=str(tmp_path), process_index=2,
                         persist=False)
    assert ident["process_index"] == 2
    assert not (tmp_path / "run_id").exists()


def test_http_ndjson_export_carries_identity():
    """The full live path: registry emit -> AsyncExporter ->
    HttpLineTransport ndjson POST -> receiver parses identity."""
    received = []
    done = threading.Event()

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            for line in self.rfile.read(n).splitlines():
                if line.strip():
                    received.append(json.loads(line))
            done.set()
            self.send_response(204)
            self.end_headers()

        def log_message(self, *a):
            pass

    server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    port = server.server_address[1]
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        reg = Registry()
        reg.set_identity(run_id="wire-test", process_index=0,
                         host="hostX")
        exporter = AsyncExporter(
            HttpLineTransport(f"http://127.0.0.1:{port}/", timeout=5),
            name="http", registry=reg)
        reg.add_sink(exporter)
        reg.emit("obs_step", {"step": 1, "step_time_s": 0.01})
        exporter.close()
        assert done.wait(5)
    finally:
        server.shutdown()
        server.server_close()
    assert received
    assert received[0]["run_id"] == "wire-test"
    assert received[0]["process_index"] == 0
    assert received[0]["host"] == "hostX"


def test_statsd_lines_carry_identity_as_name_tags():
    record = {"kind": "obs_epoch", "run_id": "r9", "process_index": 1,
              "host": "tpu-w-1", "step": 5, "mfu": 0.5}
    lines = record_to_lines(record)
    assert lines
    for line in lines:
        assert line.endswith(
            "|g|#run_id:r9,process_index:1,host:tpu-w-1")
    # Identity fields become tags, not gauges (process_index is
    # numeric and would otherwise leak into the gauge namespace).
    assert not any(".process_index:" in line.split("|")[0]
                   for line in lines)
    assert any(".step:5|g" in line for line in lines)


def test_statsd_tag_values_are_sanitized():
    lines = record_to_lines({"kind": "k", "run_id": "a|b#c,d",
                             "x": 1})
    assert lines == ["tpunet.k.x:1|g|#run_id:a_b_c_d"]


def test_serve_frontend_defaults_replica_identity():
    from tpunet.serve.frontend import ServeServer

    class _Model:
        vocab_size = 256

    class _Engine:
        def __init__(self):
            self.registry = Registry()
            self.model = _Model()

    engine = _Engine()
    server = ServeServer(engine, port=0)
    try:
        ident = engine.registry.identity()
        assert ident["run_id"].startswith("serve-")
        assert ident["host"] == socket.gethostname()
    finally:
        server.httpd.server_close()


def test_serve_frontend_respects_existing_identity():
    from tpunet.serve.frontend import ServeServer

    class _Model:
        vocab_size = 256

    class _Engine:
        def __init__(self):
            self.registry = Registry()
            self.model = _Model()

    engine = _Engine()
    engine.registry.set_identity(run_id="replica-7", process_index=0,
                                 host="h")
    server = ServeServer(engine, port=0)
    try:
        assert engine.registry.identity()["run_id"] == "replica-7"
    finally:
        server.httpd.server_close()
