"""Tier-2 (slow) regression: the obs overhead budget, including the
exporter's non-blocking promise with a DEAD endpoint configured.

Wires ``scripts/check_obs_overhead.py`` into the suite (slow-marked,
so tier-1 wall time is unaffected) with a more generous threshold than
the script's standalone default — CI boxes are noisier than a dev
machine, and the regression this guards (a per-step sync or blocking
write) shows up as 2x+, not tens of percent."""

import os
import sys

import pytest

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")

pytestmark = pytest.mark.slow


def _import_script(name):
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def test_obs_default_path_overhead_within_budget(monkeypatch):
    # main() now measures three variants (disabled / obs-no-flightrec /
    # default) and gates both the whole-subsystem ratio and the
    # flight-recorder-only A/B.
    check = _import_script("check_obs_overhead")
    monkeypatch.setattr(check, "MAX_RATIO", 1.5)   # generous for CI
    assert check.main() == 0


def test_flightrec_on_vs_off_ab(monkeypatch):
    """The default-ON flight recorder's own regression gate: same obs
    config, recorder on vs off, same step loop — a recorder that grew
    a per-step syscall or sync shows up as 2x+, not percent noise."""
    import statistics
    import tempfile

    check = _import_script("check_obs_overhead")
    results = {}
    for label, rec in (("off", False), ("on", True)):
        with tempfile.TemporaryDirectory() as d:
            trainer = check.build_trainer(True, d, flightrec=rec)
            try:
                results[label] = check.time_epochs(trainer)
            finally:
                trainer.close()
    off = statistics.median(results["off"])
    on = statistics.median(results["on"])
    ratio = on / off if off > 0 else float("inf")
    assert ratio < 1.5, (
        f"flight recorder slowed the step loop {ratio:.2f}x "
        f"(off {off * 1e3:.1f}ms, on {on * 1e3:.1f}ms)")


def test_obs_overhead_with_dead_http_endpoint(tmp_path, monkeypatch):
    """The acceptance bar for the exporter: with the endpoint down and
    per-step records on, the step loop still runs within the overhead
    envelope, and the drop/error counters account for every record."""
    import statistics
    import tempfile

    from tpunet.config import ExportConfig
    from tpunet.obs.export import build_exporters

    check = _import_script("check_obs_overhead")

    def build(workdir, exporting=False):
        trainer = check.build_trainer(True, workdir)
        if exporting:
            trainer.obs.step_records_every = 1
            exporters = build_exporters(
                ExportConfig(http="http://127.0.0.1:9/",
                             http_timeout_s=0.1, queue_size=64,
                             flush_timeout_s=2.0),
                trainer.obs.registry)
            for e in exporters:
                trainer.obs.add_sink(e)
            trainer.obs._exporters = exporters
        return trainer

    results = {}
    stats = None
    for label, exporting in (("plain", False), ("exporting", True)):
        with tempfile.TemporaryDirectory() as d:
            trainer = build(d, exporting)
            exp = trainer.obs._exporters[0] if exporting else None
            try:
                results[label] = check.time_epochs(trainer)
            finally:
                trainer.close()       # drains + closes the exporter
            if exp is not None:
                stats = exp.stats()
    plain = statistics.median(results["plain"])
    exporting = statistics.median(results["exporting"])
    ratio = exporting / plain if plain > 0 else float("inf")
    # Endpoint is dead: every record must be in sent+errors+dropped
    # (write-side drops land in the registry counter, close() already
    # folded flush leftovers in).
    assert stats is not None and stats["sent"] == 0
    assert (stats["send_errors"] + stats["dropped"]) >= stats["enqueued"]
    assert ratio < 1.5, (
        f"step loop slowed {ratio:.2f}x with a dead export endpoint "
        f"(plain {plain * 1e3:.1f}ms, exporting {exporting * 1e3:.1f}ms)")
