"""SLO engine + prober: burn-rate math on synthetic SLI streams.

Everything here drives tpunet/obs/slo.py with a FAKE clock — exact
budget arithmetic, the multi-window edge latch (one page per burst,
re-page on relapse), clock-skew and empty-window behavior, and the
prober's golden-mismatch -> correctness-breach path — so the chaos
smoke (scripts/serve_chaos_smoke.py SLO leg) can stay the only place
real sockets and real time are involved.
"""

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpunet.obs.registry import MemorySink, Registry
from tpunet.obs.slo import (DEFAULT_POLICY, SloEngine, SloPolicyError,
                            build_slo_record, load_policy)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def make_engine(policy, clock):
    registry = Registry()
    sink = MemorySink()
    registry.add_sink(sink)
    specs = load_policy_dict(policy)
    engine = SloEngine(specs, registry=registry, clock=clock)
    return engine, registry, sink


def load_policy_dict(policy: dict):
    """Parse an inline policy dict through the same validation path
    as a file (round-trip through json)."""
    import tempfile
    fd, path = tempfile.mkstemp(suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(policy, f)
        return load_policy(path)
    finally:
        os.unlink(path)


AVAIL_POLICY = {"slos": [
    {"name": "availability", "sli": "availability", "objective": 0.9,
     "compliance_window_s": 1000,
     "page": {"long_s": 100, "short_s": 20, "burn": 2.0},
     "ticket": {"long_s": 400, "short_s": 50, "burn": 1.0}}]}


def pages_of(sink, severity="page"):
    return [r for r in sink.records if r.get("kind") == "obs_alert"
            and r.get("severity") == severity
            and str(r.get("reason", "")).startswith("slo_")]


# -- policy loading ------------------------------------------------------


def test_default_policy_loads_and_matches_docs_slos_json():
    """docs/slos.json is the commented, operator-editable copy of
    DEFAULT_POLICY — the two must parse to identical specs."""
    assert load_policy("") == load_policy(
        os.path.join(REPO, "docs", "slos.json"))
    names = [s.name for s in load_policy("")]
    assert names == [s["name"] for s in DEFAULT_POLICY["slos"]]


@pytest.mark.parametrize("mutate,needle", [
    (lambda s: s.update(name="Bad-Name"), "lowercase"),
    (lambda s: s.update(sli="uptime"), "sli"),
    (lambda s: s.update(objective=1.0), "objective"),
    (lambda s: s.update(objective="high"), "objective"),
    (lambda s: s.update(compliance_window_s=0), "compliance_window_s"),
    (lambda s: (s.pop("page"), s.pop("ticket")), "at least one"),
    (lambda s: s["page"].update(short_s=500), "short_s"),
    (lambda s: s["page"].update(burn=0), "burn"),
])
def test_policy_validation_is_loud(mutate, needle):
    policy = json.loads(json.dumps(AVAIL_POLICY))
    mutate(policy["slos"][0])
    with pytest.raises(SloPolicyError, match=needle):
        load_policy_dict(policy)


def test_latency_sli_requires_threshold():
    policy = {"slos": [{"name": "ttft", "sli": "latency_ttft",
                        "objective": 0.99, "compliance_window_s": 100,
                        "page": {"long_s": 10, "short_s": 5,
                                 "burn": 1.0}}]}
    with pytest.raises(SloPolicyError, match="threshold_s"):
        load_policy_dict(policy)


def test_duplicate_names_rejected():
    policy = {"slos": AVAIL_POLICY["slos"] * 2}
    with pytest.raises(SloPolicyError, match="duplicate"):
        load_policy_dict(policy)


def test_comment_stripping_never_touches_strings(tmp_path):
    p = tmp_path / "p.json"
    p.write_text("// a full-line comment\n"
                 + json.dumps(AVAIL_POLICY))
    assert load_policy(str(p)) == load_policy_dict(AVAIL_POLICY)


# -- exact budget arithmetic ---------------------------------------------


def test_budget_arithmetic_exact():
    clock = FakeClock()
    engine, _, _ = make_engine(AVAIL_POLICY, clock)
    # 100 events inside every window: 3 bad.
    for i in range(100):
        engine.note_request(ok=i >= 3, t=clock.advance(0.1))
    (rec,) = engine.evaluate()
    assert rec["events"] == 100 and rec["bad"] == 3
    assert rec["error_rate"] == pytest.approx(0.03)
    # budget rate = 1 - 0.9 = 0.1; spent fraction = 0.03 / 0.1.
    assert rec["budget_remaining"] == pytest.approx(1.0 - 0.03 / 0.1)
    # burn = error_rate / budget over each window; all events are
    # inside both page windows here.
    assert rec["page_burn_long"] == pytest.approx(0.3)
    assert rec["page_burn_short"] == pytest.approx(0.3)
    assert not rec.get("page_firing") and not rec.get("ticket_firing")


def test_latency_threshold_judges_samples():
    clock = FakeClock()
    policy = {"slos": [{"name": "ttft", "sli": "latency_ttft",
                        "objective": 0.9, "threshold_s": 1.0,
                        "compliance_window_s": 1000,
                        "page": {"long_s": 100, "short_s": 20,
                                 "burn": 2.0}}]}
    engine, _, _ = make_engine(policy, clock)
    for s in (0.1, 0.2, 1.5, 0.3, 2.0):   # 2 of 5 over threshold
        engine.note_latency("ttft", s, t=clock.advance(1.0))
    (rec,) = engine.evaluate()
    assert rec["events"] == 5 and rec["bad"] == 2
    assert rec["threshold_s"] == pytest.approx(1.0)
    assert rec["page_burn_long"] == pytest.approx((2 / 5) / 0.1)


# -- edge latch: one page per burst, re-page on relapse ------------------


def test_edge_latch_pages_once_then_repages_on_relapse():
    clock = FakeClock()
    engine, registry, sink = make_engine(AVAIL_POLICY, clock)
    # Healthy baseline.
    for _ in range(50):
        engine.note_request(True, t=clock.advance(0.2))
    engine.evaluate()
    assert pages_of(sink) == []
    # Burst: hard outage, evaluated every second — exactly one page
    # (and one slow-burn ticket) despite many evaluations.
    for _ in range(30):
        engine.note_request(False, t=clock.advance(1.0))
        engine.evaluate()
    assert len(pages_of(sink)) == 1
    page = pages_of(sink)[0]
    assert page["reason"] == "slo_fast_burn"
    assert page["slo"] == "availability"
    assert page["burn_long"] >= 2.0
    assert len(pages_of(sink, "ticket")) == 1
    assert registry.snapshot()["slo_pages_total"] == 1
    # Recovery: good traffic clears the short window, latch re-arms.
    for _ in range(200):
        engine.note_request(True, t=clock.advance(1.0))
        engine.evaluate()
    (rec,) = engine.evaluate()
    assert not rec.get("page_firing")
    assert len(pages_of(sink)) == 1, "recovery must not page"
    # Relapse: a second burst is a SECOND page.
    for _ in range(30):
        engine.note_request(False, t=clock.advance(1.0))
        engine.evaluate()
    assert len(pages_of(sink)) == 2
    assert engine.evaluate()[0]["pages_total"] == 2


def test_page_and_ticket_latch_independently():
    """A slow burn above the ticket threshold but below the page
    threshold files a ticket and never pages."""
    clock = FakeClock()
    engine, _, sink = make_engine(AVAIL_POLICY, clock)
    # ~15% errors: burn 1.5 — over ticket (1.0), under page (2.0).
    # Errors sit at the END of each 20-event cycle so the warmup
    # prefix never shows an all-bad window.
    for i in range(400):
        engine.note_request(ok=(i % 20) < 17, t=clock.advance(1.0))
        engine.evaluate()
    assert pages_of(sink) == []
    assert len(pages_of(sink, "ticket")) >= 1
    (rec,) = engine.evaluate()
    assert rec.get("ticket_firing") and not rec.get("page_firing")


# -- empty windows and clock skew ----------------------------------------


def test_empty_window_holds_the_latch():
    """Silence is not recovery: an active page must survive a window
    with no events (wedged prober), and an idle engine must not page."""
    clock = FakeClock()
    engine, _, sink = make_engine(AVAIL_POLICY, clock)
    (rec,) = engine.evaluate()       # no events at all: no verdict
    assert "page_burn_long" not in rec and not rec.get("page_firing")
    assert pages_of(sink) == []
    # Burn hard -> page fires and latches.
    for _ in range(30):
        engine.note_request(False, t=clock.advance(1.0))
        engine.evaluate()
    assert len(pages_of(sink)) == 1
    # Total silence long enough to empty every alert window: the
    # latch HOLDS — still firing, no new page, not cleared.
    clock.advance(500.0)
    (rec,) = engine.evaluate()
    assert rec.get("page_firing") == 1
    assert len(pages_of(sink)) == 1
    # Good traffic (actual recovery evidence) clears it.
    for _ in range(30):
        engine.note_request(True, t=clock.advance(1.0))
        engine.evaluate()
    assert not engine.evaluate()[0].get("page_firing")


def test_future_stamped_events_never_crash():
    """Clock skew: an event stamped ahead of the evaluation clock
    lands in every window rather than vanishing or crashing."""
    clock = FakeClock()
    engine, _, _ = make_engine(AVAIL_POLICY, clock)
    engine.note_request(False, t=clock.t + 3600.0)
    engine.note_request(True, t=clock.t)
    (rec,) = engine.evaluate()
    assert rec["events"] == 2 and rec["bad"] == 1


# -- probe verdicts ------------------------------------------------------


CORRECT_POLICY = {"slos": [
    {"name": "correctness", "sli": "correctness", "objective": 0.99,
     "compliance_window_s": 1000,
     "page": {"long_s": 60, "short_s": 10, "burn": 1.0}}]}


def test_probe_golden_mismatch_breaches_correctness():
    clock = FakeClock()
    engine, _, sink = make_engine(CORRECT_POLICY, clock)
    for _ in range(20):
        engine.note_probe(ok=True, t=clock.advance(1.0))
        engine.evaluate()
    assert pages_of(sink) == []
    # A bad weight rollout: available, fast, WRONG tokens.
    for _ in range(10):
        engine.note_probe(ok=True, mismatch=True,
                          trace_id="feedc0dedeadbeef",
                          t=clock.advance(1.0))
        engine.evaluate()
    assert len(pages_of(sink)) == 1
    page = pages_of(sink)[0]
    assert page["sli"] == "correctness"
    assert page["trace_id"] == "feedc0dedeadbeef"
    (rec,) = engine.evaluate()
    assert rec["probe_requests"] == 30
    assert rec["probe_mismatches"] == 10
    assert rec["last_failed_trace"] == "feedc0dedeadbeef"


def test_probe_failure_feeds_availability_not_correctness():
    """A probe that never answered is an availability event only —
    correctness is unjudgeable without tokens."""
    clock = FakeClock()
    policy = {"slos": AVAIL_POLICY["slos"] + CORRECT_POLICY["slos"]}
    engine, _, _ = make_engine(policy, clock)
    engine.note_probe(ok=False, trace_id="ab" * 8,
                      t=clock.advance(1.0))
    avail, correct = engine.evaluate()
    assert avail["events"] == 1 and avail["bad"] == 1
    assert correct["events"] == 0
    assert engine.probe_failures == 1
    assert engine.last_failed_trace == "ab" * 8


# -- the prober itself (stdlib stub endpoint, no router) -----------------


class _StubEndpoint:
    """Minimal /v1/generate stream endpoint with mutable behavior."""

    def __init__(self):
        self.mode = "ok"          # ok | wrong | refuse
        self.tokens = [1, 2, 3, 4]
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: D102
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                if stub.mode == "refuse":
                    self.send_response(503)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                toks = (list(stub.tokens) if stub.mode == "ok"
                        else [9] * len(stub.tokens))
                lines = [json.dumps({"token": t, "i": i}).encode()
                         + b"\n" for i, t in enumerate(toks)]
                lines.append(json.dumps(
                    {"done": True, "finish_reason": "length",
                     "n_tokens": len(toks)}).encode() + b"\n")
                body = b"".join(lines)
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_prober_warmup_gate_and_golden_mismatch():
    """Boot-window failures (before a golden exists) must not feed
    the engine; the first clean probe arms it; wrong tokens after
    that are a mismatch; post-arm failures DO burn."""
    import types

    from tpunet.router.prober import Prober

    clock = FakeClock()
    policy = {"slos": AVAIL_POLICY["slos"] + CORRECT_POLICY["slos"]}
    engine = SloEngine(load_policy_dict(policy), registry=None,
                       clock=clock)
    registry = Registry()
    stub = _StubEndpoint()
    cfg = types.SimpleNamespace(probe_every_s=0.01,
                                probe_timeout_s=2.0)
    prober = Prober(cfg, engine, registry=registry,
                    base_url=stub.url)
    try:
        stub.mode = "refuse"           # fleet not up yet
        assert prober.probe_once() is False
        assert engine.probe_requests == 0, \
            "unarmed failures must not burn budget"
        assert registry.snapshot()["prober_failures_total"] == 1

        stub.mode = "ok"               # first clean probe arms it
        assert prober.probe_once() is True
        assert prober.golden == [1, 2, 3, 4]
        assert engine.probe_requests == 1

        stub.mode = "wrong"            # golden mismatch
        assert prober.probe_once() is True
        assert engine.probe_mismatches == 1
        assert registry.snapshot()["prober_mismatch_total"] == 1
        assert engine.last_failed_trace == prober.last_trace_id

        stub.mode = "refuse"           # post-arm failure burns
        assert prober.probe_once() is False
        assert engine.probe_failures == 1
        assert engine.probe_requests == 3
    finally:
        stub.close()


# -- record shape --------------------------------------------------------


def test_build_slo_record_shape():
    rec = build_slo_record(name="x", sli="availability",
                           objective=0.99, compliance_window_s=60.0,
                           events=10, bad=1, error_rate=0.1,
                           budget_remaining=0.5, page_burn_long=1.2,
                           page_burn_short=3.4,
                           page_burn_threshold=14.4,
                           page_window_long_s=3600.0,
                           page_window_short_s=300.0,
                           page_firing=True, pages_total=2,
                           probe_requests=5, probe_failures=1,
                           probe_mismatches=0,
                           last_failed_trace="ab" * 8)
    assert rec["page_firing"] == 1 and rec["pages_total"] == 2
    assert rec["probe_requests"] == 5
    assert rec["last_failed_trace"] == "ab" * 8
    assert json.loads(json.dumps(rec)) == rec
    # Optional fields stay absent, not null.
    lean = build_slo_record(name="x", sli="availability",
                            objective=0.99,
                            compliance_window_s=60.0)
    for key in ("error_rate", "budget_remaining", "page_firing",
                "pages_total", "probe_requests",
                "last_failed_trace", "threshold_s"):
        assert key not in lean
