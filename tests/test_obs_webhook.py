"""Alert webhook sink (tpunet/obs/export/webhook.py): the paging
contract.

Promises under test, mirroring the exporter layer's discipline
(tests/test_obs_export.py): ``write`` never blocks or raises whatever
the endpoint state; non-alert kinds are filtered before any queue
work; a full queue drops AND counts; a flaky endpoint is retried with
backoff and eventually delivers (counted once, as sent); a dead
endpoint exhausts retries into the bounded dead-letter list; close()
flushes in order with a bounded timeout; and the accounting identity
``enqueued == sent + send_errors + dropped`` survives every mode.
Plus the fleet acceptance path: an injected straggler in a
two-replica aggregator fires exactly one webhook POST with the
documented payload.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from tpunet.obs.agg import Aggregator
from tpunet.obs.export import (AlertWebhook, WebhookTransport,
                               build_payload)
from tpunet.obs.registry import Registry


class FlakyTransport:
    """In-memory endpoint: fails the first ``fail_first`` sends (the
    5xx-then-recover shape), records delivered payloads in order."""

    def __init__(self, fail_first: int = 0, gate: threading.Event = None):
        self.payloads = []
        self.fail_first = fail_first
        self.gate = gate
        self.attempts = 0

    def send(self, payload: dict) -> None:
        if self.gate is not None:
            self.gate.wait()
        self.attempts += 1
        if self.attempts <= self.fail_first:
            raise IOError("injected 5xx")
        self.payloads.append(payload)


def _receiver():
    """Stdlib HTTP receiver: 200s everything, collects JSON bodies."""
    got = []

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            n = int(self.headers.get("Content-Length", 0))
            got.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.end_headers()

        def log_message(self, *a):
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, got


ALERT = {"kind": "obs_alert", "reason": "step_stall", "step": 7,
         "severity": "fatal", "run_id": "r1", "host": "h"}


# ---------------------------------------------------------------------------
# payload template
# ---------------------------------------------------------------------------


def test_payload_matches_documented_wire_format():
    p = build_payload(ALERT)
    assert p["source"] == "tpunet"
    assert p["kind"] == "obs_alert" and p["reason"] == "step_stall"
    assert p["severity"] == "fatal"
    assert p["run_id"] == "r1" and p["host"] == "h"
    assert p["detail"] == ALERT
    assert "step_stall" in p["summary"]
    crash = build_payload({"kind": "obs_crash", "cause": "SIGSEGV",
                           "report_path": "/tmp/r.json"})
    assert crash["reason"] == "crash" and "SIGSEGV" in crash["summary"]
    reg = build_payload({"kind": "obs_regression",
                         "verdict": "regression", "regressions": 3,
                         "run_a": "A", "run_b": "B"})
    assert reg["reason"] == "regression" and "3" in reg["summary"]


def test_non_alert_kinds_are_filtered_before_the_queue():
    transport = FlakyTransport()
    wh = AlertWebhook(transport, queue_size=2)
    for i in range(100):
        wh.write({"kind": "obs_step", "step": i})
        wh.write({"kind": "obs_epoch", "epoch": i})
    wh.close()
    assert transport.payloads == []
    assert wh.stats()["enqueued"] == 0
    assert wh.stats()["dropped"] == 0        # filtered, not dropped


# ---------------------------------------------------------------------------
# failure modes
# ---------------------------------------------------------------------------


def test_dead_endpoint_never_blocks_and_dead_letters():
    reg = Registry()
    # Closed port: connection refused immediately.
    wh = AlertWebhook(WebhookTransport("http://127.0.0.1:9/hook",
                                       timeout=0.2),
                      max_retries=1, backoff_s=0.01, registry=reg)
    reg.add_sink(wh)
    t0 = time.perf_counter()
    reg.emit("obs_alert", dict(ALERT))
    assert time.perf_counter() - t0 < 0.5    # write is put_nowait
    wh.close()
    stats = wh.stats()
    assert stats["send_errors"] == 1 and stats["dead_letter"] == 1
    assert stats["enqueued"] == stats["sent"] + stats["send_errors"] \
        + stats["dropped"]
    dead = wh.dead_letters()
    assert len(dead) == 1
    assert dead[0]["payload"]["reason"] == "step_stall"
    assert dead[0]["attempts"] == 2          # first try + 1 retry
    assert reg.counter("webhook_dead_letter").value == 1


def test_flaky_endpoint_recovers_via_backoff():
    """The 5xx-then-recover shape: two failures, then delivery — the
    page arrives once, retries are counted, nothing dead-letters."""
    transport = FlakyTransport(fail_first=2)
    wh = AlertWebhook(transport, max_retries=3, backoff_s=0.01)
    wh.write(dict(ALERT))
    wh.close()
    assert len(transport.payloads) == 1
    stats = wh.stats()
    assert stats["sent"] == 1 and stats["send_errors"] == 0
    assert stats["retries"] == 2
    assert stats["enqueued"] == stats["sent"] + stats["send_errors"] \
        + stats["dropped"]


def test_queue_overflow_drops_and_counts():
    gate = threading.Event()                 # wedged endpoint
    transport = FlakyTransport(gate=gate)
    reg = Registry()
    wh = AlertWebhook(transport, queue_size=2, flush_timeout=2.0,
                      registry=reg)
    t0 = time.perf_counter()
    for i in range(20):
        wh.write({**ALERT, "step": i})
    assert time.perf_counter() - t0 < 0.5    # pure queue puts
    # 2 queued (+possibly 1 at the gate); the rest dropped and counted.
    assert reg.counter("webhook_dropped").value >= 17
    gate.set()
    wh.close()
    stats = wh.stats()
    # Total accounting: 20 writes == delivered + dropped; every page
    # that entered the queue was delivered.
    assert stats["sent"] == stats["enqueued"]
    assert stats["send_errors"] == 0
    assert stats["sent"] + stats["dropped"] == 20


def test_flush_on_close_delivers_in_order():
    transport = FlakyTransport()
    wh = AlertWebhook(transport, queue_size=64)
    for i in range(10):
        wh.write({**ALERT, "step": i})
    wh.close()
    assert [p["detail"]["step"] for p in transport.payloads] \
        == list(range(10))
    # Writes after close are dropped and counted, never delivered.
    wh.write(dict(ALERT))
    assert wh.stats()["dropped"] == 1


def test_wedged_transport_close_times_out_and_accounts():
    gate = threading.Event()                 # never set: fully wedged
    transport = FlakyTransport(gate=gate)
    wh = AlertWebhook(transport, queue_size=8, flush_timeout=0.3)
    for i in range(5):
        wh.write({**ALERT, "step": i})
    t0 = time.perf_counter()
    wh.close()
    assert time.perf_counter() - t0 < 3.0    # bounded, not forever
    stats = wh.stats()
    assert stats["enqueued"] == stats["sent"] + stats["send_errors"] \
        + stats["dropped"]
    assert stats["dropped"] >= 4
    gate.set()                               # unwedge the daemon


def test_drain_thread_registers_in_thread_registry():
    from tpunet.obs.flightrec.threads import THREADS
    wh = AlertWebhook(FlakyTransport(), queue_size=2)
    try:
        names = [h.name for h in THREADS.handles()]
        assert "webhook" in names
    finally:
        wh.close()


def test_transport_url_validation():
    with pytest.raises(ValueError):
        WebhookTransport("not-a-url")
    with pytest.raises(ValueError):
        AlertWebhook("udp://x")


def test_build_exporters_wires_the_webhook():
    from tpunet.config import ExportConfig
    from tpunet.obs.export import build_exporters
    reg = Registry()
    out = build_exporters(ExportConfig(webhook="http://127.0.0.1:9/h"),
                          reg)
    try:
        assert len(out) == 1
        assert isinstance(out[0], AlertWebhook)
    finally:
        for e in out:
            e.close()


# ---------------------------------------------------------------------------
# fleet acceptance: injected straggler -> one documented POST
# ---------------------------------------------------------------------------


def _epoch(run_id, ep, base):
    return {"kind": "obs_epoch", "run_id": run_id, "process_index": 0,
            "host": run_id, "epoch": ep, "step": 10 * ep, "steps": 10,
            "step_time_mean_s": base, "step_time_p50_s": base,
            "step_time_sample": [base + 0.0001 * i for i in range(16)],
            "examples_per_sec": 100.0, "live_processes": 1}


def test_straggler_fires_one_webhook_post_end_to_end():
    """The acceptance bar: two replicas, one straggling 5x, the
    aggregator's alert bridge fires, and exactly ONE POST with the
    documented payload lands on a stdlib HTTP receiver."""
    srv, got = _receiver()
    try:
        agg = Aggregator(straggler_factor=2.0)
        wh = AlertWebhook(
            WebhookTransport(
                f"http://127.0.0.1:{srv.server_address[1]}/hook"),
            registry=agg.registry)
        agg.registry.add_sink(wh)
        for ep in range(1, 4):
            agg.ingest(_epoch("fast", ep, 0.010), stamp_time=False)
            agg.ingest(_epoch("slow", ep, 0.050), stamp_time=False)
        agg.emit_rollup()                    # straggler fires here
        agg.emit_rollup()                    # latched: must NOT re-page
        wh.close()
        assert len(got) == 1, got
        payload = got[0]
        assert payload["source"] == "tpunet"
        assert payload["kind"] == "obs_alert"
        assert payload["reason"] == "straggler"
        assert payload["scope"] == "fleet"
        assert payload["stream"] == "slow/0"
        assert payload["detail"]["factor"] > 2.0
        assert "straggler" in payload["summary"]
        assert wh.stats()["sent"] == 1
    finally:
        srv.shutdown()
        srv.server_close()


def test_regression_record_pages_too():
    """obs_regression records page through the same sink — the
    obs_compare --webhook path."""
    srv, got = _receiver()
    try:
        reg = Registry()
        wh = AlertWebhook(
            WebhookTransport(
                f"http://127.0.0.1:{srv.server_address[1]}/"),
            registry=reg)
        reg.add_sink(wh)
        from tpunet.obs.history import emit_regression
        emit_regression(reg, {"run_a": "A", "run_b": "B",
                              "verdict": "regression",
                              "regressions": 2, "metrics": []})
        wh.close()
        assert len(got) == 1
        assert got[0]["kind"] == "obs_regression"
        assert got[0]["reason"] == "regression"
    finally:
        srv.shutdown()
        srv.server_close()
