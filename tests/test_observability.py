"""Observability: the tpunet/obs/ subsystem (metrics registry, stall
accounting, windowed profiling, sinks, the disabled-path guarantees),
per-step logging (the log_every_steps knob), host-side LR lookup, and
the non-finite-loss guard (SURVEY.md section 5: the reference has none
of these — stdout epoch lines are its only observability and a NaN run
would burn its full walltime)."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, ObsConfig, OptimConfig,
                           TrainConfig)
from tpunet.obs import MemorySink
from tpunet.obs.registry import Histogram
from tpunet.train.loop import Trainer
from tpunet.utils.logging import MetricsLogger
from tpunet.utils.timing import Timer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=32,
                     max_seq_len=64)


def _cfg(**kw):
    kw.setdefault("epochs", 1)
    kw.setdefault("checkpoint",
                  CheckpointConfig(save_best=False, save_last=False))
    return TrainConfig(
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=64, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=LM_CFG,
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        **kw,
    )


def test_log_every_steps_emits_step_lines(capsys):
    trainer = Trainer(_cfg(log_every_steps=2))
    try:
        trainer.train_one_epoch(1)  # 4 steps -> lines at steps 2 and 4
    finally:
        trainer.close()
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.strip().startswith("step ")]
    assert len(lines) == 2
    assert "loss" in lines[0] and "lr 3.000e-03" in lines[0]
    assert lines[1].strip().startswith("step 4")


def test_default_logs_no_step_lines(capsys):
    trainer = Trainer(_cfg())
    try:
        trainer.train_one_epoch(1)
    finally:
        trainer.close()
    assert "step " not in capsys.readouterr().out


def test_step_line_prints_the_lr_that_produced_the_loss(capsys):
    """optax consumes the PRE-increment count: the first step runs at
    schedule(0), so with a 4-step warmup its line must show lr 0."""
    import dataclasses
    cfg = _cfg(epochs=2, log_every_steps=1)
    cfg = cfg.replace(optim=dataclasses.replace(
        cfg.optim, schedule="constant", warmup_epochs=1.0))
    trainer = Trainer(cfg)
    try:
        trainer.train_one_epoch(1)
    finally:
        trainer.close()
    lines = [l.split() for l in capsys.readouterr().out.splitlines()
             if l.strip().startswith("step ")]
    assert lines[0][-1] == "0.000e+00"          # schedule(0)
    assert lines[3][-1] == "2.250e-03"          # schedule(3) = 3/4 ramp


def test_current_lr_follows_schedule():
    import dataclasses
    cfg = _cfg(epochs=2)
    cfg = cfg.replace(optim=dataclasses.replace(
        cfg.optim, schedule="constant", warmup_epochs=1.0))
    trainer = Trainer(cfg)  # 4 steps/epoch; warmup spans epoch 1
    try:
        assert trainer.current_lr() == pytest.approx(0.0)
        trainer.train_one_epoch(1)
        # after 4 of 4 warmup steps the ramp is complete
        assert trainer.current_lr() == pytest.approx(3e-3)
    finally:
        trainer.close()


def test_negative_log_every_steps_raises():
    with pytest.raises(ValueError, match="log_every_steps"):
        Trainer(_cfg(log_every_steps=-1))


# ---------------------------------------------------------------------------
# tpunet/obs/: registry, stall accounting, windowed profiling, sinks
# ---------------------------------------------------------------------------


def test_histogram_percentiles_exact():
    h = Histogram()
    for v in range(1, 101):        # 1..100
        h.observe(float(v))
    assert h.percentile(50) == pytest.approx(50.5)
    assert h.percentile(90) == pytest.approx(90.1)
    assert h.percentile(99) == pytest.approx(99.01)
    assert h.percentile(0) == 1.0 and h.percentile(100) == 100.0
    s = h.summary()
    assert s["count"] == 100 and s["mean"] == pytest.approx(50.5)
    h.reset()
    assert h.percentile(50) is None and h.summary() == {}


def test_histogram_single_observation():
    h = Histogram()
    h.observe(3.0)
    assert h.percentile(50) == 3.0 and h.percentile(99) == 3.0


def test_timer_lap_is_monotonic_and_independent_of_elapsed():
    t = Timer()
    first = t.lap()
    time.sleep(0.01)
    second = t.lap()
    assert first >= 0.0 and second >= 0.01
    # elapsed() spans construction -> now, not the last lap
    assert t.elapsed() >= second


def test_registry_snapshot_flattens_instruments():
    from tpunet.obs import Registry
    reg = Registry()
    reg.counter("saves").inc()
    reg.counter("saves").inc(2.0)
    reg.gauge("mem").set(7)
    for v in (1.0, 2.0, 3.0):
        reg.histogram("lap").observe(v)
    snap = reg.snapshot()
    assert snap["saves"] == 3.0
    assert snap["mem"] == 7.0
    assert snap["lap_count"] == 3 and snap["lap_p50"] == 2.0
    reg.reset_window()               # histograms clear, the rest persist
    snap = reg.snapshot()
    assert "lap_p50" not in snap and snap["saves"] == 3.0


def test_memory_sink_receives_epoch_record_with_schema(tmp_path):
    trainer = Trainer(_cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False)))
    mem = MemorySink()
    trainer.obs.add_sink(mem)
    try:
        trainer.train()
    finally:
        trainer.close()
    recs = mem.by_kind("obs_epoch")
    assert len(recs) == 1
    r = recs[0]
    assert r["epoch"] == 1 and r["steps"] == 4
    assert r["unit"] == "tokens" and r["tokens_per_sec"] > 0
    for k in ("step_time_p50_s", "step_time_p90_s", "step_time_p99_s"):
        assert r[k] > 0
    assert r["step_time_p50_s"] <= r["step_time_p99_s"]
    assert r["input_stall_s"] >= 0 and 0 <= r["stall_frac"] <= 1
    assert isinstance(r["device_memory"], list) and r["device_memory"]
    assert r["live_processes"] == 1
    # ... and the same record landed in metrics.jsonl via the JsonlSink
    on_disk = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert [x for x in on_disk if x.get("kind") == "obs_epoch"]


def test_stall_accounting_sees_slow_input_pipeline(tmp_path):
    trainer = Trainer(_cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False)))
    mem = MemorySink()
    trainer.obs.add_sink(mem)
    orig = trainer._epoch_batches

    def slow_batches(epoch):
        for batch in orig(epoch):
            time.sleep(0.03)       # fake host-input stall per fetch
            yield batch

    trainer._epoch_batches = slow_batches
    try:
        trainer.train()
    finally:
        trainer.close()
    r = mem.by_kind("obs_epoch")[0]
    assert r["input_stall_s"] >= 0.10    # 4 steps x 30ms, minus slack
    assert r["stall_frac"] > 0


def test_per_step_records_are_opt_in(tmp_path):
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False),
        obs=ObsConfig(step_records_every=2))
    trainer = Trainer(cfg)
    mem = MemorySink()
    trainer.obs.add_sink(mem)
    try:
        trainer.train()
    finally:
        trainer.close()
    steps = mem.by_kind("obs_step")
    assert [r["step"] for r in steps] == [0, 2]
    assert all(r["step_time_s"] > 0 for r in steps)


def test_default_path_no_step_records_and_no_device_sync(tmp_path,
                                                         monkeypatch):
    """The zero-overhead contract: at default obs config the loop emits
    per-EPOCH records only and never calls block_until_ready inside the
    step loop (window-edge fences belong to profiling, which is off)."""
    trainer = Trainer(_cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False)))
    calls = []
    real = jax.block_until_ready
    monkeypatch.setattr(jax, "block_until_ready",
                        lambda x: (calls.append(1), real(x))[1])
    try:
        trainer.train()
    finally:
        trainer.close()
    assert calls == []
    records = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert not [r for r in records if r.get("kind") == "obs_step"]
    assert [r for r in records if r.get("kind") == "obs_epoch"]


def test_no_obs_disables_all_records(tmp_path):
    trainer = Trainer(_cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False),
        obs=ObsConfig(enabled=False)))
    mem = MemorySink()
    trainer.obs.add_sink(mem)
    try:
        trainer.train()
    finally:
        trainer.close()
    assert mem.records == []
    records = MetricsLogger.read_records(str(tmp_path / "metrics.jsonl"))
    assert not [r for r in records if "kind" in r]
    assert len(records) == 1     # the plain epoch record still logs


def test_windowed_profiling_captures_only_the_window(tmp_path):
    trace_dir = str(tmp_path / "trace")
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck"), save_best=False,
        save_last=False),
        obs=ObsConfig(profile_start_step=1, profile_num_steps=2))
    cfg = cfg.replace(profile_dir=trace_dir)
    trainer = Trainer(cfg)
    try:
        trainer.train_one_epoch(1)   # 4 steps; window = steps [1, 3)
        assert not trainer.obs.profiler.running   # closed at step 3
    finally:
        trainer.close()
    assert os.path.isdir(trace_dir)


def test_window_ending_at_epoch_boundary_closes_at_the_edge(tmp_path):
    """A window whose end coincides with the epoch's last step must
    stop inside the epoch, not bleed across eval/checkpoint into the
    next epoch's first step."""
    trace_dir = str(tmp_path / "trace")
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck"), save_best=False,
        save_last=False),
        obs=ObsConfig(profile_start_step=2, profile_num_steps=2))
    cfg = cfg.replace(profile_dir=trace_dir)
    trainer = Trainer(cfg)
    try:
        trainer.train_one_epoch(1)   # 4 steps; window = steps [2, 4)
        assert not trainer.obs.profiler.running
    finally:
        trainer.close()
    assert os.path.isdir(trace_dir)


def test_windowed_profiling_outside_window_creates_nothing(tmp_path):
    trace_dir = str(tmp_path / "trace")
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck"), save_best=False,
        save_last=False),
        obs=ObsConfig(profile_start_step=100, profile_num_steps=2))
    cfg = cfg.replace(profile_dir=trace_dir)
    trainer = Trainer(cfg)
    try:
        trainer.train_one_epoch(1)
    finally:
        trainer.close()
    assert not os.path.exists(trace_dir)


def test_obs_validation_raises():
    with pytest.raises(ValueError, match="step_records_every"):
        Trainer(_cfg(obs=ObsConfig(step_records_every=-1)))
    with pytest.raises(ValueError, match="profile window"):
        Trainer(_cfg(obs=ObsConfig(profile_num_steps=-1)))


def test_read_records_tolerates_truncated_trailing_line(tmp_path):
    p = tmp_path / "metrics.jsonl"
    p.write_text('{"epoch": 1, "seconds": 2.0}\n'
                 '{"epoch": 2, "seconds": 2.1}\n'
                 '{"epoch": 3, "seco')          # torn final write
    records = MetricsLogger.read_records(str(p))
    assert [r["epoch"] for r in records] == [1, 2]


def test_read_records_raises_on_mid_file_corruption(tmp_path):
    p = tmp_path / "metrics.jsonl"
    p.write_text('{"epoch": 1}\nGARBAGE\n{"epoch": 2}\n')
    with pytest.raises(ValueError, match="malformed"):
        MetricsLogger.read_records(str(p))


def test_obs_report_summarizes_a_run(tmp_path, capsys):
    trainer = Trainer(_cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path), save_best=False, save_last=False)))
    try:
        trainer.train()
    finally:
        trainer.close()
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    assert obs_report.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "== epochs ==" in out
    assert "step time / stalls" in out
    assert "input-stall" in out


def test_nan_guard_raises_and_preserves_no_checkpoint(tmp_path):
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck"), save_best=False, save_last=True))
    trainer = Trainer(cfg)
    try:
        trainer.state = trainer.state.replace(
            params=jax.tree_util.tree_map(
                lambda p: p * jnp.nan, trainer.state.params))
        with pytest.raises(FloatingPointError, match="non-finite"):
            trainer.train()
        # the guard fired BEFORE save_state: no poisoned resume point
        assert trainer.ckpt.latest_step() is None
    finally:
        trainer.close()
