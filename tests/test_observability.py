"""Observability + failure detection: per-step logging (the
log_every_steps knob), host-side LR lookup, and the non-finite-loss
guard (SURVEY.md section 5: the reference has neither — stdout epoch
lines are its only observability and a NaN run would burn its full
walltime)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.train.loop import Trainer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=32,
                     max_seq_len=64)


def _cfg(**kw):
    kw.setdefault("epochs", 1)
    kw.setdefault("checkpoint",
                  CheckpointConfig(save_best=False, save_last=False))
    return TrainConfig(
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=64, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=LM_CFG,
        optim=OptimConfig(learning_rate=3e-3),
        mesh=MeshConfig(),
        **kw,
    )


def test_log_every_steps_emits_step_lines(capsys):
    trainer = Trainer(_cfg(log_every_steps=2))
    try:
        trainer.train_one_epoch(1)  # 4 steps -> lines at steps 2 and 4
    finally:
        trainer.close()
    lines = [l for l in capsys.readouterr().out.splitlines()
             if l.strip().startswith("step ")]
    assert len(lines) == 2
    assert "loss" in lines[0] and "lr 3.000e-03" in lines[0]
    assert lines[1].strip().startswith("step 4")


def test_default_logs_no_step_lines(capsys):
    trainer = Trainer(_cfg())
    try:
        trainer.train_one_epoch(1)
    finally:
        trainer.close()
    assert "step " not in capsys.readouterr().out


def test_step_line_prints_the_lr_that_produced_the_loss(capsys):
    """optax consumes the PRE-increment count: the first step runs at
    schedule(0), so with a 4-step warmup its line must show lr 0."""
    import dataclasses
    cfg = _cfg(epochs=2, log_every_steps=1)
    cfg = cfg.replace(optim=dataclasses.replace(
        cfg.optim, schedule="constant", warmup_epochs=1.0))
    trainer = Trainer(cfg)
    try:
        trainer.train_one_epoch(1)
    finally:
        trainer.close()
    lines = [l.split() for l in capsys.readouterr().out.splitlines()
             if l.strip().startswith("step ")]
    assert lines[0][-1] == "0.000e+00"          # schedule(0)
    assert lines[3][-1] == "2.250e-03"          # schedule(3) = 3/4 ramp


def test_current_lr_follows_schedule():
    import dataclasses
    cfg = _cfg(epochs=2)
    cfg = cfg.replace(optim=dataclasses.replace(
        cfg.optim, schedule="constant", warmup_epochs=1.0))
    trainer = Trainer(cfg)  # 4 steps/epoch; warmup spans epoch 1
    try:
        assert trainer.current_lr() == pytest.approx(0.0)
        trainer.train_one_epoch(1)
        # after 4 of 4 warmup steps the ramp is complete
        assert trainer.current_lr() == pytest.approx(3e-3)
    finally:
        trainer.close()


def test_negative_log_every_steps_raises():
    with pytest.raises(ValueError, match="log_every_steps"):
        Trainer(_cfg(log_every_steps=-1))


def test_nan_guard_raises_and_preserves_no_checkpoint(tmp_path):
    cfg = _cfg(checkpoint=CheckpointConfig(
        directory=str(tmp_path / "ck"), save_best=False, save_last=True))
    trainer = Trainer(cfg)
    try:
        trainer.state = trainer.state.replace(
            params=jax.tree_util.tree_map(
                lambda p: p * jnp.nan, trainer.state.params))
        with pytest.raises(FloatingPointError, match="non-finite"):
            trainer.train()
        # the guard fired BEFORE save_state: no poisoned resume point
        assert trainer.ckpt.latest_step() is None
    finally:
        trainer.close()
