"""Pallas kernel tests (interpret mode on the CPU mesh).

Parity target: tpunet.ops.depthwise_conv3x3 must match the XLA
reference depthwise conv (the op torchvision's MobileNetV2 runs via
cuDNN in the reference project) for every shape MobileNetV2 uses.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.ops import depthwise_conv3x3, depthwise_conv3x3_reference

# (h, c, stride) covering every depthwise layer of MobileNetV2 @224
MOBILENET_SHAPES = [
    (112, 32, 1),
    (112, 96, 2),
    (56, 144, 1),
    (56, 144, 2),
    (28, 192, 1),
    (28, 192, 2),
    (14, 384, 1),
    (14, 576, 1),
    (14, 576, 2),
    (7, 960, 1),
]


def _rand(shape, seed, dtype=jnp.float32):
    return jax.random.normal(jax.random.PRNGKey(seed), shape, dtype)


@pytest.mark.parametrize("h,c,stride", MOBILENET_SHAPES)
def test_matches_reference(h, c, stride):
    x = _rand((2, h, h, c), 0)
    w = _rand((3, 3, c), 1)
    got = depthwise_conv3x3(x, w, stride, True)
    want = depthwise_conv3x3_reference(x, w, stride)
    assert got.shape == want.shape
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_odd_size_and_stride2():
    x = _rand((1, 7, 7, 16), 2)
    w = _rand((3, 3, 16), 3)
    got = depthwise_conv3x3(x, w, 2, True)
    want = depthwise_conv3x3_reference(x, w, 2)
    assert got.shape == (1, 4, 4, 16)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_bfloat16_accumulates_in_f32():
    x = _rand((2, 28, 28, 64), 4, jnp.bfloat16)
    w = _rand((3, 3, 64), 5, jnp.bfloat16)
    got = depthwise_conv3x3(x, w, 1, True)
    assert got.dtype == jnp.bfloat16
    want = depthwise_conv3x3_reference(
        x.astype(jnp.float32), w.astype(jnp.float32), 1)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want), rtol=2e-2, atol=2e-2)


@pytest.mark.slow
def test_gradients_match_reference():
    x = _rand((2, 14, 14, 32), 6)
    w = _rand((3, 3, 32), 7)

    def loss_pallas(x, w):
        return jnp.sum(depthwise_conv3x3(x, w, 1, True) ** 2)

    def loss_ref(x, w):
        return jnp.sum(depthwise_conv3x3_reference(x, w, 1) ** 2)

    gx, gw = jax.grad(loss_pallas, argnums=(0, 1))(x, w)
    rx, rw = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-4, atol=1e-4)


def test_jit_composes():
    # NOTE: jax.vmap over the op is unsupported (custom_partitioning has
    # no batching rule); the op is already batched over N.
    x = _rand((4, 28, 28, 8), 8)
    w = _rand((3, 3, 8), 9)
    f = jax.jit(lambda x, w: depthwise_conv3x3(x, w, 1, True))
    np.testing.assert_allclose(
        np.asarray(f(x, w)),
        np.asarray(depthwise_conv3x3_reference(x, w, 1)),
        rtol=1e-5, atol=1e-5)


@pytest.mark.slow
def test_model_flag_same_params_same_logits(monkeypatch):
    """The pallas and XLA depthwise paths share one parameter tree and
    produce the same logits (ModelConfig.use_pallas_depthwise).

    Off-TPU the op defaults to the XLA reference, so force the kernel
    into interpret mode to actually exercise the Pallas path here."""
    import tpunet.ops as ops
    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables

    orig = ops.depthwise_conv3x3
    monkeypatch.setattr(
        ops, "depthwise_conv3x3",
        lambda x, w, stride=1, interpret=None: orig(x, w, stride, True))

    cfg = ModelConfig(dtype="float32", width_mult=0.5,
                      use_pallas_depthwise=False)  # explicit: XLA path
    ref = create_model(cfg)
    pal = create_model(dataclasses.replace(cfg, use_pallas_depthwise=True))
    variables = init_variables(ref, jax.random.PRNGKey(0), image_size=32)
    assert (jax.tree_util.tree_structure(variables) ==
            jax.tree_util.tree_structure(
                init_variables(pal, jax.random.PRNGKey(0), image_size=32)))
    x = _rand((2, 32, 32, 3), 10)
    a = ref.apply(variables, x, train=False)
    b = pal.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# IO-aware Pallas backward kernels (dx/dw): parity vs the XLA reference
# transpose, in interpret mode on CPU. Non-slow on small shapes (tier-1
# runs these); the full MobileNetV2 shape sweep is slow-marked.
# ---------------------------------------------------------------------------

def _bwd_pair(h, w_, c, stride, seed, dtype=jnp.float32):
    kx, kw, kg = jax.random.split(jax.random.PRNGKey(seed), 3)
    x = jax.random.normal(kx, (2, h, w_, c), dtype)
    w = jax.random.normal(kw, (3, 3, c), dtype)
    ho = (h - 1) // stride + 1
    wo = (w_ - 1) // stride + 1
    g = jax.random.normal(kg, (2, ho, wo, c), dtype)

    def vjp_of(f):
        _, vjp = jax.vjp(lambda xx, ww: f(xx, ww), x, w)
        return vjp(g)

    got = vjp_of(lambda xx, ww: depthwise_conv3x3(xx, ww, stride, True))
    want = vjp_of(lambda xx, ww: depthwise_conv3x3_reference(xx, ww,
                                                            stride))
    return got, want


# Odd H/W, non-square, channel counts off the 128-lane multiple — the
# property grid the stripe/halo + in-VMEM dilation logic must survive.
@pytest.mark.parametrize("h,w,c,stride", [
    (8, 8, 16, 1),
    (8, 8, 16, 2),
    (7, 7, 24, 1),      # odd H/W stride 1
    (7, 7, 24, 2),      # odd H/W stride 2 (dx phantom-row slice)
    (7, 9, 40, 1),      # non-square, off-lane channels
    (9, 7, 40, 2),
    (5, 5, 8, 2),
    (4, 6, 3, 2),       # tiny + odd channel count
])
def test_backward_kernels_match_reference(h, w, c, stride):
    (gx, gw), (rx, rw) = _bwd_pair(h, w, c, stride, seed=h * 31 + stride)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=2e-4, atol=2e-4)


def test_backward_kernels_bf16_accumulate_f32():
    """bf16 inputs: gradients come back bf16 but match the f32
    reference within bf16 rounding (the kernels accumulate in f32)."""
    kx, kw, kg = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (2, 8, 8, 32))
    w = jax.random.normal(kw, (3, 3, 32))
    g = jax.random.normal(kg, (2, 4, 4, 32))

    def vjp_of(f, x, w, g):
        _, vjp = jax.vjp(f, x, w)
        return vjp(g)

    gx, gw = vjp_of(
        lambda xx, ww: depthwise_conv3x3(xx, ww, 2, True),
        x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
        g.astype(jnp.bfloat16))
    assert gx.dtype == jnp.bfloat16 and gw.dtype == jnp.bfloat16
    rx, rw = vjp_of(
        lambda xx, ww: depthwise_conv3x3_reference(xx, ww, 2), x, w, g)
    np.testing.assert_allclose(np.asarray(gx, np.float32),
                               np.asarray(rx), rtol=5e-2, atol=5e-2)
    np.testing.assert_allclose(np.asarray(gw, np.float32),
                               np.asarray(rw), rtol=5e-2, atol=5e-2)


def test_backward_reference_escape_hatch(monkeypatch):
    """TPUNET_DEPTHWISE_REF_BWD=1 routes backward through the XLA
    reference transpose even when the kernels are requested."""
    monkeypatch.setenv("TPUNET_DEPTHWISE_REF_BWD", "1")
    (gx, gw), (rx, rw) = _bwd_pair(6, 6, 8, 1, seed=4)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.slow
@pytest.mark.parametrize("h,c,stride", MOBILENET_SHAPES)
def test_backward_kernels_mobilenet_shapes(h, c, stride):
    (gx, gw), (rx, rw) = _bwd_pair(h, h, c, stride, seed=c)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                               rtol=5e-4, atol=5e-4)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                               rtol=5e-4, atol=5e-4)
