"""Optimization-stack upgrades: LR schedules (warmup/cosine), global
gradient-norm clipping, and parameter EMA.

The reference's stack is fixed (Adam 1e-4 + StepLR(10, 0.1),
cifar10_mpi_mobilenet_224.py:147-149) and stays the default; these are
beyond-parity options and must not disturb that default.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.train.loop import Trainer
from tpunet.train.state import lr_schedule, make_optimizer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=32,
                     max_seq_len=64)


def _lm_cfg(optim, mesh=None, epochs=1):
    return TrainConfig(
        epochs=epochs,
        data=DataConfig(dataset="synthetic_lm", batch_size=16,
                        synthetic_train_size=64, synthetic_test_size=16,
                        seq_len=64, vocab_size=32),
        model=LM_CFG,
        optim=optim,
        mesh=mesh or MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


# ------------------------------------------------------------ schedules


def test_step_schedule_is_reference_steplr():
    """lr=1e-4, decay x0.1 at epochs 10 and 20 (StepLR(10, 0.1))."""
    fn = lr_schedule(OptimConfig(), steps_per_epoch=100, epochs=20)
    assert float(fn(0)) == pytest.approx(1e-4)
    assert float(fn(999)) == pytest.approx(1e-4)
    assert float(fn(1000)) == pytest.approx(1e-5)
    assert float(fn(1999)) == pytest.approx(1e-5)


def test_cosine_schedule_decays_to_zero():
    fn = lr_schedule(OptimConfig(schedule="cosine"), steps_per_epoch=100,
                     epochs=10)
    assert float(fn(0)) == pytest.approx(1e-4)
    assert float(fn(500)) == pytest.approx(5e-5, rel=1e-3)  # half-way
    assert float(fn(1000)) == pytest.approx(0.0, abs=1e-10)


def test_warmup_composes_with_any_schedule():
    # 1 epoch warmup then constant
    fn = lr_schedule(OptimConfig(schedule="constant", warmup_epochs=1.0),
                     steps_per_epoch=100, epochs=10)
    assert float(fn(0)) == pytest.approx(0.0)
    assert float(fn(50)) == pytest.approx(5e-5)
    assert float(fn(100)) == pytest.approx(1e-4)
    assert float(fn(900)) == pytest.approx(1e-4)
    # warmup + cosine: the cosine clock starts at warmup end
    fn = lr_schedule(OptimConfig(schedule="cosine", warmup_epochs=1.0),
                     steps_per_epoch=100, epochs=11)
    assert float(fn(100)) == pytest.approx(1e-4)
    assert float(fn(600)) == pytest.approx(5e-5, rel=1e-3)
    assert float(fn(1100)) == pytest.approx(0.0, abs=1e-10)


def test_unknown_schedule_raises():
    with pytest.raises(ValueError, match="unknown schedule"):
        lr_schedule(OptimConfig(schedule="nope"), 10, 1)


# ------------------------------------------------------------- clipping


def test_clip_norm_bounds_the_update():
    params = {"w": jnp.zeros(4)}
    grads = {"w": jnp.full(4, 50.0)}  # global norm 100
    tx = make_optimizer(OptimConfig(name="sgd", schedule="constant",
                                    learning_rate=1.0, clip_norm=1.0),
                        steps_per_epoch=1, epochs=1)
    st = tx.init(params)
    updates, _ = tx.update(grads, st, params)
    norm = float(jnp.linalg.norm(updates["w"]))
    assert norm == pytest.approx(1.0, rel=1e-5)  # clipped then lr=1 sgd
    # without clipping the same update has norm 100
    tx = make_optimizer(OptimConfig(name="sgd", schedule="constant",
                                    learning_rate=1.0),
                        steps_per_epoch=1, epochs=1)
    updates, _ = tx.update(grads, tx.init(params), params)
    assert float(jnp.linalg.norm(updates["w"])) == pytest.approx(100.0,
                                                                 rel=1e-5)


def test_clip_norm_trains_and_moment_rules_still_match():
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3,
                                          clip_norm=1.0)))
    try:
        m = trainer.train_one_epoch(1)
        assert np.isfinite(m["loss"])
        # Adam state nests one level deeper inside the chain; path-rule
        # moment matching is positional-path-based and must still find
        # mu/nu leaves (exercised properly in the zero1 variant below).
        flat = jax.tree_util.tree_leaves(trainer.state.opt_state)
        assert len(flat) > 2
    finally:
        trainer.close()


@pytest.mark.slow
def test_clip_norm_composes_with_zero1():
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3,
                                          clip_norm=1.0),
                              mesh=MeshConfig(data=8, zero1=True)))
    try:
        specs = [str(l.sharding.spec) for l in
                 jax.tree_util.tree_leaves(trainer.state.opt_state)
                 if hasattr(l, "sharding")]
        assert any("data" in s for s in specs), specs
        m = trainer.train_one_epoch(1)
        assert np.isfinite(m["loss"])
    finally:
        trainer.close()


# ------------------------------------------------------------------ EMA


def test_ema_decay_out_of_range_raises():
    """decay >= 1 would silently freeze the EMA at the random init."""
    with pytest.raises(ValueError, match="ema_decay"):
        Trainer(_lm_cfg(OptimConfig(ema_decay=1.0)))
    with pytest.raises(ValueError, match="ema_decay"):
        Trainer(_lm_cfg(OptimConfig(ema_decay=-0.1)))


def test_evaluate_reads_ema_params():
    """Swap the EMA tree for all-zero weights: a zero LM emits all-zero
    logits, so evaluate() must report exactly uniform CE = ln(vocab) if
    (and only if) it evaluates ema_params rather than params."""
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3,
                                          ema_decay=0.5)))
    try:
        trainer.train_one_epoch(1)
        zeros = jax.tree_util.tree_map(jnp.zeros_like,
                                       trainer.state.ema_params)
        trainer.state = trainer.state.replace(ema_params=zeros)
        m = trainer.evaluate()
        assert m["loss"] == pytest.approx(float(jnp.log(32.0)), rel=1e-5)
    finally:
        trainer.close()


def test_ema_tracks_params():
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3,
                                          ema_decay=0.5)))
    try:
        trainer.train_one_epoch(1)
        p = np.asarray(trainer.state.params["embed"]["embedding"])
        e = np.asarray(trainer.state.ema_params["embed"]["embedding"])
        assert not np.allclose(p, e)
        assert np.abs(e - p).max() < 0.1  # decay 0.5 hugs the params
    finally:
        trainer.close()


def test_ema_disabled_is_empty_and_eval_uses_params():
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3)))
    try:
        assert trainer.state.ema_params == {}
        trainer.train_one_epoch(1)
        assert np.isfinite(trainer.evaluate()["loss"])
    finally:
        trainer.close()


@pytest.mark.slow
def test_ema_covers_batch_stats_for_bn_models():
    """BatchNorm models must evaluate/save EMA params WITH EMA running
    stats — pairing EMA weights with live stats mismatches the
    normalization (the torch swa_utils update_bn problem)."""
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=16,
                        synthetic_train_size=32, synthetic_test_size=16),
        model=ModelConfig(width_mult=0.5, dtype="float32"),
        optim=OptimConfig(ema_decay=0.5),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        init_stats = jax.tree_util.tree_map(np.asarray,
                                            trainer.state.ema_batch_stats)
        assert jax.tree_util.tree_leaves(init_stats)  # BN model: nonempty
        trainer.train_one_epoch(1)
        moved = jax.tree_util.tree_map(
            lambda a, b: not np.allclose(np.asarray(a), np.asarray(b)),
            trainer.state.ema_batch_stats, init_stats)
        assert any(jax.tree_util.tree_leaves(moved))
        # same tree structure as the live stats -> eval/save can swap
        assert (jax.tree_util.tree_structure(trainer.state.ema_batch_stats)
                == jax.tree_util.tree_structure(trainer.state.batch_stats))
        assert np.isfinite(trainer.evaluate()["loss"])
    finally:
        trainer.close()


def test_warmup_longer_than_run_raises():
    with pytest.raises(ValueError, match="warmup_epochs"):
        Trainer(_lm_cfg(OptimConfig(warmup_epochs=2.0), epochs=1))


def test_ema_composes_with_fsdp():
    trainer = Trainer(_lm_cfg(OptimConfig(learning_rate=3e-3,
                                          ema_decay=0.9),
                              mesh=MeshConfig(data=8, fsdp=True)))
    try:
        qkv = trainer.state.params["block00"]["attn"]["qkv"]["kernel"]
        eqkv = trainer.state.ema_params["block00"]["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == eqkv.sharding.spec != P()
        trainer.train_one_epoch(1)
        assert np.isfinite(trainer.evaluate()["loss"])
    finally:
        trainer.close()


def test_cli_flags():
    from tpunet.config import config_from_args
    cfg = config_from_args(["--lr-schedule", "cosine", "--warmup-epochs",
                            "0.5", "--clip-norm", "1.0", "--ema-decay",
                            "0.999"])
    assert cfg.optim.schedule == "cosine"
    assert cfg.optim.warmup_epochs == 0.5
    assert cfg.optim.clip_norm == 1.0
    assert cfg.optim.ema_decay == 0.999


def test_optimizer_cli_exposure():
    from tpunet.config import config_from_args
    cfg = config_from_args(["--optimizer", "adamw", "--weight-decay",
                            "0.05", "--label-smoothing", "0.1",
                            "--eval-batch-size", "256"])
    assert cfg.optim.name == "adamw"
    assert cfg.optim.weight_decay == 0.05
    assert cfg.optim.label_smoothing == 0.1
    assert cfg.data.eval_batch_size == 256


def test_adamw_and_sgd_train():
    for name, kw in (("adamw", dict(weight_decay=0.01)),
                     ("sgd", {})):
        trainer = Trainer(_lm_cfg(OptimConfig(name=name,
                                              learning_rate=3e-3, **kw)))
        try:
            m = trainer.train_one_epoch(1)
            assert np.isfinite(m["loss"]), name
        finally:
            trainer.close()
