"""Packed-sequence LM training (--pack-docs): document packing with
segment ids, segment-masked attention through the model, boundary-
masked loss/metrics, and the end-to-end CLI path."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.data.lm import text_lm_packed
from tpunet.models import create_model, init_variables
from tpunet.train.loop import Trainer

LM_CFG = ModelConfig(name="lm", vit_hidden=64, vit_depth=2, vit_heads=4,
                     dropout_rate=0.0, dtype="float32", vocab_size=256,
                     max_seq_len=64)


def test_packing_structure(tmp_path):
    path = tmp_path / "docs.txt"
    # docs of lengths 10, 20, 10, 50 (splits), 5 at seq_len 32
    path.write_bytes(b"\n".join([b"a" * 10, b"b" * 20, b"c" * 10,
                                 b"d" * 50, b"e" * 5]))
    tx, ty, sx, sy = text_lm_packed(str(path), seq_len=32, train_frac=0.5)
    allx = np.concatenate([tx, sx])
    ally = np.concatenate([ty, sy])
    # no doc straddles a row: within a row, each segment id's tokens are
    # contiguous and share one byte value (by construction of the corpus)
    for row, seg in zip(allx, ally):
        for s in np.unique(seg):
            sel = row[seg == s]
            if s == 0:
                assert (sel == 0).all()          # padding
            else:
                assert len(np.unique(sel)) == 1  # one doc, one byte value
        # segment ids are 1..k then 0-padding, non-interleaved
        nz = seg[seg != 0]
        assert (np.diff(nz) >= 0).all()
    # every input byte survived packing
    assert (allx != 0).sum() == 10 + 20 + 10 + 50 + 5


def test_packed_target_weights():
    from tpunet.train.steps import _packed_target_weights
    segs = jnp.asarray([[1, 1, 1, 2, 2, 0, 0, 0]])
    wt = np.asarray(_packed_target_weights(segs))[0]
    # [T-1] weights: targets at positions 1,2 (within doc1) and 4
    # (within doc2) are valid; the doc boundary (pos 3) and pad are not
    np.testing.assert_array_equal(wt, [1, 1, 0, 1, 0, 0, 0])


@pytest.mark.slow
def test_model_segment_isolation():
    """With segment ids, each packed document's logits equal the same
    document run alone — nothing leaks across the packed boundary
    (model-level counterpart of the kernel's cross-segment test)."""
    model = create_model(LM_CFG)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=32)
    rng = np.random.default_rng(0)
    d1 = rng.integers(1, 256, 12)
    d2 = rng.integers(1, 256, 20)
    toks = jnp.asarray(np.concatenate([d1, d2])[None], jnp.int32)
    segs = jnp.asarray(np.concatenate([np.full(12, 1),
                                       np.full(20, 2)])[None], jnp.int32)
    packed = model.apply(variables, toks, train=False, segment_ids=segs)
    alone1 = model.apply(variables, jnp.asarray(d1[None], jnp.int32),
                         train=False)
    np.testing.assert_allclose(np.asarray(packed[0, :12]),
                               np.asarray(alone1[0]), rtol=2e-4,
                               atol=2e-4)
    # NOTE d2 alone is NOT compared: positions differ (packed d2 sits at
    # absolute positions 12..31 and learned position embeddings are
    # absolute, matching how packed training actually sees documents).
    # Instead: changing d1's content must not change d2's logits.
    toks2 = toks.at[:, :12].set((toks[:, :12] + 5) % 256)
    packed2 = model.apply(variables, toks2, train=False, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(packed[0, 12:]),
                               np.asarray(packed2[0, 12:]), rtol=2e-5,
                               atol=2e-5)
    assert not np.allclose(np.asarray(packed[0, :12]),
                           np.asarray(packed2[0, :12]))


@pytest.mark.slow
def test_packed_training_end_to_end(tmp_path):
    """Train on packed repeated documents: deterministic within-doc
    structure must be learned (accuracy high on valid targets), and
    metrics must count ONLY valid targets."""
    path = tmp_path / "docs.txt"
    path.write_bytes(b"\n".join([b"abcdefgh" * 3] * 200))  # 24-byte docs
    cfg = TrainConfig(
        epochs=6,
        data=DataConfig(dataset="text_lm", text_path=str(path),
                        batch_size=16, seq_len=48, vocab_size=256,
                        pack_docs=True),
        model=LM_CFG,
        optim=OptimConfig(learning_rate=1e-2, schedule="constant"),
        mesh=MeshConfig(),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        history = trainer.train()
    finally:
        trainer.close()
    final = history[-1]
    assert final["train_accuracy"] > 0.85, final
    # metric count excludes boundary/padding targets: with 48-byte rows
    # of two 24-byte docs, valid targets are 23 per doc, 46 per row
    # (not 47 = T-1)
    assert np.isfinite(final["test_loss"])


@pytest.mark.slow
def test_pack_docs_cli_and_validation(tmp_path):
    from tpunet.config import config_from_args
    path = tmp_path / "c.txt"
    path.write_bytes(b"\n".join([b"hello world"] * 40))
    cfg = config_from_args(["--dataset", "text_lm", "--text-file",
                            str(path), "--model", "lm", "--pack-docs",
                            "--seq-len", "32", "--batch-size", "8",
                            "--epochs", "1"])
    assert cfg.data.pack_docs
    bad = cfg.replace(model=dataclasses.replace(cfg.model,
                                                attention="ring"),
                      mesh=MeshConfig(seq=2))
    with pytest.raises(ValueError, match="segment-capable"):
        Trainer(bad)
    vit = cfg.replace(model=dataclasses.replace(cfg.model,
                                                name="mobilenet_v2"))
    with pytest.raises(ValueError):
        Trainer(vit)
    # pack_docs with a non-text_lm dataset: its labels are NOT segment
    # ids — rejected up front, not an opaque trace-time IndexError
    synth = cfg.replace(data=dataclasses.replace(
        cfg.data, dataset="synthetic_lm", synthetic_train_size=16,
        synthetic_test_size=8))
    with pytest.raises(ValueError, match="text_lm"):
        Trainer(synth)


@pytest.mark.slow
def test_packed_grad_accum_weights_by_valid_count(tmp_path):
    """Packed microbatches have UNEQUAL valid-target counts, so grad
    accumulation must weight microbatch gradients by count: accum=2
    must match accum=1 on the same global batch."""
    path = tmp_path / "docs.txt"
    # wildly uneven doc lengths -> uneven per-row valid counts
    docs = ([b"x" * 40] * 8 + [b"y" * 4] * 40) * 4
    path.write_bytes(b"\n".join(docs))

    def run(accum):
        cfg = TrainConfig(
            epochs=1,
            data=DataConfig(dataset="text_lm", text_path=str(path),
                            batch_size=16, seq_len=48, vocab_size=256,
                            pack_docs=True),
            model=LM_CFG,
            optim=OptimConfig(learning_rate=1e-3, grad_accum=accum),
            mesh=MeshConfig(data=2),
            checkpoint=CheckpointConfig(save_best=False,
                                        save_last=False),
        )
        tr = Trainer(cfg)
        try:
            m = tr.train_one_epoch(1)
            leaf = np.asarray(
                jax.tree_util.tree_leaves(tr.state.params)[0])
        finally:
            tr.close()
        return m, leaf

    m1, p1 = run(1)
    m2, p2 = run(2)
    assert abs(m1["loss"] - m2["loss"]) < 1e-4
    assert m1["count"] == m2["count"]
    np.testing.assert_allclose(p1, p2, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_packed_grad_accum_moe_aux_equal_weighting():
    """Packed + MoE + grad_accum>1: the CE gradient is normalized by
    the GLOBAL valid-target count, but the count-independent MoE aux
    load-balance loss must get EQUAL (1/accum) microbatch weighting.
    The pre-fix scheme scaled whole microbatch grads by their counts,
    biasing the aux term toward fuller microbatches. Verified against
    a hand-computed gradient with the correct per-term weighting."""
    import optax

    from tpunet.train.state import TrainState
    from tpunet.train.steps import (_packed_target_weights,
                                    make_lm_train_step)

    cfg = dataclasses.replace(LM_CFG, moe_experts=2, moe_every=1,
                              moe_aux_weight=0.1)
    model = create_model(cfg)
    variables = init_variables(model, jax.random.PRNGKey(0), seq_len=16)
    params = variables["params"]

    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(1, 256, (4, 16)), jnp.int32)
    # wildly uneven valid counts: rows 1 and 3 are mostly padding, so
    # the two strided microbatches (rows 0,2 vs rows 1,3) differ a lot
    segs = np.ones((4, 16), np.int64)
    segs[1, 4:] = 0
    segs[3, 2:] = 0
    segs = jnp.asarray(segs, jnp.int32)

    total = jnp.maximum(jnp.sum(_packed_target_weights(segs)), 1.0)

    def micro_terms(params, mx, ms):
        logits, mut = model.apply(
            {"params": params, "batch_stats": {}}, mx, train=True,
            rngs={"dropout": jax.random.PRNGKey(0)},
            mutable=["batch_stats", "losses"], segment_ids=ms)
        lg, tgt = logits[:, :-1], mx[:, 1:]
        ce = optax.softmax_cross_entropy_with_integer_labels(lg, tgt)
        wt = _packed_target_weights(ms)
        aux = 0.1 * sum(jax.tree_util.tree_leaves(mut["losses"]))
        return jnp.sum(ce * wt), aux

    def ref_loss(params):
        out = 0.0
        for i in range(2):          # strided split, as the step does
            ce_sum, aux = micro_terms(params, toks[i::2], segs[i::2])
            out = out + ce_sum / total + aux / 2.0
        return out

    expected = jax.grad(ref_loss)(params)

    step = make_lm_train_step(
        OptimConfig(learning_rate=1.0, grad_accum=2), cfg,
        packed=True)
    state = TrainState.create(apply_fn=model.apply, params=params,
                              tx=optax.sgd(1.0), batch_stats={})
    new_state, _ = jax.jit(step)(state, toks, segs,
                                 jax.random.PRNGKey(0))
    got = jax.tree_util.tree_map(lambda p, n: p - n, params,
                                 new_state.params)
    for e, g in zip(jax.tree_util.tree_leaves(expected),
                    jax.tree_util.tree_leaves(got)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(e),
                                   rtol=2e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# Packed x PP: segment ids through the pipeline executors (round 3)
# ---------------------------------------------------------------------------

PP_CFG = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=4,
                     vit_heads=2, dropout_rate=0.0, dtype="float32",
                     vocab_size=64, max_seq_len=32, pp_microbatches=2)


@pytest.mark.slow
def test_packed_pp_matches_unpipelined_and_isolates_segments():
    """segment_ids ride the executors' non-differentiable `extra`
    input (indexed per microbatch by every stage, never hopped):
    pipelined packed forward AND grads must equal the unpipelined
    TransformerLM's segment-masked path on unstacked params, under
    both schedules; mutating one document must not change another's
    logits inside the pipeline."""
    from tpunet.models.lm_pp import to_transformer_lm_params
    from tpunet.parallel import make_mesh

    rng = np.random.default_rng(7)
    toks = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    segs = jnp.asarray(np.concatenate(
        [np.full((8, 6), 1), np.full((8, 7), 2), np.full((8, 3), 0)],
        axis=1), jnp.int32)

    pp0 = create_model(PP_CFG)
    variables = init_variables(pp0, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = {"params": variables["params"]}
    lm = create_model(dataclasses.replace(PP_CFG, name="lm"))
    lm_params = to_transformer_lm_params(variables["params"])
    ref = lm.apply({"params": lm_params}, toks, train=True,
                   segment_ids=segs)

    def packed_loss(model, use_mesh, mesh):
        def loss(p):
            lg = model.apply({"params": p}, toks, train=True,
                             segment_ids=segs)
            wt = (segs[:, 1:] == segs[:, :-1]) & (segs[:, 1:] > 0)
            ce = jnp.where(wt, jnp.mean((lg[:, :-1] - 1.0) ** 2, -1),
                           0.0)
            return jnp.sum(ce) / jnp.sum(wt)
        if use_mesh:
            with mesh:
                return jax.grad(loss)(variables["params"])
        return jax.grad(loss)(variables["params"])

    mesh = make_mesh(MeshConfig(data=2, pipe=2))
    g_ref = packed_loss(pp0, False, None)
    for sched in ("gpipe", "1f1b"):
        m = create_model(dataclasses.replace(PP_CFG, pp_schedule=sched),
                         mesh=mesh)
        with mesh:
            o = m.apply(params, toks, train=True, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        g = packed_loss(m, True, mesh)
        for a, b in zip(jax.tree_util.tree_leaves(g),
                        jax.tree_util.tree_leaves(g_ref)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-6)

    # Isolation in the direction only SEGMENT masking protects:
    # mutate the EARLIER document (cols :6, segment 1) — plain causal
    # attention would leak it into the later one; the later document's
    # logits (cols 6:13, segment 2) must not move. (The reverse
    # direction would pass under causality alone and prove nothing
    # about the executors' segment plumbing.)
    m = create_model(PP_CFG, mesh=mesh)
    toks2 = toks.at[:, :6].set((toks[:, :6] + 5) % 64)
    with mesh:
        a = m.apply(params, toks, train=False, segment_ids=segs)
        b = m.apply(params, toks2, train=False, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(a[:, 6:13]),
                               np.asarray(b[:, 6:13]), atol=1e-6)
    assert not np.allclose(np.asarray(a[:, :6]), np.asarray(b[:, :6]))


def test_packed_pp_validation():
    """lm_pp + packed + RING attention is rejected (the ring merges
    per-block attention states and the flash state kernel has no
    segment operands) — Ulysses is the segment-capable SP path."""
    from tpunet.parallel import make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=2, pipe=2))
    m = create_model(dataclasses.replace(PP_CFG, attention="ring"),
                     mesh=mesh)
    variables = init_variables(m, jax.random.PRNGKey(0), batch_size=8,
                               seq_len=16)
    toks = jnp.zeros((8, 16), jnp.int32)
    with pytest.raises(ValueError, match="ring"):
        with mesh:
            m.apply(variables, toks, train=True,
                    segment_ids=jnp.ones((8, 16), jnp.int32))


# ---------------------------------------------------------------------------
# Packed x SP: the segment-capable Ulysses core
# ---------------------------------------------------------------------------

def _packed_case(b=8, t=16, vocab=64, seed=7):
    """Packed rows: doc 1 (cols :6), doc 2 (cols 6:13), padding tail."""
    rng = np.random.default_rng(seed)
    toks = jnp.asarray(rng.integers(0, vocab, (b, t)), jnp.int32)
    segs = jnp.asarray(np.concatenate(
        [np.full((b, 6), 1), np.full((b, 7), 2), np.full((b, 3), 0)],
        axis=1), jnp.int32)
    return toks, segs


@pytest.mark.slow
@pytest.mark.parametrize("name,mesh_cfg,sched", [
    ("lm", MeshConfig(data=2, seq=2), "gpipe"),
    ("lm_pp", MeshConfig(data=2, seq=2), "gpipe"),      # pipe=1 SP path
    ("lm_pp", MeshConfig(data=2, seq=2, pipe=2), "gpipe"),
    ("lm_pp", MeshConfig(data=2, seq=2, pipe=2), "1f1b"),
])
def test_packed_sp_matches_unsharded_packed(name, mesh_cfg, sched):
    """Packed x SP (Ulysses): forward and grads on dp x sp (and
    dp x sp x pp, both schedules) equal the unsharded packed lm_pp —
    the seq-sharded segment ids ride the executors' `extra` input and
    ulysses_attention's one-id-all_gather rebuilds exact global
    masking inside its full-sequence local core."""
    from tpunet.parallel import make_mesh

    toks, segs = _packed_case()
    base = create_model(dataclasses.replace(PP_CFG,
                                            attention_core="blockwise"))
    variables = init_variables(base, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = {"params": variables["params"]}
    ref = base.apply(params, toks, train=True, segment_ids=segs)

    def grads(model, mesh):
        def loss(p):
            lg = model.apply({"params": p}, toks, train=True,
                             segment_ids=segs)
            wt = (segs[:, 1:] == segs[:, :-1]) & (segs[:, 1:] > 0)
            return jnp.sum(jnp.where(wt, jnp.mean(lg[:, :-1] ** 2, -1),
                                     0.0)) / jnp.sum(wt)
        if mesh is None:
            return jax.grad(loss)(variables["params"])
        with mesh:
            return jax.grad(loss)(variables["params"])

    g_ref = grads(base, None)
    mesh = make_mesh(mesh_cfg)
    cfg = dataclasses.replace(PP_CFG, name=name, attention="ulysses",
                              attention_core="blockwise",
                              pp_schedule=sched)
    m = create_model(cfg, mesh=mesh)
    with mesh:
        if name == "lm":
            # same architecture, unstacked params
            from tpunet.models.lm_pp import to_transformer_lm_params
            lp = to_transformer_lm_params(variables["params"])
            o = m.apply({"params": lp}, toks, train=True,
                        segment_ids=segs)
        else:
            o = m.apply(params, toks, train=True, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    if name == "lm_pp":
        g = grads(m, mesh)
        for (pth, a), (_, b) in zip(
                jax.tree_util.tree_leaves_with_path(g),
                jax.tree_util.tree_leaves_with_path(g_ref)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=f"{mesh_cfg}: {jax.tree_util.keystr(pth)}")


def test_packed_sp_isolates_documents():
    """Document isolation UNDER sequence sharding, in the direction
    only segment masking protects: the packed boundary (col 6) does
    not align with the seq-shard boundary (col 8 on sp=2), so doc 2
    spans both shards — mutating doc 1 must not move doc 2's logits
    through the gathered-id masking, on dp x sp and dp x sp x pp."""
    from tpunet.parallel import make_mesh

    toks, segs = _packed_case()
    base = create_model(PP_CFG)
    variables = init_variables(base, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = {"params": variables["params"]}
    toks2 = toks.at[:, :6].set((toks[:, :6] + 5) % 64)
    for mesh_cfg in (MeshConfig(data=2, seq=2),
                     MeshConfig(data=2, seq=2, pipe=2)):
        mesh = make_mesh(mesh_cfg)
        m = create_model(dataclasses.replace(
            PP_CFG, attention="ulysses", attention_core="blockwise"),
            mesh=mesh)
        with mesh:
            a = m.apply(params, toks, train=False, segment_ids=segs)
            b = m.apply(params, toks2, train=False, segment_ids=segs)
        np.testing.assert_allclose(np.asarray(a[:, 6:13]),
                                   np.asarray(b[:, 6:13]), atol=1e-6)
        assert not np.allclose(np.asarray(a[:, :6]), np.asarray(b[:, :6]))


@pytest.mark.slow
def test_packed_pp_training_end_to_end(tmp_path):
    """Packed training through the pipeline: --pack-docs --model lm_pp
    on dp2 x pp2 (1f1b) learns the within-document structure and the
    metrics count only valid targets."""
    path = tmp_path / "docs.txt"
    path.write_bytes(b"\n".join([b"abcdefgh" * 3] * 200))
    cfg = TrainConfig(
        epochs=6,
        data=DataConfig(dataset="text_lm", text_path=str(path),
                        batch_size=16, seq_len=48, vocab_size=256,
                        pack_docs=True),
        model=dataclasses.replace(LM_CFG, name="lm_pp", vit_depth=2,
                                  pp_microbatches=2,
                                  pp_schedule="1f1b"),
        optim=OptimConfig(learning_rate=1e-2, schedule="constant"),
        mesh=MeshConfig(data=2, pipe=2),
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )
    trainer = Trainer(cfg)
    try:
        first = trainer.train_one_epoch(1)
        for e in range(2, 7):
            last = trainer.train_one_epoch(e)
    finally:
        trainer.close()
    assert np.isfinite(last["loss"])
    assert last["loss"] < first["loss"] - 0.3
    assert last["accuracy"] > 0.5
