"""Mesh / sharding unit tests on the 8-device virtual CPU mesh."""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from tpunet.config import MeshConfig
from tpunet.parallel import (batch_sharding, make_mesh, replicated_sharding,
                             shard_host_batch)


def test_default_mesh_uses_all_devices():
    mesh = make_mesh(MeshConfig())
    assert mesh.devices.size == 8
    assert mesh.axis_names == ("data", "seq", "pipe", "model")
    assert mesh.shape["data"] == 8 and mesh.shape["model"] == 1
    assert mesh.shape["seq"] == 1 and mesh.shape["pipe"] == 1


def test_explicit_mesh_shape():
    mesh = make_mesh(MeshConfig(data=4, model=2))
    assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    mesh = make_mesh(MeshConfig(data=2, seq=2, model=2))
    assert (mesh.shape["data"], mesh.shape["seq"], mesh.shape["model"]) \
        == (2, 2, 2)


def test_mesh_subset_of_devices():
    mesh = make_mesh(MeshConfig(data=2, model=1))
    assert mesh.devices.size == 2


def test_mesh_too_large_raises():
    with pytest.raises(ValueError, match="needs"):
        make_mesh(MeshConfig(data=16, model=1))


def test_shard_host_batch_roundtrip():
    mesh = make_mesh(MeshConfig())
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    gx = shard_host_batch(mesh, x)
    assert gx.shape == (8, 4)
    assert gx.sharding.spec == P(("data",))
    np.testing.assert_array_equal(jax.device_get(gx), x)
    # each device holds exactly one row
    assert all(s.data.shape == (1, 4) for s in gx.addressable_shards)


def test_replicated_sharding_spec():
    mesh = make_mesh(MeshConfig())
    assert replicated_sharding(mesh).spec == P()
    assert batch_sharding(mesh).spec == P(("data",))
