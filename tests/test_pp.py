"""Pipeline parallelism: GPipe executor correctness, pipelined-ViT
parity with its own sequential path, and training through the Trainer
on a dp x pp mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.models import create_model, init_variables
from tpunet.parallel import make_mesh
from tpunet.parallel.pp import gpipe
from tpunet.train.loop import Trainer

PP_CFG = ModelConfig(name="vit_pp", vit_patch=4, vit_hidden=64,
                     vit_depth=4, vit_heads=4, dropout_rate=0.0,
                     dtype="float32", pp_microbatches=4)


def _stage_apply(params, x):
    """Toy stage: scan of affine+tanh layers, params['w'] [L, C, C]."""
    def body(carry, pl):
        return jnp.tanh(carry @ pl["w"] + pl["b"]), None
    out, _ = jax.lax.scan(body, x, params)
    return out


def _toy(depth=4, c=8):
    rng = np.random.default_rng(0)
    params = {
        "w": jnp.asarray(rng.normal(size=(depth, c, c)) * 0.5, jnp.float32),
        "b": jnp.asarray(rng.normal(size=(depth, c)) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(8, 6, c)), jnp.float32)
    return params, x


@pytest.mark.parametrize("pipe,n_micro", [(2, 2), (4, 4), (2, 4), (4, 2)])
@pytest.mark.slow
def test_gpipe_matches_sequential(pipe, n_micro):
    params, x = _toy()
    mesh = make_mesh(MeshConfig(data=2, pipe=pipe))
    out = gpipe(_stage_apply, params, x, mesh=mesh, n_micro=n_micro)
    ref = _stage_apply(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gpipe_single_stage_is_sequential():
    params, x = _toy()
    mesh = make_mesh(MeshConfig(data=2, pipe=1))
    out = gpipe(_stage_apply, params, x, mesh=mesh, n_micro=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_stage_apply(params, x)),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_gpipe_gradients_match_sequential():
    params, x = _toy()
    mesh = make_mesh(MeshConfig(data=2, pipe=2))

    def loss_pp(p):
        return jnp.sum(gpipe(_stage_apply, p, x, mesh=mesh, n_micro=2) ** 2)

    def loss_seq(p):
        return jnp.sum(_stage_apply(p, x) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_seq = jax.grad(loss_seq)(params)
    for k in params:
        np.testing.assert_allclose(np.asarray(g_pp[k]),
                                   np.asarray(g_seq[k]),
                                   rtol=1e-4, atol=1e-4)


def test_gpipe_rejects_indivisible_microbatch():
    params, x = _toy()  # batch 8 -> local 4 per data shard
    mesh = make_mesh(MeshConfig(data=2, pipe=2))
    with pytest.raises(ValueError):
        gpipe(_stage_apply, params, x, mesh=mesh, n_micro=3)


@pytest.mark.slow
def test_pipelined_vit_matches_own_sequential_path():
    """Same params: pipelined forward (pipe=4) == sequential scan."""
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    pp_model = create_model(PP_CFG, mesh=mesh)
    seq_model = create_model(PP_CFG, mesh=None)
    variables = init_variables(seq_model, jax.random.PRNGKey(0),
                               image_size=32, batch_size=8)
    x = jnp.asarray(np.random.default_rng(1).normal(size=(8, 32, 32, 3)),
                    jnp.float32)
    a = pp_model.apply(variables, x, train=False)
    b = seq_model.apply(variables, x, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_pipelined_vit_matches_dense_vit_logits():
    """vit_pp's hand-rolled block math == the flax-module dense ViT,
    with vit params mapped into the stacked layout (pins the duplicated
    encoder math: LN eps/upcast, gelu variant, qkv reshape order)."""
    vit_cfg = dataclasses.replace(PP_CFG, name="vit")
    vit_model = create_model(vit_cfg)
    vit_vars = init_variables(vit_model, jax.random.PRNGKey(0),
                              image_size=32)
    vp = vit_vars["params"]
    L = PP_CFG.vit_depth
    stack = lambda f: jnp.stack([f(vp[f"block{i:02d}"]) for i in range(L)])
    pp_params = {
        "patch_embed": vp["patch_embed"],
        "pos_embed": vp["pos_embed"],
        "ln": vp["ln"],
        "classifier": vp["classifier"],
        "blocks_ln1s": stack(lambda b: b["ln1"]["scale"]),
        "blocks_ln1b": stack(lambda b: b["ln1"]["bias"]),
        "blocks_qkv_k": stack(lambda b: b["attn"]["qkv"]["kernel"]),
        "blocks_qkv_b": stack(lambda b: b["attn"]["qkv"]["bias"]),
        "blocks_out_k": stack(lambda b: b["attn"]["out"]["kernel"]),
        "blocks_out_b": stack(lambda b: b["attn"]["out"]["bias"]),
        "blocks_ln2s": stack(lambda b: b["ln2"]["scale"]),
        "blocks_ln2b": stack(lambda b: b["ln2"]["bias"]),
        "blocks_fc1_k": stack(lambda b: b["mlp"]["fc1"]["kernel"]),
        "blocks_fc1_b": stack(lambda b: b["mlp"]["fc1"]["bias"]),
        "blocks_fc2_k": stack(lambda b: b["mlp"]["fc2"]["kernel"]),
        "blocks_fc2_b": stack(lambda b: b["mlp"]["fc2"]["bias"]),
    }
    x = jnp.asarray(np.random.default_rng(2).normal(size=(8, 32, 32, 3)),
                    jnp.float32)
    ref = vit_model.apply(vit_vars, x, train=False)
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    pp_model = create_model(PP_CFG, mesh=mesh)
    out = pp_model.apply({"params": pp_params}, x, train=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_vit_pp_rejects_unsupported_features():
    with pytest.raises(ValueError):
        create_model(dataclasses.replace(PP_CFG, attention="ring"))
    with pytest.raises(ValueError):
        create_model(dataclasses.replace(PP_CFG, moe_experts=4))


def test_depth_not_divisible_by_stages_raises():
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    with pytest.raises(ValueError):
        create_model(dataclasses.replace(PP_CFG, vit_depth=6), mesh=mesh)


def _cfg(mesh_cfg, **model_kw):
    return TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=64, synthetic_test_size=32),
        model=dataclasses.replace(PP_CFG, **model_kw),
        optim=OptimConfig(learning_rate=1e-3),
        mesh=mesh_cfg,
        checkpoint=CheckpointConfig(save_best=False, save_last=False),
    )


@pytest.mark.slow
def test_pp_training_parity_with_dp_only():
    def run(mesh_cfg):
        tr = Trainer(_cfg(mesh_cfg))
        try:
            train_m = tr.train_one_epoch(1)
            eval_m = tr.evaluate()
        finally:
            tr.close()
        return train_m, eval_m

    base_t, base_e = run(MeshConfig(data=2))
    pp_t, pp_e = run(MeshConfig(data=2, pipe=4))
    assert abs(base_t["loss"] - pp_t["loss"]) < 1e-4
    assert abs(base_e["accuracy"] - pp_e["accuracy"]) < 1e-6

    # stacked block params actually sharded over 'pipe'
    from jax.sharding import PartitionSpec as P
    mesh = make_mesh(MeshConfig(data=2, pipe=4))
    tr = Trainer(_cfg(MeshConfig(data=2, pipe=4)), mesh=mesh)
    try:
        qkv = tr.state.params["blocks_qkv_k"]
        assert qkv.sharding.spec == P("pipe")
        mu = tr.state.opt_state[0].mu["blocks_qkv_k"]
        assert mu.sharding.spec == P("pipe")
    finally:
        tr.close()
