"""1F1B pipeline schedule (tpunet/parallel/pp.py onef1b).

Three layers of evidence, matching the executor's claims:

1. Schedule-table properties (host-side onef1b_schedule, the same
   closed form the device scan uses): every (microbatch, stage) pair
   gets exactly one F and one B tick, dependencies are satisfied, at
   most one op per stage per tick, the last stage runs one-forward-
   one-backward interleaved, and the ring-buffer slot assignment never
   overwrites a live residual.
2. Gradient parity with the GPipe executor on the 8-device CPU mesh
   (pipe=2 and pipe=4, with and without dropout): the manual VJP must
   be grad-for-grad identical to AD through the GPipe scan.
3. Peak-memory: XLA's compiled memory analysis shows the 1f1b backward
   allocating less temp memory than GPipe-AD's stacked residuals at
   pipe>=2 with many microbatches.
"""

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpunet.config import ModelConfig
from tpunet.models import create_model, init_variables
from tpunet.parallel.pp import gpipe, onef1b, onef1b_schedule


# ---------------------------------------------------------------------------
# 1. Schedule-table properties
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("S,M", [(2, 1), (2, 3), (4, 2), (4, 8), (8, 4)])
def test_schedule_table_properties(S, M):
    table = onef1b_schedule(S, M)
    assert len(table) == 2 * (M + S - 1)
    f_tick, b_tick = {}, {}
    for t, row in enumerate(table):
        assert len(row) == S
        for s, op in enumerate(row):
            if op is None:
                continue
            kind, m = op
            assert 0 <= m < M
            key = (m, s)
            if kind == "F":
                assert key not in f_tick, f"duplicate F {key}"
                f_tick[key] = t
            else:
                assert key not in b_tick, f"duplicate B {key}"
                b_tick[key] = t
    assert len(f_tick) == len(b_tick) == M * S

    for m in range(M):
        for s in range(S):
            # forward dependency: stage s after stage s-1
            if s > 0:
                assert f_tick[(m, s)] > f_tick[(m, s - 1)]
            # backward dependency: stage s after stage s+1
            if s < S - 1:
                assert b_tick[(m, s)] > b_tick[(m, s + 1)]
            # backward only after the microbatch reached the last stage
            assert b_tick[(m, s)] > f_tick[(m, S - 1)]


@pytest.mark.parametrize("S,M", [(2, 4), (4, 8)])
def test_schedule_interleaves_fwd_and_bwd(S, M):
    """The defining 1F1B property: backwards START before forwards
    FINISH (GPipe-AD runs all forwards, then all backwards). On the
    last stage the steady state is strictly F(m), B(m), F(m+1), ..."""
    table = onef1b_schedule(S, M)
    last = [row[S - 1] for row in table if row[S - 1] is not None]
    expect = []
    for m in range(M):
        expect += [("F", m), ("B", m)]
    assert last == expect
    # globally: the first backward precedes the last forward
    first_b = min(t for t, row in enumerate(table)
                  for op in row if op and op[0] == "B")
    last_f = max(t for t, row in enumerate(table)
                 for op in row if op and op[0] == "F")
    assert first_b < last_f


@pytest.mark.parametrize("S,M", [(2, 3), (4, 8), (8, 4), (4, 2)])
def test_ring_buffer_never_overwrites_live_residual(S, M):
    """Replay the schedule against a ring buffer of min(S, M) slots
    (slot = m % n_buf, as the executor indexes): a forward's write must
    never clobber a residual whose backward hasn't run yet."""
    n_buf = min(S, M)
    table = onef1b_schedule(S, M)
    live = [dict() for _ in range(S)]          # stage -> slot -> m
    for row in table:
        for s, op in enumerate(row):
            if op is None:
                continue
            kind, m = op
            slot = m % n_buf
            if kind == "F":
                assert slot not in live[s], (
                    f"stage {s}: F({m}) overwrites live residual of "
                    f"microbatch {live[s].get(slot)}")
                live[s][slot] = m
            else:
                assert live[s].get(slot) == m
                del live[s][slot]
    assert all(not d for d in live)


# ---------------------------------------------------------------------------
# 2. Gradient parity vs GPipe on the CPU mesh
# ---------------------------------------------------------------------------

def _toy_stage(params, x, key=None):
    """A 2-param nonlinear stage; scans over its stacked leading dim
    like the real models do, with per-layer dropout when keyed."""
    def body(carry, inp):
        (w, b), i = inp
        h = jnp.tanh(carry @ w + b)
        if key is not None:
            k = jax.random.fold_in(key, i)
            keep = jax.random.bernoulli(k, 0.9, h.shape)
            h = jnp.where(keep, h / 0.9, 0.0)
        return h + carry, None
    idx = jnp.arange(params[0].shape[0])
    out, _ = jax.lax.scan(body, x, (params, idx))
    return out


def _mesh(pipe, data=2):
    devs = np.array(jax.devices()[:data * pipe]).reshape(data, pipe)
    return Mesh(devs, ("data", "pipe"))


@pytest.mark.parametrize("pipe,n_micro,keyed", [
    # one representative stays in the fast tier; wider (S, M) sweeps
    # and the keyed (dropout) variants — which double the vjp work —
    # run in the slow tier
    (2, 4, False),
    pytest.param(4, 4, False, marks=pytest.mark.slow),
    pytest.param(2, 2, False, marks=pytest.mark.slow),
    pytest.param(2, 4, True, marks=pytest.mark.slow),
    pytest.param(4, 2, True, marks=pytest.mark.slow),
])
def test_grad_parity_vs_gpipe(pipe, n_micro, keyed):
    mesh = _mesh(pipe)
    rng = np.random.default_rng(0)
    L, C, B, T = 8, 16, 8, 4
    params = (jnp.asarray(rng.normal(0, 0.3, (L, C, C)), jnp.float32),
              jnp.asarray(rng.normal(0, 0.1, (L, C)), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)
    key = jax.random.PRNGKey(7) if keyed else None
    dy = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)

    def loss(executor, params, x):
        y = executor(_toy_stage, params, x, mesh=mesh,
                     n_micro=n_micro, key=key)
        return jnp.sum(y * dy)       # arbitrary cotangent

    with mesh:
        ref_v, ref_g = jax.value_and_grad(
            functools.partial(loss, gpipe), argnums=(0, 1))(params, x)
        new_v, new_g = jax.value_and_grad(
            functools.partial(loss, onef1b), argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(new_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    for r, n in zip(jax.tree_util.tree_leaves(ref_g),
                    jax.tree_util.tree_leaves(new_g)):
        np.testing.assert_allclose(np.asarray(n), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_grad_parity_with_in_stage_seq_collective():
    """Regression (review-found, round 3): a stage body containing a
    collective over the executor's ``seq_axis`` (ring ppermute here)
    must differentiate identically under 1f1b and gpipe. The broken
    version put the in-stage collective inside the F/B ``lax.cond`` —
    whose predicate varies over 'pipe' — so different stages executed
    different collective-permute ops over the same participant set:
    forward exact, gradients silently wrong (max abs error ~20 on
    O(1) grads in this setup). The fix runs one vjp per tick on a
    role-selected input whenever ``seq_axis`` is given, making the
    collective sequence device-uniform."""
    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "seq", "pipe"))

    def stage(params, x, key=None):
        W, b = params

        def layer(carry, wb):
            w, bb = wb
            h = jnp.tanh(carry @ w + bb)
            n = jax.lax.psum(1, "seq")
            cyc = [(i, (i + 1) % n) for i in range(n)]
            h = h + 0.5 * jnp.tanh(jax.lax.ppermute(h, "seq", cyc))
            return h, None

        out, _ = jax.lax.scan(layer, x, (W, b))
        return out

    rng = np.random.default_rng(0)
    params = (jnp.asarray(rng.normal(0, 0.3, (4, 8, 8)), jnp.float32),
              jnp.asarray(rng.normal(0, 0.1, (4, 8)), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (4, 8, 8)), jnp.float32)
    dy = jnp.asarray(rng.normal(0, 1, (4, 8, 8)), jnp.float32)

    def loss(executor, params, x):
        y = executor(stage, params, x, mesh=mesh, n_micro=2,
                     seq_axis="seq")
        return jnp.sum(y * dy)

    with mesh:
        ref_v, ref_g = jax.value_and_grad(
            functools.partial(loss, gpipe), argnums=(0, 1))(params, x)
        new_v, new_g = jax.value_and_grad(
            functools.partial(loss, onef1b), argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(new_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    for r, n in zip(jax.tree_util.tree_leaves(ref_g),
                    jax.tree_util.tree_leaves(new_g)):
        np.testing.assert_allclose(np.asarray(n), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_grad_parity_with_in_stage_a2a_dispatch():
    """Executor-level regression for the GShard a2a MoE lowering
    (tpunet/models/moe.py alltoall): a stage body whose layers run the
    full exchange pattern — dynamic_slice over the ep axis, tiled
    all_to_all out and back, all_gather to restore replication — must
    differentiate identically under 1f1b (manual backward, ep_axis
    convention) and gpipe (shard_map AD). Covers the transposes the
    manual backward's sums-to-truth-over-ep invariant must survive:
    all_to_all (self-transposing permutation), all_gather
    (psum-of-shares), dynamic_slice (zero-padded partials), alongside
    ep-sharded AND ep-replicated param leaves."""
    devs = np.array(jax.devices()).reshape(2, 2, 2)
    mesh = Mesh(devs, ("data", "pipe", "model"))
    from jax.sharding import PartitionSpec as P

    def stage(params, x, key=None):
        W, b = params               # W [L, e_l, C, C] ep-sharded dim 1;
        ep = jax.lax.psum(1, "model")   # b [L, C] ep-replicated
        idx = jax.lax.axis_index("model")

        def layer(carry, wb):
            w, bb = wb              # [e_l, C, C], [C]
            mb, t, c = carry.shape
            e_l = w.shape[0]
            tok = carry.reshape(mb * t, c)
            n_l = tok.shape[0] // ep
            tl = jax.lax.dynamic_slice_in_dim(tok, idx * n_l, n_l, 0)
            buf = jnp.broadcast_to(tl + bb, (ep * e_l,) + tl.shape)
            buf = jax.lax.all_to_all(buf, "model", 0, 0, tiled=True)
            # received dim 0 = (source shard, local expert); each local
            # expert applies its own w slice to every source's tokens
            h = jnp.tanh(jnp.einsum(
                "senc,ecd->send", buf.reshape(ep, e_l, n_l, c), w))
            h = h.reshape(ep * e_l, n_l, c)
            out = jax.lax.all_to_all(h, "model", 0, 0, tiled=True)
            yl = out.reshape(ep, e_l, n_l, c).mean((0, 1))
            y = jax.lax.all_gather(yl, "model", axis=0, tiled=True)
            return carry + y.reshape(mb, t, c), None

        out, _ = jax.lax.scan(layer, x, (W, b))
        return out

    rng = np.random.default_rng(0)
    L, E, C = 4, 4, 8
    params = (jnp.asarray(rng.normal(0, 0.3, (L, E, C, C)), jnp.float32),
              jnp.asarray(rng.normal(0, 0.1, (L, C)), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (4, 4, C)), jnp.float32)
    dy = jnp.asarray(rng.normal(0, 1, (4, 4, C)), jnp.float32)
    p_specs = (P("pipe", "model"), P("pipe"))

    def loss(executor, params, x, **kw):
        y = executor(stage, params, x, mesh=mesh, n_micro=2,
                     param_specs=p_specs, **kw)
        return jnp.sum(y * dy)

    with mesh:
        ref_v, ref_g = jax.value_and_grad(
            functools.partial(loss, gpipe), argnums=(0, 1))(params, x)
        new_v, new_g = jax.value_and_grad(
            functools.partial(loss, onef1b, ep_axis="model"),
            argnums=(0, 1))(params, x)
    np.testing.assert_allclose(np.asarray(new_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    for r, n in zip(jax.tree_util.tree_leaves(ref_g),
                    jax.tree_util.tree_leaves(new_g)):
        np.testing.assert_allclose(np.asarray(n), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_pipe1_fallback_matches_plain_apply():
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1),
                ("data", "pipe"))
    rng = np.random.default_rng(1)
    params = (jnp.asarray(rng.normal(0, 0.3, (4, 8, 8)), jnp.float32),
              jnp.zeros((4, 8), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (4, 3, 8)), jnp.float32)
    with mesh:
        out = onef1b(_toy_stage, params, x, mesh=mesh, n_micro=2)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_toy_stage(params, x)),
                               rtol=1e-6, atol=1e-6)


LMPP_CFG = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=4,
                       vit_heads=2, dropout_rate=0.0, dtype="float32",
                       vocab_size=64, max_seq_len=32, pp_microbatches=2)


@pytest.mark.slow
@pytest.mark.parametrize("dropout", [0.0, 0.1])
def test_lm_pp_model_grads_match_across_schedules(dropout):
    """Full-model parity: PipelinedLM grads under 1f1b == gpipe on a
    dp2 x pp2 mesh, including the embed/pos/LN params outside the
    executor, with and without pipelined dropout."""
    mesh = _mesh(2)
    cfg = dataclasses.replace(LMPP_CFG, dropout_rate=dropout)
    toks = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (4, 16)), jnp.int32)

    def grads(schedule):
        c = dataclasses.replace(cfg, pp_schedule=schedule)
        model = create_model(c, mesh=mesh)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   batch_size=4, seq_len=16)

        def loss(params):
            logits = model.apply(
                {"params": params}, toks, train=True,
                rngs={"dropout": jax.random.PRNGKey(11)})
            return jnp.mean(
                (logits - jnp.roll(logits, 1, axis=-1)) ** 2)

        with mesh:
            return variables, jax.grad(loss)(variables["params"])

    v1, g1 = grads("gpipe")
    v2, g2 = grads("1f1b")
    # identical init (same seed/architecture) is a precondition
    for a, b in zip(jax.tree_util.tree_leaves(v1),
                    jax.tree_util.tree_leaves(v2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    flat1 = jax.tree_util.tree_leaves_with_path(g1)
    flat2 = {jax.tree_util.keystr(p): l
             for p, l in jax.tree_util.tree_leaves_with_path(g2)}
    for p, r in flat1:
        n = flat2[jax.tree_util.keystr(p)]
        np.testing.assert_allclose(
            np.asarray(n), np.asarray(r), rtol=2e-4, atol=1e-6,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(p)}")


# ---------------------------------------------------------------------------
# 3. Peak-memory: 1f1b's backward must beat GPipe-AD's stacked residuals
# ---------------------------------------------------------------------------

def test_1f1b_uses_less_temp_memory_than_gpipe():
    """XLA memory analysis of the full value_and_grad program at
    pipe=2 with MANY microbatches (where GPipe-AD's O(M) stacked
    per-tick residuals dominate and 1f1b's O(min(S,M)) ring should
    win). Compares temp allocation, the bucket holding scan residuals."""
    mesh = _mesh(2)
    rng = np.random.default_rng(0)
    L, C, B, T, M = 8, 64, 32, 32, 16
    params = (jnp.asarray(rng.normal(0, 0.3, (L, C, C)), jnp.float32),
              jnp.zeros((L, C), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)

    def compile_for(executor):
        def loss(p, xx):
            y = executor(_toy_stage, p, xx, mesh=mesh, n_micro=M)
            return jnp.sum(y ** 2)
        with mesh:
            return jax.jit(jax.value_and_grad(loss)).lower(params, x
                                                           ).compile()

    mem_gpipe = compile_for(gpipe).memory_analysis()
    mem_1f1b = compile_for(onef1b).memory_analysis()
    if mem_gpipe is None or mem_1f1b is None:
        pytest.skip("memory_analysis unavailable on this backend")
    t_gpipe = mem_gpipe.temp_size_in_bytes
    t_1f1b = mem_1f1b.temp_size_in_bytes
    # The documented claim: strictly less temp memory, by a real margin.
    assert t_1f1b < 0.7 * t_gpipe, (
        f"1f1b temp {t_1f1b} not < 70% of gpipe temp {t_gpipe}")


@pytest.mark.slow
@pytest.mark.parametrize("kind", ["ulysses", "ring"])
def test_lm_pp_sp_grads_match_across_schedules_sp_pp(kind):
    """SP x PP regression (review-found bug): onef1b's manual backward
    must psum param grads over the SEQ axis too when the executor runs
    seq-sharded — without it each seq shard trains on a partial
    gradient while the forward (and thus every metrics-only test)
    looks fine. Deterministic gpipe-vs-1f1b grad comparison on a
    dp2 x sp2 x pp2 mesh through the full model, for both SP ops
    (Ulysses' all-to-all pair and the ring's scan+ppermute rotation
    exercise different collective transposes in the replayed vjp)."""
    from tpunet.config import MeshConfig
    from tpunet.parallel import make_mesh

    mesh = make_mesh(MeshConfig(data=2, seq=2, pipe=2))
    cfg = dataclasses.replace(LMPP_CFG, attention=kind)
    toks = jnp.asarray(
        np.random.default_rng(5).integers(0, 64, (4, 16)), jnp.int32)

    def grads(schedule):
        c = dataclasses.replace(cfg, pp_schedule=schedule)
        model = create_model(c, mesh=mesh)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   batch_size=4, seq_len=16)

        def loss(params):
            logits = model.apply({"params": params}, toks, train=True)
            return jnp.mean(
                (logits - jnp.roll(logits, 1, axis=-1)) ** 2)

        with mesh:
            return jax.grad(loss)(variables["params"])

    g1 = {jax.tree_util.keystr(p): l
          for p, l in jax.tree_util.tree_leaves_with_path(
              grads("gpipe"))}
    g2 = {jax.tree_util.keystr(p): l
          for p, l in jax.tree_util.tree_leaves_with_path(
              grads("1f1b"))}
    assert g1.keys() == g2.keys()
    for k in g1:
        np.testing.assert_allclose(
            np.asarray(g2[k]), np.asarray(g1[k]), rtol=2e-4, atol=1e-6,
            err_msg=f"grad mismatch at {k}")
