"""Interleaved 1F1B (virtual pipeline stages): schedule-table
properties, executor grad parity vs gpipe/1f1b, the bubble x memory
quantification, and the chunk-permuted storage order."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from tpunet.parallel.pp import (gpipe, interleaved, interleaved_bwd_schedule,
                                interleaved_fwd_schedule,
                                interleaved_layer_order, onef1b)

CASES = [(2, 4, 2), (4, 8, 2), (2, 8, 4), (4, 16, 4)]


# ---------------------------------------------------------------------------
# 1. Schedule-table properties
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("S,M,v", CASES)
def test_fwd_schedule_properties(S, M, v):
    """Each device runs F of every (m, chunk) exactly once; every hop
    (stage g-1 -> g, including the (S-1) -> 0 chunk wrap) lands with
    slack exactly 1 (the dense forward needs no arrival buffering);
    total ticks = vM + S - 1."""
    table = interleaved_fwd_schedule(S, M, v)
    assert len(table) == v * M + S - 1
    tick_of = {}
    for t, row in enumerate(table):
        for d, op in enumerate(row):
            if op is None:
                continue
            kind, m, j = op
            assert kind == "F"
            assert (d, m, j) not in tick_of
            tick_of[(d, m, j)] = t
    assert len(tick_of) == S * M * v
    for (d, m, j), t in tick_of.items():
        if d > 0:
            assert tick_of[(d - 1, m, j)] == t - 1
        elif j > 0:
            assert tick_of[(S - 1, m, j - 1)] == t - 1


@pytest.mark.parametrize("S,M,v", CASES)
def test_bwd_schedule_properties(S, M, v):
    """One F-replay and one B per (microbatch, device, chunk); F
    precedes its B; every cross-device dependency respects the 1-tick
    hop; residual/arrival ring slots never overwrite a live value
    (re-verified independently of the scheduler's own allocator)."""
    sc = interleaved_bwd_schedule(S, M, v)
    T = sc["n_ticks"]
    f_tick, b_tick = {}, {}
    for t in range(T):
        for d in range(S):
            k = sc["kind"][t, d]
            if k == 0:
                continue
            key = (d, sc["m"][t, d], sc["j"][t, d])
            tgt = f_tick if k == 1 else b_tick
            assert key not in tgt, key
            tgt[key] = t
    assert len(f_tick) == len(b_tick) == S * M * v
    for (d, m, j), tb in b_tick.items():
        assert f_tick[(d, m, j)] < tb                  # F before its B
        if d < S - 1:
            assert b_tick[(d + 1, m, j)] + 1 <= tb     # hop latency
        elif j < v - 1:
            assert b_tick[(0, m, j + 1)] + 1 <= tb
    for (d, m, j), tf in f_tick.items():
        if d > 0:
            assert f_tick[(d - 1, m, j)] + 1 <= tf
        elif j > 0:
            assert f_tick[(S - 1, m, j - 1)] + 1 <= tf

    # ring-buffer safety: replay slot writes must never clobber a value
    # still awaiting its read (residuals: F write -> B read; arrivals:
    # save tick -> consumer read tick)
    def check_ring(save, read, n):
        for d in range(S):
            live = {}                                   # slot -> free tick
            for t in range(T):
                sl = save[t, d]
                if sl >= 0:
                    assert sl < n
                    assert live.get(sl, -1) < t, (d, t, sl)
                    ends = [tt for tt in range(t, T) if read[tt, d] == sl]
                    assert ends, (d, t, sl)
                    live[sl] = ends[0]

    check_ring(sc["rs_save"], sc["rs_read"], sc["n_resid"])
    check_ring(sc["af_save"], sc["af_read"], sc["n_arr_f"])
    check_ring(sc["ab_save"], sc["ab_read"], sc["n_arr_b"])


def test_bubble_fraction_drops_v_fold():
    """The throughput story, in chunk-ticks (1 chunk = 1/v of a
    device's layers): non-interleaved schedules cost 2v(M + S - 1)
    with bubble fraction (S-1)/(M+S-1); the interleaved table
    measures ~2vM + O(vS) — the bubble shrinks by ~v (Megatron's
    1/v factor), and residency stays at the warmup bound
    O(S + vS), independent of M (the 1F1B-style memory bound)."""
    rows = []
    for S, M, v in CASES:
        sc = interleaved_bwd_schedule(S, M, v)
        useful = 2 * v * M
        base = 2 * v * (M + S - 1)
        b_int = 1 - useful / sc["n_ticks"]
        b_non = 1 - useful / base
        rows.append((S, M, v, sc["n_ticks"], base, b_int, b_non,
                     sc["n_resid"]))
        assert sc["n_ticks"] < base
        # v-fold-ish bubble reduction (edge effects at small M)
        assert b_non / b_int > 0.75 * v, (S, M, v, b_int, b_non)
        # memory: residency tracks the warmup bound, not M
        assert sc["n_resid"] <= 2 * (S - 1) + (v - 1) * S + 1
    # the quantification table the docstring promises, in test output
    print("\n S  M  v | ticks  non-int | bubble  non-int | resid")
    for r in rows:
        print(f" {r[0]}  {r[1]:2d}  {r[2]} | {r[3]:5d}  {r[4]:7d} |"
              f" {r[5]:.3f}  {r[6]:.3f}   | {r[7]}")


def test_layer_order_permutation():
    order = interleaved_layer_order(8, 2, 2)           # lc = 2
    # device 0: chunks 0, 2 -> layers 0,1,4,5; device 1: chunks 1, 3
    assert order == [0, 1, 4, 5, 2, 3, 6, 7]
    assert sorted(order) == list(range(8))


# ---------------------------------------------------------------------------
# 2. Executor grad parity vs gpipe / 1f1b
# ---------------------------------------------------------------------------

def _toy_stage(params, x, key=None):
    def body(carry, inp):
        (w, b), i = inp
        h = jnp.tanh(carry @ w + b)
        if key is not None:
            k = jax.random.fold_in(key, i)
            keep = jax.random.bernoulli(k, 0.9, h.shape)
            h = jnp.where(keep, h / 0.9, 0.0)
        return h + carry, None
    idx = jnp.arange(params[0].shape[0])
    out, _ = jax.lax.scan(body, x, (params, idx))
    return out


def _mesh(pipe, data=2):
    devs = np.array(jax.devices()[:data * pipe]).reshape(data, pipe)
    return Mesh(devs, ("data", "pipe"))


@pytest.mark.parametrize("pipe,n_micro,v", [
    (2, 4, 2),
    pytest.param(2, 2, 2, marks=pytest.mark.slow),
    pytest.param(4, 4, 2, marks=pytest.mark.slow),
    pytest.param(2, 4, 4, marks=pytest.mark.slow),
])
def test_grad_parity_vs_gpipe_and_1f1b(pipe, n_micro, v):
    """Same math, chunk-permuted storage: interleaved(perm(params))
    must match gpipe(params) and onef1b(params) value-for-value and
    grad-for-grad (grads mapped back through the permutation)."""
    mesh = _mesh(pipe)
    rng = np.random.default_rng(0)
    L, C, B, T = 2 * pipe * v, 16, 8, 4
    params = (jnp.asarray(rng.normal(0, 0.3, (L, C, C)), jnp.float32),
              jnp.asarray(rng.normal(0, 0.1, (L, C)), jnp.float32))
    order = np.asarray(interleaved_layer_order(L, pipe, v))
    perm_params = tuple(p[order] for p in params)
    x = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)
    dy = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)

    def loss_ref(executor, params, x):
        y = executor(_toy_stage, params, x, mesh=mesh, n_micro=n_micro)
        return jnp.sum(y * dy)

    def loss_int(params, x):
        y = interleaved(_toy_stage, params, x, mesh=mesh,
                        n_micro=n_micro, n_virtual=v)
        return jnp.sum(y * dy)

    with mesh:
        ref_v, ref_g = jax.value_and_grad(
            functools.partial(loss_ref, gpipe), argnums=(0, 1))(params, x)
        f1b_v, _ = jax.value_and_grad(
            functools.partial(loss_ref, onef1b),
            argnums=(0, 1))(params, x)
        int_v, int_g = jax.value_and_grad(
            loss_int, argnums=(0, 1))(perm_params, x)
    np.testing.assert_allclose(np.asarray(int_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(f1b_v), np.asarray(ref_v),
                               rtol=1e-5, atol=1e-5)
    inv = np.argsort(order)                 # storage -> natural
    for r, gi in zip(ref_g[0], (int_g[0][0][inv], int_g[0][1][inv])):
        np.testing.assert_allclose(np.asarray(gi), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(int_g[1]), np.asarray(ref_g[1]),
                               rtol=1e-4, atol=1e-5)


def test_keyed_interleaved_is_deterministic_and_replay_consistent():
    """Dropout keys fold per (microbatch, global stage): two identical
    calls agree, and the custom-vjp backward (which REPLAYS chunk
    forwards) produces finite grads consistent with its own forward
    (loss decreases along the negative gradient — a replay that drew
    different masks would break this)."""
    mesh = _mesh(2)
    rng = np.random.default_rng(1)
    L, C, B = 8, 8, 8
    params = (jnp.asarray(rng.normal(0, 0.3, (L, C, C)), jnp.float32),
              jnp.asarray(rng.normal(0, 0.1, (L, C)), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (B, 4, C)), jnp.float32)
    key = jax.random.PRNGKey(3)

    def loss(params):
        y = interleaved(_toy_stage, params, x, mesh=mesh, n_micro=4,
                        n_virtual=2, key=key)
        return jnp.mean(y ** 2)

    with mesh:
        v1, g = jax.value_and_grad(loss)(params)
        v2 = loss(params)
        eps = 1e-2
        stepped = jax.tree_util.tree_map(lambda p, d: p - eps * d,
                                         params, g)
        v3 = loss(stepped)
    assert float(v1) == float(v2)
    assert all(np.isfinite(np.asarray(t)).all()
               for t in jax.tree_util.tree_leaves(g))
    assert float(v3) < float(v1)


def test_interleaved_validation():
    mesh = _mesh(2)
    p = (jnp.zeros((8, 4, 4)), jnp.zeros((8, 4)))
    x = jnp.zeros((4, 2, 4))
    with pytest.raises(ValueError, match="n_virtual"):
        interleaved(_toy_stage, p, x, mesh=mesh, n_micro=2, n_virtual=1)
    with pytest.raises(ValueError, match="divisible by the pipe"):
        interleaved(_toy_stage, p, x, mesh=mesh, n_micro=3, n_virtual=2)
    with pytest.raises(ValueError, match="leading"):
        interleaved(_toy_stage, (jnp.zeros((6, 4, 4)),), x, mesh=mesh,
                    n_micro=2, n_virtual=4)


# ---------------------------------------------------------------------------
# 3. Memory: bounded residency vs gpipe-AD's stacked residuals
# ---------------------------------------------------------------------------

def test_interleaved_uses_less_temp_memory_than_gpipe():
    """At many microbatches the gpipe-AD backward stacks every
    per-tick intermediate (O(M)); the interleaved manual backward
    holds the warmup-bounded residual/arrival rings (independent of
    M). XLA memory analysis on the full value_and_grad programs."""
    mesh = _mesh(2)
    rng = np.random.default_rng(0)
    L, C, B, T, M, v = 8, 64, 32, 32, 16, 2
    params = (jnp.asarray(rng.normal(0, 0.3, (L, C, C)), jnp.float32),
              jnp.zeros((L, C), jnp.float32))
    x = jnp.asarray(rng.normal(0, 1, (B, T, C)), jnp.float32)

    def compile_gpipe():
        def loss(p, xx):
            y = gpipe(_toy_stage, p, xx, mesh=mesh, n_micro=M)
            return jnp.sum(y ** 2)
        with mesh:
            return jax.jit(jax.value_and_grad(loss)).lower(
                params, x).compile()

    def compile_int():
        def loss(p, xx):
            y = interleaved(_toy_stage, p, xx, mesh=mesh, n_micro=M,
                            n_virtual=v)
            return jnp.sum(y ** 2)
        with mesh:
            return jax.jit(jax.value_and_grad(loss)).lower(
                params, x).compile()

    mem_g = compile_gpipe().memory_analysis()
    mem_i = compile_int().memory_analysis()
    if mem_g is None or mem_i is None:
        pytest.skip("memory_analysis unavailable on this backend")
    assert mem_i.temp_size_in_bytes < 0.7 * mem_g.temp_size_in_bytes, (
        f"interleaved temp {mem_i.temp_size_in_bytes} not < 70% of "
        f"gpipe temp {mem_g.temp_size_in_bytes}")


# ---------------------------------------------------------------------------
# 4. Model-level: lm_pp / vit_pp with --pp-schedule interleaved
# ---------------------------------------------------------------------------

def _perm_blocks(params, L, S, v):
    """Natural-order stacked params -> chunk-permuted storage (what an
    interleaved model means by the same stack positions)."""
    order = np.asarray(interleaved_layer_order(L, S, v))
    return {k: (val[order] if k.startswith("blocks_")
                and val.shape[0] == L else val)
            for k, val in params.items()}


@pytest.mark.slow
def test_lmpp_interleaved_matches_gpipe():
    """lm_pp with pp_schedule='interleaved' == the gpipe run on the
    same SEMANTIC params (chunk-permuted into interleaved storage):
    logits exactly, grads leaf-for-leaf after un-permuting."""
    import dataclasses

    from tpunet.config import MeshConfig, ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.parallel import make_mesh

    S, v, L = 2, 2, 8
    cfg = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=L,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=64, max_seq_len=32, pp_microbatches=4,
                      pp_virtual=v)
    mesh = make_mesh(MeshConfig(data=2, pipe=S))
    gp = create_model(cfg, mesh=mesh)
    variables = init_variables(gp, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = variables["params"]
    perm = _perm_blocks(params, L, S, v)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (8, 16)),
                       jnp.int32)
    il = create_model(dataclasses.replace(cfg,
                                          pp_schedule="interleaved"),
                      mesh=mesh)

    def grads(model, p):
        def loss(p):
            lg = model.apply({"params": p}, toks)
            return jnp.mean((lg - jnp.roll(lg, 1, -1)) ** 2)
        with mesh:
            return jax.value_and_grad(loss)(p)

    with mesh:
        ref = gp.apply({"params": params}, toks)
        out = il.apply({"params": perm}, toks)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    v_ref, g_ref = grads(gp, params)
    v_int, g_int = grads(il, perm)
    np.testing.assert_allclose(float(v_int), float(v_ref), rtol=1e-6)
    inv = np.argsort(np.asarray(interleaved_layer_order(L, S, v)))
    g_int_nat = {k: (val[inv] if k.startswith("blocks_")
                     and val.shape[0] == L else val)
                 for k, val in g_int.items()}
    for k in g_ref:
        np.testing.assert_allclose(
            np.asarray(jax.tree_util.tree_leaves(g_int_nat[k])[0]),
            np.asarray(jax.tree_util.tree_leaves(g_ref[k])[0]),
            rtol=1e-4, atol=1e-6, err_msg=k)


@pytest.mark.slow
def test_lmpp_interleaved_trains_and_serves(tmp_path, capsys):
    """End to end on dp2 x pp2 with v=2: the Trainer converges, and
    the chunk-permuted checkpoint serves through the generate CLI
    with --train-pipe/--pp-virtual (the unstack permutation)."""
    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import synthetic_lm
    from tpunet.train.loop import Trainer

    sb = 8
    cfg = TrainConfig(
        epochs=4,
        data=DataConfig(dataset="synthetic_lm", batch_size=sb,
                        seq_len=32, vocab_size=32),
        model=ModelConfig(name="lm_pp", vit_hidden=64, vit_depth=4,
                          vit_heads=4, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=32, pp_microbatches=2,
                          pp_schedule="interleaved", pp_virtual=2),
        optim=OptimConfig(learning_rate=3e-3, schedule="constant"),
        mesh=MeshConfig(data=2, pipe=2),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_last=False),
    )
    tr = Trainer(cfg, dataset=synthetic_lm(2 * sb, sb, seq_len=32,
                                           vocab=32))
    try:
        history = tr.train()        # writes the best checkpoint
    finally:
        tr.close()
    assert np.isfinite(history[-1]["train_loss"])
    assert history[-1]["train_loss"] < history[0]["train_loss"]

    # No --train-pipe: the best_meta.json sidecar supplies the chunk
    # permutation (operator flags are an override, not a requirement).
    from tpunet.ckpt import Checkpointer as CK
    meta = CK(CheckpointConfig(directory=str(tmp_path / "ck"))).best_meta()
    assert meta["pp_schedule"] == "interleaved"
    assert (meta["pp_layout_pipe"], meta["pp_layout_virtual"]) == (2, 2)
    from tpunet.infer import generate as gen
    gen.main(["--checkpoint-dir", str(tmp_path / "ck"), "--model",
              "lm_pp", "--prompt", "5 7 3", "--tokens", "5",
              "--vit-hidden", "64", "--vit-depth", "4", "--vit-heads",
              "4", "--vocab-size", "32", "--max-seq-len", "32"])
    out = capsys.readouterr().out.strip().splitlines()[-1].split()
    assert out[:3] == ["5", "7", "3"] and len(out) == 8
    assert all(0 <= int(t) < 32 for t in out)


@pytest.mark.slow
def test_interleaved_resume_layout_guard(tmp_path):
    """A state checkpoint saved under the interleaved layout refuses to
    resume under gpipe (and vice versa) — the chunk-permuted stacks
    would silently execute layers out of order otherwise."""
    import dataclasses

    from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                               ModelConfig, OptimConfig, TrainConfig)
    from tpunet.data.lm import synthetic_lm
    from tpunet.train.loop import Trainer

    sb = 8
    cfg = TrainConfig(
        epochs=1,
        data=DataConfig(dataset="synthetic_lm", batch_size=sb,
                        seq_len=32, vocab_size=32),
        model=ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=4,
                          vit_heads=2, dropout_rate=0.0,
                          dtype="float32", vocab_size=32,
                          max_seq_len=32, pp_microbatches=2,
                          pp_schedule="interleaved", pp_virtual=2),
        optim=OptimConfig(learning_rate=3e-3, schedule="constant"),
        mesh=MeshConfig(data=2, pipe=2),
        checkpoint=CheckpointConfig(directory=str(tmp_path / "ck"),
                                    save_best=False, resume=True),
    )
    ds = synthetic_lm(2 * sb, sb, seq_len=32, vocab=32)
    tr = Trainer(cfg, dataset=ds)
    try:
        tr.train_one_epoch(1)
        tr.ckpt.save_state(1, tr._payload())
    finally:
        tr.close()
    bad = cfg.replace(model=dataclasses.replace(cfg.model,
                                                pp_schedule="gpipe"))
    with pytest.raises(ValueError, match="layout mismatch"):
        Trainer(bad, dataset=ds).close()


def test_interleaved_model_validation():
    import dataclasses

    from tpunet.config import MeshConfig, ModelConfig
    from tpunet.models import create_model
    from tpunet.parallel import make_mesh

    cfg = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=8,
                      vit_heads=2, vocab_size=64, max_seq_len=32,
                      pp_microbatches=4, pp_schedule="interleaved")
    mesh = make_mesh(MeshConfig(data=2, pipe=2))
    with pytest.raises(ValueError, match="pipe"):
        create_model(cfg)                        # no mesh -> pipe=1
    with pytest.raises(ValueError, match="virtual"):
        create_model(dataclasses.replace(cfg, pp_virtual=1), mesh=mesh)
    with pytest.raises(ValueError, match="chunks"):
        create_model(dataclasses.replace(cfg, vit_depth=6,
                                         pp_virtual=4), mesh=mesh)
    with pytest.raises(ValueError, match="microbatches"):
        create_model(dataclasses.replace(cfg, pp_microbatches=3),
                     mesh=mesh)
    # MoE composes when chunks hold whole super-layers...
    create_model(dataclasses.replace(cfg, moe_experts=4, moe_every=2),
                 mesh=mesh)
    # ...and is rejected when they can't (lc=2 layers per chunk vs
    # moe_every=4 super-layers of 4 layers)
    with pytest.raises(ValueError, match="super-layers"):
        create_model(dataclasses.replace(cfg, moe_experts=4,
                                         moe_every=4), mesh=mesh)
    with pytest.raises(ValueError, match="SP"):
        create_model(dataclasses.replace(cfg, attention="ulysses"),
                     mesh=mesh)
    # vit_pp too
    vcfg = ModelConfig(name="vit_pp", vit_depth=6, pp_microbatches=4,
                       pp_schedule="interleaved", pp_virtual=4)
    with pytest.raises(ValueError, match="chunks"):
        create_model(vcfg, mesh=mesh)


@pytest.mark.slow
def test_lmpp_interleaved_packed_matches_and_isolates():
    """Packed x interleaved: segment ids ride the executor's `extra`
    input (indexed per chunk-op, non-differentiable) — forward + grads
    equal the unpipelined packed run on the same semantic params, and
    mutating an earlier document never moves a later one's logits."""
    import dataclasses

    from tpunet.config import MeshConfig, ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.parallel import make_mesh

    S, v, L = 2, 2, 8
    cfg = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=L,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=64, max_seq_len=32, pp_microbatches=4,
                      pp_virtual=v)
    mesh = make_mesh(MeshConfig(data=2, pipe=S))
    base = create_model(cfg)
    variables = init_variables(base, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = variables["params"]
    perm = _perm_blocks(params, L, S, v)
    rng = np.random.default_rng(9)
    toks = jnp.asarray(rng.integers(0, 64, (8, 16)), jnp.int32)
    segs = jnp.asarray(np.concatenate(
        [np.full((8, 6), 1), np.full((8, 7), 2), np.full((8, 3), 0)],
        axis=1), jnp.int32)
    il = create_model(dataclasses.replace(cfg,
                                          pp_schedule="interleaved"),
                      mesh=mesh)

    ref = base.apply({"params": params}, toks, segment_ids=segs)
    with mesh:
        out = il.apply({"params": perm}, toks, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def grads(model, p, use_mesh):
        def loss(p):
            lg = model.apply({"params": p}, toks, segment_ids=segs)
            wt = (segs[:, 1:] == segs[:, :-1]) & (segs[:, 1:] > 0)
            return jnp.sum(jnp.where(wt, jnp.mean(lg[:, :-1] ** 2, -1),
                                     0.0)) / jnp.sum(wt)
        if use_mesh:
            with mesh:
                return jax.grad(loss)(p)
        return jax.grad(loss)(p)

    g_ref = grads(base, params, False)
    g_int = grads(il, perm, True)
    inv = np.argsort(np.asarray(interleaved_layer_order(L, S, v)))
    for k in g_ref:
        a = jax.tree_util.tree_leaves(g_int[k])[0]
        if k.startswith("blocks_") and a.shape[0] == L:
            a = a[inv]
        np.testing.assert_allclose(
            np.asarray(a),
            np.asarray(jax.tree_util.tree_leaves(g_ref[k])[0]),
            rtol=1e-4, atol=1e-6, err_msg=k)

    # isolation: perturb doc 1 (cols :6); doc 2 (cols 6:13) must hold
    toks2 = toks.at[:, :6].set((toks[:, :6] + 5) % 64)
    with mesh:
        a = il.apply({"params": perm}, toks, segment_ids=segs)
        b = il.apply({"params": perm}, toks2, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(a[:, 6:13]),
                               np.asarray(b[:, 6:13]), atol=1e-6)
    assert not np.allclose(np.asarray(a[:, :6]), np.asarray(b[:, :6]))


@pytest.mark.slow
def test_vitpp_interleaved_matches_gpipe():
    """vit_pp shares the executor and stage body with lm_pp; assert
    the image family's interleaved forward equals gpipe on permuted
    params too (grads covered at the executor + lm_pp level)."""
    import dataclasses

    from tpunet.config import MeshConfig, ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.parallel import make_mesh

    S, v, L = 2, 2, 4
    cfg = ModelConfig(name="vit_pp", vit_patch=4, vit_hidden=32,
                      vit_depth=L, vit_heads=2, dropout_rate=0.0,
                      dtype="float32", pp_microbatches=4, pp_virtual=v)
    mesh = make_mesh(MeshConfig(data=2, pipe=S))
    gp = create_model(cfg, mesh=mesh)
    with mesh:
        variables = init_variables(gp, jax.random.PRNGKey(0),
                                   image_size=16, batch_size=8)
    params = variables["params"]
    perm = _perm_blocks(params, L, S, v)
    x = jnp.asarray(np.random.default_rng(4).normal(size=(8, 16, 16, 3)),
                    jnp.float32)
    il = create_model(dataclasses.replace(cfg,
                                          pp_schedule="interleaved"),
                      mesh=mesh)
    with mesh:
        ref = gp.apply({"params": params}, x)
        out = il.apply({"params": perm}, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# 5. MoE x interleaved (EP inside virtual-stage chunks)
# ---------------------------------------------------------------------------

def _perm_moe(params, L, S, v):
    """Natural -> chunk-permuted storage at each stack granularity
    (layers [L], super-layers [G], dense-fc rows [G*(m_every-1)])."""
    orders = {L: np.asarray(interleaved_layer_order(L, S, v))}
    if "blocks_moe_wi" in params:
        G = params["blocks_moe_wi"].shape[0]
        og = interleaved_layer_order(G, S, v)
        orders[G] = np.asarray(og)
        me = L // G
        if me > 1:
            orders[G * (me - 1)] = np.asarray(
                [g * (me - 1) + o for g in og for o in range(me - 1)])
    return {k: (val[orders[val.shape[0]]] if k.startswith("blocks_")
                and val.shape[0] in orders else val)
            for k, val in params.items()}


@pytest.mark.slow
@pytest.mark.parametrize("mesh_kw,dispatch", [
    (dict(data=2, pipe=2), "auto"),                 # replicated experts
    (dict(data=2, pipe=2, model=2), "replicated"),  # EP, psum lowering
    (dict(data=2, pipe=2, model=2), "alltoall"),    # EP, GShard a2a
])
def test_lmpp_interleaved_moe_matches_gpipe(mesh_kw, dispatch):
    """MoE x interleaved: routed super-layers inside virtual-stage
    chunks — CE-like loss + weighted aux grads must equal the gpipe
    run on the same semantic params (per-granularity chunk
    permutation mapped back), including true EP (expert stacks
    P('pipe','model')) under both dispatch lowerings; the EP cases
    exercise the executor's collective-uniform one-vjp-per-tick
    backward and its unreduced-cotangent completion."""
    import dataclasses

    from tpunet.config import MeshConfig, ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.parallel import make_mesh

    S, v, L = 2, 2, 8
    cfg = ModelConfig(name="lm_pp", vit_hidden=32, vit_depth=L,
                      vit_heads=2, dropout_rate=0.0, dtype="float32",
                      vocab_size=64, max_seq_len=32, pp_microbatches=4,
                      pp_virtual=v, moe_experts=4, moe_every=2,
                      moe_capacity_factor=4.0, moe_dispatch=dispatch,
                      vocab_ce="full")
    mesh = make_mesh(MeshConfig(**mesh_kw))
    gp = create_model(dataclasses.replace(cfg, moe_dispatch="auto"),
                      mesh=mesh)
    variables = init_variables(gp, jax.random.PRNGKey(0),
                               batch_size=8, seq_len=16)
    params = variables["params"]
    perm = _perm_moe(params, L, S, v)
    toks = jnp.asarray(np.random.default_rng(3).integers(0, 64, (8, 16)),
                       jnp.int32)
    il = create_model(dataclasses.replace(cfg,
                                          pp_schedule="interleaved"),
                      mesh=mesh)

    def grads(model, p):
        def loss(p):
            lg, mut = model.apply({"params": p}, toks, train=True,
                                  mutable=["losses"])
            aux = sum(jax.tree_util.tree_leaves(mut["losses"]))
            return (jnp.mean((lg - jnp.roll(lg, 1, -1)) ** 2)
                    + 0.01 * aux)
        with mesh:
            return jax.value_and_grad(loss)(p)

    v_ref, g_ref = grads(gp, params)
    v_int, g_int = grads(il, perm)
    np.testing.assert_allclose(float(v_int), float(v_ref), rtol=1e-5)
    # map interleaved (storage-order) grads back to natural order
    invs = {}
    for size, order in ((L, interleaved_layer_order(L, S, v)),):
        invs[size] = np.argsort(np.asarray(order))
    G = params["blocks_moe_wi"].shape[0]
    og = interleaved_layer_order(G, S, v)
    invs[G] = np.argsort(np.asarray(og))
    me = L // G
    if me > 1:
        fc = np.asarray([g * (me - 1) + o for g in og
                         for o in range(me - 1)])
        invs[G * (me - 1)] = np.argsort(fc)
    for k in g_ref:
        a = jax.tree_util.tree_leaves(g_int[k])[0]
        if k.startswith("blocks_") and a.shape[0] in invs:
            a = a[invs[a.shape[0]]]
        np.testing.assert_allclose(
            np.asarray(a),
            np.asarray(jax.tree_util.tree_leaves(g_ref[k])[0]),
            rtol=1e-4, atol=1e-6, err_msg=f"{mesh_kw}/{dispatch}: {k}")
    # router grads real (the aux cotangent flows through the executor)
    assert float(np.max(np.abs(np.asarray(g_int["blocks_moe_rk"])))) > 1e-7

    # the serve-path converter inverts every granularity
    from tpunet.models.lm_pp import to_transformer_lm_params
    nat = to_transformer_lm_params(params)
    via = to_transformer_lm_params(perm, pipe=S, virtual=v)
    for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(via),
            jax.tree_util.tree_leaves_with_path(nat)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=jax.tree_util.keystr(pa))
