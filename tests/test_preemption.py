"""Preemption guard, graceful mid-run checkpoint, and metrics.jsonl."""

import json
import os
import signal

import numpy as np
import pytest

from tpunet.config import (CheckpointConfig, DataConfig, MeshConfig,
                           ModelConfig, OptimConfig, TrainConfig)
from tpunet.train.loop import Trainer
from tpunet.utils.preemption import PreemptionGuard


def test_guard_catches_signal_and_restores_handler():
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    before = signal.getsignal(signal.SIGUSR1)
    with guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.requested
    assert signal.getsignal(signal.SIGUSR1) == before


def test_second_signal_escalates():
    """A repeat SIGTERM inside the grace window used to be silently
    absorbed by the already-set flag; now it escalates."""
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    with guard:
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.requested and not guard.escalated
        os.kill(os.getpid(), signal.SIGUSR1)
        assert guard.escalated


def test_programmatic_request_is_idempotent_unless_escalating():
    guard = PreemptionGuard()
    guard.request()
    guard.request()   # the cross-host stop agreement re-requests
    assert guard.requested and not guard.escalated
    guard.request(escalate=True)
    assert guard.escalated


def test_deadline_remaining_budget():
    clock = [100.0]
    guard = PreemptionGuard(deadline_s=30.0, clock=lambda: clock[0])
    assert guard.remaining() is None       # not yet requested
    guard.request()
    assert guard.remaining() == 30.0
    clock[0] += 12.5
    assert guard.remaining() == 17.5
    clock[0] += 100.0
    assert guard.remaining() == 0.0        # clamped, never negative
    # No configured deadline -> no budget, even when requested.
    unbounded = PreemptionGuard()
    unbounded.request()
    assert unbounded.remaining() is None


def test_escalated_preemption_abandons_checkpoint(tmp_path):
    """Second signal during the grace window: best-effort abandon —
    no save, no durability wait, immediate exit path."""
    trainer = Trainer(_cfg(tmp_path))
    real_epoch = trainer.train_one_epoch

    def epoch_then_double_preempt(epoch):
        m = real_epoch(epoch)
        trainer.guard.request()
        trainer.guard.request(escalate=True)   # the second SIGTERM
        return m

    trainer.train_one_epoch = epoch_then_double_preempt
    t0 = __import__("time").monotonic()
    try:
        history = trainer.train()
    finally:
        trainer.close()
    assert history == []
    # Checkpoint work was ABANDONED: no state directory was written
    # and close() returned without blocking on durability.
    assert not os.path.isdir(os.path.join(str(tmp_path), "state"))
    assert __import__("time").monotonic() - t0 < 60.0
    # ... and no partial row either (the escalated exit skips the
    # whole preemption-save bookkeeping).
    metrics = os.path.join(str(tmp_path), "metrics.jsonl")
    rows = []
    if os.path.exists(metrics):   # lazily created on first row
        with open(metrics) as f:
            rows = [json.loads(line) for line in f if line.strip()]
    assert not [r for r in rows if r.get("partial")]


def _cfg(tmp_path, epochs=3):
    return TrainConfig(
        epochs=epochs,
        data=DataConfig(dataset="synthetic", image_size=32, batch_size=32,
                        synthetic_train_size=64, synthetic_test_size=32),
        model=ModelConfig(name="vit", vit_patch=4, vit_hidden=32,
                          vit_depth=1, vit_heads=2, dropout_rate=0.0,
                          dtype="float32"),
        optim=OptimConfig(),
        mesh=MeshConfig(data=2),
        checkpoint=CheckpointConfig(directory=str(tmp_path), keep=3),
    )


@pytest.mark.slow
def test_preempted_run_saves_state_and_resumes(tmp_path):
    trainer = Trainer(_cfg(tmp_path))
    real_epoch = trainer.train_one_epoch

    def epoch_then_preempt(epoch):
        m = real_epoch(epoch)
        trainer.guard.request()   # same path as SIGTERM
        return m

    trainer.train_one_epoch = epoch_then_preempt
    try:
        history = trainer.train()
    finally:
        trainer.close()
    assert history == []          # preempted epoch logs no completed record
    step_after_one_epoch = trainer.global_step
    assert step_after_one_epoch == 2  # 64 / 32

    # ... but metrics.jsonl self-describes the interruption: a
    # partial: true row (no eval fields — the eval pass was skipped).
    with open(os.path.join(str(tmp_path), "metrics.jsonl")) as f:
        all_rows = [json.loads(line) for line in f]
    # obs_* rows (tpunet/obs/) share the file; the training rows are
    # the kind-less ones.
    rows = [r for r in all_rows if "kind" not in r]
    obs_rows = [r for r in all_rows if r.get("kind") == "obs_epoch"]
    assert len(obs_rows) == 1 and obs_rows[0].get("partial") is True
    assert len(rows) == 1 and rows[0]["partial"] is True
    assert rows[0]["epoch"] == 1 and rows[0]["step"] == 2
    assert "test_accuracy" not in rows[0]
    assert np.isfinite(rows[0]["train_loss"])

    resumed = Trainer(_cfg(tmp_path).replace(
        checkpoint=CheckpointConfig(directory=str(tmp_path), resume=True,
                                    keep=3)))
    try:
        # mid-epoch saves are marked partial: the interrupted epoch is
        # RE-RUN on resume (at-least-once; no data silently skipped),
        # with the step counter continuing for the LR schedule.
        assert resumed.start_epoch == 1
        assert resumed.global_step == step_after_one_epoch
        m = resumed.train_one_epoch(resumed.start_epoch)
    finally:
        resumed.close()
    assert np.isfinite(m["loss"])


@pytest.mark.slow
def test_metrics_jsonl_written(tmp_path):
    trainer = Trainer(_cfg(tmp_path, epochs=2))
    try:
        trainer.train()
    finally:
        trainer.close()
    path = os.path.join(str(tmp_path), "metrics.jsonl")
    with open(path) as f:
        all_records = [json.loads(line) for line in f]
    records = [r for r in all_records if "kind" not in r]
    assert [r["epoch"] for r in records] == [1, 2]
    for r in records:
        assert {"seconds", "step", "train_loss", "test_accuracy"} <= set(r)
    # the obs subsystem interleaves its per-epoch summaries
    obs = [r for r in all_records if r.get("kind") == "obs_epoch"]
    assert [r["epoch"] for r in obs] == [1, 2]
