"""Routing + autoscaling front tier (tpunet/router/).

Three layers, cheapest first: pure-logic units (balance, policy,
records, supervisor argv), stub-replica integration (stdlib HTTP
stubs play the replicas — Retry-After honoring, webhook eviction,
re-route), and THE end-to-end acceptance test: two real
``python -m tpunet.serve`` children behind an in-process router —
greedy parity through the proxy, least-loaded spread, a mid-stream
SIGKILL that the router evicts, respawns, and survives, with
``obs_router`` records in metrics.jsonl and the fleet dashboard's
ROUTER panel rendering them.
"""

import json
import os
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from tpunet.config import RouterConfig
from tpunet.router.balance import (affinity_key, pick_replica,
                                   preferred_replica)
from tpunet.router.policy import SCALE_DOWN, SCALE_UP, AutoscalePolicy
from tpunet.router.replica import (DEAD, DRAINING, EVICTED, HEALTHY,
                                   STARTING, ReplicaHandle)

SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def make_handle(name, *, slots=4, queue=0, active=0, state=HEALTHY,
                clock=None):
    h = ReplicaHandle(name, f"http://127.0.0.1:1{name[-1]}",
                      clock=clock or time.monotonic)
    h.state = state
    h.slots = slots
    h.queue_depth = queue
    h.active_slots = active
    return h


# ---------------------------------------------------------------------------
# balance
# ---------------------------------------------------------------------------


def test_affinity_key_session_wins_over_prompt():
    assert affinity_key({"session": "u1", "prompt": "x"}, 8) == "s:u1"
    k1 = affinity_key({"prompt": "shared prefix AAAA tail1"}, 16)
    k2 = affinity_key({"prompt": "shared prefix AAAA tail2"}, 16)
    assert k1 == k2 and k1.startswith("p:")
    t1 = affinity_key({"tokens": [1, 2, 3, 99]}, 3)
    t2 = affinity_key({"tokens": [1, 2, 3, 7]}, 3)
    # The router hashes the SAME digest the replicas' prefix KV cache
    # keys its pages on — the fleet-wide warm-start contract.
    from tpunet.serve.prefixcache.keys import token_prefix_digest
    assert t1 == t2 == "t:" + token_prefix_digest([1, 2, 3], 3)
    t3 = affinity_key({"tokens": [9, 2, 3]}, 3)
    assert t3 != t1
    assert affinity_key({}, 16) is None
    assert affinity_key({"prompt": "x"}, 0) is None


def test_pick_replica_least_loaded_and_exclude():
    a = make_handle("r0", queue=4, active=4)   # load 2.0
    b = make_handle("r1", queue=0, active=1)   # load 0.25
    c = make_handle("r2", state=DEAD)
    rep, hit = pick_replica([a, b, c])
    assert rep is b and not hit
    rep, _ = pick_replica([a, b, c], exclude={"r1"})
    assert rep is a
    rep, _ = pick_replica([c])
    assert rep is None


def test_affinity_sticks_until_overloaded():
    a = make_handle("r0")
    b = make_handle("r1")
    key = "s:conversation-42"
    pref = preferred_replica([a, b], key)
    other = b if pref is a else a
    # Balanced load: affinity wins regardless of which is least.
    rep, hit = pick_replica([a, b], key, affinity_slack=0.5)
    assert rep is pref and hit
    # Preferred overloaded past the slack: least-loaded wins.
    pref.queue_depth, pref.active_slots = 4, 4   # load 2.0
    rep, hit = pick_replica([a, b], key, affinity_slack=0.5)
    assert rep is other and not hit
    # Rendezvous stability: same key, same preferred, across calls.
    assert preferred_replica([a, b], key) is pref


def test_rendezvous_only_moves_keys_of_the_removed_replica():
    reps = [make_handle(f"r{i}") for i in range(4)]
    keys = [f"s:user-{i}" for i in range(50)]
    before = {k: preferred_replica(reps, k).name for k in keys}
    survivors = [r for r in reps if r.name != "r2"]
    moved = sum(1 for k in keys
                if preferred_replica(survivors, k).name != before[k])
    displaced = sum(1 for k in keys if before[k] == "r2")
    assert moved == displaced   # nobody else's sessions moved


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------


def _policy(clock, **kw):
    kw.setdefault("scale_window_probes", 3)
    kw.setdefault("scale_cooldown_s", 10.0)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    return AutoscalePolicy(RouterConfig(**kw), clock=clock)


def test_policy_hysteresis_up_then_cooldown():
    clock = FakeClock()
    pol = _policy(clock)
    # Pressure must be SUSTAINED: two rounds don't fire.
    assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                       replicas=2) is None
    assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                       replicas=2) is None
    assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                       replicas=2) == SCALE_UP
    # Cooldown holds even under continued pressure.
    for _ in range(5):
        assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                           replicas=3) is None
    # Sustained pressure through the cooldown fires on the first
    # post-cooldown round.
    clock.t += 11.0
    assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                       replicas=3) == SCALE_UP


def test_policy_down_requires_idle_and_min_bound():
    clock = FakeClock()
    pol = _policy(clock)
    for _ in range(2):
        assert pol.observe(queue_depth=0, slots=8, ttft_p99_s=None,
                           replicas=2) is None
    assert pol.observe(queue_depth=0, slots=8, ttft_p99_s=None,
                       replicas=2) == SCALE_DOWN
    clock.t += 11.0
    # At min_replicas the down decision never fires.
    for _ in range(6):
        assert pol.observe(queue_depth=0, slots=8, ttft_p99_s=None,
                           replicas=1) is None


def test_policy_ttft_slo_burn_arms_scale_up():
    clock = FakeClock()
    pol = _policy(clock, ttft_slo_ms=100.0)
    assert pol.slo_burn(0.25) == 2.5
    for _ in range(2):
        pol.observe(queue_depth=0, slots=8, ttft_p99_s=0.25,
                    replicas=2)
    assert pol.observe(queue_depth=0, slots=8, ttft_p99_s=0.25,
                       replicas=2) == SCALE_UP


def test_policy_ignores_fleet_without_capacity():
    """Boot time (0 healthy slots) must not read as idleness — the
    regression the first live router run caught."""
    clock = FakeClock()
    pol = _policy(clock)
    for _ in range(10):
        assert pol.observe(queue_depth=0, slots=0, ttft_p99_s=None,
                           replicas=2) is None
    # And the idle streak did not silently accumulate.
    assert pol.observe(queue_depth=0, slots=8, ttft_p99_s=None,
                       replicas=2) is None


def test_policy_max_bound():
    clock = FakeClock()
    pol = _policy(clock, max_replicas=2)
    for _ in range(6):
        assert pol.observe(queue_depth=16, slots=8, ttft_p99_s=None,
                           replicas=2) is None


# ---------------------------------------------------------------------------
# supervisor argv + webhook matching (no processes)
# ---------------------------------------------------------------------------


def test_supervisor_child_argv_composition(tmp_path):
    from tpunet.router.supervisor import Supervisor
    sup = Supervisor(["--checkpoint-dir", "ck", "--slots", "4"],
                     directory=str(tmp_path), aot_cache="/aot")
    argv = sup.child_argv(1, 8123, "router-replica-1")
    assert argv[:3] == [sys.executable, "-m", "tpunet.serve"]
    assert argv[argv.index("--port") + 1] == "8123"
    assert argv[argv.index("--run-id") + 1] == "router-replica-1"
    assert argv[argv.index("--metrics-dir") + 1].endswith("replica-1")
    assert argv[argv.index("--aot-cache") + 1] == "/aot"
    assert argv[-4:] == ["--checkpoint-dir", "ck", "--slots", "4"]
    # Caller-pinned --aot-cache in serve_args is not duplicated.
    sup2 = Supervisor(["--aot-cache", "/mine"], aot_cache="/aot")
    argv2 = sup2.child_argv(0, 1, "x")
    assert argv2.count("--aot-cache") == 1


def test_on_page_evicts_only_named_evictable_replica():
    from tpunet.router.core import Router
    cfg = RouterConfig(emit_every_s=0.0)
    router = Router(cfg, replica_urls=["http://127.0.0.1:1",
                                      "http://127.0.0.1:2"])
    router.replicas[0].run_id = "router-replica-0"
    router.replicas[0].state = HEALTHY
    router.replicas[1].run_id = "router-replica-1"
    router.replicas[1].state = HEALTHY
    # Non-evict reason: acknowledged, no action.
    assert not router.on_page({"kind": "obs_alert",
                               "reason": "loss_spike",
                               "run_id": "router-replica-0"})
    assert router.replicas[0].state == HEALTHY
    # Unknown run_id: no action.
    assert not router.on_page({"kind": "obs_alert",
                               "reason": "straggler",
                               "run_id": "nobody"})
    # The real page evicts exactly the named replica.
    assert router.on_page({"kind": "obs_alert", "reason": "straggler",
                           "run_id": "router-replica-1",
                           "detail": {"factor": 3.0}})
    assert router.replicas[1].state == EVICTED
    assert router.replicas[0].state == HEALTHY
    # obs_crash pages evict too; an already-evicted replica doesn't
    # double-evict.
    assert not router.on_page({"kind": "obs_crash",
                               "run_id": "router-replica-1"})


# ---------------------------------------------------------------------------
# stub-replica integration (stdlib stubs, no engine)
# ---------------------------------------------------------------------------


class _Stub:
    def __init__(self, run_id, behavior):
        self.run_id = run_id
        self.behavior = behavior      # dict mutated by the test
        stub = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _json(self, code, obj, headers=()):
                b = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(b)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(b)

            def do_GET(self):
                if stub.behavior.get("draining"):
                    self._json(503, {"status": "draining",
                                     "run_id": stub.run_id},
                               [("Retry-After", "30")])
                    return
                if self.path == "/healthz":
                    self._json(200, {"status": "ok",
                                     "run_id": stub.run_id,
                                     "slots": 4, "queue_depth": 0,
                                     "active_slots": 0})
                else:
                    self._json(200, {"serve_requests_total":
                                     stub.behavior.get("served", 0)})

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                self.rfile.read(n)
                if stub.behavior.get("draining"):
                    self._json(503, {"error": "draining"},
                               [("Retry-After", "30")])
                    return
                stub.behavior["served"] = \
                    stub.behavior.get("served", 0) + 1
                self._json(200, {"tokens": [7],
                                 "served_by": stub.run_id})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.httpd.daemon_threads = True
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _post(base, path, obj, timeout=15):
    req = urllib.request.Request(
        base + path, json.dumps(obj).encode(),
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _wait(pred, timeout=20.0, what=""):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out: {what}")


def test_router_honors_drain_retry_after_and_no_replica_503():
    """A draining replica's 503 + Retry-After backs it off; with every
    replica draining, the router itself answers 503 with Retry-After
    (the contract the ISSUE's drain satellite names)."""
    from tpunet.router import Router, RouterServer
    stubs = [_Stub("s0", {}), _Stub("s1", {})]
    cfg = RouterConfig(probe_interval_s=0.1, emit_every_s=0.0,
                       affinity_prefix=0, route_retries=2)
    router = Router(cfg, replica_urls=[s.url for s in stubs])
    server = RouterServer(router, port=0).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        _wait(lambda: router.healthy_count() == 2, what="2 healthy")
        stubs[0].behavior["draining"] = True
        for _ in range(4):
            code, out, _ = _post(base, "/v1/generate", {"tokens": [1]})
            assert code == 200 and out["served_by"] == "s1"
        handle = next(r for r in router.replicas if r.run_id == "s0")
        assert handle.backoff_until > 0
        stubs[1].behavior["draining"] = True
        _wait(lambda: all(not r.routable() for r in router.replicas),
              what="both backed off")
        code, out, headers = _post(base, "/v1/generate",
                                   {"tokens": [1]})
        assert code == 503
        assert "Retry-After" in headers
    finally:
        server.drain()
        for s in stubs:
            s.close()


# ---------------------------------------------------------------------------
# end-to-end: 2 real serve replicas behind the router
# ---------------------------------------------------------------------------

TINY_ARGS = ["--vit-hidden", "32", "--vit-depth", "2",
             "--vit-heads", "2", "--vocab-size", "256",
             "--max-seq-len", "512"]


def _router_server(tmp_path, n=2):
    from tpunet.router.__main__ import build_argparser, build_server
    argv = ["--spawn", str(n), "--port", "0",
            "--probe-interval-s", "0.2", "--probe-timeout-s", "2",
            "--unhealthy-after", "2", "--boot-timeout-s", "240",
            "--respawn-backoff-s", "0.2", "--emit-every-s", "0.5",
            "--min-replicas", str(n), "--max-replicas", str(n),
            "--metrics-dir", str(tmp_path),
            "--aot-cache", str(tmp_path / "aot"), "--",
            "--checkpoint-dir", "", "--slots", "2",
            "--prefill-buckets", "16", "--queue-max", "16",
            "--max-new-tokens", "64"] + TINY_ARGS
    args = build_argparser().parse_args(argv)
    return build_server(args).start()


def test_router_end_to_end_two_replicas(tmp_path):
    """THE acceptance test: parity through the proxy, least-loaded
    spread, SIGKILL mid-stream -> evict -> respawn -> next request
    succeeds, obs_router records + dashboard panel."""
    import jax

    from tpunet.config import ModelConfig
    from tpunet.models import create_model, init_variables
    from tpunet.models.lm import generate

    server = _router_server(tmp_path)
    router = server.router
    base = f"http://127.0.0.1:{server.port}"
    try:
        _wait(lambda: router.healthy_count() == 2, timeout=240,
              what="both replicas healthy (cold boot)")

        # -- greedy parity through the router --------------------------
        # Children run --checkpoint-dir "" => load_lm random-inits with
        # PRNGKey(0); the same init here is the solo reference.
        model_cfg = ModelConfig(name="lm", vit_hidden=32, vit_depth=2,
                                vit_heads=2, vocab_size=256,
                                max_seq_len=512, dropout_rate=0.0)
        model = create_model(model_cfg)
        variables = init_variables(model, jax.random.PRNGKey(0),
                                   seq_len=16)
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 256, size=7).astype(np.int32)
        code, out, _ = _post(base, "/v1/generate",
                             {"tokens": prompt.tolist(),
                              "max_new_tokens": 6}, timeout=120)
        assert code == 200, out
        solo = np.asarray(generate(model, variables, prompt[None],
                                   n_new=6))[0, 7:]
        assert out["tokens"] == solo.tolist(), \
            "router proxy output diverged from solo generate"

        # -- least-loaded spread ---------------------------------------
        results = [None] * 8
        prompts = [rng.integers(0, 256, size=5).astype(int).tolist()
                   for _ in range(8)]

        def worker(i):
            results[i] = _post(base, "/v1/generate",
                               {"tokens": prompts[i],
                                "max_new_tokens": 24}, timeout=120)

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=300)
        assert all(r is not None and r[0] == 200 for r in results)
        rows = json.loads(urllib.request.urlopen(
            base + "/replicas", timeout=10).read())["replicas"]
        routed = {r["name"]: r["requests_routed"] for r in rows}
        assert all(v >= 1 for v in routed.values()), \
            f"least-loaded routing did not spread: {routed}"

        # -- SIGKILL mid-stream -> evict -> respawn --------------------
        req = urllib.request.Request(
            base + "/v1/generate",
            json.dumps({"tokens": prompts[0], "max_new_tokens": 400,
                        "stream": True}).encode(),
            {"Content-Type": "application/json"})
        resp = urllib.request.urlopen(req, timeout=120)
        first = json.loads(resp.readline())
        assert "token" in first
        # The stream's owner shows active_slots > 0 on its next probe
        # (0.2s cadence); fall back to any live replica if the stream
        # outran the probe — the evict/respawn path is the assertion,
        # and the kill is mid-stream either way (the 400-token stream
        # is still flowing).
        victim = None
        deadline = time.monotonic() + 5.0
        while victim is None and time.monotonic() < deadline:
            rows = json.loads(urllib.request.urlopen(
                base + "/replicas", timeout=10).read())["replicas"]
            victim = next((r for r in rows
                           if r["active_slots"] > 0 and r.get("pid")),
                          None)
            if victim is None:
                time.sleep(0.05)
        if victim is None:
            victim = next(r for r in rows if r.get("alive"))
        os.kill(victim["pid"], signal.SIGKILL)
        # The stream ends (error frame or truncation) — tokens already
        # sent are not retried; the CLIENT retry lands on the
        # survivor.
        try:
            for _ in resp:
                pass
        except Exception:  # noqa: BLE001 — a reset IS an accepted end
            pass
        resp.close()
        dead_name = victim["name"]
        _wait(lambda: any(
            r["name"] == dead_name and r["state"] in ("dead", "evicted",
                                                      "starting")
            for r in json.loads(urllib.request.urlopen(
                base + "/replicas", timeout=10).read())["replicas"]),
            timeout=60, what="victim evicted")
        code, out, _ = _post(base, "/v1/generate",
                             {"tokens": prompts[1],
                              "max_new_tokens": 4}, timeout=120)
        assert code == 200, f"post-kill request failed: {out}"
        _wait(lambda: router.healthy_count() == 2, timeout=240,
              what="victim respawned healthy (AOT warm boot)")
        code, out, _ = _post(base, "/v1/generate",
                             {"tokens": prompts[2],
                              "max_new_tokens": 4}, timeout=120)
        assert code == 200
        snap = json.loads(urllib.request.urlopen(
            base + "/metrics", timeout=10).read())
        assert snap["router_evictions_total"] >= 1
        assert snap["router_respawns_total"] >= 1
    finally:
        server.drain()

    # -- obs_router records in metrics.jsonl ---------------------------
    recs = [json.loads(line) for line in
            (tmp_path / "metrics.jsonl").read_text().splitlines()]
    windows = [r for r in recs if r.get("kind") == "obs_router"
               and not r.get("event")]
    events = [r for r in recs if r.get("kind") == "obs_router"
              and r.get("event")]
    assert windows, "no obs_router window records in metrics.jsonl"
    assert windows[-1]["final"]
    assert {"evict", "respawn"} <= {e["event"] for e in events}

    # The respawned child booted from the AOT store — WHEN this
    # platform can serialize executables at all. save() is
    # best-effort by contract (tpunet/utils/cache.py): on jax builds
    # where the serialize/deserialize roundtrip is unsupported the
    # store stays empty by design, so gate the assertion on a local
    # roundtrip probe instead of assuming population. The child also
    # commits entries asynchronously w.r.t. serving, so poll rather
    # than listing the directory once.
    def _aot_roundtrip_supported() -> bool:
        try:
            import jax
            from jax.experimental import serialize_executable
            compiled = jax.jit(lambda x: x + 1).lower(1.0).compile()
            blob, in_tree, out_tree = \
                serialize_executable.serialize(compiled)
            serialize_executable.deserialize_and_load(
                blob, in_tree, out_tree)
            return True
        except Exception:  # noqa: BLE001 — unsupported platform
            return False

    if _aot_roundtrip_supported():
        aot_dir = tmp_path / "aot"
        _wait(lambda: aot_dir.is_dir() and any(
            f.endswith(".aotx") for f in os.listdir(aot_dir)),
            timeout=30, what=".aotx entries committed to the store")

    # -- fleet dashboard panel -----------------------------------------
    sys.path.insert(0, SCRIPTS)
    try:
        dash = __import__("obs_dashboard")
    finally:
        sys.path.pop(0)
    from tpunet.obs.agg import Aggregator
    agg = Aggregator()
    for r in recs:
        agg.ingest(r)
    rollup = agg.rollup()
    assert rollup.get("routers") == 1
    frame = dash.render_fleet_terminal(rollup, {}, "test")
    assert "ROUTER" in frame and "router:" in frame


def test_serve_cli_rejects_bad_prefill_buckets():
    """Satellite: --prefill-buckets typos are loud exit-2 usage
    errors, validated BEFORE any heavy import (the subprocess form
    proves the full CLI path; parse unit cases ride along)."""
    import subprocess

    from tpunet.serve.__main__ import parse_prefill_buckets

    assert parse_prefill_buckets("8,32", 64) == (8, 32)
    assert parse_prefill_buckets(" 8 , 32 ", 64) == (8, 32)
    for bad in ("8,abc", "", ",", "8,0", "8,-4", "8,128"):
        with pytest.raises(SystemExit) as exc:
            parse_prefill_buckets(bad, 64)
        assert exc.value.code == 2
    out = subprocess.run(
        [sys.executable, "-m", "tpunet.serve", "--port", "0",
         "--max-seq-len", "64", "--prefill-buckets", "16,notanint"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "not an integer" in out.stderr
    out = subprocess.run(
        [sys.executable, "-m", "tpunet.serve", "--port", "0",
         "--max-seq-len", "64", "--prefill-buckets", "16,128"],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 2
    assert "exceeds --max-seq-len" in out.stderr
